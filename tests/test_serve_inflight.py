"""In-flight scheduler over the FakeBackend slot loop: slot feeding,
refill into a running batch, key switching, oversized fallback, deadline
shedding, drain on close, take_upto semantics, and the slot metrics
surface. Hermetic — the real-engine loop is covered by
tests/test_inflight_engine.py."""
from __future__ import annotations

import threading
import time

import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.core.config import GenerationConfig
from vnsum_tpu.serve import (
    InflightScheduler,
    RequestQueue,
    RequestShed,
    ServeRequest,
    ShedReason,
)


def make_backend(**kw):
    kw.setdefault("segment_words", 8)
    kw.setdefault("segment_overhead_s", 0.005)
    kw.setdefault("per_slot_segment_s", 0.0005)
    kw.setdefault("batch_overhead_s", 0.01)
    return FakeBackend(**kw)


def make_sched(backend=None, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("max_wait_s", 0.01)
    return InflightScheduler(backend or make_backend(), **kw)


# -- basic serving -----------------------------------------------------------


def test_requests_complete_with_correct_per_request_outputs():
    sched = make_sched()
    try:
        prompts = [f"tai lieu {i} noi dung rieng " * 6 for i in range(8)]
        futs = [sched.submit(p) for p in prompts]
        for p, f in zip(prompts, futs):
            c = f.result(timeout=30)
            assert c.text == FakeBackend().generate([p])[0]
            assert c.record.status == "ok"
            # TTFT is anchored at the joiner's own prefill, always — the
            # slot loop needs no tracing collector for the anchor
            assert c.record.ttft_anchored
            assert 0 <= c.record.ttft_s <= c.record.total_s
        snap = sched.metrics.snapshot()
        assert snap.completed == 8
        assert snap.segments > 0
    finally:
        sched.close()


def test_inflight_concurrent_submissions():
    """Concurrent submitters stream through shared slots (also rerun under
    VNSUM_SANITIZERS=all in CI — the lock-order/transfer detectors cover
    the queue/metrics/loop interplay)."""
    sched = make_sched()
    try:
        prompts = [f"dong thoi {i} " * (4 + i) for i in range(10)]
        results = [None] * len(prompts)
        barrier = threading.Barrier(len(prompts))

        def worker(i, p):
            barrier.wait()
            results[i] = sched.submit(p).result(timeout=30)

        threads = [
            threading.Thread(target=worker, args=(i, p))
            for i, p in enumerate(prompts)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for p, c in zip(prompts, results):
            assert c.text == FakeBackend().generate([p])[0]
    finally:
        sched.close()


def test_refill_joins_running_batch():
    """A long-running resident plus later short arrivals: the later ones
    must be admitted at a segment boundary WHILE the resident decodes
    (refills counter moves), not after it finishes."""
    backend = make_backend(segment_words=4)  # 40-word output = 10 segments
    sched = make_sched(backend)
    try:
        long_fut = sched.submit("dai " * 60)
        time.sleep(0.03)  # a few segments deep
        short_futs = [sched.submit(f"ngan {i} muoi tu " * 3) for i in range(3)]
        long_c = long_fut.result(timeout=30)
        short_cs = [f.result(timeout=30) for f in short_futs]
        snap = sched.metrics.snapshot()
        assert snap.refills >= 3, snap.refills
        # the joiners rode the resident's batch: occupancy above 1
        assert any(c.record.batch_size > 1 for c in short_cs)
        assert long_c.record.status == "ok"
    finally:
        sched.close()


def test_short_joiner_finishes_before_long_resident():
    """The whole point of in-flight batching: a short request admitted
    during a long decode completes without waiting the stranger out."""
    backend = make_backend(segment_words=4)
    sched = make_sched(backend)
    try:
        long_fut = sched.submit("rat dai " * 60)           # 10 segments
        time.sleep(0.02)
        t0 = time.monotonic()
        short_c = sched.submit("ngan gon").result(timeout=30)
        short_wall = time.monotonic() - t0
        long_c = long_fut.result(timeout=30)
        assert long_c.record.total_s > short_wall
        assert short_c.record.status == "ok"
    finally:
        sched.close()


# -- compatibility / key switching -------------------------------------------


def test_incompatible_keys_drain_and_switch():
    sched = make_sched()
    try:
        a = sched.submit("khoa mot " * 5, max_new_tokens=16)
        b = sched.submit("khoa hai " * 5, max_new_tokens=32)
        c = sched.submit(
            "khoa ba " * 5, config=GenerationConfig(temperature=0.5)
        )
        for f in (a, b, c):
            assert f.result(timeout=30).record.status == "ok"
    finally:
        sched.close()


def test_incompatible_head_is_not_starved():
    """Compatible traffic keeps arriving while an incompatible request
    waits: after switch_grace_s the loop must drain and serve it."""
    backend = make_backend()
    sched = make_sched(backend, switch_grace_s=0.05)
    try:
        sched.submit("nen " * 30).result(timeout=30)  # warm the loop's key
        stop = threading.Event()
        done_odd = []

        def odd_key():
            done_odd.append(
                sched.submit("khac khoa " * 5, max_new_tokens=16)
                .result(timeout=30)
            )

        t = threading.Thread(target=odd_key)
        t.start()

        def feeder():
            while not stop.is_set():
                sched.submit("cung khoa " * 10).result(timeout=30)

        feeders = [threading.Thread(target=feeder) for _ in range(2)]
        for f in feeders:
            f.start()
        t.join(timeout=20)
        stop.set()
        for f in feeders:
            f.join(timeout=20)
        assert done_odd and done_odd[0].record.status == "ok"
    finally:
        sched.close()


# -- oversized fallback ------------------------------------------------------


def test_oversized_prompt_falls_back_to_batch_dispatch():
    backend = make_backend()
    sched = make_sched(backend, slot_prompt_tokens=8)
    try:
        small = sched.submit("vua khit day")           # 3 words, fits
        big_prompt = "qua kho " * 20                   # 40 words > 8
        big = sched.submit(big_prompt)
        assert small.result(timeout=30).record.status == "ok"
        c = big.result(timeout=30)
        assert c.record.status == "ok"
        assert c.text == FakeBackend().generate([big_prompt])[0]
    finally:
        sched.close()


# -- shedding / shutdown -----------------------------------------------------


def test_deadline_expiring_in_queue_is_shed():
    backend = make_backend(segment_words=2, segment_overhead_s=0.03)
    sched = make_sched(backend, slots=1)
    try:
        slow = sched.submit("giu may " * 40)  # 20 segments x 30ms
        shed = sched.submit(
            "het han " * 5, deadline=time.monotonic() + 0.05
        )
        assert slow.result(timeout=30).record.status == "ok"
        with pytest.raises(RequestShed) as exc:
            shed.result(timeout=30)
        assert exc.value.reason is ShedReason.DEADLINE
    finally:
        sched.close()


def test_close_drains_resident_and_queued():
    backend = make_backend()
    sched = make_sched(backend)
    futs = [sched.submit(f"thoat {i} " * 6) for i in range(6)]
    sched.close(drain=True)
    for f in futs:
        assert f.result(timeout=1).record.status == "ok"
    assert not sched._thread.is_alive()
    with pytest.raises(RequestShed):
        sched.submit("den muon ")


def test_backend_without_slot_loop_is_rejected():
    class NoLoop(FakeBackend):
        start_slot_loop = None

    with pytest.raises(ValueError, match="start_slot_loop"):
        InflightScheduler(NoLoop())


# -- strategy fan-out rides the slots ----------------------------------------


def test_queued_backend_fanout_rides_slot_loop():
    sched = make_sched()
    try:
        qb = sched.backend_view()
        outs = qb.generate([f"chunk {i} cua tai lieu " * 4 for i in range(6)])
        ref = FakeBackend()
        assert outs == [
            ref.generate([f"chunk {i} cua tai lieu " * 4])[0]
            for i in range(6)
        ]
        assert sched.metrics.snapshot().segments > 0
    finally:
        sched.close()


# -- fused multi-step decode (--fused-segments) ------------------------------


def test_fused_scheduler_outputs_and_dispatch_counters():
    """--fused-segments 4: outputs stay byte-identical to an unfused run
    (same per-row math, coarser host cadence) and the counters expose the
    amortization — more segments retired than host dispatches (also rerun
    under VNSUM_SANITIZERS=all in CI: the transfer guard proves the fused
    boundary fetch is the only device sync)."""
    backend = make_backend(segment_words=4)
    sched = make_sched(backend, fused_segments=4)
    try:
        prompts = [f"tai lieu hop nhat {i} noi dung rieng " * 8
                   for i in range(6)]
        futs = [sched.submit(p) for p in prompts]
        for p, f in zip(prompts, futs):
            assert f.result(timeout=30).text == FakeBackend().generate([p])[0]
        snap = sched.metrics.snapshot()
        assert snap.completed == 6
        assert snap.fused_dispatches > 0
        assert snap.segments > snap.fused_dispatches
        text = sched.metrics.render_prometheus(
            queue_depth=0, queued_tokens=0, slot_state=sched.slot_state()
        )
    finally:
        sched.close()
    assert "vnsum_serve_inflight_fused_dispatches_total" in text
    assert "vnsum_serve_inflight_fused_segments_bucket" in text


def test_fused_refill_joins_at_dispatch_boundaries():
    """Joins coarsen to fused-dispatch cadence but still land WHILE the
    resident decodes — the refill counter moves before the long request
    finishes, exactly as at N=1."""
    backend = make_backend(segment_words=4, per_step_s=0.002)
    sched = make_sched(backend, fused_segments=2)
    try:
        long_fut = sched.submit("dai " * 60)
        time.sleep(0.04)  # a fused dispatch or two deep
        short_futs = [sched.submit(f"ngan {i} muoi tu " * 3)
                      for i in range(3)]
        long_c = long_fut.result(timeout=30)
        short_cs = [f.result(timeout=30) for f in short_futs]
        snap = sched.metrics.snapshot()
        assert snap.refills >= 2, snap.refills
        assert any(c.record.batch_size > 1 for c in short_cs)
        assert long_c.record.status == "ok"
        assert snap.fused_dispatches > 0
    finally:
        sched.close()


# -- take_upto unit behavior -------------------------------------------------


def test_take_upto_filters_by_key_and_bills_per_slot():
    q = RequestQueue(max_depth=8, max_queued_tokens=1000)
    a = ServeRequest(prompt="a mot hai", max_new_tokens=32, est_tokens=3)
    b = ServeRequest(prompt="b ba", max_new_tokens=64, est_tokens=2)
    c = ServeRequest(prompt="c bon nam", max_new_tokens=32, est_tokens=3)
    for r in (a, b, c):
        q.submit(r)
    assert q.queued_tokens == 8
    got = q.take_upto(4, key=(32, None))
    assert [r.prompt for r in got] == ["a mot hai", "c bon nam"]
    assert q.depth == 1 and q.queued_tokens == 2
    # head-key default
    assert [r.prompt for r in q.take_upto(1)] == ["b ba"]
    # empty + open: [] after the wait; closed + drained: None
    assert q.take_upto(1, wait_s=0.0) == []
    q.close()
    assert q.take_upto(1) is None


def test_take_upto_head_snapshot():
    q = RequestQueue(max_depth=4)
    assert q.head_snapshot() is None
    r = ServeRequest(prompt="x", max_new_tokens=16)
    q.submit(r)
    key, enq = q.head_snapshot()
    assert key == (16, None) and enq == r.enqueued_at


# -- metrics surface ---------------------------------------------------------


def test_slot_metrics_render():
    sched = make_sched()
    try:
        sched.submit("do luong " * 6).result(timeout=30)
        text = sched.metrics.render_prometheus(
            queue_depth=0, queued_tokens=0, slot_state=sched.slot_state()
        )
    finally:
        sched.close()
    assert "vnsum_serve_inflight_segments_total" in text
    assert "vnsum_serve_inflight_refills_total" in text
    assert "vnsum_serve_slots_total 4" in text
    assert "vnsum_serve_slots_busy" in text
    assert "vnsum_serve_slot_occupancy_bucket" in text
    assert "vnsum_serve_ttft_seconds_bucket" in text
