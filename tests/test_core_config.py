import json

import pytest

from vnsum_tpu.core import PipelineConfig, approach_defaults
from vnsum_tpu.core.results import DocumentRecord, ModelRunRecord, PipelineResults


def test_defaults_match_reference():
    cfg = PipelineConfig()
    assert cfg.chunk_size == 12000
    assert cfg.chunk_overlap == 200
    assert cfg.token_max == 10000
    assert cfg.max_context == 16384
    assert cfg.max_new_tokens == 1024


def test_approach_defaults():
    assert approach_defaults("mapreduce_critique")["max_new_tokens"] == 2048
    assert approach_defaults("truncated") == {"max_context": 16384}
    with pytest.raises(ValueError):
        approach_defaults("nope")


def test_roundtrip():
    cfg = PipelineConfig(approach="iterative", models=["m1"])
    cfg2 = PipelineConfig.from_dict(json.loads(cfg.to_json()))
    assert cfg2 == cfg


def test_validation():
    with pytest.raises(ValueError):
        PipelineConfig(approach="bogus")
    with pytest.raises(ValueError):
        PipelineConfig(chunk_size=100, chunk_overlap=100)
    with pytest.raises(ValueError):
        PipelineConfig.from_dict({"not_a_key": 1})


def test_results_schema(tmp_path):
    res = PipelineResults(config=PipelineConfig().to_dict())
    rec = ModelRunRecord(model="m", approach="mapreduce")
    rec.total_documents = 2
    rec.successful = 2
    rec.total_chunks = 10
    rec.total_time = 5.0
    rec.processing_details.append(
        DocumentRecord("a.txt", num_chunks=5, processing_time=2.5, summary_length_chars=100)
    )
    res.add_summarization(rec)
    res.add_evaluation("m", {"rouge1": {"f": 0.5}})
    path = res.save(tmp_path)
    data = json.loads(path.read_text())
    assert data["pipeline_info"]["framework"] == "vnsum_tpu"
    assert data["results"]["summarization"]["m"]["chunks_per_second"] == 2.0
    assert data["results"]["evaluation"]["m"]["rouge1"]["f"] == 0.5
