"""Unit tests for the vnsum_tpu.obs observability subsystem: histogram
bucket math + Prometheus text rendering, Chrome trace-event JSON schema,
ring-buffer eviction, span recording, sampling, rolling windows — plus the
core/logging handler-installation fix that rides this PR."""
from __future__ import annotations

import json
import logging
import threading
import time

import pytest

from vnsum_tpu.obs import (
    BatchTrace,
    Histogram,
    ObsHub,
    RequestTrace,
    Rolling,
    SpanRecorder,
    current_collector,
    emit,
    reset_collector,
    set_collector,
)
from vnsum_tpu.obs.export import chrome_trace, spans_to_chrome


# -- histogram bucket math ----------------------------------------------------


def test_histogram_bucket_assignment_and_counts():
    h = Histogram((0.01, 0.1, 1.0))
    for v in (0.005, 0.01, 0.05, 0.5, 2.0):
        h.observe(v)
    # boundaries are inclusive on the upper edge (Prometheus `le`)
    assert h.counts == [2, 1, 1, 1]
    assert h.count == 5
    assert h.sum == pytest.approx(2.565)


def test_histogram_rejects_bad_bounds():
    with pytest.raises(ValueError):
        Histogram(())
    with pytest.raises(ValueError):
        Histogram((1.0, 0.5))


def test_histogram_percentiles_interpolate_within_bucket():
    h = Histogram((1.0, 2.0, 4.0))
    for _ in range(100):
        h.observe(1.5)  # all land in (1, 2]
    # rank 50 of 100 falls midway through the (1,2] bucket
    assert h.percentile(0.50) == pytest.approx(1.5)
    assert h.percentile(0.99) == pytest.approx(1.99)
    # +Inf tail floors at the highest finite bound, like histogram_quantile
    h2 = Histogram((1.0,))
    h2.observe(50.0)
    assert h2.percentile(0.99) == 1.0
    # empty histogram: quantiles are 0, not NaN
    assert Histogram((1.0,)).percentile(0.5) == 0.0


def test_histogram_prometheus_rendering_is_cumulative():
    h = Histogram((0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    lines = h.render("x_seconds", "help text")
    assert lines[0] == "# HELP x_seconds help text"
    assert lines[1] == "# TYPE x_seconds histogram"
    assert 'x_seconds_bucket{le="0.1"} 1' in lines
    assert 'x_seconds_bucket{le="1"} 2' in lines       # cumulative
    assert 'x_seconds_bucket{le="+Inf"} 3' in lines
    assert "x_seconds_sum 5.55" in lines
    assert "x_seconds_count 3" in lines


def test_histogram_to_dict_has_quantiles():
    h = Histogram((1.0, 2.0))
    for v in (0.5, 1.5, 3.0):
        h.observe(v)
    d = h.to_dict()
    assert d["count"] == 3 and d["buckets"]["+Inf"] == 1
    assert set(d) >= {"p50", "p95", "p99", "sum", "count", "buckets"}


def test_histogram_merge_mismatched_ladders_raises_typed():
    from vnsum_tpu.obs.histogram import HistogramMergeError

    a = Histogram((0.1, 1.0))
    b = Histogram((0.1, 1.0, 10.0))
    with pytest.raises(HistogramMergeError) as exc:
        a.merge_from(b)
    # the typed error IS the fleet-federation contract: a ValueError
    # subclass a rollup can catch without masking real bugs
    assert isinstance(exc.value, ValueError)
    assert "different bounds" in str(exc.value)
    # from_state hits the same typed error on a counts/ladder mismatch
    state = a.state_dict()
    state["counts"] = state["counts"][:-1]
    with pytest.raises(HistogramMergeError):
        Histogram.from_state(state)


def test_histogram_merge_equals_observing_union():
    """Property: merging N worker-shaped histograms (state_dict ->
    from_state -> merge_from, the federation round trip) is EXACTLY
    observing the union of their samples — counts vector, sum, count, and
    every derived percentile agree."""
    import random

    rng = random.Random(19)
    bounds = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0)
    union = Histogram(bounds)
    merged = None
    for _worker in range(5):
        h = Histogram(bounds)
        for _ in range(rng.randrange(0, 40)):
            v = rng.choice([rng.uniform(0.0, 0.6), rng.expovariate(0.5)])
            h.observe(v)
            union.observe(v)
        wire = Histogram.from_state(h.state_dict())  # the scrape hop
        if merged is None:
            merged = wire
        else:
            merged.merge_from(wire)
    assert merged is not None
    assert merged.counts == union.counts
    assert merged.count == union.count
    assert merged.sum == pytest.approx(union.sum)
    for q in (0.5, 0.9, 0.95, 0.99):
        assert merged.percentile(q) == pytest.approx(union.percentile(q))
    assert merged.fraction_le(0.5) == pytest.approx(union.fraction_le(0.5))


# -- rolling window -----------------------------------------------------------


def test_rolling_window_evicts_old_samples():
    r = Rolling(window=2)
    r.add(1, 10)   # 10% acceptance
    assert r.rate() == pytest.approx(0.1)
    r.add(9, 10)
    r.add(10, 10)  # evicts the first sample
    assert r.samples == 2
    assert r.rate() == pytest.approx(19 / 20)
    assert Rolling(4).rate() == 0.0  # empty denominator -> 0, not ZeroDivision


# -- span recorder (the shared Tracer/RequestTrace primitive) -----------------


def test_span_recorder_hierarchical_names_and_bound():
    rec = SpanRecorder(maxlen=3)
    with rec.span("outer"):
        with rec.span("inner"):
            pass
    names = [s.name for s in rec.spans()]
    assert names == ["outer/inner", "outer"]  # closed in completion order
    for i in range(5):
        rec.add(f"extra{i}", 0.0, 0.1)
    assert len(rec.spans()) == 3  # bounded, never unbounded growth


def test_request_trace_tracks_and_finish():
    tr = RequestTrace("req-abc")
    a, b = tr.next_track(), tr.next_track()
    assert (a, b) == (1, 2)
    tr.add("queue_wait", time.monotonic(), 0.01, track=a)
    tr.finish("ok")
    assert tr.status == "ok"
    names = [s.name for s in tr.spans]
    assert "request" in names and "queue_wait" in names


def test_finished_trace_is_sealed_against_late_spans():
    # a shed closes the trace mid-fan-out while sibling prompts are still
    # queued; their eventual completions must not mutate the exported ring
    tr = RequestTrace("req-shed")
    tr.add("queue_wait", time.monotonic(), 0.01, track=1)
    tr.finish("shed:queue_full")
    n = len(tr.spans_snapshot())
    tr.add("engine", time.monotonic(), 0.2, track=2)  # straggler: dropped
    assert len(tr.spans_snapshot()) == n


def test_unsynced_prefill_does_not_anchor_ttft():
    # TpuBackend without instrument=True returns from the prefill call at
    # async DISPATCH — its emitted duration bounds submission, not device
    # time, and must not become the TTFT anchor (synced=False); an
    # instrumented (sync-bounded) prefill must
    bt = BatchTrace(batch_id=1, occupancy=2)
    t0 = time.monotonic()
    bt.event("prefill", t0, 0.0005, B=2, synced=False)
    assert bt.first_token_at is None
    bt.event("spec_prefill", t0, 0.3, B=2, synced=True)
    assert bt.first_token_at == pytest.approx(t0 + 0.3)


# -- emit / collector propagation --------------------------------------------


def test_emit_noops_without_collector():
    assert current_collector() is None
    emit("prefill", time.monotonic(), 0.1, B=4)  # must not raise or record


def test_emit_lands_on_installed_collector_and_sets_ttft_anchor():
    bt = BatchTrace(batch_id=1, occupancy=4)
    token = set_collector(bt)
    try:
        t0 = time.monotonic()
        emit("prefill", t0, 0.25, B=4)
        emit("decode", t0 + 0.25, 0.5, B=4)
    finally:
        reset_collector(token)
    assert [e.name for e in bt.events] == ["prefill", "decode"]
    assert bt.first_token_at == pytest.approx(t0 + 0.25)
    assert current_collector() is None
    emit("after", time.monotonic(), 0.1)
    assert len(bt.events) == 2  # nothing lands after reset


# -- hub: sampling + ring eviction -------------------------------------------


def test_hub_ring_evicts_oldest():
    hub = ObsHub(sample=1.0, ring=3)
    for i in range(5):
        hub.finish_request(hub.start_request(f"req-{i}"))
    reqs, _ = hub.snapshot()
    assert [r.trace_id for r in reqs] == ["req-2", "req-3", "req-4"]
    assert hub.dropped_requests == 2
    for i in range(5):
        hub.finish_batch(hub.start_batch(occupancy=i))
    _, batches = hub.snapshot()
    assert len(batches) == 3


def test_hub_sampling_rate_is_exact_deterministically():
    hub = ObsHub(sample=0.25, ring=1000)
    traced = sum(hub.start_request("r") is not None for _ in range(100))
    assert traced == 25  # error-diffusion accumulator: exact, no RNG


def test_hub_sample_zero_never_traces():
    hub = ObsHub(sample=0.0)
    assert all(hub.start_request("r") is None for _ in range(20))


# -- chrome trace export ------------------------------------------------------


def _valid_chrome(doc: dict) -> None:
    """Schema assertions matching what Perfetto's JSON importer requires."""
    json.loads(json.dumps(doc))  # JSON-serializable end to end
    assert isinstance(doc["traceEvents"], list)
    for e in doc["traceEvents"]:
        assert e["ph"] in ("X", "M")
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0
            assert isinstance(e["name"], str) and e["name"]
        else:
            assert e["name"] in ("process_name", "thread_name")
            assert "name" in e["args"]


def test_chrome_trace_has_request_and_batch_tracks():
    hub = ObsHub(sample=1.0)
    bt = hub.start_batch(occupancy=2)
    bt.event("prefill", time.monotonic(), 0.1, B=2)
    hub.finish_batch(bt, gen_tokens=40)
    tr = hub.start_request("req-xyz")
    track = tr.next_track()
    tr.add("queue_wait", time.monotonic(), 0.01, track=track)
    tr.add("engine", time.monotonic(), 0.2, track=track, batch=bt.batch_id)
    hub.finish_request(tr)

    doc = hub.chrome_trace()
    _valid_chrome(doc)
    procs = {
        e["args"]["name"]
        for e in doc["traceEvents"]
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert "engine" in procs                      # >= one batch track
    assert "request req-xyz" in procs             # >= one request track
    slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {"batch[occ=2]", "prefill", "queue_wait", "engine", "request"} <= {
        e["name"] for e in slices
    }


def test_spans_to_chrome_roundtrips_tracer_timeline():
    from vnsum_tpu.core.profiling import Tracer

    t = Tracer()
    with t.span("analyze"):
        with t.span("inner"):
            pass
    t.record("device_step", 0.25)
    doc = t.chrome_trace("pipeline")
    _valid_chrome(doc)
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert {"analyze", "analyze/inner", "device_step"} <= names


# -- logging fix (satellite) --------------------------------------------------


def _fresh_vnsum_root():
    root = logging.getLogger("vnsum")
    for h in list(root.handlers):
        if getattr(h, "_vnsum_stream_handler", False):
            root.removeHandler(h)
    return root


def test_get_logger_installs_on_vnsum_root_even_when_global_root_configured():
    from vnsum_tpu.core.logging import get_logger

    _fresh_vnsum_root()
    # the old bug: a configured GLOBAL root (pytest/absl/basicConfig) made
    # get_logger skip installation entirely, silencing all vnsum logs
    assert logging.getLogger().handlers, "pytest should have root handlers"
    get_logger("vnsum.test")
    root = logging.getLogger("vnsum")
    marked = [h for h in root.handlers
              if getattr(h, "_vnsum_stream_handler", False)]
    assert len(marked) == 1
    # idempotent: repeated calls never stack duplicates
    get_logger("vnsum.other")
    get_logger()
    marked = [h for h in root.handlers
              if getattr(h, "_vnsum_stream_handler", False)]
    assert len(marked) == 1
    # and vnsum owns its emission: no propagation to the configured global
    # root, which would print every line twice
    assert root.propagate is False


def test_json_log_formatter_emits_one_json_object_per_line():
    from vnsum_tpu.core.logging import JsonFormatter

    rec = logging.LogRecord(
        "vnsum.serve", logging.INFO, __file__, 1,
        "request %s done", ("req-1",), None,
    )
    line = JsonFormatter().format(rec)
    d = json.loads(line)
    assert d["level"] == "INFO" and d["logger"] == "vnsum.serve"
    assert d["msg"] == "request req-1 done"
    assert "ts" in d


def test_vnsum_log_json_env_selects_json_formatter(monkeypatch):
    from vnsum_tpu.core import logging as vlog

    monkeypatch.setenv("VNSUM_LOG_JSON", "1")
    _fresh_vnsum_root()
    vlog.get_logger()
    root = logging.getLogger("vnsum")
    h = next(h for h in root.handlers
             if getattr(h, "_vnsum_stream_handler", False))
    assert isinstance(h.formatter, vlog.JsonFormatter)
    # restore a plain-format handler for the rest of the session
    monkeypatch.delenv("VNSUM_LOG_JSON")
    _fresh_vnsum_root()
    vlog.get_logger()
