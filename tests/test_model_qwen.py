"""Qwen3-family support: per-head Q/K RMSNorm (qk_norm) on the shared
Llama/Qwen3 decoder stack.

Parity anchor is HF transformers' Qwen3ForCausalLM on a tiny config — the
same oracle role the reference's torch path plays for Llama
(runners/run_summarization.py:54-62; the reference sweeps qwen3:8b at
run_full_evaluation_pipeline.py:960-962 but only ever through Ollama HTTP).
"""
from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax
import jax.numpy as jnp

from vnsum_tpu.models.convert import (
    config_from_hf,
    convert_torch_model,
    load_hf_checkpoint,
    save_hf_checkpoint,
)
from vnsum_tpu.models.llama import (
    forward,
    init_kv_cache,
    init_params,
    prefill_attention_mask,
    prefill_positions,
    qwen3_8b,
    tiny_llama,
)

HF_CFG = dict(
    vocab_size=384,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    max_position_embeddings=256,
    rope_theta=10000.0,
    rms_norm_eps=1e-6,
    tie_word_embeddings=True,
    model_type="qwen3",
)


@pytest.fixture(scope="module")
def hf_model():
    torch.manual_seed(0)
    cfg = transformers.Qwen3Config(**{
        k: v for k, v in HF_CFG.items() if k != "model_type"
    })
    return transformers.Qwen3ForCausalLM(cfg).eval()


@pytest.fixture(scope="module")
def converted(hf_model):
    cfg = config_from_hf(HF_CFG, dtype=jnp.float32)
    assert cfg.qk_norm  # model_type=qwen3 flips the QK-norm path on
    params = convert_torch_model(hf_model, cfg)
    assert "q_norm" in params["layers"] and "k_norm" in params["layers"]
    return cfg, params


def _hf_logits(hf_model, tokens: np.ndarray) -> np.ndarray:
    with torch.no_grad():
        out = hf_model(torch.from_numpy(tokens).long())
    return out.logits.float().numpy()


def _our_logits(cfg, params, tokens: np.ndarray) -> np.ndarray:
    B, S = tokens.shape
    pad = np.zeros((B,), np.int32)
    cache = init_kv_cache(cfg, B, S)
    out, _ = forward(
        params, cfg, jnp.asarray(tokens),
        prefill_positions(jnp.asarray(pad), S), cache, 0,
        prefill_attention_mask(jnp.asarray(pad), S, S),
    )
    return np.asarray(out)


def test_qwen3_prefill_logit_parity(hf_model, converted):
    cfg, params = converted
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (2, 24), dtype=np.int32)
    ours = _our_logits(cfg, params, tokens)
    theirs = _hf_logits(hf_model, tokens)
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-3)


def test_qwen3_hf_checkpoint_roundtrip(tmp_path, converted):
    cfg, params = converted
    out = tmp_path / "export"
    save_hf_checkpoint(params, cfg, str(out))
    cfg2, params2 = load_hf_checkpoint(str(out), dtype=jnp.float32)
    assert cfg2.qk_norm
    assert "q_norm" in params2["layers"]
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, cfg.vocab_size, (1, 16), dtype=np.int32)
    bf = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16).astype(jnp.float32), params
    )
    np.testing.assert_array_equal(
        _our_logits(cfg, bf, tokens), _our_logits(cfg2, params2, tokens)
    )


def test_qwen3_engine_generate_and_registry():
    """The engine runs a qk_norm config end to end, and the registry
    resolves the reference's qwen3:8b model tag to the real architecture."""
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.models import MODEL_REGISTRY

    cfg8 = MODEL_REGISTRY["qwen3:8b"]()
    assert cfg8.qk_norm and cfg8.dim == 4096 and cfg8.n_layers == 36

    tiny_q = tiny_llama(qk_norm=True)
    be = TpuBackend(
        model_config=tiny_q, tokenizer="byte", batch_size=2,
        max_new_tokens=8, seed=0,
    )
    outs = be.generate(["văn bản một", "hai"])
    assert len(outs) == 2 and all(isinstance(o, str) for o in outs)


def test_qwen3_mesh_sharding():
    """qk_norm params shard over a TP mesh (new leaves replicated)."""
    from vnsum_tpu.parallel import make_mesh
    from vnsum_tpu.parallel.sharding import shard_params

    mesh = make_mesh({"data": 2, "model": 2}, platform="cpu")
    cfg = tiny_llama(qk_norm=True)
    params = init_params(jax.random.key(0), cfg)
    sharded = shard_params(params, mesh, cfg.tie_embeddings)
    assert "q_norm" in sharded["layers"]


def test_qwen3_8b_shapes_match_hf():
    """Registry config matches the published Qwen3-8B architecture."""
    cfg = qwen3_8b()
    assert (cfg.vocab_size, cfg.dim, cfg.n_layers) == (151_936, 4096, 36)
    assert (cfg.n_heads, cfg.n_kv_heads, cfg.head_dim) == (32, 8, 128)
    assert not cfg.tie_embeddings
