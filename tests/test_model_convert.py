"""HF checkpoint conversion: logit parity against transformers on CPU.

This is the correctness anchor for real-weight runs (SURVEY.md §7 hard part
#2: "Llama-3.2-3B weight port + sharding correctness (logit parity vs HF
CPU)"). A tiny random HF LlamaForCausalLM is converted and both models must
produce near-identical float32 logits.
"""
from __future__ import annotations

import json

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp

from vnsum_tpu.models.convert import (
    config_from_hf,
    convert_torch_model,
    load_hf_checkpoint,
)
from vnsum_tpu.models.llama import (
    forward_train,
    init_kv_cache,
    forward,
    prefill_attention_mask,
    prefill_positions,
)

HF_CFG = dict(
    vocab_size=384,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    max_position_embeddings=256,
    rope_theta=10000.0,
    rms_norm_eps=1e-5,
    tie_word_embeddings=True,
)


@pytest.fixture(scope="module")
def hf_model():
    torch.manual_seed(0)
    cfg = transformers.LlamaConfig(**HF_CFG)
    model = transformers.LlamaForCausalLM(cfg).eval()
    return model


@pytest.fixture(scope="module")
def converted(hf_model):
    cfg = config_from_hf(HF_CFG, dtype=jnp.float32)
    params = convert_torch_model(hf_model, cfg)
    return cfg, params


def _hf_logits(hf_model, tokens: np.ndarray) -> np.ndarray:
    with torch.no_grad():
        out = hf_model(torch.from_numpy(tokens).long())
    return out.logits.float().numpy()


def test_config_from_hf_fields(converted):
    cfg, _ = converted
    assert cfg.dim == 64
    assert cfg.n_layers == 2
    assert cfg.n_kv_heads == 2
    assert cfg.head_dim == 16
    assert cfg.tie_embeddings is True
    assert cfg.use_llama3_rope_scaling is False


def test_config_from_hf_llama3_rope():
    hf = dict(
        HF_CFG,
        rope_scaling={
            "rope_type": "llama3",
            "factor": 32.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 8192,
        },
    )
    cfg = config_from_hf(hf)
    assert cfg.use_llama3_rope_scaling
    assert cfg.rope_scale_factor == 32.0
    assert cfg.rope_original_max_len == 8192


def test_train_forward_logit_parity(hf_model, converted):
    cfg, params = converted
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 17), dtype=np.int32)
    ours = np.asarray(
        forward_train(params, cfg, jnp.asarray(tokens), remat=False)
    )
    ref = _hf_logits(hf_model, tokens)
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-3)


def test_prefill_forward_logit_parity(hf_model, converted):
    cfg, params = converted
    rng = np.random.default_rng(1)
    B, S = 2, 12
    tokens = rng.integers(0, cfg.vocab_size, size=(B, S), dtype=np.int32)
    pad = jnp.zeros((B,), jnp.int32)
    cache = init_kv_cache(cfg, B, S)
    logits, _ = forward(
        params, cfg, jnp.asarray(tokens), prefill_positions(pad, S), cache,
        0, prefill_attention_mask(pad, S, S),
    )
    ref = _hf_logits(hf_model, tokens)
    np.testing.assert_allclose(np.asarray(logits), ref, atol=2e-4, rtol=2e-3)


def test_load_hf_checkpoint_safetensors(tmp_path, hf_model, converted):
    from safetensors.torch import save_file

    cfg, params = converted
    sd = {k: v.contiguous().clone() for k, v in hf_model.state_dict().items()}
    save_file(sd, str(tmp_path / "model.safetensors"))
    (tmp_path / "config.json").write_text(json.dumps(HF_CFG))

    cfg2, params2 = load_hf_checkpoint(str(tmp_path), dtype=jnp.float32)
    assert cfg2.dim == cfg.dim and cfg2.n_layers == cfg.n_layers
    np.testing.assert_allclose(
        np.asarray(params2["layers"]["wq"]), np.asarray(params["layers"]["wq"]),
        atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(params2["embed"]), np.asarray(params["embed"]), atol=1e-6
    )


def test_sharded_checkpoint_with_index(tmp_path, hf_model, converted):
    from safetensors.torch import save_file

    cfg, params = converted
    sd = {k: v.contiguous().clone() for k, v in hf_model.state_dict().items()}
    keys = sorted(sd)
    half = len(keys) // 2
    shards = {
        "model-00001-of-00002.safetensors": {k: sd[k] for k in keys[:half]},
        "model-00002-of-00002.safetensors": {k: sd[k] for k in keys[half:]},
    }
    weight_map = {}
    for shard, tensors in shards.items():
        save_file(tensors, str(tmp_path / shard))
        for k in tensors:
            weight_map[k] = shard
    (tmp_path / "model.safetensors.index.json").write_text(
        json.dumps({"weight_map": weight_map})
    )
    (tmp_path / "config.json").write_text(json.dumps(HF_CFG))

    _, params2 = load_hf_checkpoint(str(tmp_path), dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(params2["layers"]["w_down"]),
        np.asarray(params["layers"]["w_down"]),
        atol=1e-6,
    )


def test_save_hf_checkpoint_roundtrip(tmp_path, converted):
    """save_hf_checkpoint is the exact inverse of load_hf_checkpoint: a
    params tree exported to sharded HF safetensors and loaded back must be
    bit-identical (modulo the bf16 storage dtype) and produce identical
    prefill logits. This pair is how the 3B runbook artifact proves the
    converter at real scale without the real weights."""
    import jax

    from vnsum_tpu.models.convert import save_hf_checkpoint

    cfg, params = converted
    out = tmp_path / "export"
    index = save_hf_checkpoint(params, cfg, str(out), shard_layers=1)
    # sharding actually happened: 2 layer shards + 1 head shard
    assert len(set(index["weight_map"].values())) == 3
    cfg2, params2 = load_hf_checkpoint(str(out), dtype=jnp.float32)
    assert cfg2.dim == cfg.dim and cfg2.n_layers == cfg.n_layers
    assert cfg2.tie_embeddings == cfg.tie_embeddings

    def max_diff(a, b):
        return max(
            float(jnp.max(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32))))
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
        )

    # bf16 storage: exported tensors round through bfloat16 once
    bf = jax.tree.map(lambda x: x.astype(jnp.bfloat16).astype(jnp.float32), params)
    assert max_diff(bf, params2) == 0.0

    tokens = np.arange(12, dtype=np.int32).reshape(1, 12) % cfg.vocab_size
    S = 16
    pad = np.asarray([S - 12], np.int32)
    toks = np.full((1, S), 0, np.int32)
    toks[0, 4:] = tokens
    def logits_of(p):
        cache = init_kv_cache(cfg, 1, S)
        out, _ = forward(
            p, cfg, jnp.asarray(toks), prefill_positions(jnp.asarray(pad), S),
            cache, 0, prefill_attention_mask(jnp.asarray(pad), S, S),
            last_only=True,
        )
        return np.asarray(out)

    np.testing.assert_array_equal(logits_of(bf), logits_of(params2))


def test_save_hf_checkpoint_untied(tmp_path):
    """Untied lm_head round-trips through the [vocab, dim] HF layout."""
    import jax

    from vnsum_tpu.models import init_params
    from vnsum_tpu.models.convert import save_hf_checkpoint
    from vnsum_tpu.models.llama import tiny_llama

    cfg = tiny_llama(tie_embeddings=False)
    params = init_params(jax.random.key(0), cfg)
    out = tmp_path / "export"
    save_hf_checkpoint(params, cfg, str(out))
    cfg2, params2 = load_hf_checkpoint(str(out), dtype=jnp.float32)
    assert not cfg2.tie_embeddings
    got = np.asarray(params2["lm_head"], np.float32)
    want = np.asarray(
        jnp.asarray(params["lm_head"], jnp.bfloat16).astype(jnp.float32)
    )
    np.testing.assert_array_equal(got, want)


# -- four-family trained generation parity (VERDICT r3 #4) -------------------

# harness lifted to models/fixtures.py so artifact scripts train the same
# checkpoints (VERDICT r4 #2 quality A/B); the test keeps its local aliases
from vnsum_tpu.models.fixtures import (  # noqa: E402
    GEN_CORPUS as _GEN_CORPUS,
    TRAINED_FAMILIES as _FAMILIES,
    train_tiny_family as _train_tiny_family_lib,
)


def _train_tiny_family(family: str, out_dir, steps: int = 40):
    return _train_tiny_family_lib(family, out_dir, steps=steps)

@pytest.mark.parametrize("family", sorted(_FAMILIES))
def test_trained_generation_string_parity(family, tmp_path):
    """Greedy STRING parity on a trained tiny fixture, per family: the
    engine over load_hf_checkpoint must emit exactly what transformers'
    .generate emits for the same checkpoint (the generation-level
    complement of the logit-parity tests — VERDICT r3 #4)."""
    from vnsum_tpu.backend.engine import TpuBackend

    out = tmp_path / family
    model, hf_tok = _train_tiny_family(family, out)

    cfg, params = load_hf_checkpoint(str(out), dtype=jnp.float32)
    be = TpuBackend(
        model_config=cfg, params=params, tokenizer=f"hf:{out}",
        batch_size=1, max_new_tokens=24,
    )

    prompt = "Quốc hội đã thông qua nghị quyết"
    enc = hf_tok(prompt, return_tensors="pt", add_special_tokens=False)
    input_ids = torch.cat(
        [torch.tensor([[hf_tok.bos_token_id]]), enc.input_ids], dim=1
    )
    with torch.no_grad():
        hf_out = model.generate(
            input_ids, max_new_tokens=24, do_sample=False,
            pad_token_id=hf_tok.pad_token_id,
        )
    hf_text = hf_tok.decode(
        hf_out[0, input_ids.shape[1]:], skip_special_tokens=True
    ).strip()

    ours = be.generate([prompt], max_new_tokens=24)[0]
    assert ours == hf_text, (family, ours, hf_text)
