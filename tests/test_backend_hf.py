"""HFBackend tests with a tiny random-init torch Llama built from config —
no hub access needed (zero-egress host). Capability match for the reference's
runners/run_summarization.py:17-62 (SURVEY.md §2 C8)."""
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from vnsum_tpu.backend.base import get_backend
from vnsum_tpu.backend.hf import HFBackend
from vnsum_tpu.core.config import GenerationConfig


def tiny_torch_llama():
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=300,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
    )
    torch.manual_seed(0)
    return LlamaForCausalLM(cfg)


class ByteTokenizerHF:
    """Minimal HF-tokenizer-shaped wrapper over raw bytes so the test needs
    no tokenizer files on disk."""

    pad_token_id = 0
    eos_token_id = 1
    pad_token = "<pad>"
    eos_token = "<eos>"
    chat_template = None

    def __call__(self, texts, return_tensors=None, padding=None,
                 truncation=None, max_length=None, padding_side=None):
        ids = [[b % 300 for b in t.encode()][: max_length or 64] for t in texts]
        width = max(len(x) for x in ids)
        input_ids = [[0] * (width - len(x)) + x for x in ids]  # left pad
        mask = [[0] * (width - len(x)) + [1] * len(x) for x in ids]
        import torch as _t

        class Batch(dict):
            def to(self, device):
                return self

        return Batch(
            input_ids=_t.tensor(input_ids), attention_mask=_t.tensor(mask)
        )

    def batch_decode(self, ids, skip_special_tokens=True):
        out = []
        for row in ids.tolist():
            out.append(
                bytes(t for t in row if t > 1 and t < 256).decode(errors="ignore")
            )
        return out

    def encode(self, text):
        return [b % 300 for b in text.encode()]

    def decode(self, ids, skip_special_tokens=True):
        return bytes(t for t in ids if 1 < t < 256).decode(errors="ignore")


class ChatTokenizerHF(ByteTokenizerHF):
    """Adds a chat template whose suffix must survive truncation."""

    chat_template = "stub"  # truthy: HFBackend renders via apply_chat_template

    def apply_chat_template(self, messages, tokenize=False,
                            add_generation_prompt=True, enable_thinking=False):
        return f"<U>{messages[0]['content']}<A>"


@pytest.fixture(scope="module")
def backend():
    return HFBackend(
        "tiny-test",
        model=tiny_torch_llama(),
        tokenizer=ByteTokenizerHF(),
        max_context=128,
        max_new_tokens=8,
    )


def test_generate_batch_shapes(backend):
    out = backend.generate(["xin chào", "tóm tắt văn bản này dài hơn"])
    assert len(out) == 2
    assert all(isinstance(t, str) for t in out)


def test_greedy_is_deterministic(backend):
    a = backend.generate(["một văn bản"])
    b = backend.generate(["một văn bản"])
    assert a == b


def test_empty_prompt_list(backend):
    assert backend.generate([]) == []


def test_count_tokens(backend):
    assert backend.count_tokens("abc") == 3


def test_factory_dispatch():
    be = get_backend(
        "hf",
        model_name_or_path="tiny-test",
        model=tiny_torch_llama(),
        tokenizer=ByteTokenizerHF(),
        max_context=64,
        max_new_tokens=4,
    )
    assert be.name == "hf"
    assert len(be.generate(["a"])) == 1


def test_sampling_config_accepted(backend):
    cfg = GenerationConfig(temperature=0.8, top_k=5, top_p=0.9)
    out = backend.generate(["văn bản"], max_new_tokens=4, config=cfg)
    assert len(out) == 1


def test_max_new_must_fit_context(backend):
    with pytest.raises(ValueError, match="max_context"):
        backend.generate(["x"], max_new_tokens=1024)


def test_long_prompt_truncated_before_template():
    """The chat template's generation suffix must survive truncation of long
    documents — the raw prompt is clipped first, then templated."""
    tok = ChatTokenizerHF()
    rendered = {}

    class SpyTok(ChatTokenizerHF):
        def __call__(self, texts, **kw):
            rendered["texts"] = texts
            return super().__call__(texts, **kw)

    be = HFBackend(
        "tiny-test", model=tiny_torch_llama(), tokenizer=SpyTok(),
        max_context=64, max_new_tokens=8,
    )
    be.generate(["văn bản rất dài " * 50])
    final = rendered["texts"][0]
    assert final.startswith("<U>") and final.endswith("<A>")
    # fits the input budget with the template suffix intact
    assert len(tok.encode(final)) <= 64 - 8
