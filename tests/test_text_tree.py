import json

from vnsum_tpu.text import (
    DocumentTree,
    collect_nodes_at_depth,
    extract_descendant_paragraph_text,
    replace_node_with_paragraph,
    tree_depth,
)


def make_tree():
    return {
        "type": "Document",
        "text": "Tài liệu",
        "children": [
            {
                "type": "Header",
                "text": "Chương 1",
                "children": [
                    {"type": "Paragraph", "text": "đoạn 1a"},
                    {"type": "Paragraph", "text": "đoạn 1b"},
                ],
            },
            {
                "type": "Header",
                "text": "Chương 2",
                "children": [{"type": "Paragraph", "text": "đoạn 2a"}],
            },
        ],
    }


def test_depth():
    assert tree_depth(make_tree()) == 2
    assert tree_depth({"type": "Paragraph", "text": "x"}) == 0


def test_collect_skips_paragraphs():
    t = make_tree()
    nodes = collect_nodes_at_depth(t, 1)
    assert [n["text"] for n in nodes] == ["Chương 1", "Chương 2"]
    assert collect_nodes_at_depth(t, 2) == []  # depth-2 nodes are Paragraphs


def test_extract_paragraph_text_order():
    assert (
        extract_descendant_paragraph_text(make_tree())
        == "đoạn 1a\n\nđoạn 1b\n\nđoạn 2a"
    )


def test_replace_in_place():
    t = make_tree()
    node = t["children"][0]
    replace_node_with_paragraph(node, "tóm tắt chương 1")
    assert node == {"type": "Paragraph", "text": "tóm tắt chương 1"}
    assert t["children"][0] is node


def test_document_tree_load_and_deepcopy(tmp_path):
    p = tmp_path / "tree.json"
    p.write_text(json.dumps({"doc1.txt": make_tree()}), encoding="utf-8")
    dt = DocumentTree.load(p)
    assert "doc1.txt" in dt and len(dt) == 1
    a = dt.get("doc1.txt")
    replace_node_with_paragraph(a, "mutated")
    b = dt.get("doc1.txt")
    assert b["type"] == "Document"  # original untouched
    assert dt.get("missing.txt") is None
