"""Skeleton-of-Thought strategy (arXiv 2307.15337).

SoT decodes an answer in two stages: a short OUTLINE call produces a
numbered skeleton of the answer, then every skeleton point is expanded in
parallel and the expansions are stitched back in point order. For long-doc
summarization the shape maps cleanly onto the serving stack's structured
jobs: the outline is one short request, the expansions are a gang-admitted
fan-out (one prompt per point, all sharing the SKELETON_EXPAND template
header as their prefix-cache hint), and the stitch is a pure ordered join —
no final LLM call, so end-to-end latency is outline + ONE expansion round
instead of a serial chain.

The document is truncated to the model context first (same contract as
TruncatedStrategy: SoT trades the map-reduce strategies' full-document
coverage for intra-request parallelism on what fits).
"""
from __future__ import annotations

import re

from ..backend.base import Backend
from ..text.tokenizer import Tokenizer, get_tokenizer
from .base import StrategyResult, _BatchCounter, register_strategy
from .prompts import SKELETON_EXPAND, SKELETON_OUTLINE, template_header

# "1. điểm", "2) điểm", with leading whitespace tolerated
_POINT_RE = re.compile(r"^\s*\d+[.)]\s*(.+?)\s*$")


@register_strategy
class SkeletonStrategy:
    name = "skeleton"

    def __init__(
        self,
        backend: Backend,
        tokenizer: Tokenizer | str = "byte",
        max_context: int = 16384,
        max_new_tokens: int = 1024,
        max_points: int = 8,
    ) -> None:
        self.backend = backend
        self.tok = get_tokenizer(tokenizer) if isinstance(tokenizer, str) else tokenizer
        self.max_context = max_context
        self.max_new_tokens = max_new_tokens
        # the outline prompt asks for 3-8 points; the parser enforces the
        # ceiling so a rambling outline can't fan out unboundedly
        self.max_points = max_points

    @classmethod
    def from_config(cls, backend: Backend, config, **kw):
        tok = kw.pop("tokenizer", config.tokenizer)
        return cls(
            backend, tokenizer=tok, max_context=config.max_context,
            max_new_tokens=config.max_new_tokens, **kw,
        )

    def _truncate(self, text: str) -> str:
        limit = self.max_context - self.max_new_tokens
        ids = self.tok.encode(text)
        if len(ids) > limit:
            text = self.tok.decode(ids[:limit])
        return text

    def _parse_points(self, outline: str) -> list[str]:
        """Numbered lines of the skeleton, in order. A model that ignored
        the numbering contract degrades to a single point (the whole
        outline text) — one expansion, never a lost document."""
        points = [
            m.group(1)
            for line in outline.splitlines()
            if (m := _POINT_RE.match(line))
        ]
        if not points:
            stripped = outline.strip()
            points = [stripped] if stripped else ["Tóm tắt nội dung chính."]
        return points[: self.max_points]

    def summarize_batch(
        self, docs: list[str], *, backend: Backend | None = None
    ) -> list[StrategyResult]:
        be = backend or self.backend
        if callable(getattr(be, "submit_round", None)) and callable(
            getattr(be, "harvest", None)
        ):
            return self._summarize_batch_streaming(docs, be)
        gen = _BatchCounter(be, self.max_new_tokens)
        truncated = [self._truncate(d) for d in docs]

        outlines = gen(
            [SKELETON_OUTLINE.format(content=t) for t in truncated],
            owners=list(range(len(docs))),
            references=truncated,
            cache_hints=[template_header(SKELETON_OUTLINE)] * len(docs),
        )
        points_per = [self._parse_points(o) for o in outlines]

        # expand: every point of every document in ONE batch; the document
        # rides along as the speculation reference (expansions are largely
        # extractive) and the shared expand header is the cache hint
        flat = [
            (di, SKELETON_EXPAND.format(point=p, content=truncated[di]))
            for di, points in enumerate(points_per)
            for p in points
        ]
        outs = gen(
            [p for _, p in flat],
            owners=[di for di, _ in flat],
            references=[truncated[di] for di, _ in flat],
            cache_hints=[template_header(SKELETON_EXPAND)] * len(flat),
        )
        per_doc: list[list[str]] = [[] for _ in docs]
        for (di, _), out in zip(flat, outs):
            per_doc[di].append(out)

        return [
            StrategyResult(
                summary="\n\n".join(per_doc[di]),
                num_chunks=len(points_per[di]),
                llm_calls=gen.calls_by_owner.get(di, 0),
                rounds=2,
                meta={"points": len(points_per[di])},
            )
            for di in range(len(docs))
        ]

    def _summarize_batch_streaming(
        self, docs: list[str], be: Backend
    ) -> list[StrategyResult]:
        """Streaming SoT over a submit_round/harvest backend: a document's
        expansion fan-out launches the moment ITS outline lands,
        overlapping other documents' still-running outlines, and the stitch
        is an ordered join as expansions complete. An EXPANSION failing
        typed POISON is dropped from the stitch (the gang is marked partial
        so the parent aggregate reports a degraded summary); an outline
        failure still fails the call — there is no skeleton to degrade to."""
        from concurrent.futures import FIRST_COMPLETED, wait

        truncated = [self._truncate(d) for d in docs]
        results = [StrategyResult(summary="") for _ in docs]
        calls = [0] * len(docs)
        pending: dict = {}  # future -> ("outline"|"expand", di, pi)
        expansions: list[list[str | None]] = [[] for _ in docs]
        expands_left = [0] * len(docs)
        points_per: list[list[str]] = [[] for _ in docs]

        futs = be.submit_round(
            [SKELETON_OUTLINE.format(content=t) for t in truncated],
            phase="outline",
            max_new_tokens=self.max_new_tokens,
            references=truncated,
            cache_hints=[template_header(SKELETON_OUTLINE)] * len(docs),
        )
        for di, fut in enumerate(futs):
            pending[fut] = ("outline", di, 0)
            calls[di] += 1

        while pending:
            done, _ = wait(list(pending), return_when=FIRST_COMPLETED)
            for fut in done:
                kind, di, pi = pending.pop(fut)
                out = be.harvest(fut, tolerate_poison=(kind == "expand"))
                if kind == "outline":
                    points = self._parse_points(out)
                    points_per[di] = points
                    expansions[di] = [None] * len(points)
                    expands_left[di] = len(points)
                    efuts = be.submit_round(
                        [
                            SKELETON_EXPAND.format(
                                point=p, content=truncated[di])
                            for p in points
                        ],
                        phase="expand",
                        max_new_tokens=self.max_new_tokens,
                        references=[truncated[di]] * len(points),
                        cache_hints=[template_header(SKELETON_EXPAND)]
                        * len(points),
                    )
                    for epi, efut in enumerate(efuts):
                        pending[efut] = ("expand", di, epi)
                        calls[di] += 1
                    continue
                if out is None:
                    results[di].meta["dropped_points"] = (
                        results[di].meta.get("dropped_points", 0) + 1
                    )
                else:
                    expansions[di][pi] = out
                expands_left[di] -= 1
                if expands_left[di] == 0:
                    results[di].summary = "\n\n".join(
                        e for e in expansions[di] if e is not None
                    )

        for di, r in enumerate(results):
            r.num_chunks = len(points_per[di])
            r.llm_calls = calls[di]
            r.rounds = 2
            r.meta["points"] = len(points_per[di])
        return results

    def summarize(self, doc: str, *, backend: Backend | None = None) -> StrategyResult:
        return self.summarize_batch([doc], backend=backend)[0]
