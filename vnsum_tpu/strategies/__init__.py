from .base import Strategy, StrategyResult, get_strategy, split_by_token_budget
from .critique import MapReduceCritiqueStrategy
from .hierarchical import HierarchicalStrategy
from .iterative import IterativeStrategy
from .mapreduce import MapReduceStrategy
from .skeleton import SkeletonStrategy
from .truncated import TruncatedStrategy

__all__ = [
    "Strategy",
    "StrategyResult",
    "get_strategy",
    "split_by_token_budget",
    "MapReduceStrategy",
    "MapReduceCritiqueStrategy",
    "IterativeStrategy",
    "TruncatedStrategy",
    "HierarchicalStrategy",
    "SkeletonStrategy",
]
