"""Vietnamese prompt templates, verbatim from the reference (SURVEY.md §7.4:
the prompts ARE the product — preserved exactly, cited per template).

Templates are plain ``str.format`` strings; no prompt-framework layer.

:func:`template_header` extracts the literal text before a template's first
placeholder — the cross-request cacheable prefix every strategy passes as
its ``cache_hint`` (vnsum_tpu.cache): all map prompts of all documents share
the header byte-for-byte, so one prefilled header serves the whole fan-out.
Prefix-stability of the shipped headers under tokenization is pinned by
tests/test_text_tokenizer.py (prefix caching is unsound without it).
"""


def template_header(template: str) -> str:
    """The literal prefix of ``template`` before its first ``{placeholder}``
    — by construction a string prefix of every prompt formatted from it."""
    i = template.find("{")
    return template[:i] if i >= 0 else template

# map prompt — runners/run_summarization_ollama_mapreduce.py:80-85
MAPREDUCE_MAP = """Bạn là một chuyên gia tóm tắt nội dung.
Vui lòng viết một bản tóm tắt chi tiết cho đoạn văn bản sau bằng **tiếng Việt**.

{content}

Lưu ý: Không sử dụng dấu đầu dòng, hãy viết bằng câu đầy đủ và theo đoạn văn."""

# reduce prompt — runners/run_summarization_ollama_mapreduce.py:88-96
MAPREDUCE_REDUCE = """
Sau đây là một tập hợp các bản tóm tắt:
{docs}

Hãy tổng hợp và chắt lọc chúng thành một bản tóm tắt cuối cùng, toàn diện về các chủ đề chính bằng tiếng Việt.
Không sử dụng dấu đầu dòng, hãy viết bằng câu đầy đủ và theo đoạn văn.
"""

# critique-variant map prompt — runners/..._critique.py:118-131
CRITIQUE_MAP = """Hãy tóm tắt những thông tin quan trọng từ đoạn văn bản sau bằng tiếng Việt.
        Lưu ý bao gồm đầy đủ các chi tiết quan trọng như sự kiện hay nhân vật, các chủ đề chính. Không bỏ sót thông tin quan trọng. Nên tóm tắt theo từng chương nếu có.

Chỉ viết nội dung tóm tắt. Không giải thích, không xin lỗi, không nói về quy trình.

Văn bản:
<content>
{content}
</content>

Tóm tắt:"""

# collapse/reduce prompt — runners/..._critique.py:134-149
CRITIQUE_REDUCE = """
Hãy kết hợp các bản tóm tắt được đánh dấu theo phần sau thành MỘT bản tóm tắt duy nhất bằng tiếng Việt.

Các bản tóm tắt theo phần:
<summary>
{docs}
</summary>

Yêu cầu tổng hợp: Tổng hợp các thông tin từ TẤT CẢ các phần theo trình tự logic. Tạo ra một câu chuyện/tóm tắt liền mạch, kết nối các phần với nhau. Bao gồm đầy đủ các chi tiết quan trọng như sự kiện, nhân vật, chủ đề chính. Không bỏ sót thông tin quan trọng từ bất kỳ phần nào. Giữ nguyên trình tự thời gian/logic nếu có.

Chỉ viết nội dung tóm tắt tổng hợp cuối cùng. Không đề cập đến các tag phần, không giải thích quy trình.

Tóm tắt tổng hợp:
"""

# critique prompt — runners/..._critique.py:152-170
CRITIQUE_CRITIQUE = """
So sánh bản tóm tắt với nội dung tham khảo. Có thông tin quan trọng nào bị thiếu hoặc sai không?
Các thông tin quan trọng bao gồm sự kiện hay nhân vật,các chủ đề chính. Không bỏ sót thông tin quan trọng.

Bản tóm tắt:
<summary>
{summary}
</summary>

Nội dung tham khảo:
<reference_content>
{original_chunks}
</reference_content>

Nếu không có vấn đề thì trả lời: "Không có vấn đề"
Nếu có vấn đề thì chỉ ra vấn đề cụ thể thật chi tiết và rõ ràng. không cần giải thích, không cần xin lỗi, không cần nói về quy trình.
Ví dụ: "Thiếu thông tin về sự kiện X", "Thiếu thông tin về nhân vật Y"
"""

# refine prompt — runners/..._critique.py:173-196
CRITIQUE_REFINE = """
Nhiệm vụ: Viết lại bản tóm tắt để khắc phục các vấn đề đã chỉ ra. Sử dụng nội dung tham khảo để bổ sung thông tin bị thiếu.

Bản tóm tắt hiện tại (cần sửa):
<summary>
{current_summary}
</summary>

Vấn đề cần khắc phục:
<critique>
{critique}
</critique>

Nội dung tham khảo (để bổ sung thông tin):
<reference_content>
{reference_content}
</reference_content>

Yêu cầu:
- Khắc phục TẤT CẢ các vấn đề đã chỉ ra trong phần critique
- Bổ sung thông tin bị thiếu từ nội dung tham khảo
- Giữ nguyên thông tin đúng đã có trong bản tóm tắt cũ
- Đảm bảo tóm tắt mới có đầy đủ thông tin và chính xác

Chỉ viết bản tóm tắt đã sửa. Không giải thích, không xin lỗi, không nói về quy trình.

Bản tóm tắt đã sửa:
"""

# accept-strings checked on the critique output — runners/..._critique.py:254
CRITIQUE_ACCEPT_STRINGS = ("không có vấn đề", "no issues")

# initial summary prompt — runners/..._iterative.py:106-119
ITERATIVE_INITIAL = """Bạn là một chuyên gia phân tích và tóm tắt thông tin.
Nhiệm vụ của bạn là đọc phần đầu tiên của một tài liệu dài và tạo ra một bản tóm tắt **nền tảng**.

Bản tóm tắt này phải nắm bắt được những ý chính, bối cảnh và các thông tin quan trọng nhất làm cơ sở cho việc xây dựng một bản tóm tắt toàn diện sau này. Hãy tập trung vào việc xác định các yếu tố cốt lõi (Ai, Cái gì, Khi nào, Ở đâu, Tại sao) được giới thiệu trong đoạn văn này.

Văn bản cần tóm tắt:
---
{context}
---

Bản tóm tắt nền tảng:
"""

# refine prompt — runners/..._iterative.py:121-145
ITERATIVE_REFINE = """
Bạn là một biên tập viên xuất sắc, chuyên tổng hợp và tinh chỉnh thông tin từ nhiều nguồn.
Nhiệm vụ của bạn là cập nhật và mở rộng một bản tóm tắt đã có với những thông tin mới.

Bản tóm tắt hiện có (tóm tắt các phần trước):
---
{existing_answer}
---

Thông tin mới cần tích hợp (từ phần văn bản tiếp theo):
---
{context}
---

Dựa vào thông tin mới, hãy **viết lại hoàn toàn** bản tóm tắt để tạo ra một phiên bản mới, mạch lạc và toàn diện hơn.

**Yêu cầu quan trọng:**
1.  **Tích hợp, không nối thêm:** Đừng chỉ viết thêm thông tin mới vào cuối. Hãy khéo léo lồng ghép các chi tiết mới vào bản tóm tắt hiện có, sắp xếp lại các câu và ý tưởng để tạo ra một dòng chảy tự nhiên.
2.  **Bảo toàn thông tin cốt lõi:** Đảm bảo rằng những điểm chính và bối cảnh quan trọng từ "Bản tóm tắt hiện có" không bị mất đi hoặc giảm nhẹ tầm quan trọng, trừ khi thông tin mới làm rõ hoặc thay đổi chúng một cách trực tiếp.
3.  **Tổng hợp và cân bằng:** Bản tóm tắt cuối cùng phải phản ánh một cách cân bằng toàn bộ nội dung đã biết cho đến nay, không thiên vị cho thông tin mới nhất.

Hãy viết bản tóm tắt tổng hợp cuối cùng bằng câu văn hoàn chỉnh, liền mạch thành một đoạn văn bằng tiếng Việt.

Bản tóm tắt tổng hợp cuối cùng:
"""

# single-shot truncated prompt (f-string incl. indentation) —
# runners/run_summarization_ollama.py:16-21
TRUNCATED = """
    Bạn là một chuyên gia tóm tắt nội dung.
    Vui lòng viết một bản tóm tắt chi tiết cho tài liệu sau bằng **tiếng Việt**.
    \n\n{text}.
    \n\nLưu ý: Không sử dụng dấu đầu dòng, hãy viết bằng câu đầy đủ và theo đoạn văn.
    """

# hierarchical map prompt — runners/..._hierarchical.py:83-103
HIERARCHICAL_MAP = (
    "Bạn là một chuyên gia tóm tắt nội dung. Hãy tóm tắt những thông tin quan trọng từ đoạn văn bản sau bằng tiếng Việt.\n"
    "Lưu ý bao gồm đầy đủ các chi tiết quan trọng như sự kiện hay nhân vật, các chủ đề chính. Không bỏ sót thông tin quan trọng. Nên tóm tắt theo từng chương nếu có."
    "<content>\n"
    "{content}\n\n"
    "</content>\n\n"
    "Chỉ viết nội dung tóm tắt. Không giải thích, không xin lỗi, không nói về quy trình.\n"
    "Tóm tắt:"
)

# hierarchical reduce prompt — runners/..._hierarchical.py:105-115
HIERARCHICAL_REDUCE = (
    "Sau đây là một tập hợp các bản tóm tắt:\n<docs>\n{docs}\n</docs>\n\n"
    "Hãy tổng hợp và chắt lọc chúng thành một bản tóm tắt cuối cùng bằng **tiếng Việt**\n"
    "Lưu ý bao gồm đầy đủ các chi tiết quan trọng như sự kiện hay nhân vật, các chủ đề chính. Không bỏ sót thông tin quan trọng."
    "Chỉ viết nội dung tóm tắt. Không giải thích, không xin lỗi, không nói về quy trình."
    "Không sử dụng dấu đầu dòng; hãy viết thành các câu hoàn chỉnh theo đoạn văn."
    "Tóm tắt mới:"
)

# Skeleton-of-Thought (arXiv 2307.15337) — no reference-runner counterpart:
# the outline/expand pair is new here, written in the same register as the
# reference prompts (full-sentence Vietnamese, no bullets in the output, no
# meta-talk). The outline asks for a NUMBERED skeleton because the strategy
# parses "1. ..." lines to build the expansion fan-out.
SKELETON_OUTLINE = """Bạn là một chuyên gia phân tích văn bản.
Hãy đọc tài liệu sau và lập một dàn ý gồm 3 đến 8 ý chính bao quát nội dung, mỗi ý trên một dòng theo định dạng "1. ...", "2. ...".
Mỗi ý chỉ viết ngắn gọn trong một câu. Chỉ viết dàn ý, không giải thích, không mở đầu.

Tài liệu:
{content}

Dàn ý:"""

SKELETON_EXPAND = """Bạn là một chuyên gia tóm tắt nội dung. Dựa trên tài liệu dưới đây, hãy viết một đoạn văn ngắn bằng **tiếng Việt** triển khai ý sau của bản tóm tắt.
Chỉ viết nội dung của đoạn văn, bằng câu đầy đủ, không sử dụng dấu đầu dòng, không giải thích, không nói về quy trình.

Ý cần triển khai:
{point}

Tài liệu:
{content}

Đoạn văn:"""

# final grammar/flow polish — runners/..._hierarchical.py:296-313
HIERARCHICAL_POLISH = (
    "Bạn là một biên tập viên chuyên nghiệp.\n"
    "Dưới đây là bản tóm tắt của một tài liệu:\n"
    "<summary>\n"
    "{summary}"
    "</summary>\n"
    "Hãy rà soát để sửa lỗi ngữ pháp và đảm bảo văn phong mạch lạc, rõ ràng. Không bỏ sót thông tin quan trọng.\n"
    "không cần giải thích, không cần xin lỗi, không cần nói về quy trình.\n"
    "Tóm tắt mới:\n"
)
