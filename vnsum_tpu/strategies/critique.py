"""Map-reduce with integrated self-critique.

Semantics follow runners/run_summarization_ollama_mapreduce_critique.py:112-374:
every collapse group goes reduce → critique → (if issues) refine, with
[PHẦN i] section tags and the literal accept-string check; original chunks are
the critique reference, aligned positionally by cursor; the final reduce uses
the intermediate summaries as critique context, recursively collapsing them
first when they exceed token_max // 2.

The reduce/critique/refine triple runs as three backend batches per round,
shared across every group of every document in the batch.
"""
from __future__ import annotations

from typing import Callable

from ..backend.base import Backend
from ..text.splitter import RecursiveTokenSplitter
from ..text.tokenizer import whitespace_token_count
from .base import StrategyResult, _BatchCounter, register_strategy, split_by_token_budget
from .prompts import (
    CRITIQUE_ACCEPT_STRINGS,
    CRITIQUE_CRITIQUE,
    CRITIQUE_MAP,
    CRITIQUE_REDUCE,
    CRITIQUE_REFINE,
    template_header,
)

_REF_JOIN = "\n\n---\n\n"


def _tag_sections(texts: list[str]) -> str:
    """[PHẦN i] tagging (ref :228-233)."""
    return "\n\n".join(f"[PHẦN {i + 1}]\n{t}" for i, t in enumerate(texts))


@register_strategy
class MapReduceCritiqueStrategy:
    name = "mapreduce_critique"

    def __init__(
        self,
        backend: Backend,
        splitter: RecursiveTokenSplitter,
        token_max: int = 10000,
        max_critique_iterations: int = 2,
        max_new_tokens: int | None = None,
        max_collapse_rounds: int = 15,
        count: Callable[[str], int] = whitespace_token_count,
    ) -> None:
        self.backend = backend
        self.splitter = splitter
        self.token_max = token_max
        self.max_critique_iterations = max_critique_iterations
        self.max_new_tokens = max_new_tokens
        # backstop like the reference's recursion_limit=15 (:438)
        self.max_collapse_rounds = max_collapse_rounds
        self.count = count

    @classmethod
    def from_config(cls, backend: Backend, config, **kw):
        splitter = RecursiveTokenSplitter(
            config.chunk_size, config.chunk_overlap,
            length_function=backend.count_tokens,
            # duck-typed backends without the batch method keep working via
            # the splitter's scalar fallback
            length_batch_function=getattr(
                backend, "count_tokens_batch", None
            ),
        )
        return cls(
            backend, splitter, token_max=config.token_max,
            max_critique_iterations=config.max_critique_iterations,
            max_new_tokens=config.max_new_tokens, **kw,
        )

    # one batched reduce→critique→refine pass over (texts, refs, iteration);
    # ``owners`` maps each item to its document for per-doc call accounting
    def _reduce_with_critique_batch(
        self,
        gen: _BatchCounter,
        items: list[tuple[list[str], list[str], int]],
        owners: list[int],
    ) -> list[str]:
        summaries = gen(
            [CRITIQUE_REDUCE.format(docs=_tag_sections(texts)) for texts, _, _ in items],
            owners=owners,
            cache_hints=[template_header(CRITIQUE_REDUCE)] * len(items),
        )
        need = [
            i for i, (_, _, it) in enumerate(items)
            if it < self.max_critique_iterations
        ]
        critiques = gen(
            [
                CRITIQUE_CRITIQUE.format(
                    summary=summaries[i],
                    original_chunks=_REF_JOIN.join(items[i][1]),
                )
                for i in need
            ],
            owners=[owners[i] for i in need],
            cache_hints=[template_header(CRITIQUE_CRITIQUE)] * len(need),
        )
        refine_idx: list[int] = []
        refine_prompts: list[str] = []
        for i, crit in zip(need, critiques):
            low = crit.lower()
            if any(s in low for s in CRITIQUE_ACCEPT_STRINGS):
                continue
            refine_idx.append(i)
            refine_prompts.append(
                CRITIQUE_REFINE.format(
                    current_summary=summaries[i],
                    critique=crit,
                    reference_content=_REF_JOIN.join(items[i][1]),
                )
            )
        refined_outs = gen(
            refine_prompts, owners=[owners[i] for i in refine_idx],
            cache_hints=[template_header(CRITIQUE_REFINE)] * len(refine_idx),
        )
        for i, refined in zip(refine_idx, refined_outs):
            summaries[i] = refined
        return summaries

    def summarize_batch(
        self, docs: list[str], *, backend: Backend | None = None
    ) -> list[StrategyResult]:
        gen = _BatchCounter(backend or self.backend, self.max_new_tokens)

        chunks_per_doc = [self.splitter.split_text(d) or [d] for d in docs]
        results = [
            StrategyResult(summary="", num_chunks=len(c)) for c in chunks_per_doc
        ]

        flat = [
            (di, CRITIQUE_MAP.format(content=c))
            for di, chunks in enumerate(chunks_per_doc)
            for c in chunks
        ]
        outs = gen(
            [p for _, p in flat], owners=[di for di, _ in flat],
            cache_hints=[template_header(CRITIQUE_MAP)] * len(flat),
        )
        collapsed: list[list[str]] = [[] for _ in docs]
        for (di, _), out in zip(flat, outs):
            collapsed[di].append(out)

        crit_iters = [0] * len(docs)

        for _ in range(self.max_collapse_rounds):
            pending = [
                di for di, s in enumerate(collapsed)
                if sum(self.count(x) for x in s) > self.token_max
            ]
            if not pending:
                break
            items: list[tuple[list[str], list[str], int]] = []
            owners: list[int] = []
            group_counts: dict[int, int] = {}
            for di in pending:
                groups = split_by_token_budget(collapsed[di], self.token_max, self.count)
                group_counts[di] = len(groups)
                # positional cursor into the ORIGINAL chunks (ref :279-287)
                cursor = 0
                for g in groups:
                    refs = chunks_per_doc[di][cursor : cursor + len(g)]
                    cursor += len(g)
                    items.append((g, refs or g, crit_iters[di]))
                    owners.append(di)
            outs = self._reduce_with_critique_batch(gen, items, owners)
            for di in pending:
                collapsed[di] = []
            for di, out in zip(owners, outs):
                collapsed[di].append(out)
            for di in pending:
                crit_iters[di] += 1
                results[di].rounds += 1

        # final: build critique context (recursively collapsing intermediates
        # that exceed token_max // 2, ref :305-346), then one last
        # reduce-with-critique per document — each phase batched across docs
        half = self.token_max // 2
        context: list[list[str]] = [list(c) for c in collapsed]
        need_rc = [
            di for di in range(len(docs))
            if sum(self.count(s) for s in collapsed[di]) > half
        ]
        if need_rc:
            items = []
            owners = []
            for di in need_rc:
                for g in split_by_token_budget(collapsed[di], half, self.count):
                    items.append((g, g, crit_iters[di]))
                    owners.append(di)
            outs = self._reduce_with_critique_batch(gen, items, owners)
            for di in need_rc:
                context[di] = []
            for di, out in zip(owners, outs):
                context[di].append(out)

        finals = self._reduce_with_critique_batch(
            gen,
            [(collapsed[di], context[di], crit_iters[di]) for di in range(len(docs))],
            list(range(len(docs))),
        )
        for di, f in enumerate(finals):
            results[di].summary = f
            results[di].llm_calls = gen.calls_by_owner.get(di, 0)
        return results

    def summarize(self, doc: str, *, backend: Backend | None = None) -> StrategyResult:
        return self.summarize_batch([doc], backend=backend)[0]
