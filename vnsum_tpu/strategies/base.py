"""Strategy layer scaffolding.

The reference wraps each approach in a LangGraph StateGraph whose fan-out is
serial in practice (SURVEY.md §1). Here a strategy is a plain driver object:
host-side Python owns the (data-dependent) control flow — collapse-until-fits,
critique accept checks, tree recursion — and every round's LLM calls are
submitted to the backend as ONE batch, across chunks and across documents
(SURVEY.md §7: "parallelism moves from the orchestration layer into XLA").
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Protocol

from ..backend.base import Backend
from ..text.tokenizer import whitespace_token_count


@dataclass
class StrategyResult:
    summary: str
    num_chunks: int = 1
    llm_calls: int = 0
    rounds: int = 0
    meta: dict = field(default_factory=dict)


class Strategy(Protocol):
    """Re-entrancy contract (the serving layer depends on it): a strategy
    instance holds only configuration — every run's mutable state is local
    to the summarize_batch call — so ONE instance may serve concurrent
    calls from many threads. The optional ``backend`` override lets each
    call submit its rounds through a different Backend (vnsum_tpu.serve
    passes a per-request, deadline-bound QueuedBackend into a shared
    strategy instance); token counting stays on the construction-time
    backend, which is host-side and thread-safe."""

    name: str

    def summarize_batch(
        self, docs: list[str], *, backend: Backend | None = None
    ) -> list[StrategyResult]: ...

    def summarize(
        self, doc: str, *, backend: Backend | None = None
    ) -> StrategyResult: ...


class _BatchCounter:
    """Wraps backend.generate to count calls for StrategyResult accounting.

    Although rounds batch prompts across documents, every prompt belongs to
    exactly one document — callers pass ``owners`` (one doc index per prompt)
    so `calls_by_owner` carries TRUE per-document llm_calls, matching what the
    reference's serial loop records (run_full_evaluation_pipeline.py:575-582)."""

    def __init__(self, backend: Backend, max_new_tokens: int | None = None):
        self.backend = backend
        self.max_new_tokens = max_new_tokens
        self.calls_by_owner: dict[int, int] = {}

    def __call__(
        self,
        prompts: list[str],
        owners: list[int],
        references: list[str | None] | None = None,
        cache_hints: list[str | None] | None = None,
    ) -> list[str]:
        """``references`` optionally aligns one source text per prompt —
        the seam reference-guided speculative decoding rides (strategies
        pass the chunk being summarized). ``cache_hints`` aligns one
        expected-to-recur prompt PREFIX per prompt — the prefix KV cache
        seam (strategies pass their template header, prompts.py
        template_header). Backends without either feature ignore them."""
        if not prompts:
            return []
        if len(owners) != len(prompts):
            raise ValueError("owners must tag every prompt")
        if references is not None and len(references) != len(prompts):
            raise ValueError("references must align with prompts")
        if cache_hints is not None and len(cache_hints) != len(prompts):
            raise ValueError("cache_hints must align with prompts")
        for o in owners:
            self.calls_by_owner[o] = self.calls_by_owner.get(o, 0) + 1
        # keep the legacy call shape for backends (and test doubles) that
        # predate the advisory kwargs: pass each only when it carries data
        kw = {}
        if references is not None and any(references):
            kw["references"] = references
        if cache_hints is not None and any(cache_hints):
            kw["cache_hints"] = cache_hints
        return self.backend.generate(
            prompts, max_new_tokens=self.max_new_tokens, **kw
        )


def split_by_token_budget(
    texts: list[str],
    budget: int,
    count: Callable[[str], int] = whitespace_token_count,
) -> list[list[str]]:
    """Greedy grouping: consecutive texts accumulate until adding one would
    exceed ``budget`` (langchain split_list_of_docs semantics used by the
    reference collapse, runners/..._mapreduce.py:130-137). A single oversized
    text forms its own group."""
    groups: list[list[str]] = []
    cur: list[str] = []
    cur_total = 0
    for t in texts:
        n = count(t)
        if cur and cur_total + n > budget:
            groups.append(cur)
            cur, cur_total = [], 0
        cur.append(t)
        cur_total += n
    if cur:
        groups.append(cur)
    return groups


STRATEGY_REGISTRY: dict[str, type] = {}


def register_strategy(cls):
    STRATEGY_REGISTRY[cls.name] = cls
    return cls


def get_strategy(name: str, backend: Backend, config, **kw):
    """Instantiate a strategy from PipelineConfig-style settings."""
    if name not in STRATEGY_REGISTRY:
        raise ValueError(
            f"unknown strategy {name!r}; have {sorted(STRATEGY_REGISTRY)}"
        )
    return STRATEGY_REGISTRY[name].from_config(backend, config, **kw)
