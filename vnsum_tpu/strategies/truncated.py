"""Truncated strategy: cut the document to the model context and summarize in
one shot (runners/run_summarization_ollama.py:8-37 — tokenize, keep the first
max_context − max_new_tokens tokens, decode back, single prompt).
"""
from __future__ import annotations

from ..backend.base import Backend
from ..text.tokenizer import Tokenizer, get_tokenizer
from .base import StrategyResult, _BatchCounter, register_strategy
from .prompts import TRUNCATED, template_header


@register_strategy
class TruncatedStrategy:
    name = "truncated"

    def __init__(
        self,
        backend: Backend,
        tokenizer: Tokenizer | str = "byte",
        max_context: int = 16384,
        max_new_tokens: int = 1024,
    ) -> None:
        self.backend = backend
        self.tok = get_tokenizer(tokenizer) if isinstance(tokenizer, str) else tokenizer
        self.max_context = max_context
        self.max_new_tokens = max_new_tokens

    @classmethod
    def from_config(cls, backend: Backend, config, **kw):
        tok = kw.pop("tokenizer", config.tokenizer)
        return cls(
            backend, tokenizer=tok, max_context=config.max_context,
            max_new_tokens=config.max_new_tokens, **kw,
        )

    def _truncate(self, text: str) -> str:
        limit = self.max_context - self.max_new_tokens
        ids = self.tok.encode(text)
        if len(ids) > limit:
            text = self.tok.decode(ids[:limit])
        return text

    def summarize_batch(
        self, docs: list[str], *, backend: Backend | None = None
    ) -> list[StrategyResult]:
        gen = _BatchCounter(backend or self.backend, self.max_new_tokens)
        truncated = [self._truncate(d) for d in docs]
        prompts = [TRUNCATED.format(text=t) for t in truncated]
        # the truncated document is the speculation reference (vnsum_tpu.spec);
        # the shared template header is the prefix-cache hint
        outs = gen(
            prompts, owners=list(range(len(docs))), references=truncated,
            cache_hints=[template_header(TRUNCATED)] * len(docs),
        )
        return [
            StrategyResult(summary=o, num_chunks=1, llm_calls=1, rounds=1)
            for o in outs
        ]

    def summarize(self, doc: str, *, backend: Backend | None = None) -> StrategyResult:
        return self.summarize_batch([doc], backend=backend)[0]
