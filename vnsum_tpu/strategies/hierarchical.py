"""Hierarchical tree-collapse strategy.

Semantics follow runners/run_summarization_ollama_mapreduce_hierarchical.py:
bottom-up over the document structure tree — for depth target..1, every
non-Paragraph node's descendant paragraph text is map-reduce summarized
(title-prefixed) and the node mutates into a Paragraph leaf (:242-315); then
one final map-reduce over the remaining paragraphs and a grammar/flow polish
pass. Chunk sizes are clamped to 75% of the model context (:178-179).

The reference's per-node mini map-reduce is a sequential loop (:125-154);
here every node at a level maps its chunks in one backend batch, and the
per-node reduces batch as well.
"""
from __future__ import annotations

from ..backend.base import Backend
from ..text.splitter import RecursiveTokenSplitter
from ..text.tree import (
    Node,
    collect_nodes_at_depth,
    extract_descendant_paragraph_text,
    replace_node_with_paragraph,
    tree_depth,
)
from .base import StrategyResult, _BatchCounter, register_strategy
from .prompts import (
    HIERARCHICAL_MAP,
    HIERARCHICAL_POLISH,
    HIERARCHICAL_REDUCE,
    template_header,
)


@register_strategy
class HierarchicalStrategy:
    name = "mapreduce_hierarchical"

    def __init__(
        self,
        backend: Backend,
        chunk_size: int = 12000,
        chunk_overlap: int = 200,
        max_depth: int = 1,
        max_context: int = 16384,
        max_new_tokens: int | None = None,
    ) -> None:
        self.backend = backend
        # 75%-of-context safety clamp (ref :178-179)
        self.chunk_size = min(chunk_size, int(max_context * 0.75))
        self.chunk_overlap = chunk_overlap
        self.max_depth = max_depth
        self.max_new_tokens = max_new_tokens
        self.splitter = RecursiveTokenSplitter(
            self.chunk_size, chunk_overlap,
            length_function=backend.count_tokens,
            # duck-typed backends without the batch method keep working via
            # the splitter's scalar fallback
            length_batch_function=getattr(
                backend, "count_tokens_batch", None
            ),
        )

    @classmethod
    def from_config(cls, backend: Backend, config, **kw):
        return cls(
            backend,
            chunk_size=config.chunk_size,
            chunk_overlap=config.chunk_overlap,
            max_depth=config.max_depth,
            max_context=config.max_context,
            max_new_tokens=config.max_new_tokens,
            **kw,
        )

    def _mapreduce_texts_batch(
        self, gen: _BatchCounter, texts: list[str], owners: list[int]
    ) -> tuple[list[str], list[int]]:
        """Mini map-reduce over several independent texts: map all chunks of
        all texts in one batch, then one reduce per text (single round, like
        the reference's simple graph :125-154). ``owners`` maps each text to
        its tree for per-doc call accounting. Returns (summaries, per-text
        chunk counts).

        When the backend exposes the serving layer's submit_round/harvest
        pair, the map->reduce join is per TEXT instead of a global barrier:
        a node's reduce overlaps its siblings' still-running maps (same
        prompt contents, pure scheduling — the tree mutation between levels
        stays the inherent level barrier)."""
        be = gen.backend
        if callable(getattr(be, "submit_round", None)) and callable(
            getattr(be, "harvest", None)
        ):
            return self._mapreduce_texts_streaming(be, gen, texts, owners)
        chunks_per = [self.splitter.split_text(t) or [t] for t in texts]
        flat = [
            (ti, HIERARCHICAL_MAP.format(content=c))
            for ti, chunks in enumerate(chunks_per)
            for c in chunks
        ]
        outs = gen(
            [p for _, p in flat], owners=[owners[ti] for ti, _ in flat],
            cache_hints=[template_header(HIERARCHICAL_MAP)] * len(flat),
        )
        per_text: list[list[str]] = [[] for _ in texts]
        for (ti, _), out in zip(flat, outs):
            per_text[ti].append(out)
        reduces = gen(
            [HIERARCHICAL_REDUCE.format(docs="\n\n".join(s)) for s in per_text],
            owners=owners,
            cache_hints=[template_header(HIERARCHICAL_REDUCE)] * len(per_text),
        )
        return reduces, [len(c) for c in chunks_per]

    def _mapreduce_texts_streaming(
        self, be, gen: _BatchCounter, texts: list[str], owners: list[int]
    ) -> tuple[list[str], list[int]]:
        """Streaming variant of :meth:`_mapreduce_texts_batch`: each text's
        reduce is submitted the moment its LAST map chunk completes. A map
        chunk failing typed POISON is dropped from its text's reduce
        (harvest marks the gang partial); a reduce failure still fails the
        call."""
        from concurrent.futures import FIRST_COMPLETED, wait

        chunks_per = [self.splitter.split_text(t) or [t] for t in texts]
        per_text: list[list[str | None]] = [
            [None] * len(c) for c in chunks_per
        ]
        maps_left = [len(c) for c in chunks_per]
        reduces: list[str | None] = [None] * len(texts)
        pending: dict = {}  # future -> ("map"|"reduce", ti, ci)

        def count(ti: int) -> None:
            o = owners[ti]
            gen.calls_by_owner[o] = gen.calls_by_owner.get(o, 0) + 1

        futs = be.submit_round(
            [
                HIERARCHICAL_MAP.format(content=c)
                for chunks in chunks_per
                for c in chunks
            ],
            phase="map",
            max_new_tokens=self.max_new_tokens,
            cache_hints=[template_header(HIERARCHICAL_MAP)]
            * sum(len(c) for c in chunks_per),
        )
        tags = [
            ("map", ti, ci)
            for ti, chunks in enumerate(chunks_per)
            for ci in range(len(chunks))
        ]
        for tag, fut in zip(tags, futs):
            pending[fut] = tag
            count(tag[1])

        while pending:
            done, _ = wait(list(pending), return_when=FIRST_COMPLETED)
            for fut in done:
                kind, ti, ci = pending.pop(fut)
                out = be.harvest(fut, tolerate_poison=(kind == "map"))
                if kind == "reduce":
                    reduces[ti] = out
                    continue
                per_text[ti][ci] = out
                maps_left[ti] -= 1
                if maps_left[ti] == 0:
                    survivors = [s for s in per_text[ti] if s is not None]
                    (rfut,) = be.submit_round(
                        [HIERARCHICAL_REDUCE.format(
                            docs="\n\n".join(survivors))],
                        phase="reduce",
                        max_new_tokens=self.max_new_tokens,
                        cache_hints=[template_header(HIERARCHICAL_REDUCE)],
                    )
                    pending[rfut] = ("reduce", ti, 0)
                    count(ti)

        return reduces, [len(c) for c in chunks_per]

    def summarize_tree(
        self, root: Node, *, backend: Backend | None = None
    ) -> StrategyResult:
        return self.summarize_tree_batch([root], backend=backend)[0]

    def summarize_tree_batch(
        self, roots: list[Node], *, backend: Backend | None = None
    ) -> list[StrategyResult]:
        gen = _BatchCounter(backend or self.backend, self.max_new_tokens)
        results = [StrategyResult(summary="") for _ in roots]
        targets = [min(self.max_depth, tree_depth(r)) for r in roots]
        total_chunks = [0] * len(roots)

        # lockstep bottom-up collapse: one backend round per depth level,
        # shared across trees (trees deeper than others just join later)
        for depth in range(max(targets, default=0), 0, -1):
            nodes: list[Node] = []
            owners: list[int] = []
            texts: list[str] = []
            for ri, root in enumerate(roots):
                if depth > targets[ri]:
                    continue
                for node in collect_nodes_at_depth(root, depth):
                    body = extract_descendant_paragraph_text(node)
                    if not body.strip():
                        continue
                    title = node.get("text", "") or ""
                    nodes.append(node)
                    owners.append(ri)
                    texts.append(f"{title}:\n{body}" if title else body)
            if not texts:
                continue
            summaries, chunk_counts = self._mapreduce_texts_batch(gen, texts, owners)
            for ri, node, summary, n in zip(owners, nodes, summaries, chunk_counts):
                title = node.get("text", "") or ""
                replace_node_with_paragraph(
                    node, f"{title}:\n{summary}" if title else summary
                )
                total_chunks[ri] += n
            for ri in set(owners):
                results[ri].rounds += 1

        final_texts = [extract_descendant_paragraph_text(r) for r in roots]
        all_ris = list(range(len(roots)))
        finals, final_counts = self._mapreduce_texts_batch(gen, final_texts, all_ris)
        polished = gen(
            [HIERARCHICAL_POLISH.format(summary=f) for f in finals], owners=all_ris,
            cache_hints=[template_header(HIERARCHICAL_POLISH)] * len(finals),
        )
        for ri, p in enumerate(polished):
            results[ri].summary = p
            results[ri].num_chunks = max(total_chunks[ri] + final_counts[ri], 1)
            results[ri].llm_calls = gen.calls_by_owner.get(ri, 0)
        return results

    # plain-text entry: treat the whole document as a single Document node
    def summarize_batch(
        self, docs: list[str], *, backend: Backend | None = None
    ) -> list[StrategyResult]:
        roots = [
            {
                "type": "Document",
                "text": "",
                "children": [{"type": "Paragraph", "text": d}],
            }
            for d in docs
        ]
        return self.summarize_tree_batch(roots, backend=backend)

    def summarize(self, doc: str, *, backend: Backend | None = None) -> StrategyResult:
        return self.summarize_batch([doc], backend=backend)[0]
