"""Map-reduce strategy.

Semantics follow runners/run_summarization_ollama_mapreduce.py:75-201: split →
map each chunk → collapse groups while the whitespace-token total exceeds
token_max → one final reduce. The LangGraph Send fan-out (serial in practice,
:51-52) becomes true batching: the map step for a *batch of documents* is one
backend.generate call, and each collapse round batches every group of every
document still collapsing.
"""
from __future__ import annotations

from typing import Callable

from ..backend.base import Backend
from ..text.splitter import RecursiveTokenSplitter
from ..text.tokenizer import whitespace_token_count
from .base import StrategyResult, _BatchCounter, register_strategy, split_by_token_budget
from .prompts import MAPREDUCE_MAP, MAPREDUCE_REDUCE, template_header


@register_strategy
class MapReduceStrategy:
    name = "mapreduce"

    def __init__(
        self,
        backend: Backend,
        splitter: RecursiveTokenSplitter,
        token_max: int = 10000,
        max_new_tokens: int | None = None,
        max_collapse_rounds: int = 10,
        count: Callable[[str], int] = whitespace_token_count,
        map_prompt: str = MAPREDUCE_MAP,
        reduce_prompt: str = MAPREDUCE_REDUCE,
    ) -> None:
        self.backend = backend
        self.splitter = splitter
        self.token_max = token_max
        self.max_new_tokens = max_new_tokens
        # collapse backstop, like the reference's recursion_limit=10 (:196)
        self.max_collapse_rounds = max_collapse_rounds
        self.count = count
        self.map_prompt = map_prompt
        self.reduce_prompt = reduce_prompt

    @classmethod
    def from_config(cls, backend: Backend, config, **kw):
        splitter = RecursiveTokenSplitter(
            config.chunk_size, config.chunk_overlap,
            length_function=backend.count_tokens,
            # duck-typed backends without the batch method keep working via
            # the splitter's scalar fallback
            length_batch_function=getattr(
                backend, "count_tokens_batch", None
            ),
        )
        return cls(
            backend, splitter, token_max=config.token_max,
            max_new_tokens=config.max_new_tokens, **kw,
        )

    def _reduce_one(self, texts: list[str]) -> str:
        return self.reduce_prompt.format(docs="\n\n".join(texts))

    def summarize_batch(
        self, docs: list[str], *, backend: Backend | None = None
    ) -> list[StrategyResult]:
        gen = _BatchCounter(backend or self.backend, self.max_new_tokens)

        chunks_per_doc = [self.splitter.split_text(d) or [d] for d in docs]
        results = [
            StrategyResult(summary="", num_chunks=len(c)) for c in chunks_per_doc
        ]

        # map: every chunk of every document in one batch. The chunk text
        # rides along as the speculation reference — a map summary is
        # largely extractive, exactly the overlap the reference drafter
        # (vnsum_tpu.spec) turns into accepted tokens — and the shared
        # template header is the cache_hint: every map prompt of every
        # document starts with it, so one prefilled header (vnsum_tpu.cache)
        # serves the whole fan-out
        map_hint = template_header(self.map_prompt)
        flat = [
            (di, self.map_prompt.format(content=c), c)
            for di, chunks in enumerate(chunks_per_doc)
            for c in chunks
        ]
        outs = gen(
            [p for _, p, _ in flat],
            owners=[di for di, _, _ in flat],
            references=[c for _, _, c in flat],
            cache_hints=[map_hint] * len(flat),
        )
        summaries: list[list[str]] = [[] for _ in docs]
        for (di, _, _), out in zip(flat, outs):
            summaries[di].append(out)

        # collapse + final rounds, MERGED: a document whose summaries already
        # fit token_max submits its final reduce IN THE SAME BATCH as the
        # other documents' collapse groups (both use the same reduce
        # template), so late rounds ride full dispatches instead of a
        # trailing half-empty final round (VERDICT r4 weak #3 tail packing).
        # Prompt contents are identical to the sequential formulation — a
        # doc's final runs over exactly the summaries it would have ended
        # with — and outputs are batch-invariant in the engine, so this is
        # a pure scheduling change.
        final_texts: dict[int, str] = {}
        for round_no in range(self.max_collapse_rounds + 1):
            over = [
                di
                for di, s in enumerate(summaries)
                if di not in final_texts
                and sum(self.count(x) for x in s) > self.token_max
            ]
            ready = [
                di for di in range(len(docs))
                if di not in final_texts and di not in over
            ]
            if round_no == self.max_collapse_rounds and over:
                # collapse budget exhausted (ref recursion_limit=10, :196):
                # force the final over whatever remains, as the sequential
                # formulation did
                ready += over
                over = []
            batch: list[tuple[str, int, int]] = []
            prompts: list[str] = []
            refs: list[str] = []
            for di in ready:
                batch.append(("final", di, 0))
                prompts.append(self._reduce_one(summaries[di]))
                # reduce output re-emits spans of the summaries it merges
                refs.append("\n\n".join(summaries[di]))
            grouped: dict[int, list[list[str]]] = {}
            for di in over:
                groups = split_by_token_budget(summaries[di], self.token_max, self.count)
                grouped[di] = groups
                for gi, g in enumerate(groups):
                    batch.append(("collapse", di, gi))
                    prompts.append(self._reduce_one(g))
                    refs.append("\n\n".join(g))
            if not prompts:
                break
            outs = gen(
                prompts, owners=[di for _, di, _ in batch], references=refs,
                cache_hints=[template_header(self.reduce_prompt)] * len(prompts),
            )
            for di in over:
                summaries[di] = [None] * len(grouped[di])  # type: ignore[list-item]
            for (kind, di, gi), out in zip(batch, outs):
                if kind == "final":
                    final_texts[di] = out
                else:
                    summaries[di][gi] = out
            for di in over:
                results[di].rounds += 1

        for di, r in enumerate(results):
            r.summary = final_texts[di]
            r.llm_calls = gen.calls_by_owner.get(di, 0)
        return results

    def summarize(self, doc: str, *, backend: Backend | None = None) -> StrategyResult:
        return self.summarize_batch([doc], backend=backend)[0]
