"""Map-reduce strategy.

Semantics follow runners/run_summarization_ollama_mapreduce.py:75-201: split →
map each chunk → collapse groups while the whitespace-token total exceeds
token_max → one final reduce. The LangGraph Send fan-out (serial in practice,
:51-52) becomes true batching: the map step for a *batch of documents* is one
backend.generate call, and each collapse round batches every group of every
document still collapsing.
"""
from __future__ import annotations

from typing import Callable

from ..backend.base import Backend
from ..text.splitter import RecursiveTokenSplitter
from ..text.tokenizer import whitespace_token_count
from .base import StrategyResult, _BatchCounter, register_strategy, split_by_token_budget
from .prompts import MAPREDUCE_MAP, MAPREDUCE_REDUCE, template_header


@register_strategy
class MapReduceStrategy:
    name = "mapreduce"

    def __init__(
        self,
        backend: Backend,
        splitter: RecursiveTokenSplitter,
        token_max: int = 10000,
        max_new_tokens: int | None = None,
        max_collapse_rounds: int = 10,
        count: Callable[[str], int] = whitespace_token_count,
        map_prompt: str = MAPREDUCE_MAP,
        reduce_prompt: str = MAPREDUCE_REDUCE,
    ) -> None:
        self.backend = backend
        self.splitter = splitter
        self.token_max = token_max
        self.max_new_tokens = max_new_tokens
        # collapse backstop, like the reference's recursion_limit=10 (:196)
        self.max_collapse_rounds = max_collapse_rounds
        self.count = count
        self.map_prompt = map_prompt
        self.reduce_prompt = reduce_prompt

    @classmethod
    def from_config(cls, backend: Backend, config, **kw):
        splitter = RecursiveTokenSplitter(
            config.chunk_size, config.chunk_overlap,
            length_function=backend.count_tokens,
            # duck-typed backends without the batch method keep working via
            # the splitter's scalar fallback
            length_batch_function=getattr(
                backend, "count_tokens_batch", None
            ),
        )
        return cls(
            backend, splitter, token_max=config.token_max,
            max_new_tokens=config.max_new_tokens, **kw,
        )

    def _reduce_one(self, texts: list[str]) -> str:
        return self.reduce_prompt.format(docs="\n\n".join(texts))

    def summarize_batch(
        self, docs: list[str], *, backend: Backend | None = None
    ) -> list[StrategyResult]:
        be = backend or self.backend
        if callable(getattr(be, "submit_round", None)) and callable(
            getattr(be, "harvest", None)
        ):
            # serving path: the backend exposes the non-blocking half of
            # generate, so the map->reduce barrier dissolves into an
            # ordered completion stream
            return self._summarize_batch_streaming(docs, be)
        gen = _BatchCounter(be, self.max_new_tokens)

        chunks_per_doc = [self.splitter.split_text(d) or [d] for d in docs]
        results = [
            StrategyResult(summary="", num_chunks=len(c)) for c in chunks_per_doc
        ]

        # map: every chunk of every document in one batch. The chunk text
        # rides along as the speculation reference — a map summary is
        # largely extractive, exactly the overlap the reference drafter
        # (vnsum_tpu.spec) turns into accepted tokens — and the shared
        # template header is the cache_hint: every map prompt of every
        # document starts with it, so one prefilled header (vnsum_tpu.cache)
        # serves the whole fan-out
        map_hint = template_header(self.map_prompt)
        flat = [
            (di, self.map_prompt.format(content=c), c)
            for di, chunks in enumerate(chunks_per_doc)
            for c in chunks
        ]
        outs = gen(
            [p for _, p, _ in flat],
            owners=[di for di, _, _ in flat],
            references=[c for _, _, c in flat],
            cache_hints=[map_hint] * len(flat),
        )
        summaries: list[list[str]] = [[] for _ in docs]
        for (di, _, _), out in zip(flat, outs):
            summaries[di].append(out)

        # collapse + final rounds, MERGED: a document whose summaries already
        # fit token_max submits its final reduce IN THE SAME BATCH as the
        # other documents' collapse groups (both use the same reduce
        # template), so late rounds ride full dispatches instead of a
        # trailing half-empty final round (VERDICT r4 weak #3 tail packing).
        # Prompt contents are identical to the sequential formulation — a
        # doc's final runs over exactly the summaries it would have ended
        # with — and outputs are batch-invariant in the engine, so this is
        # a pure scheduling change.
        final_texts: dict[int, str] = {}
        for round_no in range(self.max_collapse_rounds + 1):
            over = [
                di
                for di, s in enumerate(summaries)
                if di not in final_texts
                and sum(self.count(x) for x in s) > self.token_max
            ]
            ready = [
                di for di in range(len(docs))
                if di not in final_texts and di not in over
            ]
            if round_no == self.max_collapse_rounds and over:
                # collapse budget exhausted (ref recursion_limit=10, :196):
                # force the final over whatever remains, as the sequential
                # formulation did
                ready += over
                over = []
            batch: list[tuple[str, int, int]] = []
            prompts: list[str] = []
            refs: list[str] = []
            for di in ready:
                batch.append(("final", di, 0))
                prompts.append(self._reduce_one(summaries[di]))
                # reduce output re-emits spans of the summaries it merges
                refs.append("\n\n".join(summaries[di]))
            grouped: dict[int, list[list[str]]] = {}
            for di in over:
                groups = split_by_token_budget(summaries[di], self.token_max, self.count)
                grouped[di] = groups
                for gi, g in enumerate(groups):
                    batch.append(("collapse", di, gi))
                    prompts.append(self._reduce_one(g))
                    refs.append("\n\n".join(g))
            if not prompts:
                break
            outs = gen(
                prompts, owners=[di for _, di, _ in batch], references=refs,
                cache_hints=[template_header(self.reduce_prompt)] * len(prompts),
            )
            for di in over:
                summaries[di] = [None] * len(grouped[di])  # type: ignore[list-item]
            for (kind, di, gi), out in zip(batch, outs):
                if kind == "final":
                    final_texts[di] = out
                else:
                    summaries[di][gi] = out
            for di in over:
                results[di].rounds += 1

        for di, r in enumerate(results):
            r.summary = final_texts[di]
            r.llm_calls = gen.calls_by_owner.get(di, 0)
        return results

    def _summarize_batch_streaming(
        self, docs: list[str], be: Backend
    ) -> list[StrategyResult]:
        """Streaming map->reduce over a submit_round/harvest backend (the
        serving layer's QueuedBackend): a document's collapse/final reduce
        is submitted the moment its LAST map child completes, overlapping
        other documents' still-running maps instead of waiting out a global
        barrier. Prompt contents are byte-identical to the barrier
        formulation — each doc's reduce runs over exactly the summaries it
        would have ended with — and greedy decode is prompt-deterministic,
        so this is a pure scheduling change (the bench's gang phase pins
        byte-identity against the offline path).

        Degraded results: a MAP child failing typed POISON is dropped from
        its document's reduce (harvest marks the gang partial, so the
        parent aggregate folds to ``partial``); a REDUCE failure still
        fails the whole call — there is no summary to degrade to."""
        from concurrent.futures import FIRST_COMPLETED, wait

        chunks_per_doc = [self.splitter.split_text(d) or [d] for d in docs]
        results = [
            StrategyResult(summary="", num_chunks=len(c)) for c in chunks_per_doc
        ]
        calls = [0] * len(docs)
        pending: dict = {}  # future -> ("map"|"collapse"|"final", di, idx)
        map_hint = template_header(self.map_prompt)
        reduce_hint = template_header(self.reduce_prompt)

        def submit(entries, phase, hint):
            futs = be.submit_round(
                [p for _, p, _ in entries],
                phase=phase,
                max_new_tokens=self.max_new_tokens,
                references=[r for _, _, r in entries],
                cache_hints=[hint] * len(entries),
            )
            for (tag, _, _), fut in zip(entries, futs):
                pending[fut] = tag
                calls[tag[1]] += 1

        # map: still ONE fan-out round across all docs (one gang-record
        # flush; affinity co-schedules the siblings) — only the JOIN is
        # per-document now
        summaries: list[list[str | None]] = [
            [None] * len(c) for c in chunks_per_doc
        ]
        maps_left = [len(c) for c in chunks_per_doc]
        parts_left = [0] * len(docs)
        rounds_done = [0] * len(docs)
        final_texts: dict[int, str] = {}
        submit(
            [
                (("map", di, ci), self.map_prompt.format(content=c), c)
                for di, chunks in enumerate(chunks_per_doc)
                for ci, c in enumerate(chunks)
            ],
            "map",
            map_hint,
        )

        def advance(di: int) -> None:
            # this doc's maps (or its current collapse round) all landed:
            # submit the next reduce stage immediately
            texts = [s for s in summaries[di] if s is not None]
            if (
                sum(self.count(x) for x in texts) <= self.token_max
                or rounds_done[di] >= self.max_collapse_rounds
            ):
                submit(
                    [(("final", di, 0), self._reduce_one(texts),
                      "\n\n".join(texts))],
                    "reduce", reduce_hint,
                )
                return
            groups = split_by_token_budget(texts, self.token_max, self.count)
            summaries[di] = [None] * len(groups)
            parts_left[di] = len(groups)
            submit(
                [
                    (("collapse", di, gi), self._reduce_one(g), "\n\n".join(g))
                    for gi, g in enumerate(groups)
                ],
                "reduce", reduce_hint,
            )

        while pending:
            done, _ = wait(list(pending), return_when=FIRST_COMPLETED)
            for fut in done:
                kind, di, idx = pending.pop(fut)
                out = be.harvest(fut, tolerate_poison=(kind == "map"))
                if kind == "map":
                    maps_left[di] -= 1
                    if out is None:
                        results[di].meta["dropped_chunks"] = (
                            results[di].meta.get("dropped_chunks", 0) + 1
                        )
                    else:
                        summaries[di][idx] = out
                    if maps_left[di] == 0:
                        advance(di)
                elif kind == "collapse":
                    summaries[di][idx] = out
                    parts_left[di] -= 1
                    if parts_left[di] == 0:
                        rounds_done[di] += 1
                        results[di].rounds += 1
                        advance(di)
                else:
                    final_texts[di] = out

        for di, r in enumerate(results):
            r.summary = final_texts[di]
            r.llm_calls = calls[di]
        return results

    def summarize(self, doc: str, *, backend: Backend | None = None) -> StrategyResult:
        return self.summarize_batch([doc], backend=backend)[0]
