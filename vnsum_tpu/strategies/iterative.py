"""Iterative refinement strategy.

Semantics follow runners/run_summarization_ollama_iterative.py:102-210: the
first chunk seeds a foundation summary, then each subsequent chunk triggers a
full rewrite integrating the new information. Per document the chain is
inherently sequential, so batching happens ACROSS documents: round r submits
chunk r of every document that still has one as a single backend batch.
"""
from __future__ import annotations

from ..backend.base import Backend
from ..text.splitter import RecursiveTokenSplitter
from .base import StrategyResult, _BatchCounter, register_strategy
from .prompts import ITERATIVE_INITIAL, ITERATIVE_REFINE, template_header

# the refine prompt up to (not including) {context}: header + the carried
# existing_answer — a retried/replayed refine round re-prefills the whole
# prior summary verbatim, so the cache_hint covers it, not just the header
_REFINE_PREFIX = ITERATIVE_REFINE[: ITERATIVE_REFINE.find("{context}")]


@register_strategy
class IterativeStrategy:
    name = "iterative"

    def __init__(
        self,
        backend: Backend,
        splitter: RecursiveTokenSplitter,
        max_new_tokens: int | None = None,
    ) -> None:
        self.backend = backend
        self.splitter = splitter
        self.max_new_tokens = max_new_tokens

    @classmethod
    def from_config(cls, backend: Backend, config, **kw):
        splitter = RecursiveTokenSplitter(
            config.iterative_chunk_size,
            config.iterative_chunk_overlap,
            length_function=backend.count_tokens,
            # duck-typed backends without the batch method keep working via
            # the splitter's scalar fallback
            length_batch_function=getattr(
                backend, "count_tokens_batch", None
            ),
        )
        return cls(backend, splitter, max_new_tokens=config.max_new_tokens, **kw)

    def summarize_batch(
        self, docs: list[str], *, backend: Backend | None = None
    ) -> list[StrategyResult]:
        gen = _BatchCounter(backend or self.backend, self.max_new_tokens)
        chunks_per_doc = [self.splitter.split_text(d) or [d] for d in docs]
        summaries = [""] * len(docs)
        max_rounds = max(len(c) for c in chunks_per_doc) if docs else 0

        for r in range(max_rounds):
            idx = [di for di, c in enumerate(chunks_per_doc) if r < len(c)]
            if r == 0:
                prompts = [
                    ITERATIVE_INITIAL.format(context=chunks_per_doc[di][0])
                    for di in idx
                ]
                # speculation references (vnsum_tpu.spec): the seed summary
                # extracts from its chunk
                refs = [chunks_per_doc[di][0] for di in idx]
                hints = [template_header(ITERATIVE_INITIAL)] * len(idx)
            else:
                prompts = [
                    ITERATIVE_REFINE.format(
                        existing_answer=summaries[di],
                        context=chunks_per_doc[di][r],
                    )
                    for di in idx
                ]
                # a refine rewrite mostly re-emits the existing summary with
                # spans of the new chunk folded in — both are draftable
                refs = [
                    summaries[di] + "\n\n" + chunks_per_doc[di][r]
                    for di in idx
                ]
                # the cacheable prefix of a refine prompt is the header PLUS
                # the re-fed prior summary (everything before the new chunk)
                hints = [
                    _REFINE_PREFIX.format(existing_answer=summaries[di])
                    for di in idx
                ]
            outs = gen(prompts, owners=idx, references=refs, cache_hints=hints)
            for di, out in zip(idx, outs):
                summaries[di] = out

        return [
            StrategyResult(
                summary=summaries[di],
                num_chunks=len(chunks_per_doc[di]),
                llm_calls=gen.calls_by_owner.get(di, 0),
                rounds=len(chunks_per_doc[di]),
            )
            for di in range(len(docs))
        ]

    def summarize(self, doc: str, *, backend: Backend | None = None) -> StrategyResult:
        return self.summarize_batch([doc], backend=backend)[0]
