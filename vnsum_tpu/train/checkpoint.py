"""Sharded training checkpoints (orbax).

The reference's only "checkpointing" is inference-side resume-by-file: a
summary file on disk means the doc is done (run_full_evaluation_pipeline.py:
422-431, 568-570). That stays in the pipeline layer. This module adds what a
training-capable framework needs and the reference has nowhere at all
(SURVEY.md §5 "No state-dict/optimizer checkpoints exist"): atomic, versioned
train-state checkpoints — params, optimizer state, and step counter — written
and restored WITH their mesh shardings, so a restore on the same mesh topology
resumes bit-exact without gathering the model onto one host.
"""
from __future__ import annotations

from pathlib import Path

import jax

from ..core.logging import get_logger

logger = get_logger("vnsum.train.ckpt")


class TrainCheckpointer:
    """Versioned save/restore for a :class:`vnsum_tpu.train.Trainer`."""

    def __init__(self, directory: str | Path, max_to_keep: int = 3) -> None:
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = Path(directory).absolute()
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, trainer, *, wait: bool = True) -> int:
        """Write a checkpoint at the trainer's current step; returns the step."""
        step = trainer.step_count
        self.manager.save(
            step,
            args=self._ocp.args.Composite(
                params=self._ocp.args.StandardSave(trainer.params),
                opt_state=self._ocp.args.StandardSave(trainer.opt_state),
            ),
        )
        if wait:
            self.manager.wait_until_finished()
            logger.info("saved checkpoint step=%d at %s", step, self.directory)
        else:
            logger.info("queued checkpoint step=%d at %s", step, self.directory)
        return step

    def restore(self, trainer, step: int | None = None) -> int:
        """Restore params/opt_state into ``trainer`` (in place), preserving
        each leaf's current sharding; returns the restored step."""
        if step is None:
            step = self.manager.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )

        def abstract(tree):
            return jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
                tree,
            )

        restored = self.manager.restore(
            step,
            args=self._ocp.args.Composite(
                params=self._ocp.args.StandardRestore(abstract(trainer.params)),
                opt_state=self._ocp.args.StandardRestore(
                    abstract(trainer.opt_state)
                ),
            ),
        )
        trainer.params = restored["params"]
        trainer.opt_state = restored["opt_state"]
        trainer.step_count = step
        logger.info("restored checkpoint step=%d from %s", step, self.directory)
        return step

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def all_steps(self) -> list[int]:
        return list(self.manager.all_steps())

    def close(self) -> None:
        self.manager.close()
