from .trainer import TrainConfig, Trainer, lm_loss

__all__ = ["TrainConfig", "Trainer", "lm_loss"]
