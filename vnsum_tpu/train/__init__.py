from .checkpoint import TrainCheckpointer
from .trainer import TrainConfig, Trainer, lm_loss

__all__ = ["TrainCheckpointer", "TrainConfig", "Trainer", "lm_loss"]
