"""Sharded training step (fine-tuning / continued pretraining of the
summarization model).

The reference is inference-only — it has no optimizer, no checkpoints, no
training loop at all (SURVEY.md §5 "no state-dict/optimizer checkpoints").
This module makes training a first-class capability the TPU-native way: one
jit-compiled step over a (data, model, seq) mesh — DP via batch sharding, TP
via the megatron param specs, SP via ring attention — with optax AdamW,
gradient clipping, remat inside the layer scan, and donated buffers.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..core.logging import get_logger
from ..models.llama import LlamaConfig, forward_train, init_params
from ..parallel.mesh import AXES
from ..parallel.ring import ring_attention
from ..parallel.sharding import param_shardings, param_specs

logger = get_logger("vnsum.train")


def lm_loss(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,      # [B, S]
    loss_mask: jax.Array,   # [B, S] bool — positions whose NEXT token counts
    *,
    attention_fn=None,
    remat: bool = True,
) -> jax.Array:
    """Next-token cross-entropy, mean over unmasked positions."""
    logits = forward_train(
        params, cfg, tokens, attention_fn=attention_fn, remat=remat
    )
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    mask = loss_mask[:, :-1].astype(jnp.float32)
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


@dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 1e-5
    weight_decay: float = 0.01
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    remat: bool = True
    context_parallel: bool = False  # ring attention over the seq axis
    fsdp: bool = False  # shard stacked layers (+ their optimizer state)
    #                     over the mesh `fsdp` axis, ZeRO-3 style


class Trainer:
    def __init__(
        self,
        model_config: LlamaConfig,
        mesh: Mesh,
        train_config: TrainConfig | None = None,
        params: dict | None = None,
        seed: int = 0,
    ) -> None:
        from ..core.jax_cache import enable_compilation_cache

        enable_compilation_cache()
        self.cfg = model_config
        self.mesh = mesh
        self.tc = train_config or TrainConfig()
        self.step_count = 0

        self.optimizer = optax.chain(
            optax.clip_by_global_norm(self.tc.grad_clip),
            optax.adamw(
                self.tc.learning_rate,
                b1=self.tc.b1,
                b2=self.tc.b2,
                weight_decay=self.tc.weight_decay,
            ),
        )

        if self.tc.fsdp:
            if AXES.fsdp not in mesh.shape:
                raise ValueError(
                    "TrainConfig.fsdp=True needs a mesh with an 'fsdp' axis "
                    "(make_mesh({'fsdp': N, ...}))"
                )
            if self.cfg.n_layers % mesh.shape[AXES.fsdp]:
                raise ValueError(
                    f"n_layers={self.cfg.n_layers} not divisible by the "
                    f"fsdp axis ({mesh.shape[AXES.fsdp]})"
                )
        p_shardings = param_shardings(
            mesh, self.cfg.tie_embeddings, fsdp=self.tc.fsdp,
            qk_norm=self.cfg.qk_norm,
            sandwich_norms=self.cfg.sandwich_norms,
        )
        if params is None:
            # init directly into the sharded layout: each leaf is produced
            # under jit with its target sharding, so a 2-chip mesh never
            # materializes the full replicated model on one device
            init_fn = jax.jit(
                partial(init_params, cfg=self.cfg), out_shardings=p_shardings
            )
            params = init_fn(jax.random.key(seed))
        else:
            params = jax.tree.map(jax.device_put, params, p_shardings)
        self.params = params

        opt_specs = self._opt_state_specs()
        opt_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), opt_specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        self.opt_state = jax.jit(
            self.optimizer.init, out_shardings=opt_shardings
        )(self.params)

        attention_fn = None
        if self.tc.context_parallel:
            attention_fn = partial(ring_attention, mesh=mesh)

        # with fsdp the batch shards over BOTH axes, so the fsdp axis also
        # acts as data parallelism (true ZeRO-3: partitioned compute plus
        # sharded params/optimizer) instead of replicating the forward and
        # doing fsdp-fold redundant FLOPs for a memory-only win
        batch_axes = (AXES.data, AXES.fsdp) if self.tc.fsdp else AXES.data
        data_spec = NamedSharding(mesh, P(batch_axes, None))

        def step(params, opt_state, tokens, loss_mask):
            loss, grads = jax.value_and_grad(lm_loss)(
                params, self.cfg, tokens, loss_mask,
                attention_fn=attention_fn, remat=self.tc.remat,
            )
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._step = jax.jit(
            step,
            in_shardings=(p_shardings, opt_shardings, data_spec, data_spec),
            out_shardings=(p_shardings, opt_shardings, None),
            donate_argnums=(0, 1),
        )

    def _opt_state_specs(self):
        """PartitionSpecs for the optax state: any state subtree that has the
        params' exact tree structure (AdamW mu/nu) inherits the param specs;
        every other leaf (counters, empty states) replicates."""
        specs = param_specs(
            self.cfg.tie_embeddings, fsdp=self.tc.fsdp,
            qk_norm=self.cfg.qk_norm,
            sandwich_norms=self.cfg.sandwich_norms,
        )
        abstract = jax.eval_shape(
            lambda: init_params(jax.random.key(0), self.cfg)
        )
        params_def = jax.tree.structure(abstract)
        state_shape = jax.eval_shape(self.optimizer.init, abstract)

        def is_param_subtree(x):
            if isinstance(x, jax.ShapeDtypeStruct):
                return False
            try:
                return jax.tree.structure(x) == params_def
            except Exception:
                return False

        return jax.tree.map(
            lambda x: specs if is_param_subtree(x) else P(),
            state_shape,
            is_leaf=is_param_subtree,
        )

    def step(self, tokens, loss_mask=None):
        """One optimizer step; tokens [B, S] int32. Returns float loss."""
        tokens = jnp.asarray(tokens, jnp.int32)
        batch_div = self.mesh.shape.get(AXES.data, 1)
        if self.tc.fsdp:
            batch_div *= self.mesh.shape.get(AXES.fsdp, 1)
        if tokens.shape[0] % batch_div:
            raise ValueError(
                f"batch size {tokens.shape[0]} must be divisible by "
                f"data{'×fsdp' if self.tc.fsdp else ''} mesh axes ({batch_div}); "
                "with fsdp=True the batch shards over both axes"
            )
        if loss_mask is None:
            loss_mask = jnp.ones_like(tokens, dtype=bool)
        t0 = time.time()
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, tokens, loss_mask
        )
        loss = float(loss)
        self.step_count += 1
        logger.info(
            "step %d: loss=%.4f (%.2fs)", self.step_count, loss, time.time() - t0
        )
        return loss
