"""Batch evaluation pipeline.

Mirrors the reference PipelineRunner's flow (run_full_evaluation_pipeline.py:
120-947): preflight → document analysis → per-model summarization with
resume-by-file → per-model evaluation → report → structured results JSON —
with the reference's process boundaries removed: evaluation runs in-process
(no subprocess + stdout scraping, :649-784), and summarization submits
document batches to the strategy layer so all per-round LLM calls share
device batches.
"""
from __future__ import annotations

import contextlib
import os
import time
import traceback
from pathlib import Path

from ..backend.base import Backend, get_backend
from ..core.config import PipelineConfig
from ..core.faults import call_with_retries, is_retryable
from ..core.logging import get_logger, setup_run_logging
from ..core.profiling import Tracer, device_profile
from ..core.results import DocumentRecord, ModelRunRecord, PipelineResults
from ..data import DocumentDataset, analyze_documents
from ..eval import SemanticEvaluator
from ..strategies import get_strategy
from ..text import DocumentTree, clean_thinking_tokens

logger = get_logger("vnsum.pipeline")


def model_name_safe(model: str) -> str:
    """'llama3.2:3b' -> 'llama3_2_3b' (ref :170, :326)."""
    return model.replace(":", "_").replace(".", "_")


class PipelineRunner:
    def __init__(
        self,
        config: PipelineConfig,
        backend_factory=None,
        embedding_model=None,
        llm_judge=None,
    ) -> None:
        self.config = config
        self.backend_factory = backend_factory or self._default_backend_factory
        self.embedding_model = embedding_model
        # a prebuilt eval.LLMJudge (tests / artifact scripts inject tiny
        # local judges); None = resolve from EvalConfig in _build_llm_judge
        self.llm_judge = llm_judge
        self.results = PipelineResults(config=config.to_dict())
        self.tracer = Tracer()
        self.log_path = setup_run_logging(config.logs_dir)
        logger.info("pipeline configured: approach=%s backend=%s models=%s",
                    config.approach, config.backend, config.models)
        # startup self-check, like the reference's cleaner sanity log (:193-197)
        if clean_thinking_tokens("<think>x</think>ok") != "ok":
            raise RuntimeError("thinking-token cleaner self-check failed")

    # -- backend -----------------------------------------------------------

    def _default_backend_factory(self, model: str) -> Backend:
        cfg = self.config
        if cfg.backend == "ollama":
            return get_backend(
                "ollama", model=model, url=cfg.ollama_url,
                max_new_tokens=cfg.max_new_tokens,
            )
        if cfg.backend == "fake":
            return get_backend("fake")
        if cfg.backend == "hf":
            return get_backend(
                "hf", model_name_or_path=model,
                max_context=cfg.max_context,
                max_new_tokens=cfg.max_new_tokens,
            )
        if cfg.backend == "tpu":
            mesh = None
            if cfg.mesh_shape:
                import math

                import jax

                from ..parallel import make_mesh

                # the axon plugin keeps TPU default regardless of
                # JAX_PLATFORMS; when the requested mesh needs more devices
                # than the default platform has but the host CPU pool fits
                # (tests, dry runs), build the mesh there instead — ONLY
                # with explicit opt-in, so a production mesh typo fails
                # loudly instead of silently running the run on CPU
                platform = None
                need = math.prod(v for v in cfg.mesh_shape.values() if v > 0)
                if need > len(jax.devices()):
                    if not cfg.allow_cpu_mesh:
                        raise RuntimeError(
                            f"mesh {cfg.mesh_shape} needs {need} devices but "
                            f"the default platform has {len(jax.devices())}; "
                            "set allow_cpu_mesh=True (or shrink the mesh) if "
                            "a host-CPU mesh is intended"
                        )
                    if need > len(jax.devices("cpu")):
                        raise RuntimeError(
                            f"mesh {cfg.mesh_shape} needs {need} devices; "
                            f"host CPU pool has {len(jax.devices('cpu'))} "
                            "(set XLA_FLAGS=--xla_force_host_platform_"
                            "device_count)"
                        )
                    logger.info(
                        "mesh %s exceeds default platform; using cpu devices "
                        "(allow_cpu_mesh)",
                        cfg.mesh_shape,
                    )
                    platform = "cpu"
                mesh = make_mesh(dict(cfg.mesh_shape), platform=platform)
            model_cfg, params, tokenizer = self._resolve_model(model)
            if cfg.long_context:
                from ..backend.long_context import LongContextBackend

                return LongContextBackend(
                    model_config=model_cfg,
                    mesh=mesh,
                    tokenizer=tokenizer,
                    params=params,
                    batch_size=cfg.batch_size,
                    max_new_tokens=cfg.max_new_tokens,
                    # the truncated strategy cuts the DOCUMENT to
                    # max_context − max_new and then wraps it in a prompt
                    # template; give the backend headroom for that template
                    # so it never chops the closing instruction off a
                    # cap-length prompt
                    max_total_tokens=(
                        cfg.max_context + 1024
                        if cfg.approach == "truncated"
                        else None
                    ),
                    quantize=cfg.quantize,
                    # cfg.quantize alone promises weight-only (exact)
                    # quantization; the lossy int8 prefill cache needs its
                    # own explicit opt-in (--quantize-kv-long)
                    quantize_kv=cfg.long_context_quantize_kv,
                )
            return get_backend(
                "tpu",
                model_config=model_cfg,
                params=params,
                tokenizer=tokenizer,
                mesh=mesh,
                batch_size=cfg.batch_size,
                max_new_tokens=cfg.max_new_tokens,
                quantize=cfg.quantize,
                quantize_act=cfg.quantize_act,
            )
        raise ValueError(f"unknown backend {cfg.backend!r}")

    def _resolve_model(self, model: str):
        """(model_config, params, tokenizer) for the tpu backends — ONE copy
        of the checkpoint-load / tokenizer-rewrite / registry-lookup rules.

        With weights_dir set, safetensors convert + the checkpoint's own
        tokenizer (quality-parity chain; reference loads HF checkpoints at
        runners/run_summarization.py:54-62); otherwise a registry config
        with random init (benchmarks, tests)."""
        cfg = self.config
        if cfg.weights_dir:
            import jax.numpy as jnp

            from ..models.convert import load_hf_checkpoint

            model_cfg, params = load_hf_checkpoint(
                cfg.weights_dir, dtype=getattr(jnp, cfg.dtype)
            )
            tokenizer = (
                cfg.tokenizer
                if cfg.tokenizer.startswith("hf:")
                else f"hf:{cfg.weights_dir}"
            )
            return model_cfg, params, tokenizer
        from ..models import MODEL_REGISTRY

        if model not in MODEL_REGISTRY:
            raise ValueError(
                f"unknown model {model!r} for tpu backend; "
                f"have {sorted(MODEL_REGISTRY)}"
            )
        return MODEL_REGISTRY[model](), None, cfg.tokenizer

    def preflight(self, backend: Backend) -> None:
        """Backend health check before any work (ref :199-233 checked the
        Ollama server + model availability)."""
        # .label carries wrapper decorations ("ollama+retry", "fake+faults")
        # that .name deliberately drops so the dispatch below still works
        logger.info("backend: %s", getattr(backend, "label", backend.name))
        if backend.name == "ollama":
            models = backend.health_check()
            logger.info("ollama reachable; models: %s", models)
        elif backend.name == "tpu":
            import jax

            devices = jax.devices()
            logger.info("jax devices: %s", devices)
            if not devices:
                raise RuntimeError("no JAX devices available")

    # -- phases ------------------------------------------------------------

    def analyze(self) -> dict:
        cfg = self.config
        ds = DocumentDataset(cfg.docs_dir, cfg.summary_dir)
        stats = analyze_documents(
            ds, lambda t: len(t.split()), chunk_size=cfg.chunk_size,
            max_samples=cfg.max_samples,
        )
        d = stats.to_dict()
        d["per_document"] = d["per_document"][:1000]
        self.results.document_stats = d
        logger.info(
            "analyzed %d docs: %d tokens total, ~%.0f/doc",
            stats.total_documents, stats.total_tokens, stats.avg_tokens_per_doc,
        )
        return d

    def _output_dir(self, model: str) -> Path:
        # ref naming: <generated_summaries_dir>_<approach>_<model_safe> (:408)
        return Path(
            f"{self.config.generated_summaries_dir}_"
            f"{self.config.approach}_{model_name_safe(model)}"
        )

    def run_summarization_for_model(self, model: str) -> ModelRunRecord:
        cfg = self.config
        record = ModelRunRecord(model=model, approach=cfg.approach)
        t_start = time.time()

        backend = self.backend_factory(model)
        self.preflight(backend)
        strategy_kw = {}
        if cfg.approach == "truncated" and getattr(backend, "tok", None) is not None:
            # the truncated cut must count tokens with the backend's OWN
            # tokenizer — weights_dir/long-context runs rewrite it to the
            # checkpoint's HF tokenizer, and a byte-token cut there would
            # over-truncate ~4x
            strategy_kw["tokenizer"] = backend.tok
        strategy = get_strategy(cfg.approach, backend, cfg, **strategy_kw)

        ds = DocumentDataset(cfg.docs_dir, cfg.summary_dir)
        out_dir = self._output_dir(model)
        out_dir.mkdir(parents=True, exist_ok=True)

        tree = None
        if cfg.approach == "mapreduce_hierarchical":
            tree_path = Path(cfg.tree_json_path)
            if tree_path.is_file():
                tree = DocumentTree.load(tree_path)
            else:
                logger.warning(
                    "tree JSON %s missing; hierarchical will wrap plain text",
                    tree_path,
                )

        names = ds.filenames(cfg.max_samples)
        pending: list[str] = []
        for name in names:
            gen_path = out_dir / name
            if gen_path.is_file():  # resume-by-file (ref :422-431)
                logger.info("  %s: already exists, skipping", name)
                continue
            if self.config.summary_dir and not ds.has_reference(name):
                logger.warning("  %s: no reference summary, skipping", name)
                continue
            pending.append(name)

        logger.info(
            "model %s: %d docs pending (%d total)", model, len(pending), len(names)
        )

        # submit documents in batches; each batch's map/collapse rounds share
        # device batches inside the strategy. Groups default to 4x the engine
        # batch so collapse/reduce rounds still fill whole dispatches
        group_size = cfg.doc_group_size or 4 * max(cfg.batch_size, 1)
        for start in range(0, len(pending), group_size):
            group = pending[start : start + group_size]
            batch_t0 = time.time()
            # profiler windows must stay short: capture the first batch only.
            # cms are built inside run_batch so a retry gets fresh instances
            # (a generator-backed cm cannot be re-entered)
            make_profile_cm = (
                device_profile if start == 0 else contextlib.nullcontext
            )

            def run_batch():
                with self.tracer.span("batch"), make_profile_cm():
                    if cfg.approach == "mapreduce_hierarchical" and tree is not None:
                        roots, docs_fallback = [], []
                        for name in group:
                            node = tree.get(name)
                            if node is None:
                                docs_fallback.append(name)
                            roots.append((name, node))
                        results = []
                        tree_items = [(n, r) for n, r in roots if r is not None]
                        if tree_items:
                            tree_results = strategy.summarize_tree_batch(
                                [r for _, r in tree_items]
                            )
                            results.extend(
                                zip([n for n, _ in tree_items], tree_results)
                            )
                        if docs_fallback:
                            texts = [ds.read_doc(n) for n in docs_fallback]
                            results.extend(
                                zip(docs_fallback, strategy.summarize_batch(texts))
                            )
                        return results
                    texts = [ds.read_doc(n) for n in group]
                    return list(zip(group, strategy.summarize_batch(texts)))

            try:
                results = call_with_retries(
                    run_batch,
                    max_retries=cfg.max_batch_retries,
                    backoff=cfg.retry_backoff,
                    # deterministic host-side bugs fail fast; re-running a
                    # multi-minute device batch can't fix a TypeError
                    should_retry=is_retryable,
                    what=f"batch of {len(group)} docs",
                )
            except Exception as e:
                logger.error("batch failed (%s): %s", group, e)
                logger.debug("%s", traceback.format_exc())
                for name in group:
                    record.failed += 1
                    record.total_documents += 1
                    record.processing_details.append(
                        DocumentRecord(
                            name, 0, time.time() - batch_t0, 0,
                            status="failed", error=str(e),
                        )
                    )
                continue

            batch_time = time.time() - batch_t0
            # wall time is amortized (record.time_basis); chunk/call counts
            # are true per-document values from the strategy
            per_doc_time = batch_time / max(len(results), 1)
            for name, res in results:
                summary = clean_thinking_tokens(res.summary)  # ref :560-561
                (out_dir / name).write_text(summary, encoding="utf-8")
                record.total_documents += 1
                record.successful += 1
                record.total_chunks += res.num_chunks
                record.processing_details.append(
                    DocumentRecord(
                        name, res.num_chunks, per_doc_time, len(summary),
                        llm_calls=res.llm_calls,
                    )
                )
            logger.info(
                "  batch of %d docs in %.1fs (%.1fs/doc)",
                len(results), batch_time, per_doc_time,
            )

        record.total_time = time.time() - t_start
        self.results.add_summarization(record)
        return record

    def run_evaluation_for_model(self, model: str) -> dict:
        cfg = self.config
        embedder = self.embedding_model
        if embedder is None:
            from ..eval import EmbeddingModel

            with self.tracer.span("embedder_init"):
                if cfg.evaluation.embedding_dir:
                    embedder = EmbeddingModel.from_hf(
                        cfg.evaluation.embedding_dir,
                        batch_size=cfg.evaluation.bert_batch_size,
                    )
                else:
                    embedder = EmbeddingModel(
                        batch_size=cfg.evaluation.bert_batch_size
                    )
            self.embedding_model = embedder  # reuse across the model sweep
        judge = None
        if cfg.evaluation.include_llm_eval:
            judge = self._build_llm_judge()
        evaluator = SemanticEvaluator(
            embedding_model=embedder,
            include_llm_eval=judge is not None,
            llm_judge=judge,
            tracer=self.tracer,
        )
        out_path = (
            Path(cfg.results_dir) / f"{model_name_safe(model)}_results.json"
        )
        results = evaluator.evaluate_folders(
            self._output_dir(model),
            cfg.summary_dir,
            max_samples=cfg.evaluation.max_samples or cfg.max_samples,
            output=out_path,
        )
        self.results.add_evaluation(model, results["summary_statistics"])
        return results

    def _build_llm_judge(self):
        """G-Eval judge resolution: an injected judge wins, then a local
        Backend-protocol judge (EvalConfig.judge_backend — the offline path),
        then an OpenRouter-compatible endpoint when an API key is present
        (ref use_openrouter path); otherwise skipped with a warning — never
        a hard failure."""
        import os

        from ..eval import LLMJudge

        cfg = self.config.evaluation
        if self.llm_judge is not None:
            return self.llm_judge
        if cfg.judge_backend:
            return LLMJudge(backend=self._judge_backend(cfg.judge_backend))
        api_key = os.environ.get("OPENROUTER_API_KEY") or os.environ.get(
            "OPENAI_API_KEY"
        )
        if not api_key:
            logger.warning(
                "include_llm_eval=True but no OPENROUTER_API_KEY/OPENAI_API_KEY "
                "set; skipping G-Eval"
            )
            return None
        base = (
            "https://openrouter.ai/api/v1"
            if cfg.use_openrouter
            else "https://api.openai.com/v1"
        )
        return LLMJudge(api_base=base, api_key=api_key, model=cfg.llm_model)

    def _judge_backend(self, spec: str) -> Backend:
        """Resolve EvalConfig.judge_backend into a judge Backend. A bare
        string can't carry model kwargs, so each form is explicit:
        "fake" (CI), "ollama:<model>" (local server), "tpu:<registry-name>"
        (on-device judge — RANDOM weights unless the registry model maps to
        a loaded checkpoint elsewhere, so plumbing/containment runs only)."""
        name, _, arg = spec.partition(":")
        if name == "fake":
            return get_backend("fake")
        if name == "ollama":
            if not arg:
                raise ValueError(
                    "judge_backend='ollama:<model>' needs the model tag"
                )
            return get_backend(
                "ollama", model=arg, url=self.config.ollama_url
            )
        if name == "tpu":
            from ..models import MODEL_REGISTRY

            if arg not in MODEL_REGISTRY:
                raise ValueError(
                    "judge_backend='tpu:<model>' needs a registry model "
                    f"name (have {sorted(MODEL_REGISTRY)}); a bare 'tpu' "
                    "would silently judge with an unspecified model"
                )
            logger.warning(
                "tpu judge %r runs RANDOM-INIT weights on this host — "
                "scores will mostly fail to parse; use an HTTP judge or "
                "inject PipelineRunner(llm_judge=...) for real judging",
                arg,
            )
            return get_backend(
                "tpu", model_config=MODEL_REGISTRY[arg](), max_new_tokens=64
            )
        raise ValueError(f"unknown judge_backend spec {spec!r}")

    # -- orchestration -----------------------------------------------------

    def run(self) -> PipelineResults:
        with self.tracer.span("analyze"):
            self.analyze()
        for model in self.config.models:
            try:
                with self.tracer.span("summarize"):
                    self.run_summarization_for_model(model)
            except Exception as e:
                logger.error("model %s summarization failed: %s", model, e)
                logger.debug("%s", traceback.format_exc())
                rec = ModelRunRecord(
                    model=model, approach=self.config.approach,
                    status="failed", error=str(e),
                )
                self.results.add_summarization(rec)
                continue
            try:
                with self.tracer.span("evaluate"):
                    self.run_evaluation_for_model(model)
            except Exception as e:
                logger.error("model %s evaluation failed: %s", model, e)
                self.results.add_evaluation(model, {"status": "failed", "error": str(e)})
        self.results.tracing = self.tracer.to_dict()
        path = self.results.save(self.config.results_dir)
        logger.info("results saved to %s", path)
        # when device profiling is armed (VNSUM_PROFILE_DIR), drop the host
        # span timeline as Chrome trace JSON into the same directory so the
        # pipeline's wall-clock phases open in Perfetto next to the XLA
        # device trace — the offline twin of serving's /debug/trace
        profile_dir = os.environ.get("VNSUM_PROFILE_DIR")
        if profile_dir:
            from ..obs.export import save_timestamped_trace

            tp = save_timestamped_trace(
                self.tracer.chrome_trace("pipeline"), profile_dir, "pipeline"
            )
            logger.info("host span timeline saved to %s", tp)
        self.report()
        return self.results

    def report(self) -> str:
        """Human-readable summary (ref generate_summary_report :841-925,
        minus its '{:.4f}'.format('N/A') crash path)."""
        lines = ["", "=" * 60, "PIPELINE SUMMARY", "=" * 60]
        lines.append(f"approach: {self.config.approach}")
        for model, rec in self.results.summarization.items():
            lines.append(f"\nmodel {model}:")
            lines.append(
                f"  docs: {rec.get('successful', 0)} ok / {rec.get('failed', 0)} failed, "
                f"chunks: {rec.get('total_chunks', 0)}, "
                f"time: {rec.get('total_time', 0.0):.1f}s "
                f"({rec.get('chunks_per_second', 0.0):.2f} chunks/s)"
            )
            ev = self.results.evaluation.get(model)
            if ev and "rouge_scores" in ev:

                def fmt(v):
                    return f"{v:.4f}" if isinstance(v, (int, float)) else str(v)

                rs = ev["rouge_scores"]
                bs = ev.get("bert_scores", {})
                ss = ev.get("semantic_similarity", {})
                lines.append(
                    f"  rouge1/2/L: {fmt(rs.get('rouge1_f1', 'N/A'))} / "
                    f"{fmt(rs.get('rouge2_f1', 'N/A'))} / {fmt(rs.get('rougeL_f1', 'N/A'))}"
                )
                lines.append(
                    f"  bert F1: {fmt(bs.get('bert_f1', 'N/A'))}  "
                    f"semsim: {fmt(ss.get('mean', 'N/A'))}"
                )
        text = "\n".join(lines)
        logger.info("%s", text)
        return text
