"""CLI entry point, flag-compatible with the reference's argparse surface
(run_full_evaluation_pipeline.py:956-970) plus the TPU-era knobs
(--backend, --mesh, --tokenizer, --batch-size per BASELINE.json).
"""
from __future__ import annotations

import argparse

from ..core.config import APPROACHES, PipelineConfig, approach_defaults


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="vnsum-pipeline",
        description="Run the summarization evaluation pipeline",
    )
    p.add_argument("--approach", choices=APPROACHES, default="mapreduce")
    p.add_argument(
        "--models", nargs="+", default=["llama3.2:3b"],
        help="Models to evaluate (TPU backend: names in MODEL_REGISTRY)",
    )
    p.add_argument("--max-samples", type=int, default=None)
    p.add_argument("--tree-json", default="data_1/document_tree.json")
    p.add_argument("--max-depth", type=int, default=1)
    p.add_argument(
        "--backend", choices=["tpu", "ollama", "hf", "fake"], default="tpu"
    )
    p.add_argument("--ollama-url", default="http://localhost:11434")
    p.add_argument("--docs-dir", default="data_1/doc")
    p.add_argument("--summary-dir", default="data_1/summary")
    p.add_argument("--generated-summaries-dir", default="data_1/generated_summaries")
    p.add_argument("--results-dir", default="evaluation_results")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--tokenizer", default="byte", help="byte or hf:<name-or-path>")
    p.add_argument(
        "--mesh", default="", help='device mesh, e.g. "data=2,model=4"'
    )
    p.add_argument(
        "--allow-cpu-mesh", action="store_true",
        help="when --mesh needs more devices than the default platform "
        "has, rebuild it on host CPU devices instead of failing (tests / "
        "dry runs; ~100x slower than TPU — never for production)",
    )
    p.add_argument(
        "--quantize", action="store_true",
        help="int8 weight-only quantization for the tpu backend (halves "
        "decode HBM traffic). The one-chip engine's KV cache quantizes "
        "automatically whenever its Pallas kernels are active (independent "
        "of this flag); the long-context prefill cache stays exact — its "
        "lossy int8 mode is opt-in via --quantize-kv-long",
    )
    p.add_argument(
        "--quantize-act", action="store_true",
        help="W8A8 prefill: int8-quantize activations (per-token absmax) "
        "into the int8-weight matmuls — double-rate MXU dots on prefill. "
        "LOSSY (activation rounding); A/B against --quantize alone for "
        "quality runs. Requires --quantize",
    )
    p.add_argument(
        "--quantize-kv-long", action="store_true",
        help="int8-quantize the long-context prefill KV cache (halves "
        "ring-decode HBM traffic per step). LOSSY: cached K/V round-trip "
        "through per-(position,head) int8, so logits drift slightly vs the "
        "exact cache — greedy summaries can differ in late tokens. "
        "Measured drift is small (tests/test_backend_long_context.py "
        "quantize_kv parity bounds); quality-gate runs should A/B it",
    )
    p.add_argument(
        "--long-context", action="store_true",
        help="ring-attention prefill + seq-sharded decode: prompts run "
        "un-truncated up to seq_axis × the one-chip limit (requires "
        "--backend tpu and --mesh with seq>1); pair with --approach "
        "truncated --max-context <long limit> for one-shot full-document "
        "summaries",
    )
    p.add_argument(
        "--weights-dir", default=None,
        help="local HF checkpoint dir for the tpu backend (config.json + "
        "safetensors + tokenizer); e.g. a Llama-3.2-3B checkout. Converted "
        "via models.convert; the checkpoint's tokenizer is used.",
    )
    p.add_argument(
        "--embedding-dir", default=None,
        help="local HF BERT-family checkpoint dir for the embedding metrics "
        "(e.g. an all-MiniLM-L6-v2 checkout); converted via "
        "models.convert_encoder so BERTScore/semsim are pretrained-calibrated",
    )
    p.add_argument(
        "--chunk-size", type=int, default=None,
        help="override the approach-default chunk size (tokens)",
    )
    p.add_argument(
        "--token-max", type=int, default=None,
        help="override the approach-default collapse budget (tokens)",
    )
    p.add_argument(
        "--max-new-tokens", type=int, default=None,
        help="override the approach-default generation budget",
    )
    p.add_argument(
        "--max-context", type=int, default=None,
        help="truncated approach: context budget in tokens (ref default "
        "16384); with --long-context this may exceed the one-chip limit",
    )
    p.add_argument(
        "--include-llm-eval", action="store_true",
        help="run the G-Eval correctness/coherence column (reference "
        "include_llm_eval); needs OPENROUTER_API_KEY/OPENAI_API_KEY or "
        "--judge-backend",
    )
    p.add_argument(
        "--judge-backend", default=None,
        help="offline G-Eval judge over the Backend protocol: 'fake' (CI), "
        "'ollama:<model>', or 'tpu:<registry-name>'; implies "
        "--include-llm-eval",
    )
    return p


def config_from_args(args: argparse.Namespace) -> PipelineConfig:
    overrides = approach_defaults(args.approach)
    mesh_shape = {}
    if args.mesh:
        for part in args.mesh.split(","):
            k, v = part.split("=")
            mesh_shape[k.strip()] = int(v)
    for key in ("chunk_size", "token_max", "max_new_tokens", "max_context"):
        val = getattr(args, key)
        if val is not None:
            overrides[key] = val
    if args.chunk_size is not None:
        # keep overlap a small fraction of the chunk (ref default is
        # 200/12000); an overlap near chunk_size would shrink the splitter
        # stride to almost nothing
        overrides["chunk_overlap"] = min(
            overrides.get("chunk_overlap", 200), max(0, args.chunk_size // 10)
        )
        overrides["iterative_chunk_size"] = args.chunk_size
        overrides["iterative_chunk_overlap"] = overrides["chunk_overlap"]
    cfg = PipelineConfig(
        approach=args.approach,
        weights_dir=args.weights_dir,
        models=list(args.models),
        backend=args.backend,
        ollama_url=args.ollama_url,
        docs_dir=args.docs_dir,
        summary_dir=args.summary_dir,
        generated_summaries_dir=args.generated_summaries_dir,
        results_dir=args.results_dir,
        max_samples=args.max_samples,
        batch_size=args.batch_size,
        tokenizer=args.tokenizer,
        mesh_shape=mesh_shape,
        allow_cpu_mesh=args.allow_cpu_mesh,
        long_context=args.long_context,
        long_context_quantize_kv=args.quantize_kv_long,
        quantize=args.quantize,
        quantize_act=args.quantize_act,
        tree_json_path=args.tree_json,
        max_depth=args.max_depth,
        **{
            k: v
            for k, v in overrides.items()
            if k not in ("max_depth", "tree_json_path")
        },
    )
    if args.embedding_dir:
        cfg.evaluation.embedding_dir = args.embedding_dir
    if args.include_llm_eval:
        cfg.evaluation.include_llm_eval = True
    if args.judge_backend:
        cfg.evaluation.include_llm_eval = True
        cfg.evaluation.judge_backend = args.judge_backend
    return cfg


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    from .runner import PipelineRunner

    runner = PipelineRunner(config_from_args(args))
    runner.run()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
