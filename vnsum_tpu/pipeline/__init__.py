from .runner import PipelineRunner

__all__ = ["PipelineRunner"]
