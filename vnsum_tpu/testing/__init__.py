"""Deterministic test instrumentation baked into the runtime.

Unlike ``tests/`` (which consumes the framework), this package is part of
the shipped tree so production code can carry permanently-wired, zero-cost
hooks — today, the seeded fault-injection plan (:mod:`faults`) that the
backend dispatch sites call into. Nothing here imports jax or the serving
layer, so arming a plan can never change what gets compiled.
"""
from .faults import (
    FaultPlan,
    FaultSpec,
    InjectedFault,
    InjectedResourceExhausted,
    arm,
    disarm,
    fault,
    injected,
    plan_from_env,
)

__all__ = [
    "FaultPlan",
    "FaultSpec",
    "InjectedFault",
    "InjectedResourceExhausted",
    "arm",
    "disarm",
    "fault",
    "injected",
    "plan_from_env",
]
