"""Deterministic test instrumentation baked into the runtime.

Unlike ``tests/`` (which consumes the framework), this package is part of
the shipped tree so production code can carry permanently-wired, zero-cost
hooks — the seeded fault-injection plan (:mod:`faults`) that the backend
dispatch sites call into, and the process-kill chaos helpers
(:mod:`chaos`) the durable-serving soak drives. Nothing here imports jax
or the serving layer, so arming a plan can never change what gets
compiled.
"""
from .chaos import KillPoint, KillSchedule, ServerProcess, free_port
from .faults import (
    FaultPlan,
    FaultSpec,
    InjectedFault,
    InjectedResourceExhausted,
    arm,
    disarm,
    fault,
    injected,
    plan_from_env,
)

__all__ = [
    "FaultPlan",
    "FaultSpec",
    "InjectedFault",
    "InjectedResourceExhausted",
    "KillPoint",
    "KillSchedule",
    "ServerProcess",
    "arm",
    "disarm",
    "fault",
    "free_port",
    "injected",
    "plan_from_env",
]
