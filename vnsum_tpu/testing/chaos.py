"""Process-kill chaos helpers: subprocess server lifecycle + seeded kill
schedules.

The durability layer (serve/journal.py) claims that a served process can
die at ANY instruction and no accepted request is lost. In-process fault
injection (:mod:`faults`) cannot test that claim — only actually killing
the process can. These helpers let the soak harness
(``scripts/chaos_soak.py``) and tests do it deterministically:

- :class:`ServerProcess` spawns ``python -m vnsum_tpu.serve.server`` as a
  real subprocess, waits for ``/healthz``, and exposes ``sigkill()`` (the
  crash under test: no handler runs, no drain, no seal) and ``sigterm()``
  (the graceful path under test: drain + seal + exit 0).
- :class:`KillSchedule` derives the kill points from one seed: kind
  (``mid_load`` = SIGKILL while requests are in flight, i.e. mid-prefill /
  mid-decode depending on the draw; ``mid_drain`` = SIGTERM first, then
  SIGKILL a beat into the drain) and the delay before each, so a failing
  soak replays bit-for-bit from its seed.

Like the rest of this package, nothing here imports jax or the serving
layer — the server under test lives in its own process.
"""
from __future__ import annotations

import http.client
import json
import os
import random
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass

from ..core.logging import get_logger

logger = get_logger("vnsum.testing.chaos")


def free_port() -> int:
    """An OS-assigned free TCP port (racy by nature, fine for tests)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def http_json(method: str, host: str, port: int, path: str,
              payload: dict | None = None, timeout: float = 30.0,
              headers: dict | None = None):
    """One HTTP round trip -> (status, parsed JSON body | None).
    ``headers`` adds/overrides request headers (the QoS soak's X-Tenant)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        body = json.dumps(payload) if payload is not None else None
        conn.request(method, path, body=body,
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        raw = resp.read()
        try:
            return resp.status, json.loads(raw) if raw else None
        except ValueError:
            return resp.status, None
    finally:
        conn.close()


def http_delete(host: str, port: int, path: str, timeout: float = 30.0):
    """One DELETE round trip -> (status, parsed JSON body | None) — the
    churn soak's cancel verb."""
    return http_json("DELETE", host, port, path, timeout=timeout)


def parse_sse(raw: str) -> list[tuple[str | None, dict | None]]:
    """Raw SSE body -> [(event_name, payload)] (comment-only frames like
    the ``: heartbeat`` keepalive parse as (None, None))."""
    events = []
    for frame in raw.split("\n\n"):
        if not frame.strip():
            continue
        name = data = None
        for line in frame.splitlines():
            if line.startswith("event: "):
                name = line[len("event: "):]
            elif line.startswith("data: "):
                try:
                    data = json.loads(line[len("data: "):])
                # lint-allow[swallowed-exception]: a torn frame (the abandon path cuts mid-byte) parses as data=None, which the caller treats as a non-event
                except ValueError:
                    data = None
        events.append((name, data))
    return events


def sse_stream(host: str, port: int, path: str, payload: dict,
               abandon_after: int | None = None,
               headers: dict | None = None,
               timeout: float = 60.0):
    """Drive one SSE request -> (status, events). ``abandon_after=N`` reads
    about N frames and then DROPS the connection without finishing — the
    disconnecting client the churn soak simulates; None reads to the end.
    Non-200 responses return (status, parsed-JSON-or-None) like http_json."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    resp = None
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        if resp.status != 200:
            raw = resp.read()
            try:
                return resp.status, json.loads(raw) if raw else None
            # lint-allow[swallowed-exception]: a non-JSON error body becomes None — the soak only branches on status
            except ValueError:
                return resp.status, None
        if abandon_after is None:
            return 200, parse_sse(resp.read().decode(errors="replace"))
        frames = 0
        buf = b""
        while frames < abandon_after:
            chunk = resp.fp.read1(4096)
            if not chunk:
                break
            buf += chunk
            frames = buf.count(b"\n\n")
        return 200, parse_sse(buf.decode(errors="replace"))
    finally:
        # http.client hands the socket to the response for
        # Connection: close replies — closing both covers either owner
        if resp is not None:
            try:
                resp.close()
            # lint-allow[swallowed-exception]: teardown of an already-dead socket (the abandon path's whole point) has nothing left to resolve
            except Exception:
                pass
        conn.close()


class ServerProcess:
    """One serve-server subprocess under chaos control."""

    def __init__(self, port: int, *, journal_dir: str,
                 extra_args: list[str] | None = None,
                 env: dict | None = None) -> None:
        self.port = port
        self.journal_dir = journal_dir
        self.extra_args = list(extra_args or [])
        self.env = env
        self.proc: subprocess.Popen | None = None

    def start(self) -> None:
        argv = [
            sys.executable, "-m", "vnsum_tpu.serve.server",
            "--backend", "fake",
            "--port", str(self.port),
            "--journal-dir", self.journal_dir,
            *self.extra_args,
        ]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if self.env:
            env.update(self.env)
        self.proc = subprocess.Popen(
            argv, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def wait_healthy(self, timeout_s: float = 30.0) -> None:
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            if not self.alive:
                raise RuntimeError(
                    f"server exited during startup (rc={self.proc.poll()})"
                )
            try:
                status, _ = http_json(
                    "GET", "127.0.0.1", self.port, "/healthz", timeout=2.0
                )
                if status == 200:
                    return
            except OSError:
                pass
            time.sleep(0.05)
        raise TimeoutError(f"server on :{self.port} never became healthy")

    def sigkill(self) -> None:
        """The crash under test: immediate, no handler, no drain, no seal."""
        if self.alive:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=10)

    def sigterm(self) -> None:
        """The graceful path under test: drain + journal seal + exit 0."""
        if self.alive:
            self.proc.send_signal(signal.SIGTERM)

    def wait_exit(self, timeout_s: float = 30.0) -> int:
        return self.proc.wait(timeout=timeout_s)


class RouterProcess(ServerProcess):
    """One fleet-router subprocess (serve/router.py) under chaos control:
    the router spawns and owns its N engine workers, so killing a worker
    means SIGKILLing a pid read off the router's ``/healthz`` worker
    table, not a handle we hold. Readiness is the router's ``/readyz``
    (typed 503 until a worker is routable), not ``/healthz`` liveness."""

    def __init__(self, port: int, *, fleet_dir: str, spawn_workers: int = 3,
                 extra_args: list[str] | None = None,
                 env: dict | None = None) -> None:
        super().__init__(port, journal_dir=os.path.join(fleet_dir, "router"),
                         extra_args=extra_args, env=env)
        self.fleet_dir = fleet_dir
        self.spawn_workers = spawn_workers

    def start(self) -> None:
        argv = [
            sys.executable, "-m", "vnsum_tpu.serve.router",
            "--port", str(self.port),
            "--spawn-workers", str(self.spawn_workers),
            "--fleet-dir", self.fleet_dir,
            "--backend", "fake",
            *self.extra_args,
        ]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if self.env:
            env.update(self.env)
        self.proc = subprocess.Popen(
            argv, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    def wait_ready(self, timeout_s: float = 60.0) -> None:
        """Poll the router's /readyz until 200 — replay done, >=1 worker
        routable. Startup is slower than a bare server: N worker
        subprocesses must come up first."""
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            if not self.alive:
                raise RuntimeError(
                    f"router exited during startup (rc={self.proc.poll()})"
                )
            try:
                status, _ = http_json(
                    "GET", "127.0.0.1", self.port, "/readyz", timeout=2.0
                )
                if status == 200:
                    return
            except OSError:
                pass
            time.sleep(0.05)
        raise TimeoutError(f"router on :{self.port} never became ready")

    def worker_pids(self) -> dict[str, int]:
        """Live worker name -> pid off the router's /healthz table — the
        kill-target surface for fleet chaos."""
        _, payload = http_json(
            "GET", "127.0.0.1", self.port, "/healthz", timeout=5.0
        )
        return {w["name"]: w["pid"] for w in (payload or {}).get("workers", [])
                if w.get("pid")}

    def kill_worker(self, name: str) -> int:
        """SIGKILL one spawned worker by name (the crash under test: no
        drain, no seal — the router's handoff owes its unfinished work)."""
        pid = self.worker_pids()[name]
        os.kill(pid, signal.SIGKILL)
        return pid


@dataclass(frozen=True)
class KillPoint:
    """One scheduled kill. ``kind`` is ``mid_load`` (SIGKILL while traffic
    is in flight) or ``mid_drain`` (SIGTERM, then SIGKILL ``drain_gap_s``
    into the drain); ``delay_s`` is how long load runs before the kill."""

    kind: str
    delay_s: float
    drain_gap_s: float = 0.0


class KillSchedule:
    """Seeded schedule of :class:`KillPoint`\\ s. The default shape covers
    the three regimes the acceptance criteria name: an early kill (load
    just started — requests are mid-prefill), a late kill (the batch is
    deep in decode), and a drain kill (SIGTERM received, drain underway,
    then SIGKILL). With ``qos=True`` the shape swaps one mid_load for a
    ``mid_preempt`` kill: same SIGKILL-under-load mechanics, but the server
    runs with a widened eviction->PREEMPTED-journal gap
    (VNSUM_CHAOS_PREEMPT_GAP_MS) so the kill lands inside the preemption
    window the ledger invariant must survive. Non-qos schedules are
    bit-identical to their pre-QoS draws (same seed -> same soak)."""

    def __init__(self, seed: int, kills: int = 3,
                 load_window_s: float = 1.5, qos: bool = False) -> None:
        self.seed = seed
        rng = random.Random(seed)
        kinds = (
            ["mid_preempt", "mid_load", "mid_drain"] if qos
            else ["mid_load", "mid_load", "mid_drain"]
        )
        while len(kinds) < kills:
            kinds.append(rng.choice(
                ["mid_load", "mid_drain"] + (["mid_preempt"] if qos else [])
            ))
        rng.shuffle(kinds)
        self.points = [
            KillPoint(
                kind=k,
                # early draws land mid-prefill, late draws mid-decode
                delay_s=round(rng.uniform(0.15, load_window_s), 3),
                drain_gap_s=(
                    round(rng.uniform(0.05, 0.4), 3)
                    if k == "mid_drain" else 0.0
                ),
            )
            for k in kinds[:kills]
        ]

    def describe(self) -> list[dict]:
        return [
            {"kind": p.kind, "delay_s": p.delay_s,
             "drain_gap_s": p.drain_gap_s}
            for p in self.points
        ]
