"""Seeded fault injection at named backend dispatch sites.

The serving stack's recovery behavior (serve/supervisor.py: retry, batch
bisection, the degradation ladder) is unreachable by normal tests — nothing
in a healthy FakeBackend ever raises. This module makes the stack fail ON
PURPOSE, deterministically: a :class:`FaultPlan` is a seeded list of
:class:`FaultSpec` rules bound to *sites* — stable string names the backends
call :func:`fault` with at their dispatch boundaries:

====================  ======================================================
site                  fires
====================  ======================================================
``fake.dispatch``     FakeBackend.generate entry (one-shot batch dispatch)
``fake.prefill``      inside FakeBackend's cache pass, WHILE radix pins are
                      held — the pin-leak-on-crash site
``fake.slot_admit``   FakeSlotLoop.admit entry (in-flight join)
``fake.slot_step``    FakeSlotLoop.step entry (in-flight decode segment)
``engine.dispatch``   TpuBackend.generate entry
``engine.slot_admit`` TpuSlotLoop.admit entry
``engine.slot_step``  TpuSlotLoop.step entry
``journal.fsync``     RequestJournal group-commit fsync — fires INSIDE the
                      journal lock on the scheduler thread (the mid-fsync
                      wedge the watchdog classifies as a lock stall)
====================  ======================================================

Fault kinds map one-to-one onto the supervisor's failure classes:

- ``raise``     — :class:`InjectedFault` (RuntimeError; classified TRANSIENT)
- ``resource``  — :class:`InjectedResourceExhausted` (message carries
  ``RESOURCE_EXHAUSTED``, the same string a jax OOM surfaces, so the
  supervisor's string-based classifier treats both identically)
- ``fatal``     — :class:`InjectedFault` with ``.fatal = True`` (FATAL class)
- ``poison``    — fires only when a prompt in the dispatch contains
  ``match``; deterministic per batch CONTENT, which is exactly the
  poison-request scenario bisection quarantines
- ``latency``   — sleep ``delay_s`` instead of raising (SLO pressure:
  deadline sheds, drain timeouts); the sleep is an interruptible Event
  wait, so :func:`interrupt_sleeps` (the drain path) can cut it short
- ``hang``      — block at the site until released: ``delay_s > 0`` holds
  that long ("block until released" with an automatic release), ``delay_s``
  of 0 blocks FOREVER (until :func:`release_hangs` / process death). The
  watchdog's (serve/watchdog.py) stall-detection and wedged-dispatch
  recovery paths are unreachable any other way — nothing in a healthy
  backend ever just stops returning

Arming: programmatically (:func:`arm` / :func:`injected`), or hermetically
for a whole process via ``VNSUM_FAULTS``, e.g.::

    VNSUM_FAULTS='seed=7;fake.dispatch:raise@on_call=3;\
fake.dispatch:resource@every_n=5;fake.prefill:poison@match=DOC-13'

Disarmed cost is one module-global ``is None`` check per dispatch — nothing
else; no plan object exists unless armed. Every firing is appended to
``plan.fired`` so tests assert the exact schedule that ran.
"""
from __future__ import annotations

import os
import random
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field

from ..core.logging import get_logger

logger = get_logger("vnsum.testing.faults")


class InjectedFault(RuntimeError):
    """A deliberately injected failure; ``fatal=True`` marks the
    unrecoverable class for the supervisor's classifier."""

    def __init__(self, message: str, fatal: bool = False) -> None:
        super().__init__(message)
        self.fatal = fatal
        self.injected = True


class InjectedResourceExhausted(InjectedFault):
    """Injected OOM-shaped failure. The message carries RESOURCE_EXHAUSTED
    so classification matches a real jax ``XlaRuntimeError`` OOM by string,
    not by this test-only type."""

    def __init__(self, site: str, call: int) -> None:
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected allocation failure at "
            f"{site} call {call}"
        )


_KINDS = ("raise", "resource", "fatal", "poison", "latency", "hang")


@dataclass
class FaultSpec:
    """One injection rule at one site. Exactly one of ``on_call`` /
    ``every_n`` / ``probability`` selects when it fires (call indices are
    1-based and PER SITE); ``times`` caps total firings (0 = unlimited).
    ``match`` (poison kind) is the prompt substring that triggers it."""

    site: str
    kind: str = "raise"
    on_call: int | None = None
    every_n: int | None = None
    probability: float | None = None
    times: int = 0
    delay_s: float = 0.0
    match: str = ""
    fired: int = 0

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.kind == "poison" and not self.match:
            raise ValueError("poison faults need a match= substring")
        if self.kind != "poison" and not any(
            v is not None
            for v in (self.on_call, self.every_n, self.probability)
        ):
            # a selector-less non-poison spec would silently never fire and
            # the "fault-injection run" would pass vacuously green
            raise ValueError(
                f"{self.site}:{self.kind} needs on_call=, every_n=, or "
                "probability= (poison rules alone default to "
                "whenever-matched)"
            )

    def triggers(self, call_index: int, rng: random.Random) -> bool:
        if self.times and self.fired >= self.times:
            return False
        if self.on_call is not None:
            return call_index == self.on_call
        if self.every_n is not None:
            return call_index % self.every_n == 0
        if self.probability is not None:
            return rng.random() < self.probability
        # poison rules default to "whenever the match is present"
        return self.kind == "poison"


@dataclass
class FaultPlan:
    """Seeded, observable schedule of faults across sites. Thread-safe —
    dispatch sites fire from the scheduler thread, HTTP handler threads,
    and tests concurrently."""

    specs: list[FaultSpec] = field(default_factory=list)
    seed: int = 0

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)
        self._calls: dict[str, int] = {}
        self._lock = threading.Lock()
        # (site, kind, per-site call index) per firing, for test assertions
        self.fired: list[tuple[str, str, int]] = []
        # hang kinds park on this until release_hangs() (or their own
        # delay_s elapses); latency kinds wait on the interrupt event so a
        # draining server can cut a simulated sleep short (the drain-wins
        # contract) — both are plan-scoped, so disarming forgets them
        self._hang_release = threading.Event()
        self._sleep_interrupt = threading.Event()

    def release_hangs(self) -> None:
        """Unblock every thread parked in a ``hang`` fault (tests; the
        watchdog never needs it — recovery treats the thread as lost)."""
        self._hang_release.set()

    def interrupt_sleeps(self) -> None:
        """Cut every in-flight ``latency`` sleep short AND release hangs —
        what a draining backend calls so a graceful shutdown never waits
        out an injected stall (module-level :func:`interrupt_sleeps`
        routes here for the armed plan)."""
        self._sleep_interrupt.set()
        self._hang_release.set()

    def reset_interrupts(self) -> None:
        """Re-arm latency/hang blocking after a drain: interrupts are
        one-shot Events, and a plan kept armed across a closed-and-rebuilt
        server would otherwise simulate nothing (every sleep instant,
        every hang pass-through) — a vacuously green chaos run. Called
        when a new scheduler attaches (FakeBackend.reset_drain)."""
        self._sleep_interrupt.clear()
        self._hang_release.clear()

    def calls(self, site: str) -> int:
        with self._lock:
            return self._calls.get(site, 0)

    def fire(self, site: str, prompts=None) -> None:
        """Advance ``site``'s call counter and act on the first matching
        rule: sleep for latency kinds, raise for the rest."""
        with self._lock:
            n = self._calls.get(site, 0) + 1
            self._calls[site] = n
            hit: FaultSpec | None = None
            for spec in self.specs:
                if spec.site != site or not spec.triggers(n, self._rng):
                    continue
                if spec.kind == "poison" and not any(
                    spec.match in p for p in (prompts or ())
                ):
                    continue
                spec.fired += 1
                self.fired.append((site, spec.kind, n))
                hit = spec
                break
        if hit is None:
            return
        logger.warning(
            "injecting %s at %s (call %d)", hit.kind, site, n
        )
        if hit.kind == "latency":
            # interruptible: a draining backend cuts the simulated stall
            # short via interrupt_sleeps() instead of waiting it out
            self._sleep_interrupt.wait(hit.delay_s)
        elif hit.kind == "hang":
            # the wedge under test: no exception, no return — until
            # released (delay_s > 0 auto-releases; 0 = forever). The
            # watchdog must detect and recover AROUND this thread
            self._hang_release.wait(hit.delay_s if hit.delay_s > 0 else None)
        elif hit.kind == "resource":
            raise InjectedResourceExhausted(site, n)
        elif hit.kind == "fatal":
            raise InjectedFault(f"injected fatal fault at {site} call {n}",
                                fatal=True)
        elif hit.kind == "poison":
            raise InjectedFault(
                f"injected poison fault at {site} call {n} "
                f"(match={hit.match!r})"
            )
        else:
            raise InjectedFault(f"injected fault at {site} call {n}")


def parse_plan(text: str) -> FaultPlan:
    """``seed=N;site:kind@k=v,k=v;...`` -> FaultPlan (the VNSUM_FAULTS
    format; ';' or whitespace separate entries)."""
    seed = 0
    specs: list[FaultSpec] = []
    for entry in filter(None, (e.strip() for e in text.replace(";", " ").split())):
        if entry.startswith("seed="):
            seed = int(entry[len("seed="):])
            continue
        head, _, args = entry.partition("@")
        site, _, kind = head.partition(":")
        if not site or not kind:
            raise ValueError(f"malformed VNSUM_FAULTS entry {entry!r}")
        kw: dict = {}
        for pair in filter(None, args.split(",")):
            k, _, v = pair.partition("=")
            if k in ("on_call", "every_n", "times"):
                kw[k] = int(v)
            elif k in ("probability", "delay_s"):
                kw[k] = float(v)
            elif k == "match":
                kw[k] = v
            else:
                raise ValueError(f"unknown fault arg {k!r} in {entry!r}")
        specs.append(FaultSpec(site=site, kind=kind, **kw))
    return FaultPlan(specs=specs, seed=seed)


def plan_from_env() -> FaultPlan | None:
    """Parse ``VNSUM_FAULTS`` (None when unset/empty)."""
    text = os.environ.get("VNSUM_FAULTS", "").strip()
    return parse_plan(text) if text else None


# the armed plan; None = disarmed (the only state production ever sees).
# Written by arm()/disarm() only; sites read it racily — an in-flight
# dispatch may miss a plan armed mid-call, never crash.
_PLAN: FaultPlan | None = plan_from_env()


def arm(plan: FaultPlan) -> FaultPlan:
    global _PLAN
    _PLAN = plan
    return plan


def disarm() -> None:
    global _PLAN
    _PLAN = None


@contextmanager
def injected(plan: FaultPlan):
    """Arm ``plan`` for the with-block; restores the prior plan on exit."""
    global _PLAN
    prev = _PLAN
    _PLAN = plan
    try:
        yield plan
    finally:
        _PLAN = prev


def fault(site: str, prompts=None) -> None:
    """THE dispatch-site hook: free when disarmed (one global read)."""
    if _PLAN is not None:
        _PLAN.fire(site, prompts)


def interrupt_sleeps() -> None:
    """Cut the armed plan's latency sleeps short and release its hangs —
    the backend drain hook (FakeBackend.request_drain). No-op when
    disarmed."""
    if _PLAN is not None:
        _PLAN.interrupt_sleeps()


def reset_interrupts() -> None:
    """Undo :func:`interrupt_sleeps` on the armed plan — a NEW server
    attaching to a still-armed plan must get real latency/hang simulation,
    not the previous drain's pass-through. No-op when disarmed."""
    if _PLAN is not None:
        _PLAN.reset_interrupts()
