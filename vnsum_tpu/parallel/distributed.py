"""Multi-host (multi-process) runtime: jax.distributed + hybrid ICI/DCN mesh.

The reference's only cross-process channel is HTTP to a local Ollama server
(SURVEY.md §2.2 "Distributed comm backend: None"). The TPU-native equivalent
is the single-controller JAX model: every host runs this same program,
`jax.distributed.initialize` wires the cluster, and GSPMD inserts the
collectives — over ICI within a slice, over DCN between slices. Nothing here
issues an RPC by hand.

Axis placement follows the scaling-book recipe: put *data* parallelism on
DCN (gradient/batch all-reduces amortize over a whole step) and keep
*model*/*seq* axes inside a slice on ICI (their collectives sit on the
critical path of every matmul).

Typical multi-host entry:

    from vnsum_tpu.parallel import init_distributed, make_hybrid_mesh
    init_distributed()                       # env-driven (JAX_COORDINATOR...)
    mesh = make_hybrid_mesh(ici={"model": 4, "data": 2}, dcn={"data": 4})
"""
from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import AXES, make_mesh

_INITIALIZED = False


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids=None,
) -> bool:
    """Initialize the JAX distributed runtime for multi-host execution.

    Arguments fall back to the standard environment (JAX_COORDINATOR_ADDRESS
    / JAX_NUM_PROCESSES / JAX_PROCESS_ID, or the cloud-TPU metadata that
    jax.distributed auto-detects). Returns True if the runtime was (or had
    already been) initialized, False when running single-process with no
    cluster configuration — callers can treat False as "local mode".
    """
    global _INITIALIZED
    if _INITIALIZED:
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    explicit = coordinator_address is not None or num_processes not in (None, 1)
    if not explicit and not _cluster_env_detected():
        return False  # single-process dev box: nothing to wire
    try:
        # with no explicit args this uses jax.distributed's own auto-detect
        # (cloud-TPU metadata, Slurm, Open MPI)
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
    except (RuntimeError, ValueError) as e:
        # jax raises RuntimeError when the backend is already up (or on a
        # second initialize) and ValueError when auto-detect finds no
        # coordinator metadata
        if "only be called once" in str(e):
            # a launch script initialized the runtime before us; that
            # satisfies this call's contract
            pass
        elif not explicit:
            # auto-detect is best-effort: a cluster-looking env with no
            # usable metadata (or an already-up backend) degrades to local
            # mode instead of crashing single-host runs
            from ..core.logging import get_logger

            get_logger("vnsum.distributed").warning(
                "distributed auto-init failed, continuing single-process: %s", e
            )
            return False
        else:
            raise
    _INITIALIZED = True
    return True


def _cluster_env_detected() -> bool:
    """Heuristic for managed multi-host launchers whose auto-detect
    jax.distributed.initialize understands. Checked via env only — probing
    jax.devices() here would initialize the local backend and break a later
    distributed init."""
    markers = (
        "TPU_WORKER_HOSTNAMES",   # cloud TPU pod slice
        "MEGASCALE_COORDINATOR_ADDRESS",  # multislice
        "SLURM_JOB_NUM_NODES",
        "OMPI_COMM_WORLD_SIZE",
    )
    if os.environ.get("SLURM_JOB_NUM_NODES", "1") != "1":
        return True
    if os.environ.get("OMPI_COMM_WORLD_SIZE", "1") != "1":
        return True
    return any(os.environ.get(m) for m in markers[:2])


def process_count() -> int:
    return jax.process_count()


def is_primary() -> bool:
    """True on process 0 — gate log files, checkpoint writes, report emission."""
    return jax.process_index() == 0


def barrier(name: str = "vnsum") -> None:
    """Block until every process reaches this point (no-op single-process)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def make_hybrid_mesh(
    ici: dict[str, int] | None = None,
    dcn: dict[str, int] | None = None,
    *,
    platform: str | None = None,
) -> Mesh:
    """Mesh spanning multiple slices: per-axis ICI sizes within a slice and
    DCN sizes across slices. Falls back to a plain single-slice mesh when
    every DCN size is 1 (so single-host code can call this unconditionally).

    The resulting axis size is ici[axis] * dcn[axis]; device order within an
    axis puts the DCN dimension major, so shardings that keep `model`/`seq`
    DCN-free never send matmul collectives over the slow network.
    """
    ici = dict(ici or {})
    dcn = dict(dcn or {})
    names = (AXES.data, AXES.model, AXES.seq)
    unknown = (set(ici) | set(dcn)) - set(names)
    if unknown:
        raise ValueError(f"unknown mesh axes: {sorted(unknown)}")
    for ax in names:
        ici.setdefault(ax, 1)
        dcn.setdefault(ax, 1)

    if int(np.prod(list(dcn.values()))) == 1:
        return make_mesh(ici, platform=platform)

    from jax.experimental import mesh_utils

    n_slices = int(np.prod(list(dcn.values())))
    if jax.process_count() < n_slices:
        raise ValueError(
            f"hybrid mesh wants {n_slices} slices over DCN but only "
            f"{jax.process_count()} process(es) are attached — run under "
            "init_distributed() on a multi-slice deployment"
        )
    devices = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=[ici[ax] for ax in names],
        dcn_mesh_shape=[dcn[ax] for ax in names],
        devices=jax.devices(platform) if platform else jax.devices(),
    )
    return Mesh(devices, names)
