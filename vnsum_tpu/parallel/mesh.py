"""Device mesh construction.

The reference has no distributed backend at all (SURVEY.md §2.2 — its only
cross-process channel is HTTP to Ollama). Here parallelism is expressed the
TPU-native way: a named `jax.sharding.Mesh` over ICI, `NamedSharding`
annotations, and GSPMD-inserted collectives under `jit`.

Axis conventions (scaling-book style):
    data   — batch / document-chunk batch (DP)
    model  — attention heads + MLP hidden (TP, megatron-style)
    seq    — sequence/context parallelism for ring attention (SP)
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

# shard_map moved homes across JAX releases: top-level `jax.shard_map` on
# new versions, `jax.experimental.shard_map.shard_map` before that. Every
# in-repo user (ring attention, long-context engine, sharded kernel
# wrappers) imports THIS name so the version probe lives in one place.
try:
    from jax import shard_map  # jax >= 0.5-ish exports it at top level
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map
if not callable(shard_map):  # some versions export the submodule instead
    shard_map = shard_map.shard_map

# the replication-check kwarg was renamed check_rep -> check_vma; accept the
# new spelling everywhere and translate for older installs
import inspect as _inspect

if "check_vma" not in _inspect.signature(shard_map).parameters:
    _shard_map_raw = shard_map

    def shard_map(*args, **kwargs):  # noqa: F811 - deliberate compat rebind
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_raw(*args, **kwargs)


def axis_size(axis_name: str) -> int:
    """Static size of a named mesh axis from inside a shard_map body.
    ``jax.lax.axis_size`` is a late addition; ``psum`` of the constant 1 is
    the classic spelling and stays static (a Python int) at trace time."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


__all__ = [
    "AXES", "MeshAxes", "axis_size", "make_mesh", "mesh_from_spec",
    "shard_map",
]


@dataclass(frozen=True)
class MeshAxes:
    data: str = "data"
    model: str = "model"
    seq: str = "seq"
    fsdp: str = "fsdp"  # stacked-layer (stage) sharding; weights all-gather
    #                     per layer-scan step, FSDP/ZeRO-3 style


AXES = MeshAxes()


def make_mesh(
    shape: dict[str, int] | None = None, *, platform: str | None = None
) -> Mesh:
    """Build a Mesh from {axis: size}. Missing sizes default to 1; a single
    -1 entry absorbs the remaining devices (like a reshape wildcard).

    ``platform`` selects a device kind explicitly (e.g. "cpu" for the
    8-virtual-device host mesh used in tests; the axon TPU plugin keeps TPU
    as default backend regardless of JAX_PLATFORMS)."""
    devices = jax.devices(platform) if platform else jax.devices()
    n = len(devices)
    shape = dict(shape or {})
    for ax in (AXES.data, AXES.model, AXES.seq):
        shape.setdefault(ax, 1)
    # the fsdp axis is opt-in: only materialize it when requested, so
    # existing (data, model, seq) meshes keep their shape
    if AXES.fsdp in shape and shape[AXES.fsdp] in (1, None):
        shape.pop(AXES.fsdp)
    wild = [ax for ax, s in shape.items() if s == -1]
    if len(wild) > 1:
        raise ValueError("at most one mesh axis may be -1")
    fixed = int(np.prod([s for s in shape.values() if s != -1]))
    if wild:
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by fixed axes {fixed}")
        shape[wild[0]] = n // fixed
    total = int(np.prod(list(shape.values())))
    if total > n:
        raise ValueError(f"mesh shape {shape} needs {total} devices, have {n}")
    names = tuple(shape.keys())
    dims = tuple(shape[k] for k in names)
    return Mesh(np.asarray(devices[:total]).reshape(dims), names)


def mesh_from_spec(spec: str) -> Mesh:
    """Parse "data=2,model=4" into a Mesh."""
    shape: dict[str, int] = {}
    for part in spec.split(","):
        if not part.strip():
            continue
        k, v = part.split("=")
        shape[k.strip()] = int(v)
    return make_mesh(shape)
