"""Device mesh construction.

The reference has no distributed backend at all (SURVEY.md §2.2 — its only
cross-process channel is HTTP to Ollama). Here parallelism is expressed the
TPU-native way: a named `jax.sharding.Mesh` over ICI, `NamedSharding`
annotations, and GSPMD-inserted collectives under `jit`.

Axis conventions (scaling-book style):
    data   — batch / document-chunk batch (DP)
    model  — attention heads + MLP hidden (TP, megatron-style)
    seq    — sequence/context parallelism for ring attention (SP)
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh


@dataclass(frozen=True)
class MeshAxes:
    data: str = "data"
    model: str = "model"
    seq: str = "seq"
    fsdp: str = "fsdp"  # stacked-layer (stage) sharding; weights all-gather
    #                     per layer-scan step, FSDP/ZeRO-3 style


AXES = MeshAxes()


def make_mesh(
    shape: dict[str, int] | None = None, *, platform: str | None = None
) -> Mesh:
    """Build a Mesh from {axis: size}. Missing sizes default to 1; a single
    -1 entry absorbs the remaining devices (like a reshape wildcard).

    ``platform`` selects a device kind explicitly (e.g. "cpu" for the
    8-virtual-device host mesh used in tests; the axon TPU plugin keeps TPU
    as default backend regardless of JAX_PLATFORMS)."""
    devices = jax.devices(platform) if platform else jax.devices()
    n = len(devices)
    shape = dict(shape or {})
    for ax in (AXES.data, AXES.model, AXES.seq):
        shape.setdefault(ax, 1)
    # the fsdp axis is opt-in: only materialize it when requested, so
    # existing (data, model, seq) meshes keep their shape
    if AXES.fsdp in shape and shape[AXES.fsdp] in (1, None):
        shape.pop(AXES.fsdp)
    wild = [ax for ax, s in shape.items() if s == -1]
    if len(wild) > 1:
        raise ValueError("at most one mesh axis may be -1")
    fixed = int(np.prod([s for s in shape.values() if s != -1]))
    if wild:
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by fixed axes {fixed}")
        shape[wild[0]] = n // fixed
    total = int(np.prod(list(shape.values())))
    if total > n:
        raise ValueError(f"mesh shape {shape} needs {total} devices, have {n}")
    names = tuple(shape.keys())
    dims = tuple(shape[k] for k in names)
    return Mesh(np.asarray(devices[:total]).reshape(dims), names)


def mesh_from_spec(spec: str) -> Mesh:
    """Parse "data=2,model=4" into a Mesh."""
    shape: dict[str, int] = {}
    for part in spec.split(","):
        if not part.strip():
            continue
        k, v = part.split("=")
        shape[k.strip()] = int(v)
    return make_mesh(shape)
