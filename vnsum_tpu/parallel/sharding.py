"""PartitionSpec trees for model state (megatron-style tensor parallelism).

Weights are sharded on the head / hidden dimensions over the `model` axis;
batches over `data`. GSPMD inserts the all-gathers / reduce-scatters over ICI
— nothing here issues a collective by hand (scaling-book recipe; contrast
SURVEY.md §2.2: the reference has no parallelism to port).
"""
from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from .mesh import AXES

_D, _M = AXES.data, AXES.model


def param_specs(tie_embeddings: bool = True) -> dict[str, Any]:
    """PartitionSpec pytree matching models.llama param structure.

    Layer leaves carry a leading stacked-layer dim (scanned), hence the
    leading None in every layer spec.
    """
    specs = {
        "embed": P(_M, None),          # vocab-sharded embedding
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, _M, None),   # [L, D, nh, hd] — heads sharded
            "wk": P(None, None, _M, None),
            "wv": P(None, None, _M, None),
            "wo": P(None, _M, None, None),   # [L, nh, hd, D]
            "mlp_norm": P(None, None),
            "w_gate": P(None, None, _M),     # [L, D, I] — hidden sharded
            "w_up": P(None, None, _M),
            "w_down": P(None, _M, None),     # [L, I, D]
        },
        "final_norm": P(None),
    }
    if not tie_embeddings:
        specs["lm_head"] = P(None, _M)       # [D, V]
    return specs


def cache_specs() -> dict[str, Any]:
    """KV cache [L, B, C, kv_heads, hd]: batch over data, heads over model."""
    return {"k": P(None, _D, None, _M, None), "v": P(None, _D, None, _M, None)}


def batch_spec() -> P:
    """[B, S] token batches shard over data."""
    return P(_D, None)


def param_shardings(mesh: Mesh, tie_embeddings: bool = True) -> dict[str, Any]:
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        param_specs(tie_embeddings),
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Any, mesh: Mesh, tie_embeddings: bool = True) -> Any:
    """Place a param pytree onto the mesh with TP shardings."""
    shardings = param_shardings(mesh, tie_embeddings)
    return jax.tree.map(jax.device_put, params, shardings)
