"""PartitionSpec trees for model state (megatron-style tensor parallelism).

Weights are sharded on the head / hidden dimensions over the `model` axis;
batches over `data`. GSPMD inserts the all-gathers / reduce-scatters over ICI
— nothing here issues a collective by hand (scaling-book recipe; contrast
SURVEY.md §2.2: the reference has no parallelism to port).
"""
from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from .mesh import AXES

_D, _M, _F = AXES.data, AXES.model, AXES.fsdp


def param_specs(
    tie_embeddings: bool = True,
    quantized: bool = False,
    fsdp: bool = False,
    qk_norm: bool = False,
    sandwich_norms: bool = False,
) -> dict[str, Any]:
    """PartitionSpec pytree matching models.llama param structure.

    Layer leaves carry a leading stacked-layer dim (scanned); with
    ``fsdp=True`` that dim is sharded over the `fsdp` mesh axis (ZeRO-3
    style: each layer-scan step all-gathers just that layer's weights, so
    per-device parameter + optimizer memory drops by the axis size).

    With ``quantized=True`` the tree matches models.quant.quantize_params
    output: each matmul weight becomes ``{"q": <weight spec>, "s": <scale
    spec>}`` where the scale spec is the weight spec with the contracted
    axes removed (a per-output-channel scale lives on the output axes, so it
    inherits exactly their sharding).
    """
    L = _F if fsdp else None  # leading stacked-layer dim of every layer leaf
    specs = {
        "embed": P(_M, None),          # vocab-sharded embedding
        "layers": {
            "attn_norm": P(L, None),
            "wq": P(L, None, _M, None),      # [L, D, nh, hd] — heads sharded
            "wk": P(L, None, _M, None),
            "wv": P(L, None, _M, None),
            "wo": P(L, _M, None, None),      # [L, nh, hd, D]
            "mlp_norm": P(L, None),
            "w_gate": P(L, None, _M),        # [L, D, I] — hidden sharded
            "w_up": P(L, None, _M),
            "w_down": P(L, _M, None),        # [L, I, D]
        },
        "final_norm": P(None),
    }
    if qk_norm:
        # per-head Q/K norms [L, hd]: tiny, replicated over model
        specs["layers"]["q_norm"] = P(L, None)
        specs["layers"]["k_norm"] = P(L, None)
    if sandwich_norms:
        specs["layers"]["post_attn_norm"] = P(L, None)
        specs["layers"]["post_ffw_norm"] = P(L, None)
    if not tie_embeddings:
        specs["lm_head"] = P(None, _M)       # [D, V]
    if quantized:
        from ..models.quant import _CONTRACT_AXES

        def qspec(spec: P, contract_axes: tuple[int, ...]) -> dict:
            scale = P(*(ax for i, ax in enumerate(spec) if i not in contract_axes))
            return {"q": spec, "s": scale}

        for name, axes in _CONTRACT_AXES.items():
            shifted = tuple(a + 1 for a in axes)  # leading stacked-L dim
            specs["layers"][name] = qspec(specs["layers"][name], shifted)
        specs["embed"] = qspec(specs["embed"], (1,))
        if not tie_embeddings:
            specs["lm_head"] = qspec(specs["lm_head"], (0,))
    return specs


def cache_specs(quantized: bool = False) -> dict[str, Any]:
    """KV cache [L, B, kv_heads, C, hd]: batch over data, heads over model.

    With ``quantized=True`` adds the int8-cache per-(token, head) scale
    planes [L, B, kv_heads, C], which shard exactly like their cache dims.
    """
    kv = P(None, _D, _M, None, None)
    specs: dict[str, Any] = {"k": kv, "v": kv}
    if quantized:
        scale = P(None, _D, _M, None)
        specs["ks"] = scale
        specs["vs"] = scale
    return specs


def batch_spec() -> P:
    """[B, S] token batches shard over data."""
    return P(_D, None)


def param_shardings(
    mesh: Mesh,
    tie_embeddings: bool = True,
    quantized: bool = False,
    fsdp: bool = False,
    qk_norm: bool = False,
    sandwich_norms: bool = False,
) -> dict[str, Any]:
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        param_specs(tie_embeddings, quantized, fsdp, qk_norm, sandwich_norms),
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Any, mesh: Mesh, tie_embeddings: bool = True) -> Any:
    """Place a param pytree onto the mesh with TP shardings.

    Raises a config-level error (which sharded dim, which axis) instead of
    letting device_put surface a raw XLA divisibility failure.
    """
    from ..models.quant import is_quantized

    quantized = is_quantized(params)
    qk_norm = "q_norm" in params["layers"]
    sandwich = "post_attn_norm" in params["layers"]
    specs = param_specs(
        tie_embeddings, quantized, qk_norm=qk_norm, sandwich_norms=sandwich
    )

    def check(leaf, spec):
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            size = mesh.shape.get(axis, 1)
            if leaf.shape[dim] % size:
                raise ValueError(
                    f"param dim {dim} (size {leaf.shape[dim]}) is not "
                    f"divisible by mesh axis '{axis}' ({size}); shrink that "
                    "mesh axis or pick a TP-compatible model config"
                )

    jax.tree.map(check, params, specs, is_leaf=lambda x: isinstance(x, P))
    shardings = param_shardings(
        mesh, tie_embeddings, quantized, qk_norm=qk_norm,
        sandwich_norms=sandwich,
    )
    return jax.tree.map(jax.device_put, params, shardings)
