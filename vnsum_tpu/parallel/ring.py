"""Ring attention: sequence/context parallelism over the `seq` mesh axis.

The reference handles long context purely algorithmically (chunk + collapse,
SURVEY.md §5); this gives the framework true sequence parallelism so a single
forward can span sequences longer than one chip's memory. Blockwise design
following the ring-attention pattern (Liu et al.): K/V blocks rotate around
the ring via `ppermute` while each device keeps its Q block and accumulates
flash-style online-softmax partial results — compute overlaps the ICI
transfer and no device ever materializes the full [S, S] score matrix.

Implemented with `shard_map` over the full mesh: batch and heads are data-
local (no collectives), only `seq` communicates.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from .mesh import AXES, axis_size, shard_map

_NEG = jnp.float32(-1e30)


def _ring_local(qb, kb, vb, pad_lens, q_per_kv: int, axis_name: str, causal: bool):
    """Per-device body. qb [B, Sq, H, hd], kb/vb [B, Sk, KV, hd] (local);
    pad_lens [B] (or None) masks out the left-padding slots of each row."""
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Sq, H, hd = qb.shape
    KV = kb.shape[2]
    G = q_per_kv
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))

    qg = qb.reshape(B, Sq, KV, G, hd)
    q_pos = idx * Sq + jnp.arange(Sq)

    # derive accumulators from q so they carry the same varying-manual-axes
    # type as the loop outputs (fresh zeros would be "unvarying" and trip
    # shard_map's carry check)
    qt = qg.transpose(0, 2, 3, 1, 4).astype(jnp.float32)  # [B, KV, G, Sq, hd]
    o0 = qt * 0.0
    m0 = qt[..., 0] * 0.0 + _NEG
    l0 = qt[..., 0] * 0.0

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        o, m, l, k_cur, v_cur = carry
        src = (idx - i) % n  # ring: who this K/V block belongs to
        scores = (
            jnp.einsum("bskgh,bckh->bkgsc", qg, k_cur,
                       preferred_element_type=jnp.float32)
            * scale
        )
        k_pos = src * Sq + jnp.arange(k_cur.shape[1])
        allowed = None
        if causal:
            allowed = jnp.broadcast_to(
                q_pos[:, None] >= k_pos[None, :], (B, Sq, k_cur.shape[1])
            )
        if pad_lens is not None:
            valid = k_pos[None, None, :] >= pad_lens[:, None, None]
            allowed = valid if allowed is None else allowed & valid
        if allowed is not None:
            # scores [B, KV, G, Sq, Sk]; allowed [B, Sq, Sk]
            scores = jnp.where(allowed[:, None, None], scores, _NEG)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        if allowed is not None:
            # a fully-masked block would otherwise give exp(_NEG-_NEG)=1
            p = jnp.where(allowed[:, None, None], p, 0.0)
        l = l * correction + jnp.sum(p, axis=-1)
        o = o * correction[..., None] + jnp.einsum(
            "bkgsc,bckh->bkgsh", p, v_cur.astype(jnp.float32)
        )
        def rotate(kv):
            k_c, v_c = kv
            return (
                jax.lax.ppermute(k_c, axis_name, perm),
                jax.lax.ppermute(v_c, axis_name, perm),
            )

        # the last block's rotation would be discarded — skip the transfer
        # (predicate is uniform across devices, so cond is collective-safe)
        k_next, v_next = jax.lax.cond(
            i < n - 1, rotate, lambda kv: kv, (k_cur, v_cur)
        )
        return o, m_new, l, k_next, v_next

    o, m, l, _, _ = jax.lax.fori_loop(0, n, step, (o0, m0, l0, kb, vb))
    out = o / jnp.maximum(l[..., None], 1e-30)
    # [B, KV, G, Sq, hd] -> [B, Sq, H, hd]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, Sq, H, hd).astype(qb.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_per_kv: int,
    *,
    mesh: Mesh,
    causal: bool = True,
    pad_lens: jax.Array | None = None,
):
    """Drop-in attention_fn for models.llama.forward_train: global views
    [B, S, H|KV, hd], sequence dim sharded over the `seq` axis. ``pad_lens``
    [B] (engine-style left padding) masks the pad slots of each row so the
    long-context prefill can reuse the ring."""
    spec_q = P(AXES.data, AXES.seq, AXES.model, None)
    spec_kv = P(AXES.data, AXES.seq, AXES.model, None)

    if pad_lens is None:
        fn = shard_map(
            partial(
                _ring_local,
                pad_lens=None,
                q_per_kv=q_per_kv,
                axis_name=AXES.seq,
                causal=causal,
            ),
            mesh=mesh,
            in_specs=(spec_q, spec_kv, spec_kv),
            out_specs=spec_q,
        )
        return fn(q, k, v)
    fn = shard_map(
        partial(
            _ring_local, q_per_kv=q_per_kv, axis_name=AXES.seq, causal=causal
        ),
        mesh=mesh,
        in_specs=(spec_q, spec_kv, spec_kv, P(AXES.data)),
        out_specs=spec_q,
    )
    return fn(q, k, v, pad_lens)
