from .distributed import (
    barrier,
    init_distributed,
    is_primary,
    make_hybrid_mesh,
    process_count,
)
from .mesh import MeshAxes, make_mesh, mesh_from_spec
from .sharding import batch_spec, param_shardings, param_specs, shard_params

__all__ = [
    "MeshAxes",
    "barrier",
    "init_distributed",
    "is_primary",
    "make_hybrid_mesh",
    "make_mesh",
    "mesh_from_spec",
    "process_count",
    "batch_spec",
    "param_shardings",
    "param_specs",
    "shard_params",
]
