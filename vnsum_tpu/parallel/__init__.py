from .mesh import MeshAxes, make_mesh, mesh_from_spec
from .sharding import batch_spec, param_shardings, param_specs, shard_params

__all__ = [
    "MeshAxes",
    "make_mesh",
    "mesh_from_spec",
    "batch_spec",
    "param_shardings",
    "param_specs",
    "shard_params",
]
