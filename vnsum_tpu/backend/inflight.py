"""In-flight batching: a persistent slot-based decode loop with refill.

The serving path used to be Orca-before-Orca: the scheduler coalesced a
micro-batch, called a blocking ``backend.generate``, and every request that
arrived during that batch's decode waited out the full prefill+decode of
strangers. The engine already owned every ingredient of iteration-level
scheduling — segmented decode with a host-visible boundary, tail compaction,
chunked prefill, prefix-cache resume — and this module assembles them into
the missing loop (Orca OSDI'22; vLLM/PagedAttention arXiv:2309.06180's
continuous batching is the same lever over paged memory):

- a long-lived fixed-shape batch of B *slots* (one compiled program set per
  loop — no per-batch bucketing churn);
- per-slot state (budget ``t``, done flag, RNG uid, output cursor) is
  slot-indexed, so rows at different generation depths coexist
  (``engine._make_slot_segment_fn``'s per-row budgets);
- at every segment boundary, finished rows are harvested and freed slots
  are REFILLED from waiting prompts: joiners get chunked prefill (optionally
  resumed from the radix prefix cache) into a small join batch, then an
  adopt program scatters their cache rows into the resident stacked cache
  (``engine._make_adopt_fn``) and they decode together with residents.

Greedy per-request outputs stay byte-identical to the one-shot path (same
caveat class as compaction: identical per-row math, batch-shape tiling can
flip near-tie last bits on real hardware; CPU/interpret runs are exact).
Sampled streams key on (loop seed, per-request uid, row-local step), so a
request's randomness is independent of its slot, its join segment, and its
companions.

The loop is driven from ONE thread (the serving scheduler's contract —
engine access is single-threaded); nothing here locks.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from ..analysis.sanitizers import hot_path_transfer_guard
from ..core.logging import get_logger
from ..obs.trace import current_collector, emit
from ..testing.faults import fault
from .base import left_pad_batch

# jax is imported lazily (TpuSlotLoop.__init__): the shared record types
# below also serve FakeBackend's hermetic slot loop, which must not pay a
# cold jax import on its first admission

logger = get_logger("vnsum.inflight")


@dataclass
class SlotAdmission:
    """One request's admission into the loop (the TTFT anchor rides here:
    ``prefill_end`` is the sync-bounded host time the joiner's own prefill
    finished — anchored at the JOINER's prefill, not a shared batch's)."""

    key: object
    slot: int
    admitted_at: float          # time.monotonic() at admit entry
    prefill_end: float          # time.monotonic() after the prefill sync
    prompt_tokens: int = 0
    cached_tokens: int = 0      # prompt tokens resumed from the prefix cache
    occupancy: int = 0          # busy slots right after this admit


@dataclass
class SlotCompletion:
    """One finished request harvested at a segment boundary."""

    key: object
    text: str
    slot: int
    gen_tokens: int = 0


@dataclass
class SegmentResult:
    """One decode dispatch's outcome (up to ``fused_segments`` on-device
    segment boundaries per dispatch — N=1 is the classic one-segment step)."""

    completions: list = field(default_factory=list)
    live: int = 0               # rows live at dispatch start
    new_tokens: int = 0         # tokens retired across all rows this dispatch
    seconds: float = 0.0
    device_segments: int = 1    # segments the fused dispatch actually ran


@dataclass
class SlotEviction:
    """One request preempted out of its decode slot (serve/qos.py priority
    tiers). ``pin`` is a ``(cache, match)`` pair the loop took on the
    request's prompt prefix at eviction — the blocks stay pinned against
    LRU until the SCHEDULER releases them at the request's terminal
    resolution, so the restarted prefill resumes warm (None when no prefix
    cache is configured)."""

    key: object
    slot: int
    pin: object = None


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


class TpuSlotLoop:
    """Slot bookkeeping + program driving for TpuBackend's in-flight loop.

    Built by ``TpuBackend.start_slot_loop``; the compiled programs live in
    the backend's ``_seg_fns`` cache (``slot_prefill`` / ``slot_seg`` /
    ``adopt``), so loops over the same geometry reuse executables.
    """

    def __init__(self, backend, slots: int, S: int, max_new: int, gen,
                 seed: int, fused_segments: int = 1) -> None:
        import jax.numpy as jnp

        self.backend = backend
        self.slots = int(slots)
        self.S = int(S)
        self.max_new = int(max_new)
        self.gen = gen
        self.seed = seed
        # fused multi-step decode (Kernel Looping, arXiv 2410.23668): one
        # dispatch covers up to N on-device segment boundaries, and the
        # host polls array readiness instead of blocking per segment
        self.fused_segments = max(int(fused_segments), 1)
        b = backend
        B = self.slots
        # resident device state: every slot starts FREE (all-pad, done)
        self._cache = b._init_prefill_cache(B, S + max_new)
        self._cur = jnp.zeros((B,), jnp.int32)
        self._done = jnp.ones((B,), bool)
        self._t = jnp.zeros((B,), jnp.int32)
        self._out = jnp.full((B, max_new), b.tok.pad_id, jnp.int32)
        self._pads = jnp.full((B,), S, jnp.int32)
        if b.mesh is not None:
            # pin the per-slot vectors to the cache's batch layout (rows
            # over `data`) instead of leaving them on the default device for
            # GSPMD to re-layout on every segment dispatch
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            row = NamedSharding(b.mesh, P("data"))
            self._cur = jax.device_put(self._cur, row)
            self._done = jax.device_put(self._done, row)
            self._t = jax.device_put(self._t, row)
            self._out = jax.device_put(
                self._out, NamedSharding(b.mesh, P("data", None))
            )
            self._pads = jax.device_put(self._pads, row)
        # host-side slot table: caller key per busy slot (None = free),
        # per-request RNG uid, last fetched per-row t; prompts are kept so
        # the fault-injection poison matcher sees residents at every
        # segment, symmetric with FakeSlotLoop
        self._keys: list = [None] * B
        self._prompts: list[str | None] = [None] * B
        self._uids: list[int] = [0] * B
        self._admissions: dict[int, SlotAdmission] = {}
        self._t_host = np.zeros((B,), np.int64)
        self._uid_next = 0
        self.segments = 0           # on-device segments retired
        self.fused_dispatches = 0   # host dispatches (== segments at N=1)
        self.refills = 0
        # boundary out-buffer snapshot: when step(fetch_outputs=True) rode
        # the control fetch, partial_outputs serves from it instead of
        # paying a second d2h per boundary (None = no snapshot resident)
        self._out_snap = None
        self._closed = False

    # -- introspection ---------------------------------------------------

    @property
    def active(self) -> int:
        return sum(1 for k in self._keys if k is not None)

    @property
    def free(self) -> int:
        return self.slots - self.active

    # -- admission (prefill + adopt) -------------------------------------

    # hot path
    def admit(self, items) -> tuple[list[SlotAdmission], list]:
        """Admit up to the free-slot budget from ``items`` (an iterable of
        ``(key, prompt, cache_hint)``). Returns (admissions, rejected_keys):
        rejected keys had prompts longer than the loop's S budget and must
        be routed through the one-shot path by the caller; items beyond the
        admitted count are simply not consumed (the caller retries at the
        next segment boundary). Join groups are bucketed to data_size * 2^k
        (power of two single-chip; multiples of the mesh data axis sharded,
        so join rows always divide over `data`) and capped at the free-slot
        count so every scatter target — including all-pad filler rows —
        lands on a distinct free slot; with fewer free slots than data_size
        the admit defers to the next boundary."""
        if self._closed:
            raise RuntimeError("slot loop is closed")
        import jax
        import jax.numpy as jnp

        b = self.backend
        t_admit = time.monotonic()
        tracing = current_collector() is not None
        items = list(items)
        if not items or not self.free:
            return [], []
        # seeded fault injection (vnsum_tpu.testing.faults); no-op unless a
        # plan is armed. Any raise propagates with the matched chains still
        # unpinned (matching happens below) or released by the finally
        fault("engine.slot_admit", prompts=[it[1] for it in items])
        keys = [it[0] for it in items]
        prompts = [it[1] for it in items]
        hints = [it[2] for it in items]
        encoded = b.tok.encode_batch(prompts, add_bos=True)
        rejected = [
            keys[i] for i in range(len(items)) if len(encoded[i]) > self.S
        ]
        ok = [i for i in range(len(items)) if len(encoded[i]) <= self.S]
        if not ok:
            return [], rejected
        free_slots = [s for s, k in enumerate(self._keys) if k is None]
        n = min(len(ok), len(free_slots))
        # the join bucket starts at the mesh data-axis size (join batches
        # shard their rows over `data` exactly like the resident batch, so
        # Bj must stay divisible by it; 1 single-chip) and grows by doubling
        data_size = (
            b.mesh.shape.get("data", 1) if b.mesh is not None else 1
        )
        Bj = data_size
        while Bj < n:
            Bj *= 2
        if Bj > len(free_slots):
            # the bucket's filler rows need free slots too — shrink the
            # admit to the largest data_size * 2^k that fits outright; with
            # fewer free slots than DP rows need, wait for the next boundary
            if len(free_slots) < data_size:
                return [], rejected
            n = Bj = _pow2_floor(len(free_slots) // data_size) * data_size
        take = ok[:n]

        pc = b.prefix_cache
        matches = None
        if pc is not None:
            matches = {i: pc.match(encoded[i], max_tokens=len(encoded[i]) - 1)
                       for i in take}
            # order the join group by UNCOVERED suffix so its shared resume
            # boundary K is as deep as the coldest row allows (same policy
            # as generate()'s cache ordering)
            take.sort(key=lambda i: (len(encoded[i]) - matches[i].tokens,
                                     len(encoded[i])))
        try:
            group_ids = [encoded[i] for i in take]
            group_hints = [hints[i] for i in take]
            tokens, pad_lens = left_pad_batch(
                group_ids, Bj, self.S, b.tok.pad_id
            )
            resume = None
            if matches is not None:
                group_matches = [matches[i] for i in take]
                resume = b._prepare_resume(
                    list(range(len(take))), group_ids, group_matches,
                    pad_lens, Bj, self.S, self.max_new, tracing,
                )
            K = resume[0] if resume else 0
            uids = [self._uid_next + j for j in range(len(take))]
            self._uid_next += len(take)
            uids_np = np.zeros((Bj,), np.int32)
            uids_np[: len(take)] = uids
            prefill = b._get_seg_fn(
                "slot_prefill", Bj, self.S, self.max_new, self.gen, K
            )
            t_pre = time.monotonic()
            with hot_path_transfer_guard():
                if resume:
                    first, join_cache, done0 = prefill(
                        b.params, tokens, pad_lens, self.seed, uids_np,
                        resume[1],
                    )
                else:
                    first, join_cache, done0 = prefill(
                        b.params, tokens, pad_lens, self.seed, uids_np
                    )
                if pc is not None:
                    # prefix-cache insertion reads the join cache BEFORE the
                    # adopt dispatch donates it (copies enter the stream
                    # first, same ordering argument as the continuous path)
                    b._cache_insert(
                        join_cache, list(range(len(take))), group_ids,
                        group_matches, group_hints, pad_lens, tracing,
                    )
                # the joiners' first token IS their TTFT: bound the prefill
                # dispatch with the cheapest output so the anchor is honest
                # lint-allow[host-sync-in-hot-path]: sync makes the per-joiner TTFT anchor real, one [Bj] bool fetch per admit
                jax.device_get(done0)
                prefill_end = time.monotonic()
                # lint-allow[host-sync-in-hot-path]: host list -> host array for the scatter indices, no device sync
                slot_idx = np.asarray(free_slots[:Bj], np.int32)
                adopt = b._get_seg_fn(
                    "adopt", Bj, self.S, self.max_new, self.gen
                )
                (self._cache, self._cur, self._done, self._t, self._out,
                 self._pads) = adopt(
                    self._cache, self._cur, self._done, self._t, self._out,
                    self._pads, join_cache, first, done0,
                    jnp.asarray(pad_lens), slot_idx,
                )
        finally:
            if matches is not None:
                for m in matches.values():
                    pc.release(m)

        # the adopt scatter rewrote out rows: any boundary snapshot is stale
        self._out_snap = None
        skipped = resume[2] if resume else [0] * len(take)
        admissions: list[SlotAdmission] = []
        occupancy = self.active + len(take)
        for j, i in enumerate(take):
            slot = free_slots[j]
            self._keys[slot] = keys[i]
            self._prompts[slot] = prompts[i]
            self._uids[slot] = uids[j]
            self._t_host[slot] = 0
            adm = SlotAdmission(
                key=keys[i], slot=slot, admitted_at=t_admit,
                prefill_end=prefill_end,
                prompt_tokens=len(encoded[i]),
                cached_tokens=int(skipped[j]),
                occupancy=occupancy,
            )
            self._admissions[slot] = adm
            admissions.append(adm)
        self.refills += len(take)
        st = b.stats
        st.batches += 1
        st.prompts += len(take)
        st.prompt_tokens += sum(len(group_ids[j]) for j in range(len(take)))
        st.by_bucket[(Bj, self.S)] = st.by_bucket.get((Bj, self.S), 0) + 1
        if pc is not None:
            hit = sum(skipped)
            st.cache_hit_tokens += hit
            st.cache_miss_tokens += sum(len(g) for g in group_ids) - hit
        if tracing:
            emit("prefill", t_pre, prefill_end - t_pre, B=Bj, S=self.S,
                 occupancy=len(take), synced=True)
        return admissions, rejected

    # -- one decode segment ----------------------------------------------

    @staticmethod
    def _await_retirement(arrays) -> None:
        """Async host polling: request the d2h copies up front (non-
        blocking), then poll ``jax.Array`` readiness with a backing-off
        sleep until the fused dispatch retires. The host never blocks
        inside the runtime while the device is still looping — the poll
        is pure host time, and the later explicit ``device_get`` finds the
        copies already landed. ``is_ready``/``copy_to_host_async`` perform
        no implicit transfer, so the transfer-guard sanitizer stays green
        on this path."""
        for a in arrays:
            a.copy_to_host_async()
        spin = 0.0001
        while not all(a.is_ready() for a in arrays):
            time.sleep(spin)
            spin = min(spin * 2, 0.005)

    # hot path
    def step(self) -> SegmentResult:
        """Advance every live slot by up to ``segment_tokens *
        fused_segments`` tokens in ONE dispatch (the on-device while_loop
        owns the early all-rows-done stop), then harvest finished rows at
        the boundary. The host does not block per segment: it dispatches
        the fused program, polls array readiness asynchronously, and pays
        ONE coalesced done/t/out fetch when the dispatch retires. The out
        snapshot it leaves behind serves ``partial_outputs`` — a streaming
        boundary costs one d2h, not two."""
        if self._closed:
            raise RuntimeError("slot loop is closed")
        res = SegmentResult(live=self.active)
        if not res.live:
            return res
        fault("engine.slot_step",
              prompts=[p for p in self._prompts if p is not None])
        import jax

        b = self.backend
        tracing = current_collector() is not None
        seg_fn = b._get_seg_fn(
            "slot_seg", self.slots, self.S, self.max_new, self.gen,
            fused=self.fused_segments,
        )
        t0 = time.monotonic()
        self._out_snap = None
        with hot_path_transfer_guard():
            # lint-allow[host-sync-in-hot-path]: host list -> host array for the uids argument, no device sync
            uids_np = np.asarray(self._uids, np.int32)
            (self._t, self._cur, self._cache, self._done,
             self._out) = seg_fn(
                b.params, self._t, self._cur, self._cache, self._done,
                uids_np, self._out, self._pads, self.seed,
            )
            # whether a row finished is unknowable before the done poll, so
            # the out buffer ALWAYS rides the boundary fetch — one coalesced
            # d2h covers harvest AND streaming instead of the former
            # fetch-done-then-maybe-fetch-out / fetch-out-again-per-stream
            # pattern (a [B, max_new] int32 block, small next to a segment's
            # compute)
            ctrl = (self._done, self._t, self._out)
            self._await_retirement(ctrl)
            # ONE explicit fetch for the whole boundary: control values and
            # the output buffer together (the copies already landed — this
            # resolves them without a fresh device sync)
            # lint-allow[host-sync-in-hot-path]: segment-boundary done/t/out fetch is the loop's control dependency, already resident host-side via the async copies
            done_h, t_h, out_h = jax.device_get(ctrl)
            finished = [
                s for s, k in enumerate(self._keys)
                if k is not None and done_h[s]
            ]
        res.seconds = time.monotonic() - t0
        deltas = [
            int(t_h[s]) - int(self._t_host[s])
            for s, k in enumerate(self._keys) if k is not None
        ]
        res.new_tokens = int(sum(deltas))
        # how many on-device segment boundaries the fused dispatch crossed:
        # the deepest row's advance, in segment_tokens units (early-stopped
        # dispatches report fewer than fused_segments)
        seg_tokens = max(int(b.segment_tokens), 1)
        res.device_segments = min(
            max(-(-max(deltas, default=0) // seg_tokens), 1),
            self.fused_segments,
        )
        for s, k in enumerate(self._keys):
            if k is not None:
                self._t_host[s] = int(t_h[s])
        self._out_snap = out_h
        for s in finished:
            text = b._detok(out_h[s], tuple(self.gen.eos_ids))
            res.completions.append(SlotCompletion(
                key=self._keys[s], text=text, slot=s,
                gen_tokens=int(t_h[s]),
            ))
            self._keys[s] = None
            self._prompts[s] = None
            self._admissions.pop(s, None)
        self.segments += res.device_segments
        self.fused_dispatches += 1
        if tracing:
            emit("decode_seg", t0, res.seconds, B=self.slots, S=self.S,
                 live=res.live, refill=True,
                 fused=res.device_segments)
        return res

    # -- preemption / streaming (serve/qos.py + serve/stream.py) ---------

    def evict(self, keys, pin: bool = True) -> list[SlotEviction]:
        """Free the slots of ``keys`` mid-decode (priority-tier preemption
        and request cancellation): their done flags flip on device so the
        next segment skips them, their host rows clear, and — when a
        prefix cache is configured and ``pin`` is True — each evictee's
        prompt prefix is matched and left PINNED (the returned
        SlotEviction.pin) so its cached blocks survive LRU until the
        scheduler releases them. ``pin=False`` is the CANCEL path: the
        request is terminal, so there is no restart prefill to keep warm —
        taking a pin would only be refcount churn the scheduler
        immediately unwinds. The evictee's decode state is dropped either
        way; a preemption requeue restarts it from its prompt (greedy
        restarts are byte-identical by engine determinism)."""
        import jax.numpy as jnp

        b = self.backend
        targets = {id(k) for k in keys}
        slots = [
            s for s, k in enumerate(self._keys)
            if k is not None and id(k) in targets
        ]
        if not slots:
            return []
        self._done = self._done.at[jnp.asarray(slots, jnp.int32)].set(True)
        out: list[SlotEviction] = []
        pc = b.prefix_cache if pin else None
        for s in slots:
            ev_pin = None
            if pc is not None:
                ids = b.tok.encode_batch([self._prompts[s]], add_bos=True)[0]
                m = pc.match(ids, max_tokens=len(ids) - 1)
                ev_pin = (pc, m)
            out.append(SlotEviction(key=self._keys[s], slot=s, pin=ev_pin))
            self._keys[s] = None
            self._prompts[s] = None
            self._admissions.pop(s, None)
        return out

    def partial_outputs(self, keys) -> dict:
        """Decoded-so-far text per resident key, keyed by ``id(key)`` (keys
        are arbitrary caller objects, not necessarily hashable) — the
        streaming harvest. Served from the boundary SNAPSHOT step() left
        behind (the out buffer rode the coalesced done/t/out fetch), so a
        streaming boundary pays zero extra d2h; rows are cut at their
        host-tracked cursor so unwritten tail slots never leak into a
        delta. The device fetch below is the cold fallback only — a caller
        polling between an admit and the next step, where the snapshot was
        invalidated by the adopt scatter."""
        targets = {id(k) for k in keys}
        rows = [
            s for s, k in enumerate(self._keys)
            if k is not None and id(k) in targets
        ]
        if not rows:
            return {}
        out_h = self._out_snap
        if out_h is None:
            import jax

            # lint-allow[host-sync-in-hot-path]: cold fallback off the boundary cadence (post-admit, pre-step); the hot path serves the coalesced snapshot above
            out_h = jax.device_get(self._out)
        eos = tuple(self.gen.eos_ids)
        return {
            id(self._keys[s]): self.backend._detok(
                out_h[s][: int(self._t_host[s])], eos
            )
            for s in rows
        }

    # -- lifecycle -------------------------------------------------------

    def outstanding(self) -> list:
        """Keys still resident (the caller drains before closing)."""
        return [k for k in self._keys if k is not None]

    def close(self) -> None:
        self._closed = True
        # drop the device state promptly — the resident cache is the big
        # HBM tenant, and a replacement loop allocates its own
        self._cache = None
        self._cur = self._done = self._t = self._out = self._pads = None
        self._out_snap = None
