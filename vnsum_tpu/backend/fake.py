"""Deterministic fake backend for hermetic strategy tests (SURVEY.md §4:
the reference has no test double at all — every run needs a live Ollama).

Two modes:
- extractive (default): return the first `summary_words` words of the longest
  <content>-like region of the prompt — deterministic, content-dependent, and
  shrinking, so collapse loops terminate the way real summarization does;
- scripted: pop canned responses in order (for critique accept-paths etc.).

An optional latency model (``batch_overhead_s`` + ``per_prompt_s``) makes a
generate() call sleep like a device dispatch: a fixed per-call cost plus a
much smaller marginal per-row cost — the economics that make micro-batching
win. The serving scheduler tests and scripts/bench_serving.py use it to
measure batching effects hermetically; it defaults off so every existing
test is unchanged. ``batch_sizes`` records the prompt count of each call
(``calls`` flattens prompts, which hides batch boundaries).
"""
from __future__ import annotations

import re
import time

from ..core.config import GenerationConfig
from ..text.tokenizer import whitespace_token_count

_BLOCK = re.compile(
    r"<(?:content|summary|docs|reference_content|critique)>\n?(.*?)\n?</(?:content|summary|docs|reference_content|critique)>",
    re.DOTALL,
)


class FakeBackend:
    name = "fake"

    def __init__(
        self,
        responses: list[str] | None = None,
        summary_words: int = 40,
        prefix: str = "",
        batch_overhead_s: float = 0.0,
        per_prompt_s: float = 0.0,
    ) -> None:
        self._responses = list(responses) if responses else None
        self.summary_words = summary_words
        self.prefix = prefix
        self.batch_overhead_s = batch_overhead_s
        self.per_prompt_s = per_prompt_s
        self.calls: list[str] = []
        self.batch_sizes: list[int] = []

    def _one(self, prompt: str) -> str:
        if self._responses is not None:
            if not self._responses:
                raise RuntimeError("FakeBackend ran out of scripted responses")
            return self._responses.pop(0)
        blocks = _BLOCK.findall(prompt)
        source = max(blocks, key=len) if blocks else prompt
        words = source.split()
        return self.prefix + " ".join(words[: self.summary_words])

    def generate(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
    ) -> list[str]:
        self.calls.extend(prompts)
        self.batch_sizes.append(len(prompts))
        if self.batch_overhead_s or self.per_prompt_s:
            time.sleep(self.batch_overhead_s + self.per_prompt_s * len(prompts))
        return [self._one(p) for p in prompts]

    def count_tokens(self, text: str) -> int:
        return whitespace_token_count(text)

    def count_tokens_batch(self, texts: list[str]) -> list[int]:
        return [whitespace_token_count(t) for t in texts]
