"""Deterministic fake backend for hermetic strategy tests (SURVEY.md §4:
the reference has no test double at all — every run needs a live Ollama).

Two modes:
- extractive (default): return the first `summary_words` words of the longest
  <content>-like region of the prompt — deterministic, content-dependent, and
  shrinking, so collapse loops terminate the way real summarization does;
- scripted: pop canned responses in order (for critique accept-paths etc.).
"""
from __future__ import annotations

import re

from ..core.config import GenerationConfig
from ..text.tokenizer import whitespace_token_count

_BLOCK = re.compile(
    r"<(?:content|summary|docs|reference_content|critique)>\n?(.*?)\n?</(?:content|summary|docs|reference_content|critique)>",
    re.DOTALL,
)


class FakeBackend:
    name = "fake"

    def __init__(
        self,
        responses: list[str] | None = None,
        summary_words: int = 40,
        prefix: str = "",
    ) -> None:
        self._responses = list(responses) if responses else None
        self.summary_words = summary_words
        self.prefix = prefix
        self.calls: list[str] = []

    def _one(self, prompt: str) -> str:
        if self._responses is not None:
            if not self._responses:
                raise RuntimeError("FakeBackend ran out of scripted responses")
            return self._responses.pop(0)
        blocks = _BLOCK.findall(prompt)
        source = max(blocks, key=len) if blocks else prompt
        words = source.split()
        return self.prefix + " ".join(words[: self.summary_words])

    def generate(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
    ) -> list[str]:
        self.calls.extend(prompts)
        return [self._one(p) for p in prompts]

    def count_tokens(self, text: str) -> int:
        return whitespace_token_count(text)

    def count_tokens_batch(self, texts: list[str]) -> list[int]:
        return [whitespace_token_count(t) for t in texts]
