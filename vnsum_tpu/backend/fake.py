"""Deterministic fake backend for hermetic strategy tests (SURVEY.md §4:
the reference has no test double at all — every run needs a live Ollama).

Two modes:
- extractive (default): return the first `summary_words` words of the longest
  <content>-like region of the prompt — deterministic, content-dependent, and
  shrinking, so collapse loops terminate the way real summarization does;
- scripted: pop canned responses in order (for critique accept-paths etc.).

An optional latency model (``batch_overhead_s`` + ``per_prompt_s``) makes a
generate() call sleep like a device dispatch: a fixed per-call cost plus a
much smaller marginal per-row cost — the economics that make micro-batching
win. The serving scheduler tests and scripts/bench_serving.py use it to
measure batching effects hermetically; it defaults off so every existing
test is unchanged. ``batch_sizes`` records the prompt count of each call
(``calls`` flattens prompts, which hides batch boundaries).

Speculative-decoding plumbing (vnsum_tpu.spec) is mirrored synthetically:
``generate`` accepts per-prompt ``references`` (recorded in
``references_seen``), and when speculation is requested (``config.spec_k``
> 0, or the constructor's ``spec_k``) each prompt gets a deterministic
SpecRecord at the configured ``spec_acceptance`` rate, retrievable once via
``take_spec_report()`` — the same contract TpuBackend exposes — so serve
and strategy tests can exercise acceptance-rate metrics without a model.

The prefix KV cache (vnsum_tpu.cache) is mirrored the same way:
``prefix_cache_blocks > 0`` runs the REAL radix index (cache/radix.py) over
whitespace words — block matching, ref-counted pins, LRU eviction — with no
device pool behind it. ``cache_hints`` bound insertion exactly like the
engine, hit counts flow through ``take_cache_report()`` /
``cached_prefix_tokens()`` / ``prefix_cache_stats()``, and the optional
``per_token_s`` latency term scales the simulated prefill sleep with
UNCACHED tokens only, so hermetic serving benches see real TTFT improvement
from cache hits (scripts/bench_serving.py --shared-prefix arm).
"""
from __future__ import annotations

import re
import time

from ..core.config import GenerationConfig
from ..obs.trace import current_collector, emit
from ..spec import SpecRecord
from ..testing.faults import fault
from ..text.tokenizer import whitespace_token_count

_BLOCK = re.compile(
    r"<(?:content|summary|docs|reference_content|critique)>\n?(.*?)\n?</(?:content|summary|docs|reference_content|critique)>",
    re.DOTALL,
)


class FakeBackend:
    name = "fake"

    def __init__(
        self,
        responses: list[str] | None = None,
        summary_words: int = 40,
        prefix: str = "",
        batch_overhead_s: float = 0.0,
        per_prompt_s: float = 0.0,
        per_token_s: float = 0.0,
        spec_k: int = 0,
        spec_acceptance: float = 0.5,
        prefix_cache_blocks: int = 0,
        cache_block_tokens: int = 8,
        segment_words: int = 8,
        segment_overhead_s: float = 0.0,
        per_slot_segment_s: float = 0.0,
        per_step_s: float = 0.0,
        dp_replicas: int = 1,
    ) -> None:
        self._responses = list(responses) if responses else None
        self.summary_words = summary_words
        self.prefix = prefix
        self.batch_overhead_s = batch_overhead_s
        self.per_prompt_s = per_prompt_s
        # per-UNCACHED-prompt-token prefill cost: the lever that makes
        # prefix-cache hits show up as TTFT/goodput improvement hermetically
        self.per_token_s = per_token_s
        # default spec_k applied when a call's config doesn't carry one —
        # mirrors TpuBackend's generation=GenerationConfig(spec_k=...)
        self.spec_k = spec_k
        self.spec_acceptance = spec_acceptance
        # synthetic prefix cache: the real radix index over whitespace
        # words, matching TpuBackend's hit/insert/evict dynamics without a
        # device pool (tokens here are words, consistent with count_tokens)
        self.prefix_index = None
        if prefix_cache_blocks:
            from ..cache.radix import RadixIndex

            self.prefix_index = RadixIndex(
                prefix_cache_blocks, cache_block_tokens
            )
        # in-flight slot loop latency model (start_slot_loop): each decode
        # segment advances live rows by ``segment_words`` words and sleeps
        # segment_overhead_s + per_slot_segment_s * live — the per-segment
        # analogue of the one-shot batch_overhead_s/per_prompt_s model
        self.segment_words = max(int(segment_words), 1)
        self.segment_overhead_s = segment_overhead_s
        self.per_slot_segment_s = per_slot_segment_s
        # per-DECODE-STEP cost, charged by BOTH paths: a one-shot batch
        # decodes until its LONGEST row finishes (per_step_s * max output
        # words — the ragged-tail convoy a real fixed batch pays), while the
        # slot loop pays only for the steps a segment actually runs. This
        # is the economics in-flight refill exploits, modeled symmetrically.
        self.per_step_s = per_step_s
        # data-parallel replica model (the sharded-serving bench,
        # scripts/bench_serving.py sharded phase): per-ROW marginal costs
        # divide over replicas (rows spread across the data axis and run
        # concurrently) while per-dispatch overheads and per-STEP depth
        # costs don't — replication buys row throughput, not step latency.
        # 1 = single-chip, every existing test unchanged.
        self.dp_replicas = max(int(dp_replicas), 1)
        # degradation-ladder hook (serve/supervisor.py NO_CACHE_INSERT):
        # False stops prefix-index insertion while hits keep serving —
        # same contract as TpuBackend.set_prefix_cache_inserts
        self.cache_inserts_enabled = True
        self.calls: list[str] = []
        self.batch_sizes: list[int] = []
        self.references_seen: list[str | None] = []
        self.cache_hints_seen: list[str | None] = []
        self._spec_report: list[SpecRecord] = []
        self._cache_report: list[int] = []
        # cooperative cancel flag (serve/scheduler.py::_dispatch): polled at
        # the simulated segment boundaries of a one-shot dispatch; True
        # aborts the remaining decode sleep — the hermetic mirror of an
        # engine checking a cancel flag between decode segments. None = off
        # (every pre-cancellation caller unchanged)
        self._cancel_poll = None
        self.cancel_aborts = 0
        # drain-wins flag (serve/scheduler.py close -> request_drain): a
        # draining server must never wait out simulated device time — every
        # sleep here is pure simulation, so aborting it changes wall clock,
        # never outputs. Also cuts injected `latency` fault sleeps short
        self._draining = False

    def _one(self, prompt: str) -> str:
        if self._responses is not None:
            if not self._responses:
                raise RuntimeError("FakeBackend ran out of scripted responses")
            return self._responses.pop(0)
        blocks = _BLOCK.findall(prompt)
        source = max(blocks, key=len) if blocks else prompt
        words = source.split()
        return self.prefix + " ".join(words[: self.summary_words])

    def _cache_pass(
        self,
        prompts: list[str],
        cache_hints: list[str | None] | None,
    ) -> int:
        """Match then insert, mirroring the engine's per-call order: ALL
        prompts match up front (pinned), insertion follows — so duplicates
        within one call miss together, exactly like a shared engine batch.
        Returns total UNCACHED tokens for the latency model; fills
        _cache_report with per-prompt hit counts."""
        idx = self.prefix_index
        words_per = [p.split() for p in prompts]
        matches = [
            idx.match(w, max_tokens=len(w) - 1) for w in words_per
        ]
        # pins released on EVERY path: a fault firing mid-pass (the
        # fake.prefill injection site sits exactly here, while the matched
        # chains are pinned) must not leak refcounts — leaked pins would
        # make blocks uneviciable forever, the serving-stack analogue of a
        # KV-block leak on a crashed device batch
        try:
            fault("fake.prefill", prompts=prompts)
            if self.cache_inserts_enabled:
                for i, (w, m) in enumerate(zip(words_per, matches)):
                    hint = cache_hints[i] if cache_hints else None
                    if hint:
                        # mirror the engine's _hint_prefix_len: the hint
                        # bounds insertion only up to its true common prefix
                        # with the prompt — a hint the prompt doesn't start
                        # with caches nothing, instead of caching unique
                        # content by length
                        hw = hint.split()
                        upto = 0
                        while (
                            upto < min(len(hw), len(w)) and hw[upto] == w[upto]
                        ):
                            upto += 1
                    else:
                        upto = len(w) - 1
                    idx.insert(w, min(upto, len(w) - 1))
        finally:
            for m in matches:
                idx.release(m)
        self._cache_report = [m.tokens for m in matches]
        return sum(
            len(w) - m.tokens for w, m in zip(words_per, matches)
        )

    def generate(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        references: list[str | None] | None = None,
        cache_hints: list[str | None] | None = None,
    ) -> list[str]:
        # seeded fault injection (vnsum_tpu.testing.faults): free when
        # disarmed; fires BEFORE call bookkeeping so a retried dispatch is
        # indistinguishable from a fresh one to the latency model
        fault("fake.dispatch", prompts=prompts)
        self.calls.extend(prompts)
        self.batch_sizes.append(len(prompts))
        self.references_seen.extend(
            references if references is not None else [None] * len(prompts)
        )
        self.cache_hints_seen.extend(
            cache_hints if cache_hints is not None else [None] * len(prompts)
        )
        if self.prefix_index is not None:
            uncached = self._cache_pass(prompts, cache_hints)
        else:
            uncached = sum(len(p.split()) for p in prompts)
            self._cache_report = []
        t0 = time.monotonic() if current_collector() is not None else 0.0
        outs_early = None
        rep = self.dp_replicas
        prefill_s = self.batch_overhead_s + self.per_token_s * -(-uncached // rep)
        decode_s = self.per_prompt_s * -(-len(prompts) // rep)
        if self.per_step_s:
            # the batch decodes until its LONGEST row finishes — every
            # rider pays the convoy (what in-flight refill avoids)
            outs_early = [self._one(p) for p in prompts]
            decode_s += self.per_step_s * max(
                (len(o.split()) for o in outs_early), default=0
            )
        if prefill_s or decode_s:
            self._sleep_cancellable(prefill_s + decode_s)
        # engine-telemetry contract mirror: the latency model's fixed
        # per-dispatch cost (plus the per-uncached-token prefill term) plays
        # the prefill phase and the marginal per-row cost plays decode, so
        # hermetic serving runs get the same prefill/decode structure (and
        # TTFT anchor) TpuBackend emits — emit() is a no-op unless the
        # scheduler installed a BatchTrace
        if t0:
            emit("prefill", t0, prefill_s, B=len(prompts))
            emit("decode", t0 + prefill_s, decode_s, B=len(prompts))
        outs = (
            outs_early if outs_early is not None
            else [self._one(p) for p in prompts]
        )
        k = config.spec_k if config is not None else self.spec_k
        self._spec_report = [
            self._synthetic_spec(k, references[i] if references else None, o)
            for i, o in enumerate(outs)
        ] if k > 0 else []
        return outs

    def _synthetic_spec(self, k: int, reference, out: str) -> SpecRecord:
        """Deterministic per-prompt stats: a row with a reference drafts k
        per step and keeps spec_acceptance of them; one with no reference
        drafts nothing (matching the real drafter's degradation)."""
        steps = max(len(out.split()), 1)
        drafted = k * steps if reference else 0
        return SpecRecord(
            draft_tokens=drafted,
            accepted_tokens=int(drafted * self.spec_acceptance),
            verify_steps=steps,
        )

    def set_cancel_poll(self, poll) -> None:
        """Arm (or clear, with None) the cooperative cancel flag the
        scheduler sets around a one-shot dispatch — the backend-optional
        hook checked at segment boundaries, same shape as
        take_spec_report's duck typing."""
        self._cancel_poll = poll

    def request_drain(self) -> None:
        """Graceful-shutdown hook (duck-typed; serve/scheduler.py close):
        abort in-flight and future simulated sleeps — including any armed
        `latency` fault-plan sleeps — so drain always beats fake device
        time. Outputs are unaffected; only the wall clock shrinks. Real
        backends simply don't expose this."""
        self._draining = True
        from ..testing.faults import interrupt_sleeps

        interrupt_sleeps()

    def reset_drain(self) -> None:
        """Undo request_drain (duck-typed; a NEW scheduler attaching to a
        reused backend calls this): drain is scoped to the server that
        drained, not to the backend's remaining lifetime — without the
        reset, every later sleep and armed latency/hang fault would
        pass through instantly and simulate nothing."""
        self._draining = False
        from ..testing.faults import reset_interrupts

        reset_interrupts()

    def _sleep_cancellable(self, seconds: float) -> bool:
        """The dispatch sleep, sliced at segment granularity: each slice is
        one simulated decode segment (``segment_words`` steps). An armed
        cancel poll returning True abandons the remainder — the whole batch
        was cancelled, so burning more simulated device time would only
        model waste — and a draining server (request_drain) aborts
        unconditionally: the sleep is simulation, and SIGTERM must win over
        it. Returns True when aborted."""
        # slice: segment-grained with a cancel poll armed (poll cadence is
        # the contract), coarse 50ms otherwise (drain responsiveness only)
        seg = (
            max(self.per_step_s * self.segment_words, 0.002)
            if self._cancel_poll is not None else 0.05
        )
        t_end = time.monotonic() + seconds
        while True:
            if self._draining:
                return True
            if self._cancel_poll is not None and self._cancel_poll():
                self.cancel_aborts += 1
                return True
            remaining = t_end - time.monotonic()
            if remaining <= 0:
                return False
            time.sleep(min(seg, remaining))

    def take_spec_report(self) -> list[SpecRecord]:
        """Per-prompt SpecRecords of the LAST generate call (empty when
        speculation was off), cleared on read — the backend-optional hook
        the serving scheduler attributes acceptance metrics through."""
        report, self._spec_report = self._spec_report, []
        return report

    def take_cache_report(self) -> list[int]:
        """Per-prompt prefix-cache hit tokens of the LAST generate call
        (empty when the cache is off), cleared on read — the same
        attribution hook TpuBackend exposes."""
        report, self._cache_report = self._cache_report, []
        return report

    def set_prefix_cache_inserts(self, enabled: bool) -> None:
        """Degradation-ladder hook: gate prefix-index insertion (hits still
        serve). Engine-thread-only, like every other mutation here."""
        self.cache_inserts_enabled = bool(enabled)

    def cached_prefix_tokens(self, text: str, cache_hint: str | None = None) -> int:
        """Read-only probe in whitespace-word tokens (consistent with
        count_tokens) — the admission-discount hook."""
        if self.prefix_index is None:
            return 0
        words = text.split()
        return self.prefix_index.probe(words, max_tokens=len(words) - 1)

    def prefix_cache_stats(self) -> dict | None:
        if self.prefix_index is None:
            return None
        return self.prefix_index.stats_dict()

    def count_tokens(self, text: str) -> int:
        return whitespace_token_count(text)

    def count_tokens_batch(self, texts: list[str]) -> list[int]:
        return [whitespace_token_count(t) for t in texts]

    # -- in-flight slot loop (mirrors TpuBackend.start_slot_loop) --------

    def start_slot_loop(
        self,
        slots: int | None = None,
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        prompt_tokens: int = 0,
        fused_segments: int = 1,
    ) -> "FakeSlotLoop":
        """The in-flight batching contract, hermetically: admission runs the
        REAL radix prefix index (when configured) and sleeps the prefill
        model (batch_overhead_s + per_token_s * uncached words); each step()
        advances live rows by ``segment_words`` words of their deterministic
        extractive output and sleeps the segment model. ``prompt_tokens``
        bounds admitted prompts exactly like the engine's S bucket (0 =
        unlimited) so scheduler fallback paths are testable without a
        device. ``fused_segments`` mirrors TpuSlotLoop's fused multi-step
        decode: one step() covers up to N segments and charges
        ``segment_overhead_s`` ONCE per dispatch (per-step cost unchanged)
        — the dispatch-amortization economics the fused A/B measures."""
        max_new = max_new_tokens
        if max_new is None and config is not None:
            max_new = config.max_new_tokens
        return FakeSlotLoop(self, slots or 8, prompt_tokens, max_new,
                            fused_segments=fused_segments)


class FakeSlotLoop:
    """Slot-loop double over FakeBackend's latency + cache model; the
    admission/segment/harvest contract matches backend/inflight.TpuSlotLoop
    (shared record types), so serving tests and the hermetic bench exercise
    the same scheduler paths the real engine loop serves."""

    def __init__(self, backend: FakeBackend, slots: int, prompt_tokens: int,
                 max_new: int | None, fused_segments: int = 1) -> None:
        from .inflight import (
            SegmentResult,
            SlotAdmission,
            SlotCompletion,
            SlotEviction,
        )

        self._SegmentResult = SegmentResult
        self._SlotAdmission = SlotAdmission
        self._SlotCompletion = SlotCompletion
        self._SlotEviction = SlotEviction
        self.backend = backend
        self.slots = int(slots)
        self.S = int(prompt_tokens)  # 0 = unlimited
        self.max_new = max_new
        self._keys: list = [None] * self.slots
        self._words: list[list[str] | None] = [None] * self.slots
        self._prompts: list[str | None] = [None] * self.slots
        self._emitted: list[int] = [0] * self.slots
        self.fused_segments = max(int(fused_segments), 1)
        self.segments = 0           # inner segments retired (device cadence)
        self.fused_dispatches = 0   # step() calls that did work
        self.refills = 0
        self._closed = False

    @property
    def active(self) -> int:
        return sum(1 for k in self._keys if k is not None)

    @property
    def free(self) -> int:
        return self.slots - self.active

    def admit(self, items):
        if self._closed:
            raise RuntimeError("slot loop is closed")
        b = self.backend
        t_admit = time.monotonic()
        items = list(items)
        if not items or not self.free:
            return [], []
        fault("fake.slot_admit", prompts=[p for _k, p, _h in items])
        rejected = [
            k for k, p, _h in items
            if self.S and len(p.split()) > self.S
        ]
        ok = [(k, p, h) for k, p, h in items
              if not (self.S and len(p.split()) > self.S)]
        take = ok[: self.free]
        if not take:
            return [], rejected
        prompts = [p for _k, p, _h in take]
        hints = [h for _k, _p, h in take]
        if b.prefix_index is not None:
            uncached = b._cache_pass(prompts, hints)
            report = b._cache_report
            b._cache_report = []
        else:
            uncached = sum(len(p.split()) for p in prompts)
            report = [0] * len(take)
        prefill_s = (
            b.batch_overhead_s
            + b.per_token_s * -(-uncached // b.dp_replicas)
        )
        if prefill_s:
            time.sleep(prefill_s)
        prefill_end = time.monotonic()
        emit("prefill", t_admit, prefill_end - t_admit, B=len(take))
        free_slots = [s for s, k in enumerate(self._keys) if k is None]
        admissions = []
        occupancy = self.active + len(take)
        for j, (key, prompt, _hint) in enumerate(take):
            slot = free_slots[j]
            words = b._one(prompt).split()
            if self.max_new is not None:
                words = words[: self.max_new]
            self._keys[slot] = key
            self._words[slot] = words
            self._prompts[slot] = prompt
            self._emitted[slot] = 0
            admissions.append(self._SlotAdmission(
                key=key, slot=slot, admitted_at=t_admit,
                prefill_end=prefill_end,
                prompt_tokens=len(prompt.split()),
                cached_tokens=int(report[j]),
                occupancy=occupancy,
            ))
        self.refills += len(take)
        b.batch_sizes.append(len(take))
        b.calls.extend(prompts)
        return admissions, rejected

    def step(self):
        """One FUSED dispatch: up to ``fused_segments`` inner segments with
        the on-device early stop mirrored (a window whose rows all finish
        stops advancing), harvest at dispatch retirement only. The latency
        model charges ``segment_overhead_s`` ONCE per dispatch — that is
        the dispatch/sync tax fusing amortizes — while per-slot-segment and
        per-step costs accrue for the work actually run, so the fused A/B
        is honest hermetically."""
        if self._closed:
            raise RuntimeError("slot loop is closed")
        res = self._SegmentResult(live=self.active)
        if not res.live:
            return res
        # resident prompts ride the poison matcher: a poison RESIDENT
        # crashes segments, not just its own admission
        fault("fake.slot_step", prompts=[
            p for p in self._prompts if p is not None
        ])
        b = self.backend
        t0 = time.monotonic()
        steps = 0
        segments_run = 0
        slot_segment_units = 0  # sum over inner segments of ceil(live/rep)
        for _ in range(self.fused_segments):
            live_rows = [
                s for s, k in enumerate(self._keys)
                if k is not None and self._emitted[s] < len(self._words[s])
            ]
            if not live_rows:
                break  # the on-device all-rows-done stop
            seg_steps = 0
            for s in live_rows:
                words = self._words[s]
                advance = min(b.segment_words, len(words) - self._emitted[s])
                seg_steps = max(seg_steps, advance)
                self._emitted[s] += advance
                res.new_tokens += advance
            steps += seg_steps
            segments_run += 1
            slot_segment_units += -(-len(live_rows) // b.dp_replicas)
        res.device_segments = max(segments_run, 1)
        seg_s = (
            # ONE dispatch overhead per fused window — the host round-trip
            # cost fusing exists to amortize
            b.segment_overhead_s
            # live rows spread over DP replicas, per inner segment actually
            # run; segment depth doesn't
            + b.per_slot_segment_s * slot_segment_units
            + b.per_step_s * steps
        )
        if seg_s:
            time.sleep(seg_s)
        for s, k in enumerate(self._keys):
            if k is None:
                continue
            words = self._words[s]
            if self._emitted[s] >= len(words):
                res.completions.append(self._SlotCompletion(
                    key=k, text=" ".join(words), slot=s,
                    gen_tokens=len(words),
                ))
                self._keys[s] = None
                self._words[s] = None
                self._prompts[s] = None
        self.segments += res.device_segments
        self.fused_dispatches += 1
        res.seconds = time.monotonic() - t0
        emit("decode_seg", t0, res.seconds, live=res.live, refill=True,
             fused=res.device_segments)
        return res

    def evict(self, keys, pin: bool = True):
        """Preemption/cancellation double (mirrors TpuSlotLoop.evict): free
        the slots, drop decode progress, and — with the synthetic radix
        index on and ``pin`` True — return each evictee's prompt prefix
        PINNED so the requeue's admission finds it warm and unevicted.
        ``pin=False`` is the cancel path: terminal, nothing to keep warm."""
        b = self.backend
        targets = {id(k) for k in keys}
        out = []
        for s, k in enumerate(self._keys):
            if k is None or id(k) not in targets:
                continue
            ev_pin = None
            if pin and b.prefix_index is not None:
                words = self._prompts[s].split()
                m = b.prefix_index.match(words, max_tokens=len(words) - 1)
                ev_pin = (b.prefix_index, m)
            out.append(self._SlotEviction(key=k, slot=s, pin=ev_pin))
            self._keys[s] = None
            self._words[s] = None
            self._prompts[s] = None
            self._emitted[s] = 0
        return out

    def partial_outputs(self, keys) -> dict:
        """Decoded-so-far text per resident key, keyed by ``id(key)`` —
        keys are arbitrary caller objects, not necessarily hashable
        (mirrors TpuSlotLoop.partial_outputs)."""
        targets = {id(k) for k in keys}
        return {
            id(k): " ".join(self._words[s][: self._emitted[s]])
            for s, k in enumerate(self._keys)
            if k is not None and id(k) in targets
        }

    def outstanding(self) -> list:
        return [k for k in self._keys if k is not None]

    def close(self) -> None:
        self._closed = True
