"""Deterministic fake backend for hermetic strategy tests (SURVEY.md §4:
the reference has no test double at all — every run needs a live Ollama).

Two modes:
- extractive (default): return the first `summary_words` words of the longest
  <content>-like region of the prompt — deterministic, content-dependent, and
  shrinking, so collapse loops terminate the way real summarization does;
- scripted: pop canned responses in order (for critique accept-paths etc.).

An optional latency model (``batch_overhead_s`` + ``per_prompt_s``) makes a
generate() call sleep like a device dispatch: a fixed per-call cost plus a
much smaller marginal per-row cost — the economics that make micro-batching
win. The serving scheduler tests and scripts/bench_serving.py use it to
measure batching effects hermetically; it defaults off so every existing
test is unchanged. ``batch_sizes`` records the prompt count of each call
(``calls`` flattens prompts, which hides batch boundaries).

Speculative-decoding plumbing (vnsum_tpu.spec) is mirrored synthetically:
``generate`` accepts per-prompt ``references`` (recorded in
``references_seen``), and when speculation is requested (``config.spec_k``
> 0, or the constructor's ``spec_k``) each prompt gets a deterministic
SpecRecord at the configured ``spec_acceptance`` rate, retrievable once via
``take_spec_report()`` — the same contract TpuBackend exposes — so serve
and strategy tests can exercise acceptance-rate metrics without a model.

The prefix KV cache (vnsum_tpu.cache) is mirrored the same way:
``prefix_cache_blocks > 0`` runs the REAL radix index (cache/radix.py) over
whitespace words — block matching, ref-counted pins, LRU eviction — with no
device pool behind it. ``cache_hints`` bound insertion exactly like the
engine, hit counts flow through ``take_cache_report()`` /
``cached_prefix_tokens()`` / ``prefix_cache_stats()``, and the optional
``per_token_s`` latency term scales the simulated prefill sleep with
UNCACHED tokens only, so hermetic serving benches see real TTFT improvement
from cache hits (scripts/bench_serving.py --shared-prefix arm).
"""
from __future__ import annotations

import re
import time

from ..core.config import GenerationConfig
from ..obs.trace import current_collector, emit
from ..spec import SpecRecord
from ..text.tokenizer import whitespace_token_count

_BLOCK = re.compile(
    r"<(?:content|summary|docs|reference_content|critique)>\n?(.*?)\n?</(?:content|summary|docs|reference_content|critique)>",
    re.DOTALL,
)


class FakeBackend:
    name = "fake"

    def __init__(
        self,
        responses: list[str] | None = None,
        summary_words: int = 40,
        prefix: str = "",
        batch_overhead_s: float = 0.0,
        per_prompt_s: float = 0.0,
        per_token_s: float = 0.0,
        spec_k: int = 0,
        spec_acceptance: float = 0.5,
        prefix_cache_blocks: int = 0,
        cache_block_tokens: int = 8,
    ) -> None:
        self._responses = list(responses) if responses else None
        self.summary_words = summary_words
        self.prefix = prefix
        self.batch_overhead_s = batch_overhead_s
        self.per_prompt_s = per_prompt_s
        # per-UNCACHED-prompt-token prefill cost: the lever that makes
        # prefix-cache hits show up as TTFT/goodput improvement hermetically
        self.per_token_s = per_token_s
        # default spec_k applied when a call's config doesn't carry one —
        # mirrors TpuBackend's generation=GenerationConfig(spec_k=...)
        self.spec_k = spec_k
        self.spec_acceptance = spec_acceptance
        # synthetic prefix cache: the real radix index over whitespace
        # words, matching TpuBackend's hit/insert/evict dynamics without a
        # device pool (tokens here are words, consistent with count_tokens)
        self.prefix_index = None
        if prefix_cache_blocks:
            from ..cache.radix import RadixIndex

            self.prefix_index = RadixIndex(
                prefix_cache_blocks, cache_block_tokens
            )
        self.calls: list[str] = []
        self.batch_sizes: list[int] = []
        self.references_seen: list[str | None] = []
        self.cache_hints_seen: list[str | None] = []
        self._spec_report: list[SpecRecord] = []
        self._cache_report: list[int] = []

    def _one(self, prompt: str) -> str:
        if self._responses is not None:
            if not self._responses:
                raise RuntimeError("FakeBackend ran out of scripted responses")
            return self._responses.pop(0)
        blocks = _BLOCK.findall(prompt)
        source = max(blocks, key=len) if blocks else prompt
        words = source.split()
        return self.prefix + " ".join(words[: self.summary_words])

    def _cache_pass(
        self,
        prompts: list[str],
        cache_hints: list[str | None] | None,
    ) -> int:
        """Match then insert, mirroring the engine's per-call order: ALL
        prompts match up front (pinned), insertion follows — so duplicates
        within one call miss together, exactly like a shared engine batch.
        Returns total UNCACHED tokens for the latency model; fills
        _cache_report with per-prompt hit counts."""
        idx = self.prefix_index
        words_per = [p.split() for p in prompts]
        matches = [
            idx.match(w, max_tokens=len(w) - 1) for w in words_per
        ]
        for i, (w, m) in enumerate(zip(words_per, matches)):
            hint = cache_hints[i] if cache_hints else None
            if hint:
                # mirror the engine's _hint_prefix_len: the hint bounds
                # insertion only up to its true common prefix with the
                # prompt — a hint the prompt doesn't start with caches
                # nothing, instead of caching unique content by length
                hw = hint.split()
                upto = 0
                while (
                    upto < min(len(hw), len(w)) and hw[upto] == w[upto]
                ):
                    upto += 1
            else:
                upto = len(w) - 1
            idx.insert(w, min(upto, len(w) - 1))
            idx.release(m)
        self._cache_report = [m.tokens for m in matches]
        return sum(
            len(w) - m.tokens for w, m in zip(words_per, matches)
        )

    def generate(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        references: list[str | None] | None = None,
        cache_hints: list[str | None] | None = None,
    ) -> list[str]:
        self.calls.extend(prompts)
        self.batch_sizes.append(len(prompts))
        self.references_seen.extend(
            references if references is not None else [None] * len(prompts)
        )
        self.cache_hints_seen.extend(
            cache_hints if cache_hints is not None else [None] * len(prompts)
        )
        if self.prefix_index is not None:
            uncached = self._cache_pass(prompts, cache_hints)
        else:
            uncached = sum(len(p.split()) for p in prompts)
            self._cache_report = []
        t0 = time.monotonic() if current_collector() is not None else 0.0
        prefill_s = self.batch_overhead_s + self.per_token_s * uncached
        if prefill_s or self.per_prompt_s:
            time.sleep(prefill_s + self.per_prompt_s * len(prompts))
        # engine-telemetry contract mirror: the latency model's fixed
        # per-dispatch cost (plus the per-uncached-token prefill term) plays
        # the prefill phase and the marginal per-row cost plays decode, so
        # hermetic serving runs get the same prefill/decode structure (and
        # TTFT anchor) TpuBackend emits — emit() is a no-op unless the
        # scheduler installed a BatchTrace
        if t0:
            emit("prefill", t0, prefill_s, B=len(prompts))
            emit("decode", t0 + prefill_s,
                 self.per_prompt_s * len(prompts), B=len(prompts))
        outs = [self._one(p) for p in prompts]
        k = config.spec_k if config is not None else self.spec_k
        self._spec_report = [
            self._synthetic_spec(k, references[i] if references else None, o)
            for i, o in enumerate(outs)
        ] if k > 0 else []
        return outs

    def _synthetic_spec(self, k: int, reference, out: str) -> SpecRecord:
        """Deterministic per-prompt stats: a row with a reference drafts k
        per step and keeps spec_acceptance of them; one with no reference
        drafts nothing (matching the real drafter's degradation)."""
        steps = max(len(out.split()), 1)
        drafted = k * steps if reference else 0
        return SpecRecord(
            draft_tokens=drafted,
            accepted_tokens=int(drafted * self.spec_acceptance),
            verify_steps=steps,
        )

    def take_spec_report(self) -> list[SpecRecord]:
        """Per-prompt SpecRecords of the LAST generate call (empty when
        speculation was off), cleared on read — the backend-optional hook
        the serving scheduler attributes acceptance metrics through."""
        report, self._spec_report = self._spec_report, []
        return report

    def take_cache_report(self) -> list[int]:
        """Per-prompt prefix-cache hit tokens of the LAST generate call
        (empty when the cache is off), cleared on read — the same
        attribution hook TpuBackend exposes."""
        report, self._cache_report = self._cache_report, []
        return report

    def cached_prefix_tokens(self, text: str, cache_hint: str | None = None) -> int:
        """Read-only probe in whitespace-word tokens (consistent with
        count_tokens) — the admission-discount hook."""
        if self.prefix_index is None:
            return 0
        words = text.split()
        return self.prefix_index.probe(words, max_tokens=len(words) - 1)

    def prefix_cache_stats(self) -> dict | None:
        if self.prefix_index is None:
            return None
        return self.prefix_index.stats_dict()

    def count_tokens(self, text: str) -> int:
        return whitespace_token_count(text)

    def count_tokens_batch(self, texts: list[str]) -> list[int]:
        return [whitespace_token_count(t) for t in texts]
