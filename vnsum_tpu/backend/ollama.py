"""Ollama HTTP backend — behavioral port of the reference's OllamaLLM
(runners/run_summarization_ollama_mapreduce.py:37-60, with the drifted copies'
fixes folded in: `think: false` from ..._critique.py:63-79, the 600 s timeout
from ..._hierarchical.py:64-65, and thinking-token cleaning from
run_full_evaluation_pipeline.py:66-117).

Kept as an alternate backend behind the same interface (BASELINE.json:
`--backend=tpu|ollama`). Unlike the reference's fake-async `_acall`
(...mapreduce.py:51-52), batches here run over a thread pool, so a
multi-worker Ollama server actually sees concurrent requests.
"""
from __future__ import annotations

import json

from concurrent.futures import ThreadPoolExecutor

from ..core.config import GenerationConfig

from .base import resolve_max_new
from ..core.faults import call_with_retries
from ..core.logging import get_logger
from ..text.cleaning import clean_thinking_tokens
from ..text.tokenizer import whitespace_token_count

logger = get_logger("vnsum.backend.ollama")


class OllamaBackend:
    name = "ollama"

    def __init__(
        self,
        model: str = "llama3.2:3b",
        url: str = "http://localhost:11434",
        max_new_tokens: int = 1024,
        timeout: float = 600.0,
        connect_timeout: float = 5.0,
        clean_output: bool = True,
        concurrency: int = 4,
        max_retries: int = 3,
        retry_backoff: float = 1.0,
        retry_jitter: float = 0.25,
    ) -> None:
        self.model = model
        self.url = url.rstrip("/")
        self.max_new_tokens = max_new_tokens
        # split (connect, read) timeouts: a dead host fails in seconds at
        # the TCP handshake instead of burning the 600 s READ budget a slow
        # generation legitimately needs — requests accepts the tuple form
        self.timeout = timeout
        self.connect_timeout = connect_timeout
        self.clean_output = clean_output
        self.concurrency = concurrency
        self.max_retries = max(0, max_retries)
        self.retry_backoff = retry_backoff
        # jittered backoff: this backend fans prompts over a thread pool,
        # and unjittered retries from `concurrency` workers re-slam a
        # recovering server in lockstep
        self.retry_jitter = retry_jitter

    @property
    def _timeouts(self) -> tuple[float, float]:
        return (self.connect_timeout, self.timeout)

    def health_check(self) -> list[str]:
        """GET /api/tags; returns available model names
        (ref run_full_evaluation_pipeline.py:199-233)."""
        import requests

        resp = requests.get(
            f"{self.url}/api/tags", timeout=(self.connect_timeout, 10)
        )
        resp.raise_for_status()
        return [m["name"] for m in resp.json().get("models", [])]

    def _one(self, prompt: str, max_new: int, config: GenerationConfig | None) -> str:
        import requests

        options: dict = {"num_predict": max_new}
        if config is not None:
            options["temperature"] = config.temperature
            if config.top_k > 0:
                options["top_k"] = config.top_k
            if config.top_p < 1.0:
                options["top_p"] = config.top_p
            if config.seed:
                options["seed"] = config.seed
        payload = {
            "model": self.model,
            "prompt": prompt,
            "stream": False,
            "think": False,
            "options": options,
        }
        def attempt() -> str:
            resp = requests.post(
                f"{self.url}/api/generate", json=payload,
                timeout=self._timeouts,
            )
            resp.raise_for_status()
            text = resp.json()["response"]
            return clean_thinking_tokens(text) if self.clean_output else text

        # requests' JSONDecodeError does NOT subclass json.JSONDecodeError
        # when simplejson is installed (it is here), so catch both; getattr
        # keeps test doubles that stub out `requests` working
        json_errors = (
            getattr(
                getattr(requests, "exceptions", None),
                "JSONDecodeError",
                json.JSONDecodeError,
            ),
            json.JSONDecodeError,
        )

        def transient(e: Exception) -> bool:
            # ConnectionError yes; NOT requests.Timeout (with the 600 s read
            # timeout a hung server would stall ~40 min/prompt across
            # retries); HTTP 5xx, 429 (load shed), 408 (request timeout);
            # a truncated/garbled 200 body (JSONDecodeError, or KeyError for
            # a body missing "response") is also a server-side transient.
            # NOT plain ValueError: MissingSchema/InvalidURL subclass it and
            # are unfixable config errors that must fail fast.
            if isinstance(e, requests.HTTPError):
                status = e.response.status_code if e.response is not None else 0
                return status >= 500 or status in (408, 429)
            return isinstance(
                e, (requests.ConnectionError, *json_errors, KeyError)
            )

        # the reference has no retries anywhere (SURVEY.md §5 "Failure
        # detection"), so one dropped connection voids a whole document there
        return call_with_retries(
            attempt,
            max_retries=self.max_retries,
            backoff=self.retry_backoff,
            jitter=self.retry_jitter,
            retryable=(
                requests.ConnectionError,
                requests.HTTPError,
                *json_errors,
                KeyError,
            ),
            should_retry=transient,
            what="ollama call",
        )

    def generate(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        references: list[str | None] | None = None,  # spec metadata; unused
        cache_hints: list[str | None] | None = None,  # cache metadata; unused
    ) -> list[str]:
        max_new = resolve_max_new(max_new_tokens, config, self.max_new_tokens)
        if len(prompts) == 1:
            return [self._one(prompts[0], max_new, config)]
        with ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            return list(pool.map(lambda p: self._one(p, max_new, config), prompts))

    def count_tokens(self, text: str) -> int:
        """Whitespace estimate, matching OllamaLLM.get_num_tokens
        (...mapreduce.py:58-60) for collapse-gating parity."""
        return whitespace_token_count(text)

    def count_tokens_batch(self, texts: list[str]) -> list[int]:
        return [whitespace_token_count(t) for t in texts]
