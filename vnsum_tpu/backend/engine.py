"""TpuBackend — batched, mesh-sharded on-device generation.

This is the component the reference lacks entirely: its map fan-out executes
serially over HTTP (SURVEY.md §1 "critical architectural observation",
runners/run_summarization_ollama_mapreduce.py:51-52). Here a list of prompts
becomes length-bucketed, fixed-shape [B, S] device batches:

- left-padded prompts so prefill's last row and every decode step share one
  write index across the batch (static shapes, no ragged gather);
- one jit-compiled prefill + early-exit `while_loop` decode program per
  (B, S) bucket, cached — bucketing bounds XLA recompiles, and decode stops
  as soon as every row has emitted EOS instead of paying the full budget;
- greedy or sampled decoding with per-sequence EOS masking inside the loop;
- params and token batches carry NamedShardings over a (data, model) mesh, so
  the same program runs single-chip or TP/DP-sharded with GSPMD collectives.

Telemetry: the host loops publish phase events (tokenize, prefill,
dispatch, decode_seg, spec_step, detokenize) through obs.trace.emit() — host
timestamps around device calls whose sync the loop already paid (done-mask /
result fetches), a no-op unless a collector is installed (the serving
scheduler's BatchTrace; see backend/base.py for the contract). These feed
the vnsum_serve_ttft_seconds anchor and the /debug/trace batch tracks.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.sanitizers import hot_path_transfer_guard
from ..core.config import GenerationConfig
from ..core.logging import get_logger
from .base import (
    fold_seed,
    left_pad_batch,
    mask_unsampleable,
    resolve_max_new,
    sampling_vocab,
    terminator_ids,
    trim_to_eos,
)
from ..core.profiling import annotate
from ..obs.trace import current_collector, emit
from ..testing.faults import fault
from ..models.llama import (
    LlamaConfig,
    decode_attention_mask,
    forward,
    init_kv_cache,
    init_params,
    llama32_3b,
    prefill_attention_mask,
    prefill_positions,
    verify_attention_mask,
    verify_positions,
)
from ..models.sampling import draft_acceptance_rows, sample_logits_rows
from ..text.tokenizer import Tokenizer, get_tokenizer

logger = get_logger("vnsum.engine")

_BUCKETS = (64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384)


def _bucket_len(n: int, max_len: int) -> int:
    for b in _BUCKETS:
        if n <= b and b <= max_len:
            return b
    return max_len


@dataclass
class EngineStats:
    """Wall-clock + token accounting for bench.py / run records."""

    calls: int = 0
    prompts: int = 0
    prompt_tokens: int = 0
    generated_tokens: int = 0
    compile_seconds: float = 0.0
    generate_seconds: float = 0.0
    batches: int = 0
    # speculative decoding (spec path): batched verify forwards run, draft
    # tokens proposed to them, and draft tokens the model kept. Mean
    # accepted-per-step = spec_accepted_tokens / spec_verify_steps; every
    # step additionally retires one model-own token.
    spec_verify_steps: int = 0
    spec_draft_tokens: int = 0
    spec_accepted_tokens: int = 0
    # prefix KV cache (vnsum_tpu.cache): prompt tokens whose prefill was
    # skipped by resuming from cached prefix blocks, vs tokens prefilled
    # from scratch — hit/(hit+miss) is the prefill-token reduction
    cache_hit_tokens: int = 0
    cache_miss_tokens: int = 0
    compactions: int = 0
    compacted_batch_sizes: list = field(default_factory=list)
    by_bucket: dict = field(default_factory=dict)
    # host-phase wall clock (always on: the timers wrap pure-host work) plus,
    # under instrument=True, the device phases "prefill"/"decode" measured by
    # explicit result-fetch sync (jax.device_get — block_until_ready is
    # unreliable on the tunnel, PERF.md measurement hygiene; every hot-path
    # fetch is a lint-acknowledged device_get, see analysis/rules/host_sync)
    phase_seconds: dict = field(default_factory=dict)
    # instrument=True: one record per device dispatch {B, S, steps,
    # prefill_s, decode_s} — enough to reconstruct FLOP and HBM-byte budgets
    # per batch shape without re-deriving them from logs
    dispatches: list = field(default_factory=list)

    def add_phase(self, name: str, seconds: float) -> None:
        self.phase_seconds[name] = self.phase_seconds.get(name, 0.0) + seconds

    @property
    def tokens_per_second(self) -> float:
        total = self.prompt_tokens + self.generated_tokens
        return total / self.generate_seconds if self.generate_seconds else 0.0


class TpuBackend:
    name = "tpu"

    def __init__(
        self,
        model_config: LlamaConfig | None = None,
        tokenizer: str | Tokenizer = "byte",
        mesh=None,
        params=None,
        batch_size: int = 8,
        max_new_tokens: int = 1024,
        generation: GenerationConfig | None = None,
        seed: int = 0,
        flash: str | bool = "auto",
        quantize: bool = False,
        quantize_act: bool = False,
        quantize_kv: str | bool = "auto",
        continuous: str | bool = "auto",
        segment_tokens: int = 128,
        min_batch: int = 8,
        interpret: bool = False,
        instrument: bool = False,
        prefill_chunk_tokens: int = 0,
        spec_max_ref_tokens: int = 4096,
        cache_blocks: int = 0,
        cache_block_tokens: int = 64,
    ) -> None:
        from ..core.jax_cache import enable_compilation_cache

        enable_compilation_cache()  # per-bucket programs amortize on disk
        self.cfg = model_config or llama32_3b()
        if quantize_act:
            # W8A8 prefill (models.llama._proj): double-rate s8xs8 MXU
            # dots on multi-token forwards. LOSSY (per-token activation
            # rounding) and meaningless without int8 weights
            if not quantize:
                raise ValueError(
                    "quantize_act (W8A8 prefill) requires quantize=True — "
                    "without int8 weights there is no s8xs8 matmul to run"
                )
            import dataclasses

            self.cfg = dataclasses.replace(self.cfg, w8a8_prefill=True)
        self.interpret = bool(interpret)
        # Pallas flash prefill: "auto" enables it on real TPU (the kernel
        # needs Mosaic; CPU tests pass interpret=True explicitly). Under a
        # mesh the kernels run per-shard inside shard_map — batch and heads
        # are data/model-local, so no cross-chip softmax is needed.
        if flash == "auto":
            flash = jax.default_backend() == "tpu"
        # sliding-window (Gemma) configs run the kernels too: the per-layer
        # window is a runtime scalar the kernels clamp their k-range with
        # (ops/flash_attention.py, ops/decode_attention.py)
        self.flash = bool(flash)
        # int8 KV cache halves decode-attention HBM traffic; the in-kernel
        # dequant needs the Pallas path, so "auto" follows flash AND actual
        # kernel support (head_dim lane alignment — e.g. llama32_1b's
        # head_dim=64 can't take the kernels, and the dense fallback would
        # dequantize the whole cache per step)
        kernels_supported = self.cfg.head_dim % 128 == 0 or self.interpret
        if quantize_kv == "auto":
            quantize_kv = self.flash and kernels_supported
        elif quantize_kv and not (self.flash and kernels_supported):
            raise ValueError(
                "quantize_kv=True needs the Pallas kernels (flash=True and "
                "head_dim a multiple of 128); the dense fallback would "
                "dequantize the whole cache per step"
            )
        self.quantize_kv = bool(quantize_kv)
        self.tok = get_tokenizer(tokenizer) if isinstance(tokenizer, str) else tokenizer
        self.mesh = mesh
        self.batch_size = batch_size
        self.max_new_tokens = max_new_tokens
        self.gen_cfg = generation or GenerationConfig()
        if max_new_tokens >= self.cfg.max_seq_len:
            raise ValueError(
                f"max_new_tokens={max_new_tokens} must be < "
                f"max_seq_len={self.cfg.max_seq_len}"
            )
        # continuous scheduling (segmented decode + tail compaction): decode
        # runs in fixed segments; at segment boundaries finished rows are
        # harvested and the survivors compacted into a half-size program, so
        # ragged generation lengths don't pay full-batch decode for the tail.
        # Streams are keyed per row (seed, uid, step) so compaction never
        # changes which random draws a surviving row makes; across the
        # batch-shape change, logits can still differ in the last bits
        # (different matmul tilings accumulate in different orders), so
        # outputs are bit-identical on same-shape replays and test-exact in
        # CPU/interpret runs, but near-tie tokens can flip across a
        # compaction on real hardware. Under a mesh, compaction only halves
        # down to batch shapes that stay divisible by the data axis.
        #
        # "auto" policy, from the measured A/B (artifacts/compaction_ab.json,
        # PERF.md finding 13): the segmented path LOST token-normalized at
        # BOTH tested shapes (0.68x at B=8/S=8192, 0.82x at B=64/S=1024,
        # compactions firing 6-8 times) — segment-boundary host syncs, the
        # un-donated compaction gather, and the cross-dispatch resident
        # carry outweigh the shed-row cache savings at summary-length decode
        # budgets. One-shot (early-exit while_loop) is the default; the
        # segmented scheduler remains available explicitly for workloads
        # with long ragged tails (multi-hundred-token budgets where a few
        # stragglers pin an otherwise-finished batch).
        if continuous == "auto":
            continuous = False
        self.continuous = bool(continuous)
        self.segment_tokens = max(segment_tokens, 1)
        self.min_batch = max(min_batch, 1)
        # prefill in slices of this many tokens (0 = whole prompt): caps
        # prefill transients at CL tokens' worth so decode batches beyond
        # the whole-prompt memory ceiling fit (B=16 at S=8192 on one v5e —
        # measured 1.36x decode / 1.10x whole-dispatch vs 2x B=8,
        # artifacts/b16_chunked_prefill.json)
        if prefill_chunk_tokens < 0 or (
            prefill_chunk_tokens and prefill_chunk_tokens % 128
        ):
            raise ValueError(
                "prefill_chunk_tokens must be a non-negative multiple of 128"
            )
        self.prefill_chunk_tokens = int(prefill_chunk_tokens)
        # instrument=True: run the SPLIT prefill + decode programs (same
        # _make_parts bodies as the one-shot jit, so identical math) with a
        # result-fetch sync between them, so stats.phase_seconds carries a
        # real per-phase device-time budget. Decode runs as ONE full-length
        # segment and compaction is disabled — the only deltas vs the
        # one-shot program are the extra dispatch boundary and the done
        # fetch, a few percent of wall clock (artifacts/compaction_ab.json).
        self.instrument = bool(instrument)
        if instrument:
            self.continuous = True
            self.segment_tokens = 1 << 30      # single full-length segment
            self.min_batch = max(self.min_batch, batch_size)  # no compaction
        self.stats = EngineStats()
        self._fns: dict[tuple[int, int, int], callable] = {}
        self._seg_fns: dict = {}
        self._compact_fn = None
        self._seed = seed
        self._dispatch = 0
        # reference-guided speculative decoding (vnsum_tpu.spec): cap on
        # tokens encoded per reference (matching window, not attention — a
        # longer reference only loses tail draft coverage)
        self.spec_max_ref_tokens = int(spec_max_ref_tokens)
        self._spec_report: list = []
        self._warned_spec_fallback = False
        # radix prefix KV cache (vnsum_tpu.cache): cache_blocks > 0 retains
        # prefix KV blocks on device after prefill and later batches resume
        # prefill from the matched prefix, computing only the suffix. Under
        # a mesh the block pool shards its KV heads over `model` (mirroring
        # cache_specs) and the gather/extract programs run as sharded
        # dynamic_update_slice — the host-side radix index is unchanged.
        self.prefix_cache = None
        self._cache_report: list = []
        self._hint_ids_cache: dict[str, list[int]] = {}
        # degradation-ladder hook (serve/supervisor.py NO_CACHE_INSERT):
        # False stops pool insertion/eviction churn while matched prefixes
        # keep serving resume-prefill hits
        self.cache_inserts_enabled = True
        if cache_blocks:
            if not 1 <= cache_block_tokens <= 128:
                # the resume boundary K is 128-aligned, and the padded-gather
                # safety argument (scratch writes land inside the recomputed
                # [K, S) span) needs blocks no wider than that alignment
                raise ValueError("cache_block_tokens must be in [1, 128]")
            from ..cache import PrefixCache

            self.prefix_cache = PrefixCache(
                cache_blocks, cache_block_tokens,
                n_layers=self.cfg.n_layers,
                n_kv_heads=self.cfg.n_kv_heads,
                head_dim=self.cfg.head_dim, dtype=self.cfg.dtype,
                quantized=self.quantize_kv, mesh=mesh,
            )
            logger.info(
                "prefix KV cache: %d blocks x %d tokens (%.1f MB HBM)",
                cache_blocks, cache_block_tokens,
                self.prefix_cache.store.hbm_bytes / 1e6,
            )

        if params is None:
            t0 = time.time()
            from ..models import jitted_init

            params = jitted_init(init_params, self.cfg, seed)
            logger.info("initialized random params in %.1fs", time.time() - t0)
        if quantize:
            from ..models.quant import is_quantized, quantize_params

            if not is_quantized(params):
                t0 = time.time()
                params = jax.jit(quantize_params)(params)
                logger.info("int8-quantized params in %.1fs", time.time() - t0)
        if mesh is not None:
            from ..parallel.sharding import shard_params

            params = shard_params(params, mesh, self.cfg.tie_embeddings)
            if batch_size % mesh.shape.get("data", 1):
                raise ValueError("batch_size must be divisible by mesh data axis")
        self.params = params

    # -- compiled program per bucket ------------------------------------

    def _sampling_setup(self, gen: GenerationConfig):
        """(eos ids, vocab limit, restrict fn) — the ONE sampling restriction
        shared by the plain decode programs (_make_parts) and the spec verify
        step, so the two paths can never disagree on what is sampleable.
        Never sample a token the tokenizer cannot render as text — but keep
        every terminator sampleable even when it sits above the decodable
        range (ByteTokenizer's eos_id=257 >= 256 raw bytes)."""
        terminators = terminator_ids(self.tok, gen)
        eos = jnp.asarray(terminators, dtype=jnp.int32)
        vocab_limit, allowed = sampling_vocab(
            self.tok, self.cfg.vocab_size, terminators
        )
        allowed_dev = None if allowed is None else jnp.asarray(allowed)

        def restrict(row_logits):  # [..., vocab_limit]
            return mask_unsampleable(row_logits, allowed_dev)

        return eos, vocab_limit, restrict

    def _make_parts(self, B: int, S: int, max_new: int, gen: GenerationConfig,
                    resume_from: int = 0):
        """The two traceable halves every generation program is composed of:

        prefill_part(params, tokens, pad_lens, seed[, cache])
            -> (first_token, cache, done0)
        decode_part(params, t0, cur, cache, done, uids, out, pad_lens,
                    t_end, seed)
            -> (t, cur, cache, done, out)

        Sampling is counter-based per row: step t of row uid draws from
        fold_in(fold_in(key(seed), uid), t). A row's stream therefore
        depends only on (seed, uid, t) — never on its batch position — so
        the continuous scheduler can compact a sampled batch mid-decode
        with bit-identical surviving outputs (greedy was always safe).

        The one-shot program is prefill + one decode to t_end=max_new in a
        single jit; the continuous scheduler jits them separately and runs
        decode in segments — ONE body definition serves both, so the paths
        cannot drift.

        ``resume_from=K`` (prefix KV cache, vnsum_tpu.cache) builds the
        resume-prefill variant: prefill_part takes a cache pre-seeded with
        gathered prefix blocks and runs the forward only over cache slots
        [K, S) — positions and masks are unchanged, so the math over the
        computed span is identical to full prefill's."""
        cfg = self.cfg
        C = S + max_new
        eos, vocab_limit, restrict = self._sampling_setup(gen)
        pad_id = self.tok.pad_id
        use_flash, use_flash_decode = self._decode_settings(S, C)
        mesh = self.mesh
        quantize_kv = self.quantize_kv
        interpret = self.interpret
        layer_window = self._layer_window_fn()

        # prefill runs whole-prompt or in prefill_chunk_tokens slices —
        # chunking caps transient activations (q/k/v, MLP intermediates)
        # at a chunk's worth, which is what lets B=16 decode fit at S=8192
        # (measured 1.36x decode vs 2x B=8 dispatches,
        # artifacts/b16_chunked_prefill.json); see _prefill_forward
        def prefill_part(params, tokens, pad_lens, seed, cache=None):
            logits, cache = self._prefill_forward(
                params, tokens, pad_lens, B, S, C, use_flash, layer_window,
                cache=cache, start=resume_from,
            )
            base = jax.random.key(seed)
            uids0 = jnp.arange(B, dtype=jnp.int32)
            keys0 = jax.vmap(
                lambda u: jax.random.fold_in(jax.random.fold_in(base, u), 0)
            )(uids0)
            first = sample_logits_rows(
                restrict(logits[:, -1, :vocab_limit]), keys0,
                gen.temperature, gen.top_k, gen.top_p,
            )
            # all-pad dummy rows (batch bucketing filler) start done, else
            # their garbage decode would keep the early exit from firing
            done0 = pad_lens == S
            return first, cache, done0

        def decode_part(
            params, t0, cur, cache, done, uids, out, pad_lens, t_end, seed
        ):
            base = jax.random.key(seed)
            # decode loop with early exit: a while_loop instead of a fixed
            # lax.scan, so the program stops as soon as every row has hit
            # EOS (real summaries end far before the max_new budget)
            def emit_token(out, cur, done, t):
                emit = jnp.where(done, pad_id, cur)
                out = jax.lax.dynamic_update_slice(out, emit[:, None], (0, t))
                return out, done | jnp.isin(cur, eos)

            def cond(carry):
                t, _cur, _cache, done, _out = carry
                return (t < t_end) & ~jnp.all(done)

            def body(carry):
                t, cur, cache, done, out = carry
                out, done = emit_token(out, cur, done, t)
                pos = (S - pad_lens) + t
                mask_t = decode_attention_mask(pad_lens, S + t, C)
                stacked_fn = None
                if use_flash_decode and mesh is not None:
                    from ..ops.sharded import sharded_flash_decode

                    def stacked_fn(q, cache, layer_idx):
                        return sharded_flash_decode(
                            mesh, q, cache, layer_idx, pad_lens, S + t,
                            cfg.q_per_kv, layer_window(layer_idx),
                            interpret=interpret,
                        )
                elif use_flash_decode:
                    from ..ops.decode_attention import flash_decode_attention

                    def stacked_fn(q, cache, layer_idx):
                        return flash_decode_attention(
                            q, cache, layer_idx, pad_lens, S + t,
                            cfg.q_per_kv, layer_window(layer_idx),
                            interpret=interpret,
                        )

                logits, cache = forward(
                    params, cfg, cur[:, None], pos[:, None], cache, S + t,
                    mask_t, stacked_attention_fn=stacked_fn,
                )
                step_keys = jax.vmap(
                    lambda u: jax.random.fold_in(
                        jax.random.fold_in(base, u), t + 1
                    )
                )(uids)
                nxt = sample_logits_rows(
                    restrict(logits[:, -1, :vocab_limit]), step_keys,
                    gen.temperature, gen.top_k, gen.top_p,
                )
                return (t + 1, nxt, cache, done, out)

            # each iteration emits BEFORE sampling, so on exit (budget spent
            # or all rows done) every live slot is already written and the
            # rest remain pad from the init — identical to a full-length scan
            return jax.lax.while_loop(
                cond, body, (t0, cur, cache, done, out)
            )

        return prefill_part, decode_part

    def _make_fn(self, B: int, S: int, max_new: int, gen: GenerationConfig,
                 resume_from: int = 0):
        pad_id = self.tok.pad_id
        prefill_part, decode_part = self._make_parts(
            B, S, max_new, gen, resume_from
        )
        # with the prefix cache on, the one-shot program also returns its
        # final cache: decode never touches slots < S, so the prompt's
        # prefix KV survives for post-call insertion into the block pool
        return_cache = self.prefix_cache is not None

        def run(params, tokens, pad_lens, seed, cache):
            first, cache, done0 = prefill_part(
                params, tokens, pad_lens, seed, cache
            )
            out0 = jnp.full((B, max_new), pad_id, dtype=jnp.int32)
            uids = jnp.arange(B, dtype=jnp.int32)
            _, _, cache, _, out = decode_part(
                params, jnp.int32(0), first, cache, done0, uids, out0,
                pad_lens, max_new, seed,
            )
            return (out, cache) if return_cache else out  # out: [B, max_new]

        if resume_from:
            # the seeded cache is consumed — donate its buffer
            return jax.jit(run, donate_argnums=(4,))

        def generate(params, tokens, pad_lens, seed):
            return run(params, tokens, pad_lens, seed, None)

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            out_sh = NamedSharding(self.mesh, P("data", None))
            if return_cache:
                # the returned final cache keeps the (data, model) cache
                # layout — a bare single sharding would broadcast P(data,)
                # over every cache leaf and silently re-layout the pool copies
                from ..parallel.sharding import cache_specs

                out_sh = (
                    out_sh,
                    jax.tree.map(
                        lambda s: NamedSharding(self.mesh, s),
                        cache_specs(quantized=self.quantize_kv),
                        is_leaf=lambda x: not isinstance(x, dict),
                    ),
                )
            return jax.jit(
                generate,
                in_shardings=self._mesh_in_shardings(),
                out_shardings=out_sh,
            )
        return jax.jit(generate)

    def _mesh_in_shardings(self):
        """in_shardings for (params, tokens, pad_lens, seed) — shared by the
        one-shot and continuous prefill builders so the two paths cannot
        compile against different input layouts."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..models.quant import is_quantized
        from ..parallel.sharding import param_shardings

        ns = lambda spec: NamedSharding(self.mesh, spec)
        return (
            param_shardings(
                self.mesh, self.cfg.tie_embeddings, is_quantized(self.params),
                qk_norm=self.cfg.qk_norm,
                sandwich_norms=self.cfg.sandwich_norms,
            ),
            ns(P("data", None)),
            ns(P("data")),
            None,
        )

    def _get_fn(self, B: int, S: int, max_new: int, gen: GenerationConfig,
                resume_from: int = 0):
        # seed is a runtime argument to the compiled program, not a trace
        # constant — exclude it from the cache key so seed sweeps reuse code
        key = (B, S, max_new, gen.with_(seed=0), resume_from)
        if key not in self._fns:
            t0 = time.time()
            self._fns[key] = self._make_fn(B, S, max_new, gen, resume_from)
            logger.info(
                "built generate fn for bucket B=%d S=%d new=%d resume=%d",
                B, S, max_new, resume_from,
            )
            self.stats.compile_seconds += time.time() - t0
        return self._fns[key]

    # -- shared prefill wiring -------------------------------------------

    def _layer_window_fn(self):
        """Per-layer runtime window scalar for sliding-window (Gemma)
        configs: 0 on global layers, else the config window — one compiled
        kernel serves both kinds. None-returning on dense configs."""
        cfg = self.cfg
        if cfg.sliding_window:
            from ..models.llama import _layer_global_flags

            win_flags = _layer_global_flags(cfg)

            def layer_window(layer_idx):
                return jnp.where(
                    win_flags[layer_idx], 0, cfg.sliding_window
                ).astype(jnp.int32)

            return layer_window
        return lambda layer_idx: None

    def _init_prefill_cache(self, B: int, C: int):
        """Fresh KV cache with the mesh layout pinned (batch over data,
        heads over model) instead of left to GSPMD propagation."""
        cache = init_kv_cache(self.cfg, B, C, quantized=self.quantize_kv)
        if self.mesh is not None:
            from jax.sharding import NamedSharding

            from ..parallel.sharding import cache_specs

            cache = jax.lax.with_sharding_constraint(
                cache,
                jax.tree.map(
                    lambda s: NamedSharding(self.mesh, s),
                    cache_specs(quantized=self.quantize_kv),
                    is_leaf=lambda x: not isinstance(x, dict),
                ),
            )
        return cache

    def _prefill_stacked(self, use_flash, pad_lens, layer_window,
                         q_offset: int = 0):
        """Flash/sharded-flash stacked-attention fn for a prefill-style
        forward whose queries start at cache slot ``q_offset`` (0 = whole
        prompt; chunked prefill passes each chunk's start). None when the
        dense path is in effect."""
        cfg = self.cfg
        mesh = self.mesh
        interpret = self.interpret
        if not use_flash:
            return None
        if mesh is not None:
            from ..ops.sharded import sharded_flash_prefill

            def stacked_fn(q, cache, layer_idx):
                return sharded_flash_prefill(
                    mesh, q, cache, layer_idx, pad_lens, cfg.q_per_kv,
                    layer_window(layer_idx), q_offset, interpret=interpret,
                )
        else:
            from ..ops.flash_attention import flash_prefill_attention

            def stacked_fn(q, cache, layer_idx):
                return flash_prefill_attention(
                    q, cache, layer_idx, pad_lens, cfg.q_per_kv,
                    layer_window(layer_idx), q_offset, interpret=interpret,
                )

        return stacked_fn

    def _prefill_forward(self, params, tokens, pad_lens, B, S, C,
                         use_flash, layer_window, cache=None, start=0):
        """Whole- or chunked-prompt prefill; returns (last-position logits,
        cache). ONE copy shared by prefill_part (_make_parts) and the choice
        scorer (_make_choice_fn), so the two paths cannot drift AND the
        chunked path's memory headroom applies to both. Called inside traced
        functions — pad_lens is a tracer; chunk boundaries are trace-static.

        ``start`` > 0 is the prefix-cache resume boundary K: ``cache``
        arrives pre-seeded with gathered prefix KV for slots < K and the
        forward runs only over [K, S) — the same shape as chunked prefill's
        later chunks (positions/masks are sliced, q_offset places the
        queries), so resume and chunked share all their machinery."""
        cfg = self.cfg
        if cache is None:
            cache = self._init_prefill_cache(B, C)
        positions = prefill_positions(pad_lens, S)
        mask = prefill_attention_mask(pad_lens, S, C)
        CL = self.prefill_chunk_tokens
        span = S - start
        n_chunks = -(-span // CL) if CL and span > CL else 1
        if n_chunks == 1:
            if start:
                tokens = tokens[:, start:]
                positions = positions[:, start:]
                mask = mask[:, start:, :]
            return forward(
                params, cfg, tokens, positions, cache, start, mask,
                last_only=True,
                stacked_attention_fn=self._prefill_stacked(
                    use_flash, pad_lens, layer_window, q_offset=start
                ),
            )
        # chunked: transient activations scale with the CHUNK length, not
        # the full S — the kernel's q_offset places chunk c's queries at
        # cache slots [lo, hi) (see prefill_part's rationale comment)
        for c in range(n_chunks):
            lo, hi = start + c * CL, min(S, start + (c + 1) * CL)
            logits, cache = forward(
                params, cfg, tokens[:, lo:hi], positions[:, lo:hi],
                cache, lo, mask[:, lo:hi, :],
                last_only=(c == n_chunks - 1),
                stacked_attention_fn=self._prefill_stacked(
                    use_flash, pad_lens, layer_window, q_offset=lo
                ),
            )
        return logits, cache

    # -- constrained choice scoring --------------------------------------

    def _make_choice_fn(self, B: int, S: int, K: int):
        """Compiled multiple-choice scorer: one prefill, last-position
        logits gathered at K candidate token ids, per-row argmax index.

        This is the constrained-decoding primitive behind the G-Eval device
        judge (eval/geval.py LLMJudge(constrained=True)): the JSON verdict
        template is forced on the host and only the score token is chosen
        by device logits, so the judge cannot emit an unparseable verdict.
        The reference's judge loop (evaluate/evaluate_summaries_semantic.py:
        203-433) trusts a remote LLM to emit parseable JSON and contains
        per-case failures; containment still exists here, but constrained
        choice makes success the typical case instead of the lucky one."""
        C = S  # no decode budget — the cache only satisfies forward()
        use_flash, _ = self._decode_settings(S, C)
        mesh = self.mesh
        layer_window = self._layer_window_fn()

        def choose(params, tokens, pad_lens, choice_ids):
            logits, _ = self._prefill_forward(
                params, tokens, pad_lens, B, S, C, use_flash, layer_window
            )
            row = logits[:, -1, :]                       # [B, V] float32
            picked = jnp.take(row, choice_ids, axis=-1)  # [B, K]
            # argmax over the K picked logits is the full decision — no
            # softmax needed (monotone), so none is paid
            return jnp.argmax(picked, axis=-1).astype(jnp.int32)

        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            ns = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
            return jax.jit(
                choose,
                in_shardings=(
                    self._mesh_in_shardings()[0],
                    ns(P("data", None)),
                    ns(P("data")),
                    None,
                ),
            )
        return jax.jit(choose)

    # hot path
    def score_choices(
        self, prompts: list[str], choices: list[str]
    ) -> list[int]:
        """For each prompt, return the index of the choice whose FIRST token
        has the highest next-token logit after prefilling the prompt.

        Prompts that exceed the context are truncated from the LEFT — the
        tail is where a forced template ends, so it must survive. Choices
        must differ in their first token id (single-token constraint; the
        G-Eval judge uses the digits "1".."5", one byte each)."""
        ids = []
        for c in choices:
            enc = self.tok.encode(c, add_bos=False)
            if not enc:
                raise ValueError(f"choice {c!r} encodes to no tokens")
            ids.append(enc[0])
        if len(set(ids)) != len(ids):
            raise ValueError("choices must differ in their first token")
        choice_dev = jnp.asarray(ids, dtype=jnp.int32)

        self.stats.calls += 1
        self.stats.prompts += len(prompts)
        max_input = self.cfg.max_seq_len
        encoded: list[list[int]] = []
        t_enc = time.time()
        for tok_ids in self.tok.encode_batch(prompts, add_bos=True):
            if len(tok_ids) > max_input:
                tok_ids = [tok_ids[0]] + tok_ids[-(max_input - 1):]
            encoded.append(tok_ids)
            self.stats.prompt_tokens += len(tok_ids)
        self.stats.add_phase("tokenize_host", time.time() - t_enc)

        order = sorted(range(len(encoded)), key=lambda i: len(encoded[i]))
        results: list[int] = [0] * len(encoded)
        # sanitizer hook (analysis pkg): nullcontext in production; under
        # VNSUM_SANITIZERS=transfer any IMPLICIT device->host transfer in
        # this dispatch loop raises, while the lint-acknowledged explicit
        # device_get fetches pass
        with hot_path_transfer_guard():
            for start in range(0, len(order), self.batch_size):
                group = order[start : start + self.batch_size]
                # max_new=0: choice scoring has no decode budget, so the
                # whole context is prompt space; bucketing/padding rules
                # are shared with generate() via _pack_group
                tokens, pad_lens, B, S = self._pack_group(group, encoded, 0)
                key = ("choice", B, S, len(ids))
                if key not in self._fns:
                    t0 = time.time()
                    self._fns[key] = self._make_choice_fn(B, S, len(ids))
                    logger.info("built choice fn for bucket B=%d S=%d", B, S)
                    self.stats.compile_seconds += time.time() - t0
                t_disp = time.time()
                with annotate(f"choice[B={B},S={S}]"):
                    idx = self._fns[key](
                        self.params, tokens, pad_lens, choice_dev
                    )
                # lint-allow[host-sync-in-hot-path]: result fetch = the sync that makes the choice timing real
                idx_h = jax.device_get(idx)
                if self.instrument:
                    self.stats.add_phase("choice", time.time() - t_disp)
                self.stats.batches += 1
                self.stats.by_bucket[(B, S)] = (
                    self.stats.by_bucket.get((B, S), 0) + 1
                )
                for row, i in enumerate(group):
                    results[i] = int(idx_h[row])
        return results

    # -- continuous scheduling programs ---------------------------------

    def _decode_settings(self, S: int, C: int):
        use_flash = self.flash
        use_flash_decode = False
        if use_flash:
            if self.interpret:  # interpret mode has no lane-alignment limits
                return True, True
            from ..ops.decode_attention import supports_decode
            from ..ops.flash_attention import supports_flash

            use_flash = supports_flash(S, C, self.cfg.head_dim)
            use_flash_decode = supports_decode(C, self.cfg.head_dim)
        return use_flash, use_flash_decode

    def _make_prefill_fn(self, B: int, S: int, max_new: int, gen,
                         resume_from: int = 0):
        prefill_part, _ = self._make_parts(B, S, max_new, gen, resume_from)

        if resume_from:
            return jax.jit(prefill_part, donate_argnums=(4,))

        def prefill(params, tokens, pad_lens, seed):
            return prefill_part(params, tokens, pad_lens, seed)

        if self.mesh is not None:
            return jax.jit(prefill, in_shardings=self._mesh_in_shardings())
        return jax.jit(prefill)

    def _make_segment_fn(self, B: int, S: int, max_new: int, gen):
        """One decode segment: advance up to ``segment_tokens`` steps (early
        exit on all-EOS), carrying (t, cur, cache, done, key, out) across
        host boundaries so finished rows can be harvested and the batch
        compacted between segments. Shares its loop body with the one-shot
        program via _make_parts."""
        _, decode_part = self._make_parts(B, S, max_new, gen)
        seg = self.segment_tokens

        def segment(params, t0, cur, cache, done, uids, out, pad_lens, seed):
            t_end = jnp.minimum(t0 + seg, max_new)
            t, cur, cache, done, out = decode_part(
                params, t0, cur, cache, done, uids, out, pad_lens, t_end, seed
            )
            return t, cur, cache, done, out

        # donate the cache and out buffers: segments overwrite them in place
        return jax.jit(segment, donate_argnums=(3, 6))

    def _make_compact_fn(self):
        def compact(cache, cur, done, out, pad_lens, idx):
            cache = {k: jnp.take(v, idx, axis=1) for k, v in cache.items()}
            return (
                cache, cur[idx], done[idx], out[idx], pad_lens[idx]
            )

        # no donation: the gathered outputs are smaller than the inputs, so
        # the buffers can't be reused (donating only triggers warnings)
        return jax.jit(compact)

    # -- in-flight slot serving programs (backend/inflight.py) -----------

    def _make_slot_prefill_fn(self, B: int, S: int, max_new: int, gen,
                              resume_from: int = 0):
        """Prefill for a JOIN group of the in-flight slot loop: the same
        forward as _make_parts' prefill_part (shared _prefill_forward, so
        chunked and resume prefill ride along), but the first-token sampling
        keys fold per-REQUEST uids passed in rather than the row's position
        in the join batch — a request's sampled stream must not depend on
        when it joined or who it joined with."""
        C = S + max_new
        _eos, vocab_limit, restrict = self._sampling_setup(gen)
        use_flash, _ = self._decode_settings(S, C)
        layer_window = self._layer_window_fn()

        def slot_prefill(params, tokens, pad_lens, seed, uids, cache=None):
            logits, cache = self._prefill_forward(
                params, tokens, pad_lens, B, S, C, use_flash, layer_window,
                cache=cache, start=resume_from,
            )
            base = jax.random.key(seed)
            keys0 = jax.vmap(
                lambda u: jax.random.fold_in(jax.random.fold_in(base, u), 0)
            )(uids)
            first = sample_logits_rows(
                restrict(logits[:, -1, :vocab_limit]), keys0,
                gen.temperature, gen.top_k, gen.top_p,
            )
            # all-pad filler rows (join-batch bucketing) start done
            done0 = pad_lens == S
            return first, cache, done0

        if resume_from:
            # the prefix-cache-seeded cache is consumed — donate its buffer;
            # under a mesh its layout is committed by the sharded gather, so
            # propagation (not in_shardings) carries the mesh layout through
            return jax.jit(slot_prefill, donate_argnums=(5,))
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # same input layouts as every other prefill builder, plus the
            # per-request uids vector riding the batch rows on `data`
            return jax.jit(
                slot_prefill,
                in_shardings=self._mesh_in_shardings()
                + (NamedSharding(self.mesh, P("data")),),
            )
        return jax.jit(slot_prefill)

    def _make_slot_segment_fn(self, B: int, S: int, max_new: int, gen,
                              fused_segments: int = 1):
        """One in-flight decode segment: advance every live slot by up to
        ``segment_tokens`` tokens with PER-ROW step counters — the refill
        path's defining requirement is that slots at different generation
        depths decode together, so the shared scalar ``t`` of decode_part
        becomes a [B] vector and masks/positions/cache-write slots ride the
        spec-verify machinery (verify_attention_mask + vector write_index,
        num_q=1). For any single row the emitted-token math is exactly
        decode_part's, so greedy outputs match the one-shot path with the
        same caveat class as compaction (batch-shape tiling last bits).

        ``fused_segments`` fuses N host boundaries into ONE dispatch
        (Kernel Looping, arXiv 2410.23668): the same while_loop simply runs
        to ``segment_tokens * N`` with the on-device all-rows-done stop
        unchanged — per-row math is identical to N back-to-back dispatches,
        so greedy outputs are byte-identical to N=1 by construction; only
        the host's join/poll cadence coarsens."""
        cfg = self.cfg
        C = S + max_new
        eos, vocab_limit, restrict = self._sampling_setup(gen)
        _, use_flash_decode = self._decode_settings(S, C)
        # the per-row-fills Pallas kernel is the one genuinely single-chip
        # piece left (multi-position ragged reads, like spec verify); under
        # a mesh the dense per-row path below serves the same math
        use_kernel = use_flash_decode and self.mesh is None
        interpret = self.interpret
        layer_window = self._layer_window_fn()
        seg = self.segment_tokens * max(int(fused_segments), 1)

        def segment(params, t, cur, cache, done, uids, out, pads, seed):
            base = jax.random.key(seed)

            def emit_row(o, c, tt, d):
                # done rows hold a frozen cursor: an unguarded write would
                # clobber the row's last real token with its stale cur
                upd = jax.lax.dynamic_update_slice(o, c[None], (tt,))
                return jnp.where(d, o, upd)

            def cond(carry):
                k, _t, _cur, _cache, done, _out = carry
                return (k < seg) & ~jnp.all(done)

            def body(carry):
                k, t, cur, cache, done, out = carry
                # emit BEFORE sampling, mirroring decode_part: on exit every
                # live token is written and the rest stay pad from the init
                out = jax.vmap(emit_row)(out, cur, t, done)
                done = done | jnp.isin(cur, eos)
                fills = S + t                                   # [B]
                positions = verify_positions(pads, fills, 1)
                mask = verify_attention_mask(pads, fills, 1, C)
                stacked_fn = None
                if use_kernel:
                    from ..ops.decode_attention import (
                        flash_spec_verify_attention,
                    )

                    def stacked_fn(q, cache_d, layer_idx):
                        return flash_spec_verify_attention(
                            q, cache_d, layer_idx, pads, fills,
                            cfg.q_per_kv, layer_window(layer_idx),
                            interpret=interpret,
                        )

                logits, cache = forward(
                    params, cfg, cur[:, None], positions, cache, fills,
                    mask, stacked_attention_fn=stacked_fn,
                )
                step_keys = jax.vmap(
                    lambda u, tt: jax.random.fold_in(
                        jax.random.fold_in(base, u), tt + 1
                    )
                )(uids, t)
                nxt = sample_logits_rows(
                    restrict(logits[:, -1, :vocab_limit]), step_keys,
                    gen.temperature, gen.top_k, gen.top_p,
                )
                # done rows freeze t (their out cursor) and cur; live rows
                # advance exactly like decode_part's shared t
                t = jnp.where(done, t, t + 1)
                done = done | (t >= max_new)
                cur = jnp.where(done, cur, nxt)
                return (k + 1, t, cur, cache, done, out)

            _, t, cur, cache, done, out = jax.lax.while_loop(
                cond, body, (jnp.int32(0), t, cur, cache, done, out)
            )
            return t, cur, cache, done, out

        # donate the resident cache and out buffers: segments overwrite
        # them in place, exactly like the continuous path's segment fn
        return jax.jit(segment, donate_argnums=(3, 6))

    def _make_adopt_fn(self, Bj: int):
        """Refill program: scatter a join group's freshly prefilled cache
        rows and per-row state into the resident slot batch at the target
        slot indices — one advanced-index scatter per cache leaf, the same
        per-row dynamic_update_slice-class machinery the prefix-cache store
        uses for gathers. ``slot_idx`` entries are DISTINCT free slots by
        construction (the loop caps the join bucket at the free-slot
        count), so scatter ordering never matters."""
        pad_id = self.tok.pad_id

        def adopt(cache, cur, done, t, out, pads,
                  join_cache, first, done0, join_pads, slot_idx):
            cache = {
                k: v.at[:, slot_idx].set(join_cache[k])
                for k, v in cache.items()
            }
            cur = cur.at[slot_idx].set(first)
            done = done.at[slot_idx].set(done0)
            t = t.at[slot_idx].set(0)
            out = out.at[slot_idx].set(pad_id)
            pads = pads.at[slot_idx].set(join_pads)
            return cache, cur, done, t, out, pads

        # donate the resident cache/out (overwritten in place); the join
        # cache is NOT donated — the scatter reads it into differently
        # shaped outputs, so donation would only trigger warnings
        return jax.jit(adopt, donate_argnums=(0, 4))

    def start_slot_loop(
        self,
        slots: int | None = None,
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        prompt_tokens: int = 0,
        fused_segments: int = 1,
    ):
        """Open a persistent in-flight serving loop: a fixed-shape decode
        batch of ``slots`` rows where finished rows are harvested at every
        segment boundary and freed slots are REFILLED from new prompts
        (chunked prefill + adopt-scatter into the resident cache) instead of
        only compacted — Orca-style iteration-level scheduling over the
        segmented-decode machinery. Under a mesh the resident batch rows
        shard over `data` and heads over `model` (the same layout every
        other decode program uses), so the loop runs TP/DP-sharded; the
        slot count must stay divisible by the data axis. ``prompt_tokens``
        fixes the prompt bucket S (0 = the full context minus the decode
        budget); prompts that don't fit are rejected at admit for the
        caller to route through the one-shot path, which remains
        generate()'s default. ``fused_segments`` fuses N decode segments
        into one dispatch with async host polling (see TpuSlotLoop.step) —
        joins/cancels/preemption coarsen to the fused cadence while greedy
        outputs stay byte-identical to N=1."""
        from .inflight import TpuSlotLoop

        n_slots = slots or self.batch_size
        if self.mesh is not None:
            data_size = self.mesh.shape.get("data", 1)
            if n_slots % data_size:
                raise ValueError(
                    f"slots={n_slots} must be divisible by the mesh data "
                    f"axis ({data_size}) — resident batch rows shard over it"
                )
            if data_size > 1 and n_slots < 2 * data_size:
                # join batches need >= data_size free slots before they can
                # form; at slots == data_size that means ONLY a fully
                # drained loop can refill — legal, but it silently degrades
                # iteration-level scheduling to batch dispatch
                logger.warning(
                    "slots=%d with mesh data axis %d: refill can only fire "
                    "once >= %d slots are free, so in-flight joins will be "
                    "rare — use slots >= %d to keep refill granular",
                    n_slots, data_size, data_size, 2 * data_size,
                )
        gen = config or self.gen_cfg
        max_new = resolve_max_new(max_new_tokens, gen, self.max_new_tokens)
        if max_new >= self.cfg.max_seq_len:
            raise ValueError(
                f"max_new_tokens={max_new} must be < "
                f"max_seq_len={self.cfg.max_seq_len}"
            )
        max_input = self.cfg.max_seq_len - max_new
        S = prompt_tokens or _bucket_len(max_input, max_input)
        if S > max_input:
            raise ValueError(
                f"prompt_tokens={S} exceeds the context budget "
                f"{max_input} (max_seq_len - max_new_tokens)"
            )
        return TpuSlotLoop(
            self, n_slots, S, max_new, gen, seed=self._next_seed(gen),
            fused_segments=fused_segments,
        )

    def _get_seg_fn(self, kind: str, B: int, S: int, max_new: int, gen,
                    resume_from: int = 0, fused: int = 1):
        key = (kind, B, S, max_new, gen.with_(seed=0), resume_from, fused)
        if key not in self._seg_fns:
            t0 = time.time()
            if kind == "prefill":
                fn = self._make_prefill_fn(B, S, max_new, gen, resume_from)
            elif kind == "slot_prefill":
                fn = self._make_slot_prefill_fn(B, S, max_new, gen, resume_from)
            elif kind == "slot_seg":
                fn = self._make_slot_segment_fn(B, S, max_new, gen, fused)
            elif kind == "adopt":
                fn = self._make_adopt_fn(B)
            else:
                fn = self._make_segment_fn(B, S, max_new, gen)
            self._seg_fns[key] = fn
            logger.info("built %s fn for bucket B=%d S=%d", kind, B, S)
            self.stats.compile_seconds += time.time() - t0
        return self._seg_fns[key]

    def _next_seed(self, gen: GenerationConfig) -> int:
        s = fold_seed(gen.seed, self._seed, self._dispatch)
        self._dispatch += 1
        return s

    # hot path
    def _run_group_continuous(
        self, group, encoded, max_new: int, gen, results, seed: int,
        packed=None, resume=None, insert_cb=None,
    ) -> None:
        """Generate one prompt group with segmented decode + tail compaction.

        After each segment the done mask is fetched; when the live rows fit
        a half-size (or smaller) program, finished rows are harvested and
        the survivors gathered into it. Output is identical to the one-shot
        path for greedy AND sampled decode — greedy depends only on the
        row's own cache, and sampled streams are keyed by (seed, row uid,
        step), not batch position.

        ``resume`` = (K, seeded_cache) runs the resume-prefill variant over
        [K, S) against prefix-cache blocks already gathered into the cache;
        ``insert_cb(cache)`` fires right after prefill (the copies dispatch
        before the first segment's donation can retire the buffer) so new
        prefix blocks enter the pool."""
        tokens, pads, B, S = (
            packed if packed is not None
            else self._pack_group(group, encoded, max_new)
        )
        rows: list[int | None] = [None] * B
        for row, i in enumerate(group):
            rows[row] = i

        # telemetry gate (vnsum_tpu.obs): resolved ONCE per dispatch — the
        # collector is installed around the whole generate() call, so inside
        # it the answer cannot change, and per-segment emit bookkeeping
        # (timestamps, mask reductions, kwargs) is skipped entirely when off
        tracing = current_collector() is not None
        K = resume[0] if resume else 0
        prefill = self._get_seg_fn("prefill", B, S, max_new, gen, K)
        t_pre = time.time()
        t_pre_m = time.monotonic()
        with annotate(f"prefill[B={B},S={S}]"):
            if resume:
                cur, cache, done = prefill(
                    self.params, tokens, pads, seed, resume[1]
                )
            else:
                cur, cache, done = prefill(self.params, tokens, pads, seed)
            if self.instrument:
                # fetch forces the dispatch to completion: [B] bools, the
                # cheapest output — prefill device time is now bounded
                # lint-allow[host-sync-in-hot-path]: instrument=True exists to bound prefill with exactly this sync
                jax.device_get(done)
        prefill_s = time.time() - t_pre
        # engine step telemetry (vnsum_tpu.obs): host timestamps around the
        # dispatched device call — no extra sync; without instrument=True the
        # dispatch is async and this bounds submission, not device time
        if tracing:
            emit("prefill", t_pre_m, prefill_s, B=B, S=S,
                 occupancy=len(group), synced=self.instrument)
        if self.instrument:
            self.stats.add_phase("prefill", prefill_s)
        self.stats.batches += 1
        self.stats.by_bucket[(B, S)] = self.stats.by_bucket.get((B, S), 0) + 1
        if insert_cb is not None:
            # prefix-cache insertion must read the cache BEFORE the first
            # segment dispatch donates its buffer; the copies dispatch here,
            # in stream order ahead of the donation
            insert_cb(cache)

        out = jnp.full((B, max_new), self.tok.pad_id, dtype=jnp.int32)
        pad_dev = jnp.asarray(pads)
        # per-row RNG identity: sampling keys fold in the row's INITIAL slot
        # index, carried across compactions so surviving streams never change
        uid_of_slot = list(range(B))
        t = jnp.int32(0)
        if self._compact_fn is None:
            self._compact_fn = self._make_compact_fn()
        compact = self._compact_fn

        decode_s = 0.0
        t_h = 0
        while True:
            t_seg = time.time()
            t_seg_m = time.monotonic() if tracing else 0.0
            segment = self._get_seg_fn("segment", B, S, max_new, gen)
            # lint-allow[host-sync-in-hot-path]: host list -> host array for the uids argument, no device sync
            uids_np = np.asarray(uid_of_slot, dtype=np.int32)
            with annotate(f"decode_seg[B={B},S={S}]"):
                t, cur, cache, done, out = segment(
                    self.params, t, cur, cache, done, uids_np, out, pad_dev,
                    seed,
                )
            # ONE explicit fetch for both control values: done gates the
            # harvest/compaction decision and t bounds the budget — this
            # sync IS the segment boundary (and makes its timing real)
            # lint-allow[host-sync-in-hot-path]: segment-boundary done/t fetch is the scheduler's control dependency
            done_h, t_h = jax.device_get((done, t))
            t_h = int(t_h)
            seg_s = time.time() - t_seg
            decode_s += seg_s
            # per-segment telemetry: the done fetch above already synced, so
            # these are true device-step timings; kv_frac is the cache fill
            # at segment end — the decode-attention byte budget driver. The
            # mask reduction + kwargs are gated: untraced runs pay nothing
            if tracing:
                emit("decode_seg", t_seg_m, seg_s, B=B, S=S, steps=t_h,
                     live=int((~done_h).sum()),
                     kv_frac=round((S + t_h) / (S + max_new), 4))
            live = [r for r, orig in enumerate(rows) if orig is not None]
            active = [r for r in live if not done_h[r]]
            if t_h >= max_new or not active:
                break

            # compact when the survivors fit a half-size program (under a
            # mesh, only down to batches the data axis still divides)
            data_size = (
                self.mesh.shape.get("data", 1) if self.mesh is not None else 1
            )
            B_new = B
            while (
                B_new // 2 >= max(len(active), self.min_batch, 1)
                and (B_new // 2) % data_size == 0
            ):
                B_new //= 2
            if B_new < B:
                # lint-allow[host-sync-in-hot-path]: harvesting finished rows' tokens before their slots are compacted away
                out_h = jax.device_get(out)
                for r in live:
                    if done_h[r]:  # harvest leaving rows
                        results[rows[r]] = self._detok(out_h[r], tuple(gen.eos_ids))
                # pad the gather index with done slots (kept inert by done=True)
                filler = [r for r in range(B) if r not in active]
                idx = active + filler[: B_new - len(active)]
                idx_dev = jnp.asarray(idx, dtype=jnp.int32)
                cache, cur, done, out, pad_dev = compact(
                    cache, cur, done, out, pad_dev, idx_dev
                )
                rows = [rows[r] if r in active else None for r in idx]
                uid_of_slot = [uid_of_slot[r] for r in idx]
                B = B_new
                self.stats.compactions += 1
                self.stats.compacted_batch_sizes.append(B_new)
                logger.info(
                    "compacted decode batch to B=%d (%d live, t=%d)",
                    B, len(active), t_h,
                )

        if self.instrument:
            self.stats.add_phase("decode", decode_s)
            self.stats.dispatches.append(
                {
                    "B": B, "S": S, "steps": t_h,
                    "prefill_s": round(prefill_s, 3),
                    "decode_s": round(decode_s, 3),
                }
            )

        # lint-allow[host-sync-in-hot-path]: final result fetch — the generation is over, detok needs the tokens
        out_h = jax.device_get(out)
        for r, orig in enumerate(rows):
            if orig is not None and results[orig] is None:
                results[orig] = self._detok(out_h[r], tuple(gen.eos_ids))

    # -- speculative decoding (reference-guided, vnsum_tpu.spec) ---------

    def _make_spec_fn(self, B: int, S: int, R: int, max_new: int, k: int,
                      gen: GenerationConfig):
        """One jitted speculative step: draft (n-gram suffix match against
        the per-row reference), verify (ONE forward over k+1 query positions
        per row against the KV cache), accept (exact argmax prefix for
        greedy, rejection-style for sampling — models.sampling), emit.

        Per-row state raggedness is the defining difference from decode_part:
        rows accept different draft counts, so fills/emitted counts are [B]
        vectors, cache writes land at per-row slots (llama._cache_write),
        and rejected tokens "roll back" by simply not advancing the row's
        fill — the stale slots sit beyond every mask and are overwritten by
        the next step's write at that row's true fill.

        Cache/out geometry: C = S + max_new + k + 1 and the out buffer is
        max_new + k + 1 wide, so a step entered at e = max_new - 1 (or a
        done row parked at e = max_new) can always write its fixed-shape
        k+1 tokens without dynamic_update_slice's start-clamp silently
        shifting the write onto valid earlier slots."""
        from ..spec import NO_TOKEN, propose_drafts

        cfg = self.cfg
        k1 = k + 1
        C = S + max_new + k1
        N = max(gen.spec_ngram, 1)
        eos, vocab_limit, restrict = self._sampling_setup(gen)
        pad_id = self.tok.pad_id
        _, use_flash_decode = self._decode_settings(S, C)
        # the multi-position Pallas kernel is single-chip; under a data-only
        # mesh the dense per-row verify path serves the same math (generate()
        # degrades to plain decode only when `model` is sharded — the ragged
        # per-row fills don't compose with head-sharded kernel dispatch yet)
        use_verify_kernel = use_flash_decode and self.mesh is None
        interpret = self.interpret
        layer_window = self._layer_window_fn()

        def spec_step(params, cur, cache, done, e, out, pads, ref,
                      ref_lens, seed):
            base = jax.random.key(seed)
            uids = jnp.arange(B, dtype=jnp.int32)
            fills = S + e                                       # [B]

            # --- draft: last N emitted tokens (incl. cur) vs reference ---
            if N > 1:
                out_pad = jnp.concatenate(
                    [jnp.full((B, N - 1), NO_TOKEN, jnp.int32), out], axis=1
                )
                hist = jax.vmap(
                    lambda row, s: jax.lax.dynamic_slice(row, (s,), (N - 1,))
                )(out_pad, e)
                tail = jnp.concatenate([hist, cur[:, None]], axis=1)
            else:
                tail = cur[:, None]
            drafts, n_draft = propose_drafts(ref, ref_lens, tail, k)
            # done rows draft nothing; live rows never draft past the token
            # budget (acceptance may not push e beyond max_new)
            n_draft = jnp.where(done, 0, n_draft)
            n_draft = jnp.minimum(n_draft, jnp.maximum(max_new - e - 1, 0))

            # --- batched verify forward over k+1 positions per row ---
            toks = jnp.concatenate([cur[:, None], drafts], axis=1)  # [B, k1]
            positions = verify_positions(pads, fills, k1)
            mask = verify_attention_mask(pads, fills, k1, C)
            stacked_fn = None
            if use_verify_kernel:
                from ..ops.decode_attention import flash_spec_verify_attention

                def stacked_fn(q, cache_d, layer_idx):
                    return flash_spec_verify_attention(
                        q, cache_d, layer_idx, pads, fills, cfg.q_per_kv,
                        layer_window(layer_idx), interpret=interpret,
                    )

            logits, cache = forward(
                params, cfg, toks, positions, cache, fills, mask,
                stacked_attention_fn=stacked_fn,
            )
            logits = restrict(logits[:, :, :vocab_limit])

            # --- accept + emit ---
            # position i (when reached) emits stream token e + i: key on
            # that absolute position so acceptance raggedness never replays
            # a row's randomness
            pos_ids = e[:, None] + jnp.arange(k1, dtype=jnp.int32)[None, :] + 1
            keys = jax.vmap(
                lambda u, ps: jax.vmap(
                    lambda p: jax.random.fold_in(jax.random.fold_in(base, u), p)
                )(ps)
            )(uids, pos_ids)
            m, nxt = draft_acceptance_rows(
                logits, drafts, n_draft, keys,
                gen.temperature, gen.top_k, gen.top_p,
            )

            idx = jnp.arange(k1, dtype=jnp.int32)[None, :]
            is_term = jnp.isin(toks, eos)
            no_term_before = jnp.cumprod(
                jnp.concatenate(
                    [jnp.ones((B, 1), jnp.int32),
                     (~is_term[:, :-1]).astype(jnp.int32)],
                    axis=1,
                ),
                axis=1,
            ).astype(bool)
            # emit cur plus accepted drafts, cut just after a terminator —
            # the terminator itself is emitted (and detok-stripped) exactly
            # like the plain decode path's emit-before-done-check
            valid = (idx <= m[:, None]) & no_term_before & ~done[:, None]
            emit = jnp.where(valid, toks, pad_id)
            out = jax.vmap(
                lambda o, v, s: jax.lax.dynamic_update_slice(o, v, (s,))
            )(out, emit, e)
            n_emit = valid.sum(axis=1).astype(jnp.int32)
            e_new = e + n_emit
            done_new = done | (is_term & valid).any(axis=1) | (e_new >= max_new)
            cur_new = jnp.where(done, cur, nxt)
            accepted = jnp.maximum(n_emit - 1, 0)
            return cur_new, cache, done_new, e_new, out, n_draft, accepted

        return jax.jit(spec_step, donate_argnums=(2, 5))

    def _get_spec_fn(self, B, S, R, max_new, k, gen):
        key = ("spec", B, S, R, max_new, k, gen.with_(seed=0))
        if key not in self._fns:
            t0 = time.time()
            self._fns[key] = self._make_spec_fn(B, S, R, max_new, k, gen)
            logger.info(
                "built spec fn for bucket B=%d S=%d R=%d k=%d", B, S, R, k
            )
            self.stats.compile_seconds += time.time() - t0
        return self._fns[key]

    # hot path
    def _run_group_spec(
        self, group, encoded, references, max_new: int, gen, results,
        report, seed: int,
    ) -> None:
        """Generate one prompt group with reference-guided speculation:
        shared prefill, then a host loop of jitted spec steps (draft →
        batched verify → accept). Every step retires >= 1 token per live
        row, so the loop is bounded by max_new; rows whose reference never
        matches degrade to exactly one token per step."""
        from ..spec import NO_TOKEN, SpecRecord, encode_references

        k = gen.spec_k
        tokens, pads, B, S = self._pack_group(group, encoded, max_new)

        # per-row reference buffers, R bucketed to a power of two so ref
        # length variation doesn't fan out fresh XLA programs
        refs_group = [references[i] if references else None for i in group]
        ref_np, ref_lens_np = encode_references(
            self.tok, refs_group, self.spec_max_ref_tokens
        )
        R = 64
        while R < ref_np.shape[1]:
            R *= 2
        ref_full = np.full((B, R), NO_TOKEN, dtype=np.int32)
        ref_full[: len(group), : ref_np.shape[1]] = ref_np
        lens_full = np.zeros((B,), dtype=np.int32)
        lens_full[: len(group)] = ref_lens_np

        tracing = current_collector() is not None  # once per dispatch
        prefill = self._get_seg_fn("prefill", B, S, max_new + k + 1, gen)
        t_pre = time.time()
        t_pre_m = time.monotonic()
        with annotate(f"spec_prefill[B={B},S={S}]"):
            cur, cache, done = prefill(self.params, tokens, pads, seed)
        if self.instrument:
            # lint-allow[host-sync-in-hot-path]: instrument=True exists to bound prefill with exactly this sync
            jax.device_get(done)
            self.stats.add_phase("prefill", time.time() - t_pre)
        if tracing:
            emit("spec_prefill", t_pre_m, time.time() - t_pre, B=B, S=S,
                 occupancy=len(group), synced=self.instrument)
        self.stats.batches += 1
        self.stats.by_bucket[(B, S)] = self.stats.by_bucket.get((B, S), 0) + 1

        fn = self._get_spec_fn(B, S, R, max_new, k, gen)
        pad_dev = jnp.asarray(pads)
        ref_dev = jnp.asarray(ref_full)
        lens_dev = jnp.asarray(lens_full)
        out = jnp.full((B, max_new + k + 1), self.tok.pad_id, dtype=jnp.int32)
        e = jnp.zeros((B,), dtype=jnp.int32)

        drafted = np.zeros((B,), dtype=np.int64)
        accepted = np.zeros((B,), dtype=np.int64)
        steps_live = np.zeros((B,), dtype=np.int64)
        # lint-allow[host-sync-in-hot-path]: prefill done mask seeds the host loop's exit condition
        prev_done = jax.device_get(done)
        t_dec = time.time()
        while not prev_done.all():
            t_step = time.monotonic() if tracing else 0.0
            with annotate(f"spec_step[B={B},S={S},k={k}]"):
                cur, cache, done, e, out, nd, acc = fn(
                    self.params, cur, cache, done, e, out, pad_dev,
                    ref_dev, lens_dev, seed,
                )
            steps_live += ~prev_done
            # ONE explicit fetch per verify step: draft/accept counts feed
            # the acceptance stats and done drives the loop exit — this is
            # the sync the host loop already owes
            # lint-allow[host-sync-in-hot-path]: per-step nd/acc/done fetch is the verify loop's control dependency
            nd_h, acc_h, prev_done = jax.device_get((nd, acc, done))
            drafted += nd_h
            accepted += acc_h
            self.stats.spec_verify_steps += 1
            # per-verify-step telemetry: the nd/acc/done fetches above are
            # the sync the loop already paid — drafted vs accepted feeds the
            # rolling acceptance gauge's per-step ground truth. Gated: the
            # sums/kwargs cost nothing on untraced runs
            if tracing:
                emit("spec_step", t_step, time.monotonic() - t_step, B=B,
                     k=k, live=int((~prev_done).sum()),
                     drafted=int(nd_h.sum()), accepted=int(acc_h.sum()))
        if self.instrument:
            self.stats.add_phase("spec_decode", time.time() - t_dec)
        self.stats.spec_draft_tokens += int(drafted[: len(group)].sum())
        self.stats.spec_accepted_tokens += int(accepted[: len(group)].sum())

        # lint-allow[host-sync-in-hot-path]: final result fetch — detok needs the emitted tokens
        out_h = jax.device_get(out)[:, :max_new]
        for row, i in enumerate(group):
            results[i] = self._detok(out_h[row], tuple(gen.eos_ids))
            report[i] = SpecRecord(
                draft_tokens=int(drafted[row]),
                accepted_tokens=int(accepted[row]),
                verify_steps=int(steps_live[row]),
            )

    # -- prefix KV cache (vnsum_tpu.cache) -------------------------------

    def _prepare_resume(self, group, encoded, matches, pad_lens, B, S,
                        max_new: int, tracing: bool):
        """Compute the trace-static skip boundary K for one packed group and
        gather the matched prefix blocks into a seeded cache.

        Slot arithmetic (left-padded rows; pad_r = S - len_r):

        - K = 128-aligned floor of (S - longest uncovered suffix): for every
          row, slots [pad_r, K) are covered by matched blocks, so ONE static
          boundary serves the whole batch; rows whose prompt starts at or
          after K (pad_r >= K) need no blocks at all.
        - row r gathers ceil((K - pad_r)/BLK) blocks at slots pad_r + i*BLK;
          ragged rows pad with the scratch block, whose writes land at slots
          >= K — inside the span the suffix prefill (slots [K, S)) or decode
          (slots >= S, each written before it is ever attended) overwrites —
          so padding can never corrupt a live row.

        Returns (K, seeded_cache, skipped_per_row) or None when the group
        has no usable 128-aligned coverage."""
        pc = self.prefix_cache
        BLK = pc.block_tokens
        max_suffix = max(len(encoded[i]) - matches[i].tokens for i in group)
        # the scratch-padding safety argument needs clamped writes
        # (dynamic_update_slice clamps starts to C - BLK) to still land at
        # slots >= K. S is usually a 128-multiple bucket, making C - BLK >=
        # K automatic — but the bucket FALLBACK (prompt longer than the last
        # bucket) is max_input, which need not be aligned, so cap K
        # explicitly rather than assume it.
        # K is quantized to a coarse grid (max(128, S/8) steps): each
        # distinct K compiles its own resume program per (B, S, max_new)
        # bucket, so a fine grid would let a warm server accrete up to S/128
        # executables per bucket — 8 variants bounds compile churn while
        # giving up at most one step of skip
        step = max(128, S // 8 // 128 * 128)
        K = min(S - max_suffix, S + max_new - BLK) // step * step
        if K < 128:
            return None
        ids_rows: list[list[int]] = []
        nb_max = 0
        for row, i in enumerate(group):
            pad = int(pad_lens[row])
            need = K - pad
            n = -(-need // BLK) if need > 0 else 0
            blocks = matches[i].blocks[:n]
            ids_rows.append(blocks)
            nb_max = max(nb_max, len(blocks))
        if nb_max == 0:
            return None
        t0 = time.time()
        t0_m = time.monotonic() if tracing else 0.0
        ids = np.full((B, nb_max), pc.store.scratch_id, dtype=np.int32)
        for row, blocks in enumerate(ids_rows):
            ids[row, : len(blocks)] = blocks
        cache = self._init_prefill_cache(B, S + max_new)
        cache = pc.gather(cache, ids, pad_lens)
        skipped = [
            max(K - int(pad_lens[row]), 0) for row in range(len(group))
        ]
        if tracing:
            emit("cache_gather", t0_m, time.time() - t0, B=B, K=K,
                 blocks=int((ids != pc.store.scratch_id).sum()),
                 hit_tokens=sum(skipped))
        return K, cache, skipped

    def _cache_insert(self, cache, group, encoded, matches, hints, pad_lens,
                      tracing: bool) -> int:
        """Index the freshly prefilled prompts and copy their new prefix
        blocks into the pool. A cache_hint bounds the insertion to the
        hint-covered prefix (template headers, carried-forward summaries) so
        unique content tails don't churn the pool; without one the whole
        prompt (minus its last token) is insertable and LRU manages it."""
        pc = self.prefix_cache
        if not self.cache_inserts_enabled:
            # ladder rung NO_CACHE_INSERT: stop pool churn; hits still serve
            return 0
        BLK = pc.block_tokens
        t0 = time.time()
        t0_m = time.monotonic() if tracing else 0.0
        evict0 = pc.index.stats.evictions
        new_blocks = 0
        for row, i in enumerate(group):
            ids = encoded[i]
            target = len(ids) - 1
            hint = hints[i] if hints else None
            if hint:
                target = min(self._hint_prefix_len(hint, ids), target)
            upto = target // BLK * BLK
            if upto > matches[i].tokens:
                new_blocks += pc.insert(
                    cache, row, int(pad_lens[row]), ids, upto
                )
        if tracing and (new_blocks or pc.index.stats.evictions != evict0):
            emit("cache_insert", t0_m, time.time() - t0, blocks=new_blocks,
                 evictions=pc.index.stats.evictions - evict0)
        return new_blocks

    def _hint_prefix_len(self, hint: str, ids: list[int]) -> int:
        """Token-aligned hint boundary: the longest common prefix of the
        hint's own encoding and the prompt's. Exact when tokenization is
        prefix-stable (tests/test_text_tokenizer.py pins the shipped
        templates); safely shorter when a merge crosses the boundary."""
        hint_ids = self._hint_ids_cache.get(hint)
        if hint_ids is None:
            if len(self._hint_ids_cache) >= 256:
                self._hint_ids_cache.clear()
            hint_ids = self.tok.encode(hint, add_bos=True)
            self._hint_ids_cache[hint] = hint_ids
        n = min(len(hint_ids), len(ids))
        k = 0
        while k < n and hint_ids[k] == ids[k]:
            k += 1
        return k

    def set_prefix_cache_inserts(self, enabled: bool) -> None:
        """Degradation-ladder hook (serve/supervisor.py): gate prefix-cache
        insertion while hits keep serving. Engine-thread-only, like every
        generate() call — the serving scheduler applies rung changes
        lazily on its own thread for exactly this reason."""
        self.cache_inserts_enabled = bool(enabled)

    def cached_prefix_tokens(self, text: str, cache_hint: str | None = None) -> int:
        """Read-only probe: how many prompt tokens the prefix cache would
        serve right now. Thread-safe (the radix probe path), used by the
        serving queue to bill only uncached tokens against the admission
        token budget. An estimate — the usable skip also depends on batch
        composition (the 128-aligned K)."""
        if self.prefix_cache is None:
            return 0
        ids = self.tok.encode(text, add_bos=True)
        # same truncation generate() applies for the default decode budget,
        # so the admission discount can never exceed what a dispatch could
        # actually reuse
        max_input = self.cfg.max_seq_len - self.max_new_tokens
        if len(ids) > max_input:
            ids = ids[:max_input]
        return self.prefix_cache.probe(ids, max_tokens=len(ids) - 1)

    def prefix_cache_stats(self) -> dict | None:
        """Pool/index counters for /metrics gauges (None = cache off)."""
        if self.prefix_cache is None:
            return None
        return self.prefix_cache.stats_dict()

    def take_cache_report(self) -> list[int]:
        """Per-prompt prefill tokens served from the prefix cache on the
        LAST generate call (empty when the cache was off), cleared on read —
        the same attribution hook shape as take_spec_report."""
        report, self._cache_report = self._cache_report, []
        return report

    def take_spec_report(self):
        """Per-prompt SpecRecords of the LAST generate call, aligned with
        its prompt order (empty when speculation was off), cleared on read.
        The serving scheduler attributes per-request acceptance metrics
        through this hook; engine access is single-threaded by the serving
        contract (serve/scheduler.py), so read-after-generate is safe."""
        report, self._spec_report = self._spec_report, []
        return report

    # -- public API ------------------------------------------------------

    def _pack_group(self, group, encoded, max_new: int):
        """Pack one prompt group into a fixed-shape left-padded batch.

        Shared by the one-shot and continuous paths — their greedy-parity
        guarantee depends on identical bucketing and padding."""
        t_pack = time.time()
        max_input = self.cfg.max_seq_len - max_new
        data_size = self.mesh.shape.get("data", 1) if self.mesh is not None else 1
        S = _bucket_len(max(len(encoded[i]) for i in group), max_input)
        # bucket the batch dim too, so a trailing partial group doesn't pay
        # for all-pad rows up to the full batch_size
        B = data_size
        while B < len(group):
            B *= 2
        B = min(B, self.batch_size)
        tokens, pad_lens = left_pad_batch(
            [encoded[i] for i in group], B, S, self.tok.pad_id
        )
        self.stats.add_phase("pack_host", time.time() - t_pack)
        return tokens, pad_lens, B, S

    # hot path
    def generate(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        references: list[str | None] | None = None,
        cache_hints: list[str | None] | None = None,
    ) -> list[str]:
        gen = config or self.gen_cfg
        max_new = resolve_max_new(max_new_tokens, gen, self.max_new_tokens)
        if max_new >= self.cfg.max_seq_len:
            raise ValueError(
                f"max_new_tokens={max_new} must be < max_seq_len={self.cfg.max_seq_len}"
            )
        if not prompts:
            return []
        if references is not None and len(references) != len(prompts):
            raise ValueError(
                f"references must align with prompts: got {len(references)} "
                f"for {len(prompts)}"
            )
        if cache_hints is not None and len(cache_hints) != len(prompts):
            raise ValueError(
                f"cache_hints must align with prompts: got {len(cache_hints)} "
                f"for {len(prompts)}"
            )
        # seeded fault injection (vnsum_tpu.testing.faults): one global
        # None-check when disarmed; sits after input validation so injected
        # faults exercise DISPATCH recovery, not the argument checks
        fault("engine.dispatch", prompts=prompts)

        # reference-guided speculative decoding: needs spec_k > 0 AND at
        # least one reference to draft from. Data-parallel meshes run the
        # dense verify path (rows are replica-local, same math); only
        # `model`-sharded meshes degrade to plain decode (same outputs in
        # greedy, just one token per step) — the multi-position verify
        # kernel is the one genuinely single-chip piece left.
        spec_on = (
            gen.spec_k > 0
            and references is not None
            and any(references)
        )
        if (
            spec_on
            and self.mesh is not None
            and self.mesh.shape.get("model", 1) > 1
        ):
            if not self._warned_spec_fallback:
                self._warned_spec_fallback = True
                logger.warning(
                    "spec_k=%d requested under a model-sharded mesh; the "
                    "spec verify step is data-parallel only — falling back "
                    "to plain decode",
                    gen.spec_k,
                )
            spec_on = False
        spec_report: list = (
            [None] * len(prompts) if spec_on else []
        )

        self.stats.calls += 1
        self.stats.prompts += len(prompts)
        # cleared up front: a call that errors mid-loop must not leave a
        # previous call's per-prompt cache attribution behind for the
        # scheduler's take_cache_report to misread
        self._cache_report = []

        # telemetry gate, resolved once per generate() call (see the obs
        # contract in backend/base.py): untraced runs skip every emit's
        # timestamp/kwargs work, not just the emit itself
        tracing = current_collector() is not None
        max_input = self.cfg.max_seq_len - max_new
        encoded: list[list[int]] = []
        t_enc = time.time()
        t_enc_m = time.monotonic()
        # ONE batched call into the tokenizer (Rust side parallelizes and
        # skips per-prompt Python overhead; measured 1.4x on this phase)
        for ids in self.tok.encode_batch(prompts, add_bos=True):
            if len(ids) > max_input:
                ids = ids[:max_input]
            encoded.append(ids)
            self.stats.prompt_tokens += len(ids)
        self.stats.add_phase("tokenize_host", time.time() - t_enc)
        if tracing:
            emit("tokenize", t_enc_m, time.time() - t_enc,
                 prompts=len(prompts))

        # prefix KV cache (vnsum_tpu.cache): match every prompt against the
        # radix index (pinning the matched blocks against eviction for the
        # duration of the call) and order rows by UNCOVERED suffix length —
        # a group's usable skip K is S minus its longest suffix, so one cold
        # row mixed into a warm group would zero everyone's reuse. Spec
        # calls skip the cache: the verify path's per-row fills don't share
        # prefill's single resume boundary.
        pc = self.prefix_cache
        use_cache = pc is not None and not spec_on
        matches = None
        cache_report = [0] * len(encoded)
        if use_cache:
            t_cl = time.time()
            t_cl_m = time.monotonic() if tracing else 0.0
            matches = [
                pc.match(ids, max_tokens=len(ids) - 1) for ids in encoded
            ]
            if tracing:
                emit("cache_lookup", t_cl_m, time.time() - t_cl,
                     prompts=len(encoded),
                     hit_tokens=sum(m.tokens for m in matches))
            order = sorted(
                range(len(encoded)),
                key=lambda i: (len(encoded[i]) - matches[i].tokens,
                               len(encoded[i])),
            )
        else:
            # group indices by bucketed length, then emit fixed-shape batches
            order = sorted(range(len(encoded)), key=lambda i: len(encoded[i]))
        results: list[str | None] = [None] * len(encoded)
        t0 = time.time()
        # the segmented path only pays off when the budget spans multiple
        # segments (otherwise there is nothing to compact and the extra
        # prefill/segment dispatches cost ~3% on a homogeneous batch).
        # Sampling is compaction-safe: per-row counter-based keys (see
        # _make_parts) make each row's stream independent of batch position
        continuous = self.continuous and (
            self.instrument or max_new > self.segment_tokens
        )
        try:
            # sanitizer hook (analysis pkg): nullcontext in production;
            # under VNSUM_SANITIZERS=transfer any IMPLICIT device->host
            # transfer inside the dispatch loop raises, while the
            # lint-acknowledged explicit device_get fetches pass
            with hot_path_transfer_guard():
                for start in range(0, len(order), self.batch_size):
                    group = order[start : start + self.batch_size]
                    seed = self._next_seed(gen)
                    # per-GROUP spec routing: a coalesced batch can mix
                    # referenced and reference-less requests, and length-sorting
                    # may put all the refless ones in one group — that group
                    # would pay the (k+1)-wide verify forward to retire one
                    # token per step, so it takes the plain path instead
                    # (identical greedy output either way; its spec_report rows
                    # stay zero)
                    if spec_on and any(references[i] for i in group):
                        self._run_group_spec(
                            group, encoded, references, max_new, gen, results,
                            spec_report, seed,
                        )
                        continue
                    tokens, pad_lens, B, S = self._pack_group(
                        group, encoded, max_new
                    )
                    resume = None
                    if matches is not None:
                        resume = self._prepare_resume(
                            group, encoded, matches, pad_lens, B, S, max_new,
                            tracing,
                        )
                    if resume is not None:
                        for row, i in enumerate(group):
                            cache_report[i] = resume[2][row]
                    insert_cb = None
                    if use_cache:
                        def insert_cb(cache, _g=group, _p=pad_lens):
                            self._cache_insert(
                                cache, _g, encoded, matches, cache_hints, _p,
                                tracing,
                            )
                    if continuous:
                        self._run_group_continuous(
                            group, encoded, max_new, gen, results, seed,
                            packed=(tokens, pad_lens, B, S),
                            resume=resume and resume[:2], insert_cb=insert_cb,
                        )
                        continue
                    K = resume[0] if resume else 0
                    fn = self._get_fn(B, S, max_new, gen, resume_from=K)
                    t_disp = time.monotonic() if tracing else 0.0
                    with annotate(f"generate[B={B},S={S}]"):
                        if K:
                            res = fn(self.params, tokens, pad_lens, seed,
                                     resume[1])
                        else:
                            res = fn(self.params, tokens, pad_lens, seed)
                        # with the prefix cache on, the program also returns its
                        # final cache so new prefix blocks can be pooled
                        out_dev, final_cache = res if pc is not None else (res, None)
                        # lint-allow[host-sync-in-hot-path]: one-shot result fetch bounds the dispatch and feeds detok
                        out = jax.device_get(out_dev)
                    # the fused prefill+decode program has no observable
                    # midpoint: one "dispatch" event bounds the whole device
                    # call (the result fetch above synced it) — TTFT consumers
                    # treat its end as the first-token upper bound
                    if tracing:
                        emit("dispatch", t_disp, time.monotonic() - t_disp,
                             B=B, S=S, occupancy=len(group), max_new=max_new)
                    self.stats.batches += 1
                    self.stats.by_bucket[(B, S)] = (
                        self.stats.by_bucket.get((B, S), 0) + 1
                    )
                    if insert_cb is not None:
                        insert_cb(final_cache)
                    t_detok = time.monotonic() if tracing else 0.0
                    for row, i in enumerate(group):
                        results[i] = self._detok(out[row], tuple(gen.eos_ids))
                    if tracing:
                        emit("detokenize", t_detok, time.monotonic() - t_detok,
                             rows=len(group))
        finally:
            if matches is not None:
                for m in matches:
                    pc.release(m)
        self.stats.generate_seconds += time.time() - t0
        if use_cache:
            hit = sum(cache_report)
            self.stats.cache_hit_tokens += hit
            self.stats.cache_miss_tokens += (
                sum(len(e) for e in encoded) - hit
            )
        self._cache_report = cache_report if use_cache else []
        if spec_on:
            from ..spec import SpecRecord

            # rows whose group took the plain path report zeros, keeping
            # the per-prompt alignment the serving scheduler relies on
            spec_report = [r if r is not None else SpecRecord()
                           for r in spec_report]
        self._spec_report = spec_report
        return results  # type: ignore[return-value]

    def _detok(self, ids: np.ndarray, extra_eos: tuple[int, ...] = ()) -> str:
        self.stats.generated_tokens += int((ids != self.tok.pad_id).sum())
        out = trim_to_eos(
            ids.tolist(), self.tok.eos_id, self.tok.pad_id, extra_eos
        )
        return self.tok.decode(out).strip()

    def count_tokens(self, text: str) -> int:
        return self.tok.count(text)

    def count_tokens_batch(self, texts: list[str]) -> list[int]:
        """Batched count for the splitter's length function — one Rust-side
        call per split level instead of one per sentence piece."""
        return self.tok.count_batch(texts)
