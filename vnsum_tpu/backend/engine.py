"""TpuBackend — batched, mesh-sharded on-device generation.

This is the component the reference lacks entirely: its map fan-out executes
serially over HTTP (SURVEY.md §1 "critical architectural observation",
runners/run_summarization_ollama_mapreduce.py:51-52). Here a list of prompts
becomes length-bucketed, fixed-shape [B, S] device batches:

- left-padded prompts so prefill's last row and every decode step share one
  write index across the batch (static shapes, no ragged gather);
- one jit-compiled prefill + early-exit `while_loop` decode program per
  (B, S) bucket, cached — bucketing bounds XLA recompiles, and decode stops
  as soon as every row has emitted EOS instead of paying the full budget;
- greedy or sampled decoding with per-sequence EOS masking inside the loop;
- params and token batches carry NamedShardings over a (data, model) mesh, so
  the same program runs single-chip or TP/DP-sharded with GSPMD collectives.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core.config import GenerationConfig
from ..core.logging import get_logger
from ..core.profiling import annotate
from ..models.llama import (
    LlamaConfig,
    decode_attention_mask,
    forward,
    init_kv_cache,
    init_params,
    llama32_3b,
    prefill_attention_mask,
    prefill_positions,
)
from ..models.sampling import sample_logits
from ..text.tokenizer import Tokenizer, get_tokenizer

logger = get_logger("vnsum.engine")

_BUCKETS = (64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384)


def _bucket_len(n: int, max_len: int) -> int:
    for b in _BUCKETS:
        if n <= b and b <= max_len:
            return b
    return max_len


@dataclass
class EngineStats:
    """Wall-clock + token accounting for bench.py / run records."""

    calls: int = 0
    prompts: int = 0
    prompt_tokens: int = 0
    generated_tokens: int = 0
    compile_seconds: float = 0.0
    generate_seconds: float = 0.0
    batches: int = 0
    by_bucket: dict = field(default_factory=dict)

    @property
    def tokens_per_second(self) -> float:
        total = self.prompt_tokens + self.generated_tokens
        return total / self.generate_seconds if self.generate_seconds else 0.0


class TpuBackend:
    name = "tpu"

    def __init__(
        self,
        model_config: LlamaConfig | None = None,
        tokenizer: str | Tokenizer = "byte",
        mesh=None,
        params=None,
        batch_size: int = 8,
        max_new_tokens: int = 1024,
        generation: GenerationConfig | None = None,
        seed: int = 0,
        flash: str | bool = "auto",
        quantize: bool = False,
        quantize_kv: str | bool = "auto",
    ) -> None:
        self.cfg = model_config or llama32_3b()
        # Pallas flash prefill: "auto" enables it on real TPU only (the
        # kernel needs Mosaic; CPU tests use interpret mode explicitly)
        if flash == "auto":
            flash = jax.default_backend() == "tpu" and mesh is None
        elif flash and mesh is not None:
            raise ValueError(
                "flash=True is incompatible with a mesh: the Pallas kernels "
                "run per-chip (no shard_map wiring); under GSPMD they would "
                "force an all-gather of the stacked KV cache every step"
            )
        self.flash = bool(flash)
        # int8 KV cache halves decode-attention HBM traffic; the in-kernel
        # dequant needs the Pallas path, so "auto" follows flash AND actual
        # kernel support (head_dim lane alignment — e.g. llama32_1b's
        # head_dim=64 can't take the kernels, and the dense fallback would
        # dequantize the whole cache per step)
        kernels_supported = self.cfg.head_dim % 128 == 0
        if quantize_kv == "auto":
            quantize_kv = self.flash and kernels_supported
        elif quantize_kv and not (self.flash and kernels_supported):
            raise ValueError(
                "quantize_kv=True requires the Pallas kernels (flash=True "
                "and head_dim a multiple of 128); the dense fallback would "
                "dequantize the whole cache per step"
            )
        self.quantize_kv = bool(quantize_kv)
        self.tok = get_tokenizer(tokenizer) if isinstance(tokenizer, str) else tokenizer
        self.mesh = mesh
        self.batch_size = batch_size
        self.max_new_tokens = max_new_tokens
        self.gen_cfg = generation or GenerationConfig()
        if max_new_tokens >= self.cfg.max_seq_len:
            raise ValueError(
                f"max_new_tokens={max_new_tokens} must be < "
                f"max_seq_len={self.cfg.max_seq_len}"
            )
        self.stats = EngineStats()
        self._fns: dict[tuple[int, int, int], callable] = {}
        self._seed = seed

        if params is None:
            t0 = time.time()
            params = init_params(jax.random.key(seed), self.cfg)
            logger.info("initialized random params in %.1fs", time.time() - t0)
        if quantize:
            from ..models.quant import is_quantized, quantize_params

            if not is_quantized(params):
                t0 = time.time()
                params = jax.jit(quantize_params)(params)
                logger.info("int8-quantized params in %.1fs", time.time() - t0)
        if mesh is not None:
            from ..parallel.sharding import shard_params

            params = shard_params(params, mesh, self.cfg.tie_embeddings)
            if batch_size % mesh.shape.get("data", 1):
                raise ValueError("batch_size must be divisible by mesh data axis")
        self.params = params

    # -- compiled program per bucket ------------------------------------

    def _make_fn(self, B: int, S: int, max_new: int, gen: GenerationConfig):
        cfg = self.cfg
        C = S + max_new
        eos = jnp.asarray(
            list(gen.eos_ids) or [self.tok.eos_id], dtype=jnp.int32
        )
        pad_id = self.tok.pad_id

        use_flash = self.flash
        use_flash_decode = False
        if use_flash:
            from ..ops.decode_attention import supports_decode
            from ..ops.flash_attention import supports_flash

            use_flash = supports_flash(S, C, cfg.head_dim)
            use_flash_decode = supports_decode(C, cfg.head_dim)

        mesh = self.mesh
        quantize_kv = self.quantize_kv

        def generate(params, tokens, pad_lens, seed):
            cache = init_kv_cache(cfg, B, C, quantized=quantize_kv)
            if mesh is not None:
                # pin the cache layout (batch over data, heads over model)
                # instead of leaving it to GSPMD propagation
                from jax.sharding import NamedSharding

                from ..parallel.sharding import cache_specs

                cache = jax.lax.with_sharding_constraint(
                    cache,
                    jax.tree.map(
                        lambda s: NamedSharding(mesh, s), cache_specs(),
                        is_leaf=lambda x: not isinstance(x, dict),
                    ),
                )
            positions = prefill_positions(pad_lens, S)
            mask = prefill_attention_mask(pad_lens, S, C)
            prefill_stacked_fn = None
            if use_flash:
                from ..ops.flash_attention import flash_prefill_attention

                def prefill_stacked_fn(q, cache, layer_idx):
                    return flash_prefill_attention(
                        q, cache, layer_idx, pad_lens, cfg.q_per_kv
                    )

            logits, cache = forward(
                params, cfg, tokens, positions, cache, 0, mask,
                last_only=True, stacked_attention_fn=prefill_stacked_fn,
            )
            key = jax.random.key(seed)
            key, sub = jax.random.split(key)
            first = sample_logits(
                logits[:, -1], sub, gen.temperature, gen.top_k, gen.top_p
            )

            # decode loop with early exit: a while_loop instead of a fixed
            # lax.scan, so the program stops as soon as every row has hit EOS
            # (real summaries end far before the max_new budget; the scan
            # would pay for the full budget every time)
            def emit_token(out, cur, done, t):
                emit = jnp.where(done, pad_id, cur)
                out = jax.lax.dynamic_update_slice(out, emit[:, None], (0, t))
                return out, done | jnp.isin(cur, eos)

            def cond(carry):
                t, _cur, _cache, done, _key, _out = carry
                return (t < max_new) & ~jnp.all(done)

            def body(carry):
                t, cur, cache, done, key, out = carry
                out, done = emit_token(out, cur, done, t)
                pos = (S - pad_lens) + t
                mask_t = decode_attention_mask(pad_lens, S + t, C)
                stacked_fn = None
                if use_flash_decode:
                    from ..ops.decode_attention import flash_decode_attention

                    def stacked_fn(q, cache, layer_idx):
                        return flash_decode_attention(
                            q, cache, layer_idx, pad_lens, S + t,
                            cfg.q_per_kv,
                        )

                logits, cache = forward(
                    params, cfg, cur[:, None], pos[:, None], cache, S + t,
                    mask_t, stacked_attention_fn=stacked_fn,
                )
                key, sub = jax.random.split(key)
                nxt = sample_logits(
                    logits[:, -1], sub, gen.temperature, gen.top_k, gen.top_p
                )
                return (t + 1, nxt, cache, done, key, out)

            # each iteration emits BEFORE sampling, so on exit (budget spent
            # or all rows done) every live slot is already written and the
            # rest remain pad from the init — identical to a full-length scan
            out0 = jnp.full((B, max_new), pad_id, dtype=jnp.int32)
            # all-pad dummy rows (batch bucketing filler) start done, else
            # their garbage decode would keep the early exit from firing
            done0 = pad_lens == S
            *_, out = jax.lax.while_loop(
                cond, body, (jnp.int32(0), first, cache, done0, key, out0)
            )
            return out  # [B, max_new]

        fn = jax.jit(generate)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..models.quant import is_quantized
            from ..parallel.sharding import param_shardings

            ns = lambda spec: NamedSharding(self.mesh, spec)
            fn = jax.jit(
                generate,
                in_shardings=(
                    param_shardings(
                        self.mesh, cfg.tie_embeddings, is_quantized(self.params)
                    ),
                    ns(P("data", None)),
                    ns(P("data")),
                    None,
                ),
                out_shardings=ns(P("data", None)),
            )
        return fn

    def _get_fn(self, B: int, S: int, max_new: int, gen: GenerationConfig):
        key = (B, S, max_new, gen)
        if key not in self._fns:
            t0 = time.time()
            self._fns[key] = self._make_fn(B, S, max_new, gen)
            logger.info("built generate fn for bucket B=%d S=%d new=%d", B, S, max_new)
            self.stats.compile_seconds += time.time() - t0
        return self._fns[key]

    # -- public API ------------------------------------------------------

    def generate(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
    ) -> list[str]:
        gen = config or self.gen_cfg
        max_new = max_new_tokens or (
            config.max_new_tokens if config else self.max_new_tokens
        )
        if max_new >= self.cfg.max_seq_len:
            raise ValueError(
                f"max_new_tokens={max_new} must be < max_seq_len={self.cfg.max_seq_len}"
            )
        if not prompts:
            return []

        self.stats.calls += 1
        self.stats.prompts += len(prompts)

        max_input = self.cfg.max_seq_len - max_new
        encoded: list[list[int]] = []
        for p in prompts:
            ids = self.tok.encode(p, add_bos=True)
            if len(ids) > max_input:
                ids = ids[:max_input]
            encoded.append(ids)
            self.stats.prompt_tokens += len(ids)

        # group indices by bucketed length, then emit fixed-shape batches
        order = sorted(range(len(encoded)), key=lambda i: len(encoded[i]))
        results: list[str | None] = [None] * len(encoded)
        t0 = time.time()
        data_size = self.mesh.shape.get("data", 1) if self.mesh is not None else 1
        for start in range(0, len(order), self.batch_size):
            group = order[start : start + self.batch_size]
            S = _bucket_len(
                max(len(encoded[i]) for i in group), max_input
            )
            # bucket the batch dim too, so a trailing partial group doesn't
            # pay for all-pad rows up to the full batch_size
            B = data_size
            while B < len(group):
                B *= 2
            B = min(B, self.batch_size)
            tokens = np.full((B, S), self.tok.pad_id, dtype=np.int32)
            pad_lens = np.full((B,), S, dtype=np.int32)
            for row, i in enumerate(group):
                ids = encoded[i]
                tokens[row, S - len(ids) :] = ids  # left padding
                pad_lens[row] = S - len(ids)
            fn = self._get_fn(B, S, max_new, gen)
            with annotate(f"generate[B={B},S={S}]"):
                out = np.asarray(fn(self.params, tokens, pad_lens, self._seed))
            self.stats.batches += 1
            self.stats.by_bucket[(B, S)] = self.stats.by_bucket.get((B, S), 0) + 1
            for row, i in enumerate(group):
                results[i] = self._detok(out[row])
        self.stats.generate_seconds += time.time() - t0
        return results  # type: ignore[return-value]

    def _detok(self, ids: np.ndarray) -> str:
        self.stats.generated_tokens += int((ids != self.tok.pad_id).sum())
        out: list[int] = []
        for t in ids.tolist():
            if t == self.tok.eos_id or t == self.tok.pad_id:
                break
            out.append(t)
        return self.tok.decode(out).strip()

    def count_tokens(self, text: str) -> int:
        return self.tok.count(text)
