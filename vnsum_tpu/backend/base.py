"""The Backend protocol — the seam the whole framework hangs on.

The reference's equivalent is the OllamaLLM langchain wrapper duplicated five
times (SURVEY.md §2 C2). Here there is ONE interface, and it is batched:
`generate` takes a *list* of prompts so strategies can submit every LLM call
of a round (across chunks and across documents) as one unit. TpuBackend turns
that into sharded device batches; OllamaBackend loops over HTTP for parity;
FakeBackend is the deterministic hermetic test double (SURVEY.md §4).
"""
from __future__ import annotations

from typing import Protocol, runtime_checkable

from ..core.config import GenerationConfig


@runtime_checkable
class Backend(Protocol):
    name: str

    def generate(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
    ) -> list[str]:
        """Generate one completion per prompt, order-preserving."""
        ...

    def count_tokens(self, text: str) -> int:
        ...


def get_backend(spec: str, **kwargs) -> Backend:
    """Factory: "fake", "ollama", "tpu", or "hf"."""
    if spec == "fake":
        from .fake import FakeBackend

        return FakeBackend(**kwargs)
    if spec == "ollama":
        from .ollama import OllamaBackend

        return OllamaBackend(**kwargs)
    if spec == "tpu":
        from .engine import TpuBackend

        return TpuBackend(**kwargs)
    if spec == "hf":
        from .hf import HFBackend

        return HFBackend(**kwargs)
    raise ValueError(f"unknown backend {spec!r} (use tpu|ollama|hf|fake)")
