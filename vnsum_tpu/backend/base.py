"""The Backend protocol — the seam the whole framework hangs on.

The reference's equivalent is the OllamaLLM langchain wrapper duplicated five
times (SURVEY.md §2 C2). Here there is ONE interface, and it is batched:
`generate` takes a *list* of prompts so strategies can submit every LLM call
of a round (across chunks and across documents) as one unit. TpuBackend turns
that into sharded device batches; OllamaBackend loops over HTTP for parity;
FakeBackend is the deterministic hermetic test double (SURVEY.md §4).

Optional observability contract (vnsum_tpu.obs): backends MAY publish phase
telemetry from inside generate() via ``obs.trace.emit(name, t0, dur, ...)``
— host timestamps around already-dispatched device calls, never extra
device syncs. emit() no-ops on a single contextvar read unless a caller
(the serving scheduler, a bench) installed a collector, so backends wrap
their hot paths unconditionally. Recognized phase names: "tokenize",
"prefill"/"spec_prefill" (their end is the TTFT anchor), "decode",
"decode_seg", "spec_step", "dispatch" (fused one-shot program),
"detokenize". TpuBackend and FakeBackend implement it; HTTP parity backends
(ollama/hf) simply emit nothing.

Optional prefix-cache contract (vnsum_tpu.cache): backends with a prefix KV
cache additionally expose ``cached_prefix_tokens(text, cache_hint=None)``
(thread-safe read-only probe — the serving queue bills only uncached tokens
against its admission budget), ``take_cache_report()`` (per-prompt cached
token counts of the last generate, cleared on read — scheduler attribution
into ServeRequestRecord), and ``prefix_cache_stats()`` (pool gauges for
/metrics). The scheduler discovers all three via getattr, so plain backends
need none of them. TpuBackend implements the real thing; FakeBackend mirrors
it synthetically (a real radix index over whitespace words, no device pool).
"""
from __future__ import annotations

from typing import Protocol, runtime_checkable

from ..core.config import GenerationConfig


@runtime_checkable
class Backend(Protocol):
    name: str

    def generate(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        references: list[str | None] | None = None,
        cache_hints: list[str | None] | None = None,
    ) -> list[str]:
        """Generate one completion per prompt, order-preserving.

        ``references`` optionally carries one source text per prompt (None
        entries allowed) for reference-guided speculative decoding
        (vnsum_tpu.spec): strategies pass the chunk being summarized, and a
        backend with ``config.spec_k > 0`` drafts from it.

        ``cache_hints`` optionally carries one string per prompt naming the
        prompt PREFIX the caller expects to recur (template headers,
        carried-forward summaries) for the radix prefix KV cache
        (vnsum_tpu.cache): a backend with the cache enabled bounds its block
        insertion to the hinted prefix so unique content tails don't churn
        the pool. Both are advisory metadata, never semantic inputs —
        backends without the feature accept and ignore them, and greedy
        outputs are identical either way."""
        ...

    def count_tokens(self, text: str) -> int:
        ...

    def count_tokens_batch(self, texts: list[str]) -> list[int]:
        """Batched count — the splitter issues one call per split
        level instead of one per sentence piece."""
        ...


# -- shared device-batch helpers (TpuBackend + LongContextBackend) ----------
# Greedy parity between the one-chip engine and the seq-sharded long-context
# engine depends on identical packing / seed / detokenize semantics — keep
# ONE copy of each here.


def fold_seed(gen_seed: int, backend_seed: int, dispatch: int) -> int:
    """Per-batch PRNG seed folded from (config seed, backend seed, dispatch
    index): sampled batches draw fresh randomness, same-seed reruns over the
    same call sequence replay bit-exactly, greedy ignores the key."""
    return (
        gen_seed * 0x9E3779B1 + backend_seed * 0x85EBCA77 + dispatch
    ) & 0x7FFFFFFF


def left_pad_batch(encoded_group, B: int, S: int, pad_id: int):
    """Pack encoded prompts into a fixed-shape left-padded [B, S] batch;
    rows beyond the group are all-pad filler. Returns (tokens, pad_lens)."""
    import numpy as np

    tokens = np.full((B, S), pad_id, dtype=np.int32)
    pad_lens = np.full((B,), S, dtype=np.int32)
    for row, ids in enumerate(encoded_group):
        tokens[row, S - len(ids):] = ids
        pad_lens[row] = S - len(ids)
    return tokens, pad_lens


def trim_to_eos(
    ids, eos_id: int, pad_id: int, extra_eos: tuple[int, ...] = ()
) -> list[int]:
    """Cut a generated id row at its first EOS/pad slot. ``extra_eos`` carries
    the active GenerationConfig.eos_ids — custom stop tokens are emitted
    before the done check fires, so they must be stripped like native EOS."""
    stops = {eos_id, pad_id, *extra_eos}
    out: list[int] = []
    for t in ids:
        if t in stops:
            break
        out.append(t)
    return out


def decodable_vocab_limit(tok, model_vocab_size: int) -> int:
    """Sampling range that can actually become text: the model head may be
    larger than the tokenizer (random-init 128k-vocab model + byte tokenizer
    in benches/tests), and a tokenizer may carry padded/special ids its
    decode() drops (ByteTokenizer ids >= 256). Sampling outside this range
    yields silently-vanishing tokens and empty summaries. Real HF
    tokenizers set decodable == vocab == model head, making this a no-op."""
    tok_limit = getattr(
        tok, "decodable_vocab_size", getattr(tok, "vocab_size", None)
    )
    return min(model_vocab_size, tok_limit or model_vocab_size)


_warned_unsampleable: set = set()


def sampling_vocab(tok, model_vocab_size: int, terminators=()):
    """(limit, allowed-or-None) restriction the engines apply to logits
    before sampling.

    ``limit`` extends :func:`decodable_vocab_limit` just far enough to cover
    every terminator id (EOS must stay *sampleable*, or a model trained to
    emit it — e.g. a ByteTokenizer fixture where eos_id=257 sits above the
    256 decodable bytes — can never stop early and always burns the full
    max_new budget). ``allowed`` is a bool [limit] numpy mask, or None when
    every id below ``limit`` is fair game (the common HF case); ids in
    [decodable, limit) that are not terminators stay blocked so sampling
    cannot emit text-invisible filler tokens.

    Terminators at or above the model head (e.g. a special id above a
    padded-head Qwen3's 151936 logits) are physically unsampleable —
    warn loudly instead of silently never terminating.
    """
    import numpy as np

    decodable = decodable_vocab_limit(tok, model_vocab_size)
    terms = sorted({int(t) for t in terminators})
    dropped = [t for t in terms if not 0 <= t < model_vocab_size]
    # the engines rebuild programs per (B, S, max_new) bucket; the condition
    # is a per-backend constant, so warn once per distinct case, not per
    # compile (the key is the condition itself, not the tok object)
    warn_key = (model_vocab_size, decodable, tuple(dropped))
    if dropped and warn_key not in _warned_unsampleable:
        _warned_unsampleable.add(warn_key)
        from ..core.logging import get_logger

        get_logger("vnsum.backend").warning(
            "terminator ids %s lie outside the model head (vocab %d) and "
            "can never be sampled; generation will run to max_new unless "
            "another terminator fires",
            dropped, model_vocab_size,
        )
    terms = [t for t in terms if 0 <= t < model_vocab_size]
    limit = max([decodable] + [t + 1 for t in terms])
    if limit == decodable:
        return limit, None
    allowed = np.zeros((limit,), dtype=bool)
    allowed[:decodable] = True
    allowed[terms] = True
    return limit, allowed


def terminator_ids(tok, gen) -> tuple[int, ...]:
    """The ONE effective stop-token set both engines use for done detection,
    sampleability (sampling_vocab), and detok stripping: the tokenizer's
    native EOS is always a terminator, custom GenerationConfig.eos_ids add
    to it rather than replace it. A token in only one of those three roles
    would either leak into text or burn the batch budget on thrown-away
    tokens — keep the policy in this single place."""
    return tuple(sorted({tok.eos_id, *gen.eos_ids}))


def mask_unsampleable(row_logits, allowed):
    """Apply a :func:`sampling_vocab` mask to a [B, limit] logits slice —
    blocked ids get float32 min so neither argmax nor categorical can pick
    them. ``allowed=None`` (everything decodable) is the identity. ONE copy
    shared by the one-chip and long-context engines so the masking semantics
    cannot drift between them."""
    if allowed is None:
        return row_logits
    import jax.numpy as jnp

    return jnp.where(allowed, row_logits, jnp.finfo(jnp.float32).min)


def resolve_max_new(
    max_new_tokens: int | None, config, backend_default: int
) -> int:
    """Decode-budget resolution shared by every backend: explicit argument >
    explicit config override > the backend's constructor default. A config
    passed only for temperature/eos (max_new_tokens=None) keeps the
    constructor budget."""
    if max_new_tokens is not None:
        return max_new_tokens
    if config is not None and config.max_new_tokens is not None:
        return config.max_new_tokens
    return backend_default


def get_backend(spec: str, **kwargs) -> Backend:
    """Factory: "fake", "ollama", "tpu", or "hf"."""
    if spec == "fake":
        from .fake import FakeBackend

        return FakeBackend(**kwargs)
    if spec == "ollama":
        from .ollama import OllamaBackend

        return OllamaBackend(**kwargs)
    if spec == "tpu":
        from .engine import TpuBackend

        return TpuBackend(**kwargs)
    if spec == "hf":
        from .hf import HFBackend

        return HFBackend(**kwargs)
    raise ValueError(f"unknown backend {spec!r} (use tpu|ollama|hf|fake)")
