"""The Backend protocol — the seam the whole framework hangs on.

The reference's equivalent is the OllamaLLM langchain wrapper duplicated five
times (SURVEY.md §2 C2). Here there is ONE interface, and it is batched:
`generate` takes a *list* of prompts so strategies can submit every LLM call
of a round (across chunks and across documents) as one unit. TpuBackend turns
that into sharded device batches; OllamaBackend loops over HTTP for parity;
FakeBackend is the deterministic hermetic test double (SURVEY.md §4).
"""
from __future__ import annotations

from typing import Protocol, runtime_checkable

from ..core.config import GenerationConfig


@runtime_checkable
class Backend(Protocol):
    name: str

    def generate(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
    ) -> list[str]:
        """Generate one completion per prompt, order-preserving."""
        ...

    def count_tokens(self, text: str) -> int:
        ...


# -- shared device-batch helpers (TpuBackend + LongContextBackend) ----------
# Greedy parity between the one-chip engine and the seq-sharded long-context
# engine depends on identical packing / seed / detokenize semantics — keep
# ONE copy of each here.


def fold_seed(gen_seed: int, backend_seed: int, dispatch: int) -> int:
    """Per-batch PRNG seed folded from (config seed, backend seed, dispatch
    index): sampled batches draw fresh randomness, same-seed reruns over the
    same call sequence replay bit-exactly, greedy ignores the key."""
    return (
        gen_seed * 0x9E3779B1 + backend_seed * 0x85EBCA77 + dispatch
    ) & 0x7FFFFFFF


def left_pad_batch(encoded_group, B: int, S: int, pad_id: int):
    """Pack encoded prompts into a fixed-shape left-padded [B, S] batch;
    rows beyond the group are all-pad filler. Returns (tokens, pad_lens)."""
    import numpy as np

    tokens = np.full((B, S), pad_id, dtype=np.int32)
    pad_lens = np.full((B,), S, dtype=np.int32)
    for row, ids in enumerate(encoded_group):
        tokens[row, S - len(ids):] = ids
        pad_lens[row] = S - len(ids)
    return tokens, pad_lens


def trim_to_eos(
    ids, eos_id: int, pad_id: int, extra_eos: tuple[int, ...] = ()
) -> list[int]:
    """Cut a generated id row at its first EOS/pad slot. ``extra_eos`` carries
    the active GenerationConfig.eos_ids — custom stop tokens are emitted
    before the done check fires, so they must be stripped like native EOS."""
    stops = {eos_id, pad_id, *extra_eos}
    out: list[int] = []
    for t in ids:
        if t in stops:
            break
        out.append(t)
    return out


def decodable_vocab_limit(tok, model_vocab_size: int) -> int:
    """Sampling range that can actually become text: the model head may be
    larger than the tokenizer (random-init 128k-vocab model + byte tokenizer
    in benches/tests), and a tokenizer may carry padded/special ids its
    decode() drops (ByteTokenizer ids >= 256). Sampling outside this range
    yields silently-vanishing tokens and empty summaries. Real HF
    tokenizers set decodable == vocab == model head, making this a no-op."""
    tok_limit = getattr(
        tok, "decodable_vocab_size", getattr(tok, "vocab_size", None)
    )
    return min(model_vocab_size, tok_limit or model_vocab_size)


def resolve_max_new(
    max_new_tokens: int | None, config, backend_default: int
) -> int:
    """Decode-budget resolution shared by every backend: explicit argument >
    explicit config override > the backend's constructor default. A config
    passed only for temperature/eos (max_new_tokens=None) keeps the
    constructor budget."""
    if max_new_tokens is not None:
        return max_new_tokens
    if config is not None and config.max_new_tokens is not None:
        return config.max_new_tokens
    return backend_default


def get_backend(spec: str, **kwargs) -> Backend:
    """Factory: "fake", "ollama", "tpu", or "hf"."""
    if spec == "fake":
        from .fake import FakeBackend

        return FakeBackend(**kwargs)
    if spec == "ollama":
        from .ollama import OllamaBackend

        return OllamaBackend(**kwargs)
    if spec == "tpu":
        from .engine import TpuBackend

        return TpuBackend(**kwargs)
    if spec == "hf":
        from .hf import HFBackend

        return HFBackend(**kwargs)
    raise ValueError(f"unknown backend {spec!r} (use tpu|ollama|hf|fake)")
