from .base import Backend, get_backend
from .fake import FakeBackend
from .ollama import OllamaBackend

__all__ = [
    "Backend", "get_backend", "FakeBackend", "OllamaBackend", "TpuBackend",
    "LongContextBackend",
]


def __getattr__(name):
    # TpuBackend pulls in jax; keep it lazy so host-only tools (cleaners,
    # token stats, Ollama-backed runs) never pay for it.
    if name == "TpuBackend":
        from .engine import TpuBackend

        return TpuBackend
    if name == "LongContextBackend":
        from .long_context import LongContextBackend

        return LongContextBackend
    raise AttributeError(name)
