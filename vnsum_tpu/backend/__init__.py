from .base import Backend, get_backend
from .fake import FakeBackend
from .ollama import OllamaBackend

__all__ = ["Backend", "get_backend", "FakeBackend", "OllamaBackend", "TpuBackend"]


def __getattr__(name):
    # TpuBackend pulls in jax; keep it lazy so host-only tools (cleaners,
    # token stats, Ollama-backed runs) never pay for it.
    if name == "TpuBackend":
        from .engine import TpuBackend

        return TpuBackend
    raise AttributeError(name)
