"""Long-context generation: ring-attention prefill + seq-sharded decode.

The reference cannot run a 54k-token document through its model at all — its
truncated strategy cuts inputs to 16384−2048 tokens
(runners/run_summarization_ollama.py:8-13, config
run_full_evaluation_pipeline.py:1004-1007), and the engine's one-chip path
(`backend.engine`) clips the same way because a single chip can't hold the KV
cache. This module removes that ceiling with sequence parallelism:

- **Prefill** runs the full prompt as ONE forward with the sequence dim
  sharded over the mesh `seq` axis: blockwise ring attention
  (`parallel.ring`, K/V blocks rotating via `ppermute`) so no device ever
  holds the full [S, S] scores or the full KV cache — an N-way seq axis
  multiplies the maximum prompt length by N.
- **Decode** keeps the prefill KV cache frozen and seq-sharded. Each step,
  every device computes an online-softmax partial over its local cache shard;
  partials merge over the seq axis with `pmax`/`psum` (log-sum-exp
  renormalization), then merge again with the attention over the small
  replicated cache of freshly generated tokens. New-token KV is appended only
  to that replicated decode cache — the sharded prefill cache is never
  touched again, so there is no resharding traffic in the loop.

The decode step reuses `models.llama.forward` via its `stacked_attention_fn`
seam (the decode-side cache write, RoPE, and MLP are the same code the
one-chip engine runs); the merge math is the only new device code.
"""
from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..core.config import GenerationConfig
from ..core.logging import get_logger
from .base import (
    fold_seed,
    left_pad_batch,
    mask_unsampleable,
    resolve_max_new,
    sampling_vocab,
    terminator_ids,
    trim_to_eos,
)
from ..models.llama import (
    LlamaConfig,
    _embed_lookup,
    _lm_head_logits,
    _rmsnorm,
    _rope_cos_sin,
    cache_free_block,
    forward,
    init_kv_cache,
    prefill_positions,
)
from ..models.sampling import sample_logits
from ..parallel.mesh import AXES, axis_size, shard_map
from ..parallel.ring import ring_attention
from ..text.tokenizer import Tokenizer, get_tokenizer

logger = get_logger("vnsum.long")

_NEG = jnp.float32(-1e30)


# -- prefill -----------------------------------------------------------------


def long_prefill(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,     # [B, S] int32, left-padded; S sharded over `seq`
    pad_lens: jax.Array,   # [B] int32
    mesh: Mesh,
    *,
    remat: bool = True,
):
    """One ring-attention forward over the full (sharded) prompt.

    Returns (last_logits [B, V] f32, prefill_cache {"k","v": [L, B, KV, S,
    hd]}) — the ENGINE-NATIVE stacked layout, S sharded over the seq axis.
    Remat is on by default: prefill is one giant forward, and recomputing
    block activations is far cheaper than holding S-long intermediates for
    XLA's scheduler."""
    B, S = tokens.shape
    x = _embed_lookup(params["embed"], tokens, cfg.dtype)
    positions = prefill_positions(pad_lens, S)
    cos, sin = _rope_cos_sin(cfg, positions)
    attention = partial(ring_attention, mesh=mesh, pad_lens=pad_lens)

    def block(x, lp):
        # ONE copy of the decoder math (models.llama.cache_free_block, the
        # same block forward_train scans) — here the k/v become the cache,
        # transposed PER LAYER to the engine-native [B, KV, S, hd] order
        # (ops/decode_attention's axis order) so the scan stacks the final
        # layout directly — a post-scan whole-cache transpose would hold
        # two full copies at the exact moment of peak HBM use
        x, (k, v) = cache_free_block(x, lp, cos, sin, cfg, attention)
        return x, (k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3))

    if remat:
        block = jax.checkpoint(block)

    x, (ks, vs) = jax.lax.scan(block, x, params["layers"])
    cache_spec = NamedSharding(
        mesh, P(None, AXES.data, AXES.model, AXES.seq, None)
    )
    ks = jax.lax.with_sharding_constraint(ks, cache_spec)
    vs = jax.lax.with_sharding_constraint(vs, cache_spec)

    x = _rmsnorm(x[:, -1:, :], params["final_norm"], cfg.norm_eps)
    logits = _lm_head_logits(x, params, cfg)
    return logits[:, 0], {"k": ks, "v": vs}


def quantize_prefill_cache(cache: dict) -> dict:
    """[L, B, KV, S, hd] bf16 cache -> int8 values + per-(layer, head,
    token) f32 scales. Decode streams every shard's cache each step, so this
    halves long-context decode HBM traffic (the engine's per-vector scheme,
    models.llama._quantize_kv — axis-agnostic over leading dims)."""
    from ..models.llama import _quantize_kv

    k8, ks = _quantize_kv(cache["k"])
    v8, vs = _quantize_kv(cache["v"])
    return {"k": k8, "v": v8, "ks": ks, "vs": vs}


# -- decode over the sharded prefill cache -----------------------------------


def _prefill_partial_local(
    q, k_loc, v_loc, pad_lens, k_scale=None, v_scale=None, *,
    q_per_kv, axis_name,
):
    """Per-device online-softmax partial over the local prefill-cache shard,
    merged across the seq axis inside (pmax/psum). q [B, H, hd];
    k_loc/v_loc [B, KV, S_loc, hd] (int8 when k_scale/v_scale [B, KV, S_loc]
    are given). Returns (o [B, H, hd] f32, m, l [B, H]). Dense fallback for
    head dims the Pallas kernel can't take (see _kernel_partial_local)."""
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, H, hd = q.shape
    KV = k_loc.shape[1]
    S_loc = k_loc.shape[2]
    G = q_per_kv

    qg = q.reshape(B, KV, G, hd)
    if k_scale is not None:
        # int8 cache stays int8 into the MXU (the dtype convert fuses into
        # the tile load); the per-(head, token) scale is constant over the
        # contracted hd dim, so it factors out of the dot EXACTLY and
        # multiplies the scores instead — the f32-dequantized shard copy
        # never materializes.
        scores = (
            jnp.einsum("bkgh,bksh->bkgs", qg, k_loc.astype(qg.dtype),
                       preferred_element_type=jnp.float32)
            * k_scale[:, :, None, :]
            / jnp.sqrt(jnp.float32(hd))
        )
    else:
        scores = (
            jnp.einsum("bkgh,bksh->bkgs", qg, k_loc,
                       preferred_element_type=jnp.float32)
            / jnp.sqrt(jnp.float32(hd))
        )
    k_pos = idx * S_loc + jnp.arange(S_loc)
    valid = k_pos[None, :] >= pad_lens[:, None]  # [B, S_loc]
    scores = jnp.where(valid[:, None, None], scores, _NEG)

    m = jnp.max(scores, axis=-1)                      # [B, KV, G]
    p = jnp.where(valid[:, None, None], jnp.exp(scores - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    if v_scale is not None:
        # same trick on the value side: scale the probabilities along s
        # (constant over hd), keep v int8 in the matmul
        pv = p * v_scale[:, :, None, :]
        o = jnp.einsum("bkgs,bksh->bkgh", pv, v_loc.astype(jnp.float32))
    else:
        o = jnp.einsum("bkgs,bksh->bkgh", p, v_loc.astype(jnp.float32))

    m_g = jax.lax.pmax(m, axis_name)
    corr = jnp.exp(m - m_g)
    l_g = jax.lax.psum(l * corr, axis_name)
    o_g = jax.lax.psum(o * corr[..., None], axis_name)
    return (
        o_g.reshape(B, H, hd),
        m_g.reshape(B, H),
        l_g.reshape(B, H),
    )


def _kernel_partial_local(
    q, k_all, v_all, pad_lens, layer_idx, k_scale=None, v_scale=None, *,
    q_per_kv, axis_name, interpret,
):
    """Kernelized shard-local partial (VERDICT r3 #5): the stacked-cache
    decode kernel runs on each device's cache shard — layer selection via
    scalar prefetch (no per-layer extraction copy), int8 K/V streamed with
    in-kernel dequant — and its unnormalized (o, m, l) state LSE-merges
    across the seq axis exactly like the dense partial's.

    q [B, H, hd]; k_all/v_all the WHOLE local stacked shard
    [L, B, KV, S_loc, hd] (+ scales [L, B, KV, S_loc])."""
    idx = jax.lax.axis_index(axis_name)
    S_loc = k_all.shape[3]
    # left-pad boundary in this shard's local coordinates: rows whose global
    # pad falls past the shard mask out entirely (the kernel then emits
    # m=-inf, l=0 — inert in the merge)
    pads_local = jnp.clip(pad_lens - idx * S_loc, 0, S_loc)
    cache = {"k": k_all, "v": v_all}
    if k_scale is not None:
        cache.update(ks=k_scale, vs=v_scale)
    from ..ops.decode_attention import flash_decode_attention

    o, m, l = flash_decode_attention(
        q[:, None], cache, layer_idx, pads_local, S_loc - 1, q_per_kv,
        return_partials=True, interpret=interpret,
    )
    m_g = jax.lax.pmax(m, axis_name)
    corr = jnp.exp(m - m_g)
    l_g = jax.lax.psum(l * corr, axis_name)
    o_g = jax.lax.psum(o * corr[..., None], axis_name)
    return o_g, m_g, l_g


def make_long_decode_attention(
    mesh: Mesh, prefill_cache: dict, pad_lens: jax.Array, q_per_kv: int,
    *, decode_kernel: str | bool = "auto", interpret: bool = False,
):
    """Build the merged attention for models.llama.forward's
    ``stacked_attention_fn`` seam: the returned ``attention(q, cache,
    layer_idx, t)`` attends over BOTH the frozen seq-sharded prefill cache
    (closure) and the small replicated decode cache, valid slots 0..t; the
    decode loop binds ``t`` per step via a lambda.

    ``decode_kernel`` "auto" runs the Pallas stacked-cache kernel on each
    shard when the head dim is lane-aligned (or under interpret), else the
    dense einsum partial — the kernel consumes the whole stacked shard with
    the layer chosen by scalar prefetch, so the per-step per-layer
    extraction copy of the shard never materializes."""
    quantized = "ks" in prefill_cache
    hd = prefill_cache["k"].shape[-1]
    if decode_kernel == "auto":
        # real kernels need Mosaic on the MESH's devices (not the process
        # default backend — on this host the TPU plugin is default even
        # when the mesh is host-CPU) AND a lane-aligned head dim
        # (supports_decode — ONE copy of that rule); interpret mode
        # simulates them anywhere
        from ..ops.decode_attention import supports_decode

        S_total = prefill_cache["k"].shape[3]
        mesh_platform = next(iter(mesh.devices.flat)).platform
        decode_kernel = interpret or (
            mesh_platform == "tpu" and supports_decode(S_total, hd)
        )
    out_specs = (
        P(AXES.data, AXES.model, None),
        P(AXES.data, AXES.model),
        P(AXES.data, AXES.model),
    )
    if decode_kernel:
        kv_spec = P(None, AXES.data, AXES.model, AXES.seq, None)
        scale_spec = P(None, AXES.data, AXES.model, AXES.seq)
        in_specs = [
            P(AXES.data, AXES.model, None), kv_spec, kv_spec, P(AXES.data),
            P(),
        ]
        if quantized:
            in_specs += [scale_spec, scale_spec]
        partial_fn = shard_map(
            partial(
                _kernel_partial_local, q_per_kv=q_per_kv,
                axis_name=AXES.seq, interpret=interpret,
            ),
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=out_specs,
            check_vma=False,
        )
    else:
        kv_spec = P(AXES.data, AXES.model, AXES.seq, None)
        scale_spec = P(AXES.data, AXES.model, AXES.seq)
        in_specs = [
            P(AXES.data, AXES.model, None), kv_spec, kv_spec, P(AXES.data),
        ]
        if quantized:
            in_specs += [scale_spec, scale_spec]
        partial_fn = shard_map(
            partial(
                _prefill_partial_local, q_per_kv=q_per_kv, axis_name=AXES.seq
            ),
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=out_specs,
        )

    def attention(q, cache, layer_idx, t):
        """q [B, 1, H, hd]; cache = small decode cache [L, B, KV, C, hd];
        attends prefill shards + decode slots 0..t."""
        B, _, H, hd = q.shape
        q1 = q[:, 0]

        if decode_kernel:
            args = [
                q1, prefill_cache["k"], prefill_cache["v"], pad_lens,
                jnp.asarray(layer_idx, jnp.int32),
            ]
            if quantized:
                args += [prefill_cache["ks"], prefill_cache["vs"]]
        else:

            def layer(name):
                return jax.lax.dynamic_index_in_dim(
                    prefill_cache[name], layer_idx, 0, keepdims=False
                )

            args = [q1, layer("k"), layer("v"), pad_lens]
            if quantized:
                args += [layer("ks"), layer("vs")]
        o1, m1, l1 = partial_fn(*args)

        # decode-cache partial (replicated math; C = max_new is small)
        k_dec = jax.lax.dynamic_index_in_dim(
            cache["k"], layer_idx, 0, keepdims=False
        )  # [B, KV, C, hd]
        v_dec = jax.lax.dynamic_index_in_dim(
            cache["v"], layer_idx, 0, keepdims=False
        )
        KV = k_dec.shape[1]
        C = k_dec.shape[2]
        qg = q1.reshape(B, KV, q_per_kv, hd)
        scores = (
            jnp.einsum("bkgh,bkch->bkgc", qg, k_dec.astype(qg.dtype),
                       preferred_element_type=jnp.float32)
            / jnp.sqrt(jnp.float32(hd))
        )
        valid = (jnp.arange(C) <= t)[None, None, None, :]
        scores = jnp.where(valid, scores, _NEG)
        m2 = jnp.max(scores, axis=-1)
        p = jnp.where(valid, jnp.exp(scores - m2[..., None]), 0.0)
        l2 = jnp.sum(p, axis=-1)
        o2 = jnp.einsum("bkgc,bkch->bkgh", p, v_dec.astype(jnp.float32))
        m2 = m2.reshape(B, H)
        l2 = l2.reshape(B, H)
        o2 = o2.reshape(B, H, hd)

        # log-sum-exp merge of the two partials
        m = jnp.maximum(m1, m2)
        c1 = jnp.exp(m1 - m)
        c2 = jnp.exp(m2 - m)
        l = l1 * c1 + l2 * c2
        o = o1 * c1[..., None] + o2 * c2[..., None]
        out = o / jnp.maximum(l, 1e-30)[..., None]
        return out[:, None].astype(q.dtype)  # [B, 1, H, hd]

    return attention


# -- full generation program -------------------------------------------------


def generate_long_tokens(
    params: dict,
    cfg: LlamaConfig,
    mesh: Mesh,
    tokens: jax.Array,     # [B, S] left-padded, S % seq_axis == 0
    pad_lens: jax.Array,   # [B]
    max_new: int,
    *,
    eos_ids,
    pad_id: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    seed: int = 0,
    quantize_kv: bool = False,
    vocab_limit: int = 0,
    vocab_allowed=None,
    decode_kernel: str | bool = "auto",
    interpret: bool = False,
) -> jax.Array:
    """Traceable end-to-end long-context generation; returns [B, max_new].

    jit this with params/tokens shardings; the prompt may exceed single-chip
    memory by the seq-axis factor. ``quantize_kv`` stores the frozen prefill
    cache int8 (decode streams every shard per step — traffic halves, and
    the freed HBM doubles the context that fits)."""
    B, S = tokens.shape
    eos = jnp.asarray(list(eos_ids), dtype=jnp.int32)
    # 0 = full model vocab; a smaller tokenizer vocab restricts sampling to
    # decodable ids (same rationale as engine.py's vocab_limit). The bool
    # ``vocab_allowed`` mask keeps terminators above the decodable range
    # sampleable while blocking text-invisible filler ids (base.sampling_vocab)
    V = vocab_limit or None
    allowed = None if vocab_allowed is None else jnp.asarray(vocab_allowed)

    def restrict(row_logits):  # [B, V]
        return mask_unsampleable(row_logits, allowed)

    last_logits, prefill_cache = long_prefill(
        params, cfg, tokens, pad_lens, mesh
    )
    if quantize_kv:
        prefill_cache = quantize_prefill_cache(prefill_cache)
    key = jax.random.key(seed)
    key, sub = jax.random.split(key)
    first = sample_logits(
        restrict(last_logits[:, :V]), sub, temperature, top_k, top_p
    )
    done0 = pad_lens == S  # all-pad filler rows start done

    attention = make_long_decode_attention(
        mesh, prefill_cache, pad_lens, cfg.q_per_kv,
        decode_kernel=decode_kernel, interpret=interpret,
    )
    decode_cache0 = init_kv_cache(cfg, B, max_new)
    out0 = jnp.full((B, max_new), pad_id, dtype=jnp.int32)

    def cond(carry):
        t, _cur, _cache, done, _key, _out = carry
        return (t < max_new) & ~jnp.all(done)

    def body(carry):
        t, cur, cache, done, key, out = carry
        emit = jnp.where(done, pad_id, cur)
        out = jax.lax.dynamic_update_slice(out, emit[:, None], (0, t))
        done = done | jnp.isin(cur, eos)
        pos = (S - pad_lens) + t
        # decode-cache mask is handled inside the attention (slots 0..t);
        # forward()'s own mask argument covers only dense fallbacks — pass
        # the same slot validity for shape consistency
        mask_t = (jnp.arange(max_new) <= t)[None, None, :].repeat(B, axis=0)
        logits, cache = forward(
            params, cfg, cur[:, None], pos[:, None], cache, t, mask_t,
            stacked_attention_fn=lambda q, c, li: attention(q, c, li, t),
        )
        key, sub = jax.random.split(key)
        nxt = sample_logits(
            restrict(logits[:, -1, :V]), sub, temperature, top_k, top_p
        )
        return (t + 1, nxt, cache, done, key, out)

    *_, out = jax.lax.while_loop(
        cond, body, (jnp.int32(0), first, decode_cache0, done0, key, out0)
    )
    return out


class LongContextBackend:
    """Backend-protocol generation over a seq-sharded mesh: prompts up to
    (seq_axis × single-chip limit) tokens run UN-truncated. Pair with
    strategies.truncated (max_context set to the long limit) to summarize
    VN-LongSum's 54k-token docs in one shot — a capability the reference's
    16k context fundamentally cannot match."""

    name = "tpu"
    label = "tpu+long-context"

    def __init__(
        self,
        model_config: LlamaConfig | None = None,
        mesh: Mesh | None = None,
        tokenizer: str | Tokenizer = "byte",
        params=None,
        batch_size: int = 1,
        max_new_tokens: int = 1024,
        max_total_tokens: int | None = None,
        generation: GenerationConfig | None = None,
        seed: int = 0,
        quantize: bool = False,
        quantize_kv: bool = False,
        decode_kernel: str | bool = "auto",
        interpret: bool = False,
    ) -> None:
        from ..models.llama import init_params, llama32_3b

        from ..core.jax_cache import enable_compilation_cache

        enable_compilation_cache()
        if (model_config is not None) and model_config.sliding_window:
            raise NotImplementedError(
                "LongContextBackend runs ring attention (global K/V "
                "streaming); sliding-window (Gemma local) configs are "
                "one-chip-engine only"
            )
        if mesh is None or AXES.seq not in mesh.shape:
            raise ValueError(
                "LongContextBackend needs a mesh with a 'seq' axis — that "
                "axis is what multiplies the context ceiling"
            )
        self.cfg = model_config or llama32_3b()
        self.mesh = mesh
        self.tok = get_tokenizer(tokenizer) if isinstance(tokenizer, str) else tokenizer
        # prompts here are near the memory ceiling by definition — default to
        # one row at a time; raise only when the per-row cache share allows.
        # Rounded DOWN to a data-axis multiple (the value is the caller's
        # HBM high-water mark) — except that at least data_size rows must
        # exist to shard over the data axis at all, so a smaller request is
        # floored up. Either adjustment is loud: memory budgets depend on it.
        data_size = mesh.shape.get(AXES.data, 1)
        self.batch_size = max(
            data_size, (max(batch_size, 1) // data_size) * data_size
        )
        if self.batch_size != batch_size:
            logger.warning(
                "batch_size adjusted %d -> %d (mesh data axis %d needs a "
                "divisible row count); per-dispatch memory scales with it",
                batch_size, self.batch_size, data_size,
            )
        self.max_new_tokens = max_new_tokens
        # the long path deliberately ignores cfg.max_seq_len (that is the
        # ONE-CHIP ceiling); the real limit is RoPE numerical range + HBM
        self.max_total_tokens = max_total_tokens or (
            self.cfg.max_seq_len * mesh.shape[AXES.seq]
        )
        if max_new_tokens >= self.max_total_tokens:
            raise ValueError(
                f"max_new_tokens={max_new_tokens} must be < "
                f"max_total_tokens={self.max_total_tokens}"
            )
        self.gen_cfg = generation or GenerationConfig()
        self._seed = seed
        self._dispatch = 0
        self._fns: dict = {}
        self.quantize_kv = bool(quantize_kv)
        self.decode_kernel = decode_kernel
        self.interpret = bool(interpret)
        if params is None:
            from ..models import jitted_init

            params = jitted_init(init_params, self.cfg, seed)
        if quantize:
            from ..models.quant import is_quantized, quantize_params

            if not is_quantized(params):
                params = jax.jit(quantize_params)(params)
        from ..parallel.sharding import shard_params

        self.params = shard_params(params, mesh, self.cfg.tie_embeddings)

    def _bucket(self, n: int) -> int:
        """Round S up to a multiple of (seq_axis × 128) with pow2-ish steps
        to bound recompiles."""
        step = self.mesh.shape[AXES.seq] * 128
        b = step
        while b < n:
            b *= 2
        return min(b, ((self.max_total_tokens + step - 1) // step) * step)

    def _next_seed(self, gen: GenerationConfig) -> int:
        s = fold_seed(gen.seed, self._seed, self._dispatch)
        self._dispatch += 1
        return s

    def generate(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        references: list[str | None] | None = None,  # spec metadata; unused
        cache_hints: list[str | None] | None = None,  # cache metadata; unused
    ) -> list[str]:
        gen = config or self.gen_cfg
        max_new = resolve_max_new(max_new_tokens, gen, self.max_new_tokens)
        if max_new >= self.max_total_tokens:
            raise ValueError(
                f"max_new_tokens={max_new} must be < "
                f"max_total_tokens={self.max_total_tokens}"
            )
        if not prompts:
            return []
        data_size = self.mesh.shape.get(AXES.data, 1)

        encoded = []
        for p in prompts:
            ids = self.tok.encode(p, add_bos=True)
            if len(ids) > self.max_total_tokens - max_new:
                ids = ids[: self.max_total_tokens - max_new]
            encoded.append(ids)

        # length-sorted groups of at most batch_size rows, each bucketed for
        # ITS longest member: prompts at this scale sit near the HBM ceiling,
        # so one giant longest-prompt batch would OOM and make every short
        # prompt pay the longest prefill
        order = sorted(range(len(encoded)), key=lambda i: len(encoded[i]))
        results: list[str | None] = [None] * len(encoded)
        for start in range(0, len(order), self.batch_size):
            group = order[start : start + self.batch_size]
            S = self._bucket(max(len(encoded[i]) for i in group))
            B = data_size
            while B < len(group):
                B *= 2
            # batch_size is the caller's HBM high-water mark — never exceed
            # it just to reach a power of two (batch_size % data == 0 is
            # checked at construction, so the clamp stays shardable)
            B = min(B, self.batch_size)
            tokens, pad_lens = left_pad_batch(
                [encoded[i] for i in group], B, S, self.tok.pad_id
            )

            fn = self._get_fn(B, S, max_new, gen)
            t0 = time.time()
            out = np.asarray(
                fn(self.params, tokens, pad_lens, self._next_seed(gen))
            )
            logger.info(
                "long generate: B=%d S=%d new=%d in %.1fs",
                B, S, max_new, time.time() - t0,
            )
            for row, i in enumerate(group):
                ids = trim_to_eos(
                    out[row].tolist(), self.tok.eos_id, self.tok.pad_id,
                    tuple(gen.eos_ids),
                )
                results[i] = self.tok.decode(ids).strip()
        return results  # type: ignore[return-value]

    def _get_fn(self, B: int, S: int, max_new: int, gen: GenerationConfig):
        key = (B, S, max_new, gen.with_(seed=0))
        if key not in self._fns:
            from ..models.quant import is_quantized
            from ..parallel.sharding import param_shardings

            ns = lambda spec: NamedSharding(self.mesh, spec)
            eos_ids = terminator_ids(self.tok, gen)
            vocab_limit, vocab_allowed = sampling_vocab(
                self.tok, self.cfg.vocab_size, eos_ids
            )

            def program(params, tokens, pad_lens, seed):
                return generate_long_tokens(
                    params, self.cfg, self.mesh, tokens, pad_lens, max_new,
                    eos_ids=eos_ids, pad_id=self.tok.pad_id,
                    temperature=gen.temperature, top_k=gen.top_k,
                    top_p=gen.top_p, seed=seed,
                    quantize_kv=self.quantize_kv,
                    vocab_limit=vocab_limit,
                    vocab_allowed=vocab_allowed,
                    decode_kernel=self.decode_kernel,
                    interpret=self.interpret,
                )

            self._fns[key] = jax.jit(
                program,
                in_shardings=(
                    param_shardings(
                        self.mesh, self.cfg.tie_embeddings,
                        is_quantized(self.params),
                        qk_norm=self.cfg.qk_norm,
                        sandwich_norms=self.cfg.sandwich_norms,
                    ),
                    ns(P(AXES.data, AXES.seq)),
                    ns(P(AXES.data)),
                    None,
                ),
                out_shardings=ns(P(AXES.data, None)),
            )
            logger.info("built long-context fn B=%d S=%d new=%d", B, S, max_new)
        return self._fns[key]

    def count_tokens(self, text: str) -> int:
        return self.tok.count(text)

    def count_tokens_batch(self, texts: list[str]) -> list[int]:
        return self.tok.count_batch(texts)
