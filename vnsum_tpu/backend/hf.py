"""HF transformers (torch) backend — capability match for the reference's
only direct-ML execution path, `runners/run_summarization.py:17-62` (SURVEY.md
§2 C8): AutoModelForCausalLM + chat template with thinking disabled, greedy
`model.generate`, input truncated to `max_context - max_new_tokens`.

In this framework it serves two roles:
- a CPU/GPU parity oracle for the JAX engine (same prompts, greedy decode,
  comparable outputs), and
- a fallback backend on hosts without TPU access.

Torch and transformers are imported lazily so the rest of the framework never
pays for them; models must already be on disk (zero-egress hosts have no HF
hub access).
"""
from __future__ import annotations

from ..core.config import GenerationConfig

from .base import resolve_max_new
from ..core.faults import call_with_retries, is_retryable
from ..core.logging import get_logger
from ..text.cleaning import clean_thinking_tokens

logger = get_logger("vnsum.backend.hf")


class HFBackend:
    name = "hf"

    def __init__(
        self,
        model_name_or_path: str,
        *,
        model=None,
        tokenizer=None,
        max_context: int = 16384,
        max_new_tokens: int = 1024,
        device: str = "cpu",
        use_chat_template: bool = True,
        clean_output: bool = True,
        torch_dtype=None,
        load_retries: int = 2,
        load_backoff: float = 1.0,
        hub_timeout_s: float = 10.0,
    ) -> None:
        import os

        # HTTP hygiene for the only network path this backend has — hub
        # downloads inside from_pretrained: bound the connect/read phases.
        # huggingface_hub reads these envs AT MODULE IMPORT (constants.py),
        # so they must be set BEFORE the transformers import below pulls it
        # in; without them a dead proxy hangs on the library's much larger
        # defaults.
        os.environ.setdefault("HF_HUB_ETAG_TIMEOUT", str(int(hub_timeout_s)))
        os.environ.setdefault(
            "HF_HUB_DOWNLOAD_TIMEOUT", str(int(hub_timeout_s))
        )

        import torch
        from transformers import AutoModelForCausalLM, AutoTokenizer

        # belt and braces: when huggingface_hub was imported BEFORE this
        # constructor (its constants module snapshots the env at import),
        # the setdefaults above changed nothing — overwrite the live
        # constants too, so the bound applies regardless of import order
        # and of per-instance hub_timeout_s values
        import sys as _sys

        hub_constants = getattr(
            _sys.modules.get("huggingface_hub"), "constants", None
        )
        if hub_constants is not None:
            hub_constants.HF_HUB_ETAG_TIMEOUT = int(hub_timeout_s)
            hub_constants.HF_HUB_DOWNLOAD_TIMEOUT = int(hub_timeout_s)

        self._torch = torch
        self.model_name = model_name_or_path
        self.max_context = max_context
        self.max_new_tokens = max_new_tokens
        self.device = device
        self.use_chat_template = use_chat_template
        self.clean_output = clean_output

        def _load_should_retry(e: BaseException) -> bool:
            # transformers raises PLAIN OSError for permanent problems
            # ("not a local folder and is not a valid model identifier"),
            # while genuinely transient network failures arrive as OSError
            # SUBCLASSES (requests.ConnectionError, timeouts) — so fail
            # fast on the exact type, retry the rest through the shared
            # PERMANENT_ERRORS filter
            if type(e) is OSError:
                return False
            return is_retryable(e)

        def _load(what, fn):
            return call_with_retries(
                fn,
                max_retries=load_retries,
                backoff=load_backoff,
                jitter=0.25,
                should_retry=_load_should_retry,
                what=what,
            )

        # injectable for tests / pre-loaded models (no hub access on TPU hosts)
        self.tokenizer = tokenizer or _load(
            f"load tokenizer {model_name_or_path}",
            lambda: AutoTokenizer.from_pretrained(model_name_or_path),
        )
        if model is None:
            model = _load(
                f"load model {model_name_or_path}",
                lambda: AutoModelForCausalLM.from_pretrained(
                    model_name_or_path,
                    torch_dtype=torch_dtype or torch.float32,
                ),
            )
        self.model = model.to(device).eval()
        if self.tokenizer.pad_token_id is None:
            self.tokenizer.pad_token = self.tokenizer.eos_token

    def _render(self, prompt: str) -> str:
        """Chat template with thinking disabled (ref :29-39,
        enable_thinking=False); plain passthrough when the tokenizer has no
        template or templating is off."""
        if not self.use_chat_template:
            return prompt
        if getattr(self.tokenizer, "chat_template", None) is None:
            return prompt
        try:
            return self.tokenizer.apply_chat_template(
                [{"role": "user", "content": prompt}],
                tokenize=False,
                add_generation_prompt=True,
                enable_thinking=False,
            )
        except TypeError:  # template without enable_thinking support
            return self.tokenizer.apply_chat_template(
                [{"role": "user", "content": prompt}],
                tokenize=False,
                add_generation_prompt=True,
            )

    def generate(
        self,
        prompts: list[str],
        *,
        max_new_tokens: int | None = None,
        config: GenerationConfig | None = None,
        references: list[str | None] | None = None,  # spec metadata; unused
        cache_hints: list[str | None] | None = None,  # cache metadata; unused
    ) -> list[str]:
        torch = self._torch
        max_new = resolve_max_new(max_new_tokens, config, self.max_new_tokens)
        max_input = self.max_context - max_new  # ref :40-43
        if max_input <= 0:
            raise ValueError(
                f"max_new_tokens={max_new} must be < max_context={self.max_context}"
            )
        if not prompts:
            return []

        # truncate the raw prompt BEFORE templating (ref :40-43 truncates the
        # document first) — right-truncating the rendered string would cut the
        # template's assistant-generation suffix and the model would continue
        # the user turn instead of summarizing
        overhead = (
            len(self.tokenizer.encode(self._render("")))
            if self.use_chat_template
            else 0
        )
        budget = max(max_input - overhead, 1)
        clipped = []
        for p in prompts:
            ids = self.tokenizer.encode(p)
            if len(ids) > budget:
                p = self.tokenizer.decode(
                    ids[:budget], skip_special_tokens=True
                )
            clipped.append(p)
        rendered = [self._render(p) for p in clipped]
        enc = self.tokenizer(
            rendered,
            return_tensors="pt",
            padding=True,
            truncation=True,
            max_length=max_input,
            padding_side="left",
        ).to(self.device)

        do_sample = config is not None and config.temperature > 0.0
        kwargs: dict = {
            "max_new_tokens": max_new,
            "do_sample": do_sample,  # greedy default, ref :44
            "pad_token_id": self.tokenizer.pad_token_id,
        }
        if do_sample:
            kwargs["temperature"] = config.temperature
            if config.top_k > 0:
                kwargs["top_k"] = config.top_k
            if config.top_p < 1.0:
                kwargs["top_p"] = config.top_p

        with torch.no_grad():
            out = self.model.generate(**enc, **kwargs)
        new_tokens = out[:, enc["input_ids"].shape[1] :]
        texts = self.tokenizer.batch_decode(new_tokens, skip_special_tokens=True)
        if self.clean_output:
            texts = [clean_thinking_tokens(t) for t in texts]
        return [t.strip() for t in texts]

    def count_tokens(self, text: str) -> int:
        return len(self.tokenizer.encode(text))

    def count_tokens_batch(self, texts: list[str]) -> list[int]:
        return [len(ids) for ids in self.tokenizer(list(texts))["input_ids"]]
