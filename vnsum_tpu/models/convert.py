"""HF Llama checkpoint -> stacked JAX pytree conversion.

The reference runs its only direct-ML path through HF transformers
(runners/run_summarization.py:54-62, ``AutoModelForCausalLM.from_pretrained``
with ``device_map="auto"``). The TPU framework keeps HF format as the
*interchange* format only: weights are converted once, host-side, into the
stacked-layer pytree of :mod:`vnsum_tpu.models.llama` and from then on live as
sharded JAX arrays on the mesh.

Conversion notes:
- HF ``Linear.weight`` is stored ``[out, in]``; our einsum layouts are
  ``[in, ...out]``, so every projection is transposed (and reshaped to split
  the head dims). No RoPE permutation is needed: HF Llama checkpoints already
  use the rotate-half convention that :func:`..models.llama._apply_rope`
  implements.
- Per-layer weights are stacked on a leading ``L`` dim so the decoder runs as
  one ``lax.scan`` over layers.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Mapping

import numpy as np

from .llama import LlamaConfig

# HF key templates -> (our nested key path, converter)
_LAYER_KEYS: dict[str, str] = {
    "self_attn.q_proj.weight": "wq",
    "self_attn.k_proj.weight": "wk",
    "self_attn.v_proj.weight": "wv",
    "self_attn.o_proj.weight": "wo",
    "mlp.gate_proj.weight": "w_gate",
    "mlp.up_proj.weight": "w_up",
    "mlp.down_proj.weight": "w_down",
    "input_layernorm.weight": "attn_norm",
    "post_attention_layernorm.weight": "mlp_norm",
}

# Qwen3 adds per-head Q/K RMSNorms (same HF naming in Qwen3* checkpoints)
_QK_NORM_KEYS: dict[str, str] = {
    "self_attn.q_norm.weight": "q_norm",
    "self_attn.k_norm.weight": "k_norm",
}

# Gemma3 sandwich norms: post_attention_layernorm is the POST-attention
# norm there (Llama reuses that HF name for the pre-MLP norm), and the MLP
# pre-norm is pre_feedforward_layernorm
_GEMMA_NORM_KEYS: dict[str, str] = {
    "input_layernorm.weight": "attn_norm",
    "post_attention_layernorm.weight": "post_attn_norm",
    "pre_feedforward_layernorm.weight": "mlp_norm",
    "post_feedforward_layernorm.weight": "post_ffw_norm",
}


def _layer_keys(cfg: LlamaConfig) -> dict[str, str]:
    keys = dict(_LAYER_KEYS)
    if cfg.sandwich_norms:
        keys.update(_GEMMA_NORM_KEYS)  # remaps the two shared HF norm names
    if cfg.qk_norm:
        keys.update(_QK_NORM_KEYS)
    return keys


def config_from_hf(hf: Mapping[str, Any], **overrides) -> LlamaConfig:
    """Build a :class:`LlamaConfig` from a parsed HF ``config.json`` dict."""
    if "text_config" in hf:
        # multimodal wrapper (gemma-3-4b+ repos ship
        # Gemma3ForConditionalGeneration): the decoder lives in text_config
        inner = dict(hf["text_config"])
        inner.setdefault("model_type", hf.get("model_type", "llama"))
        hf = inner
    rope_scaling = hf.get("rope_scaling") or {}
    rope_type = rope_scaling.get("rope_type", rope_scaling.get("type"))
    head_dim = hf.get("head_dim") or (
        hf["hidden_size"] // hf["num_attention_heads"]
    )
    model_type = hf.get("model_type", "llama")
    gemma = model_type.startswith("gemma3")
    if model_type.startswith("phi3") and (
        hf.get("partial_rotary_factor") or 1.0
    ) != 1.0:
        raise NotImplementedError(
            "partial rotary (phi3-small style) is not supported; phi-4 "
            "uses the full rotary dim"
        )
    kw: dict[str, Any] = dict(
        qk_norm=model_type.startswith("qwen3") or gemma,
        vocab_size=hf["vocab_size"],
        dim=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=head_dim,
        intermediate=hf["intermediate_size"],
        # defaults below mirror HF LlamaConfig's defaults, since they fill in
        # for keys absent from config.json
        rope_theta=hf.get("rope_theta", 10_000.0),
        norm_eps=hf.get("rms_norm_eps", 1e-6),
        max_seq_len=hf.get("max_position_embeddings", 16_384),
        tie_embeddings=hf.get("tie_word_embeddings", False),
        use_llama3_rope_scaling=rope_type == "llama3",
    )
    if rope_type == "llama3":
        kw.update(
            rope_scale_factor=rope_scaling.get("factor", 32.0),
            rope_low_freq_factor=rope_scaling.get("low_freq_factor", 1.0),
            rope_high_freq_factor=rope_scaling.get("high_freq_factor", 4.0),
            rope_original_max_len=rope_scaling.get(
                "original_max_position_embeddings", 8192
            ),
        )
    elif rope_type == "linear":
        kw["rope_linear_factor"] = rope_scaling.get("factor", 1.0)
    elif rope_type is not None:
        # e.g. Phi-3's "longrope" (per-band short/long factor arrays):
        # silently dropping a scaling scheme would load fine and generate
        # subtly wrong logits — fail loudly instead
        raise NotImplementedError(
            f"rope_scaling type {rope_type!r} is not supported "
            "(have: llama3, linear)"
        )
    if gemma:
        n_layers = hf["num_hidden_layers"]
        layer_types = hf.get("layer_types")
        if layer_types:
            is_global = tuple(t == "full_attention" for t in layer_types)
        else:
            pattern = hf.get("sliding_window_pattern", 6)
            is_global = tuple(
                (i + 1) % pattern == 0 for i in range(n_layers)
            )
        kw.update(
            act="gelu_tanh",
            sandwich_norms=True,
            norm_plus_one=True,
            embed_scale=True,
            query_scale=float(hf.get("query_pre_attn_scalar") or 0.0),
            sliding_window=int(hf.get("sliding_window") or 0),
            layer_is_global=is_global,
            rope_local_theta=float(
                hf.get("rope_local_base_freq", 10_000.0)
            ),
            # Gemma ties embeddings unless the config says otherwise
            tie_embeddings=hf.get("tie_word_embeddings", True),
        )
    kw.update(overrides)
    return LlamaConfig(**kw)


def convert_hf_state_dict(
    get: Callable[[str], np.ndarray], cfg: LlamaConfig, dtype=None
) -> dict:
    """Convert HF-named tensors into the stacked pytree.

    ``get(name)`` returns the tensor for one HF key — a callable so shard
    files can be memory-mapped and each tensor materialized only once.
    """
    import jax.numpy as jnp

    dtype = dtype or cfg.dtype
    H, KV, hd, D, I = (
        cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.dim, cfg.intermediate,
    )

    def conv(name: str, arr: np.ndarray) -> np.ndarray:
        if name == "wq":
            return arr.T.reshape(D, H, hd)
        if name in ("wk", "wv"):
            return arr.T.reshape(D, KV, hd)
        if name == "wo":
            return arr.T.reshape(H, hd, D)
        if name in ("w_gate", "w_up", "w_down"):
            return arr.T
        return arr  # norms, embed

    layer_keys = _layer_keys(cfg)
    layers: dict[str, list[np.ndarray]] = {k: [] for k in layer_keys.values()}
    for li in range(cfg.n_layers):
        for hf_key, ours in layer_keys.items():
            raw = np.asarray(get(f"model.layers.{li}.{hf_key}"))
            layers[ours].append(conv(ours, raw))

    params = {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), dtype),
        "layers": {
            k: jnp.asarray(np.stack(v), dtype) for k, v in layers.items()
        },
        "final_norm": jnp.asarray(get("model.norm.weight"), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = jnp.asarray(
            np.asarray(get("lm_head.weight")).T, dtype
        )
    return params


def _phi_fused_getter(
    get: Callable[[str], np.ndarray], cfg: LlamaConfig
) -> Callable[[str], np.ndarray]:
    """Adapter for Phi-3/Phi-4 checkpoints (the reference sweeps phi4:14b):
    attention arrives as ONE fused ``qkv_proj`` [(H+2KV)*hd, D] and the MLP
    as ``gate_up_proj`` [2I, D]; serve the split q/k/v/gate/up names the
    shared converter expects as row slices of the fused tensors."""
    H, KV, hd, I = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.intermediate
    q_rows, kv_rows = H * hd, KV * hd

    def fused(name: str) -> np.ndarray:
        if ".self_attn." in name and name.endswith("_proj.weight"):
            part = name.rsplit(".", 2)[-2]  # q_proj / k_proj / v_proj
            if part in ("q_proj", "k_proj", "v_proj"):
                w = np.asarray(get(name.replace(part, "qkv_proj")))
                if part == "q_proj":
                    return w[:q_rows]
                if part == "k_proj":
                    return w[q_rows : q_rows + kv_rows]
                return w[q_rows + kv_rows : q_rows + 2 * kv_rows]
        if ".mlp." in name and name.endswith("gate_proj.weight"):
            w = np.asarray(get(name.replace("gate_proj", "gate_up_proj")))
            return w[:I]
        if ".mlp." in name and name.endswith("up_proj.weight"):
            w = np.asarray(get(name.replace("up_proj", "gate_up_proj")))
            return w[I:]
        return get(name)

    return fused


def _safetensors_getter(model_dir: str) -> Callable[[str], np.ndarray]:
    """Key -> tensor across one or many ``*.safetensors`` shards."""
    from safetensors import safe_open

    index_path = os.path.join(model_dir, "model.safetensors.index.json")
    if os.path.exists(index_path):
        with open(index_path) as f:
            weight_map: dict[str, str] = json.load(f)["weight_map"]
    else:
        shards = sorted(
            f for f in os.listdir(model_dir) if f.endswith(".safetensors")
        )
        if not shards:
            raise FileNotFoundError(f"no .safetensors files in {model_dir}")
        weight_map = {}
        for shard in shards:
            with safe_open(os.path.join(model_dir, shard), framework="np") as f:
                for key in f.keys():
                    weight_map[key] = shard

    handles: dict[str, Any] = {}

    def get(name: str) -> np.ndarray:
        shard = weight_map[name]
        if shard not in handles:
            handles[shard] = safe_open(
                os.path.join(model_dir, shard), framework="np"
            )
        return handles[shard].get_tensor(name)

    get.has = weight_map.__contains__  # cheap layout probes, no tensor I/O
    return get


def load_hf_checkpoint(
    model_dir: str, dtype=None, **config_overrides
) -> tuple[LlamaConfig, dict]:
    """Load ``config.json`` + safetensors shards from a local HF model dir.

    ``dtype`` applies to BOTH the converted params and the returned config —
    the config's dtype drives KV-cache/activation dtypes downstream, and a
    float32 param tree against a bfloat16 cache is a dispatch-time error.

    Multimodal Gemma3 repos (Gemma3ForConditionalGeneration) are handled:
    the decoder config is unwrapped from ``text_config`` and tensor keys
    resolve under the ``language_model.`` prefix (vision-tower tensors are
    simply never requested)."""
    if dtype is not None:
        config_overrides.setdefault("dtype", dtype)
    with open(os.path.join(model_dir, "config.json")) as f:
        cfg = config_from_hf(json.load(f), **config_overrides)
    get = _safetensors_getter(model_dir)
    probe = "model.embed_tokens.weight"
    if not get.has(probe):
        mm = f"language_model.{probe}"
        if not get.has(mm):
            raise KeyError(
                f"neither {probe!r} nor {mm!r} found in {model_dir} — not a "
                "Llama/Qwen3/Gemma3 text or multimodal checkpoint layout"
            )
        inner = get

        def get(name: str, _inner=inner):  # noqa: F811
            return _inner(f"language_model.{name}")

        get.has = lambda name, _h=inner.has: _h(f"language_model.{name}")

    # Phi-3/Phi-4 fused-projection layout: probe and adapt
    if get.has("model.layers.0.self_attn.qkv_proj.weight"):
        get = _phi_fused_getter(get, cfg)

    params = convert_hf_state_dict(get, cfg, dtype)
    return cfg, params


def save_hf_checkpoint(
    params: dict,
    cfg: LlamaConfig,
    out_dir: str,
    shard_layers: int = 8,
) -> dict:
    """Export a stacked pytree back to HF Llama format (the exact inverse of
    :func:`load_hf_checkpoint`): ``config.json`` + sharded ``*.safetensors``
    + ``model.safetensors.index.json``.

    Round-tripping through this pair is how the 3B runbook artifact proves
    the converter at real scale without network access to the real weights
    (the reference simply downloads them, runners/run_summarization.py:54-62).
    Returns the index dict that was written."""
    import ml_dtypes
    from safetensors.numpy import save_file

    os.makedirs(out_dir, exist_ok=True)
    H, KV, hd, D = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.dim
    np_bf16 = ml_dtypes.bfloat16

    def to_np(x) -> np.ndarray:
        return np.asarray(x).astype(np_bf16)

    def deconv(ours: str, arr: np.ndarray) -> np.ndarray:
        # inverse of convert_hf_state_dict.conv: back to HF [out, in] layout
        if ours == "wq":
            return arr.reshape(D, H * hd).T
        if ours in ("wk", "wv"):
            return arr.reshape(D, KV * hd).T
        if ours == "wo":
            return arr.reshape(H * hd, D).T
        if ours in ("w_gate", "w_up", "w_down"):
            return arr.T
        return arr  # norms

    if cfg.sandwich_norms:
        arch, mtype = ["Gemma3ForCausalLM"], "gemma3_text"
    elif cfg.qk_norm:
        arch, mtype = ["Qwen3ForCausalLM"], "qwen3"
    else:
        arch, mtype = ["LlamaForCausalLM"], "llama"
    hf_cfg = {
        "architectures": arch,
        "model_type": mtype,
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.dim,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "head_dim": cfg.head_dim,
        "intermediate_size": cfg.intermediate,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.norm_eps,
        "max_position_embeddings": cfg.max_seq_len,
        "tie_word_embeddings": cfg.tie_embeddings,
        "torch_dtype": "bfloat16",
    }
    if cfg.use_llama3_rope_scaling:
        hf_cfg["rope_scaling"] = {
            "rope_type": "llama3",
            "factor": cfg.rope_scale_factor,
            "low_freq_factor": cfg.rope_low_freq_factor,
            "high_freq_factor": cfg.rope_high_freq_factor,
            "original_max_position_embeddings": cfg.rope_original_max_len,
        }
    elif cfg.rope_linear_factor:
        hf_cfg["rope_scaling"] = {
            "rope_type": "linear", "factor": cfg.rope_linear_factor,
        }
    if cfg.sandwich_norms:
        hf_cfg.update(
            hidden_activation="gelu_pytorch_tanh",
            query_pre_attn_scalar=cfg.query_scale or cfg.head_dim,
            sliding_window=cfg.sliding_window,
            layer_types=[
                "full_attention" if g else "sliding_attention"
                for g in (
                    cfg.layer_is_global or [True] * cfg.n_layers
                )
            ],
            rope_local_base_freq=cfg.rope_local_theta,
        )
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=2)

    ours_to_hf = {v: k for k, v in _layer_keys(cfg).items()}
    weight_map: dict[str, str] = {}
    shard_id, n_shards = 0, (cfg.n_layers + shard_layers - 1) // shard_layers
    n_shards += 1  # embeddings/norm shard
    total_bytes = 0

    def write_shard(tensors: dict[str, np.ndarray]) -> None:
        nonlocal shard_id, total_bytes
        name = f"model-{shard_id + 1:05d}-of-{n_shards:05d}.safetensors"
        # safetensors writes the raw buffer of ml_dtypes.bfloat16 arrays —
        # strides are IGNORED, so any transposed/F-order view would be
        # silently saved scrambled; force C-order explicitly
        tensors = {k: np.ascontiguousarray(v) for k, v in tensors.items()}
        save_file(tensors, os.path.join(out_dir, name))
        for k, v in tensors.items():
            weight_map[k] = name
            total_bytes += v.nbytes
        shard_id += 1

    # per-layer shards, materializing one layer group at a time so host RSS
    # stays ~shard-sized even for multi-GB checkpoints
    for start in range(0, cfg.n_layers, shard_layers):
        tensors = {}
        for li in range(start, min(start + shard_layers, cfg.n_layers)):
            for ours, stacked in params["layers"].items():
                tensors[f"model.layers.{li}.{ours_to_hf[ours]}"] = deconv(
                    ours, to_np(stacked[li])
                )
        write_shard(tensors)

    head = {
        "model.embed_tokens.weight": to_np(params["embed"]),
        "model.norm.weight": to_np(params["final_norm"]),
    }
    if not cfg.tie_embeddings:
        head["lm_head.weight"] = to_np(params["lm_head"]).T
    write_shard(head)

    index = {
        "metadata": {"total_size": total_bytes},
        "weight_map": weight_map,
    }
    with open(os.path.join(out_dir, "model.safetensors.index.json"), "w") as f:
        json.dump(index, f)
    return index


def convert_torch_model(model, cfg: LlamaConfig, dtype=None) -> dict:
    """Convert an in-memory HF ``LlamaForCausalLM`` (tests, small models)."""
    sd = {k: v.detach().cpu().float().numpy() for k, v in model.state_dict().items()}
    return convert_hf_state_dict(sd.__getitem__, cfg, dtype)
