from .llama import (
    LlamaConfig,
    forward,
    init_kv_cache,
    init_params,
    llama32_1b,
    llama32_3b,
    tiny_llama,
)
from .sampling import sample_logits

__all__ = [
    "LlamaConfig",
    "forward",
    "init_kv_cache",
    "init_params",
    "llama32_1b",
    "llama32_3b",
    "tiny_llama",
    "sample_logits",
]
