from .llama import (
    LlamaConfig,
    dequantize_cache_layer,
    forward,
    init_kv_cache,
    init_params,
    is_quantized_cache,
    gemma3_4b,
    llama32_1b,
    llama32_3b,
    phi4_14b,
    qwen3_0p6b,
    qwen3_8b,
    tiny_llama,
)
from .sampling import sample_logits


def jitted_init(init_fn, cfg, seed: int = 0):
    """Run a param-init function as ONE compiled program.

    Eager per-leaf dispatch through the device tunnel costs minutes for a
    3B tree (and seconds even for the tiny eval encoder); a jitted init is
    a single cacheable program. Shared by the generation engine, the
    long-context backend, and the evaluation embedder."""
    import functools

    import jax

    return jax.jit(functools.partial(init_fn, cfg=cfg))(jax.random.key(seed))

# model name -> config factory (names match the reference's Ollama tags where
# an equivalent open-weights architecture exists)
MODEL_REGISTRY = {
    "llama3.2:3b": llama32_3b,
    "llama3.2-3b": llama32_3b,
    "llama3.2:1b": llama32_1b,
    "llama3.2-1b": llama32_1b,
    "qwen3:8b": qwen3_8b,
    "qwen3-8b": qwen3_8b,
    "qwen3:0.6b": qwen3_0p6b,
    "qwen3-0.6b": qwen3_0p6b,
    "gemma3:4b": gemma3_4b,
    "gemma3-4b": gemma3_4b,
    "phi4:14b": phi4_14b,
    "phi4-14b": phi4_14b,
    "tiny": tiny_llama,
}

__all__ = [
    "jitted_init",
    "LlamaConfig",
    "forward",
    "init_kv_cache",
    "init_params",
    "gemma3_4b",
    "llama32_1b",
    "phi4_14b",
    "llama32_3b",
    "qwen3_0p6b",
    "qwen3_8b",
    "tiny_llama",
    "sample_logits",
]
