"""HF BERT-family checkpoint -> stacked JAX encoder pytree conversion.

The reference scores semantics with sentence-transformers all-MiniLM-L6-v2
and multilingual BERT (evaluate/evaluate_summaries_semantic.py:128-133,
:577-582) — both BERT-architecture encoders. This module converts any such
checkpoint (MiniLM, mBERT, PhoBERT-style BERT clones) into the stacked-layer
pytree of :mod:`vnsum_tpu.models.encoder`, the same way
:mod:`vnsum_tpu.models.convert` treats Llama: HF format is the interchange
format, converted once host-side, then living as JAX arrays on device.

Conversion notes:
- HF ``Linear.weight`` is ``[out, in]``; our layouts are ``[in, out]``, so
  every projection transposes.
- BERT's token_type (segment) embeddings: sentence encoders always run with
  ``token_type_ids=0``, so ``token_type_embeddings[0]`` is folded into the
  word-embedding table at conversion time — the runtime model has no segment
  input at all.
- Per-layer tensors stack on a leading ``L`` dim for the ``lax.scan`` body.
- State dicts may carry a ``bert.`` (or other encoder-attribute) prefix
  depending on which AutoModel class saved them; the prefix is detected.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Mapping

import numpy as np

from .encoder import EncoderConfig

# HF key templates (under encoder.layer.{i}.) -> our stacked-layer key
_LAYER_KEYS: dict[str, str] = {
    "attention.self.query.weight": "wq",
    "attention.self.query.bias": "bq",
    "attention.self.key.weight": "wk",
    "attention.self.key.bias": "bk",
    "attention.self.value.weight": "wv",
    "attention.self.value.bias": "bv",
    "attention.output.dense.weight": "wo",
    "attention.output.dense.bias": "bo",
    "attention.output.LayerNorm.weight": "attn_norm_w",
    "attention.output.LayerNorm.bias": "attn_norm_b",
    "intermediate.dense.weight": "w_up",
    "intermediate.dense.bias": "b_up",
    "output.dense.weight": "w_down",
    "output.dense.bias": "b_down",
    "output.LayerNorm.weight": "mlp_norm_w",
    "output.LayerNorm.bias": "mlp_norm_b",
}


def encoder_config_from_hf(hf: Mapping[str, Any], **overrides) -> EncoderConfig:
    """Build an :class:`EncoderConfig` from a parsed HF BERT ``config.json``."""
    kw: dict[str, Any] = dict(
        vocab_size=hf["vocab_size"],
        dim=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=hf["num_attention_heads"],
        intermediate=hf["intermediate_size"],
        max_len=hf.get("max_position_embeddings", 512),
        norm_eps=hf.get("layer_norm_eps", 1e-12),
    )
    kw.update(overrides)
    return EncoderConfig(**kw)


def _detect_prefix(has: Callable[[str], bool]) -> str:
    """Find the state-dict prefix in front of ``embeddings.*`` keys."""
    for prefix in ("", "bert.", "model.", "encoder."):
        if has(f"{prefix}embeddings.word_embeddings.weight"):
            return prefix
    raise KeyError(
        "could not find embeddings.word_embeddings.weight under any known "
        "prefix — is this a BERT-architecture checkpoint?"
    )


def convert_hf_encoder_state_dict(
    get: Callable[[str], np.ndarray],
    cfg: EncoderConfig,
    dtype=None,
    has: Callable[[str], bool] | None = None,
) -> dict:
    """Convert HF-named tensors into the stacked encoder pytree.

    ``get(name)`` returns one HF tensor; ``has(name)`` (optional) reports key
    existence for prefix detection — defaults to trying ``get``.
    """
    import jax.numpy as jnp

    dtype = dtype or cfg.dtype

    if has is None:
        def has(name: str) -> bool:  # noqa: F811 - intentional default
            try:
                get(name)
                return True
            except (KeyError, IndexError):
                return False

    prefix = _detect_prefix(has)

    def g(name: str) -> np.ndarray:
        return np.asarray(get(prefix + name))

    def conv(ours: str, arr: np.ndarray) -> np.ndarray:
        return arr.T if ours.startswith("w") else arr  # weights transpose

    layers: dict[str, list[np.ndarray]] = {k: [] for k in _LAYER_KEYS.values()}
    for li in range(cfg.n_layers):
        for hf_key, ours in _LAYER_KEYS.items():
            layers[ours].append(conv(ours, g(f"encoder.layer.{li}.{hf_key}")))

    # fold segment-0 embedding into the word table (see module docstring)
    tok_embed = g("embeddings.word_embeddings.weight")
    if has(prefix + "embeddings.token_type_embeddings.weight"):
        tok_embed = tok_embed + g("embeddings.token_type_embeddings.weight")[0]

    return {
        "tok_embed": jnp.asarray(tok_embed, dtype),
        "pos_embed": jnp.asarray(
            g("embeddings.position_embeddings.weight"), dtype
        ),
        "embed_norm": {
            "w": jnp.asarray(g("embeddings.LayerNorm.weight"), dtype),
            "b": jnp.asarray(g("embeddings.LayerNorm.bias"), dtype),
        },
        "layers": {
            k: jnp.asarray(np.stack(v), dtype) for k, v in layers.items()
        },
    }


def load_hf_encoder(
    model_dir: str, dtype=None, **config_overrides
) -> tuple[EncoderConfig, dict]:
    """Load ``config.json`` + safetensors shards from a local HF encoder dir
    (e.g. a saved all-MiniLM-L6-v2 or bert-base-multilingual-cased checkout)."""
    from .convert import _safetensors_getter

    with open(os.path.join(model_dir, "config.json")) as f:
        cfg = encoder_config_from_hf(json.load(f), **config_overrides)
    get = _safetensors_getter(model_dir)
    params = convert_hf_encoder_state_dict(get, cfg, dtype)
    return cfg, params


def convert_torch_encoder(model, cfg: EncoderConfig, dtype=None) -> dict:
    """Convert an in-memory HF ``BertModel`` (tests, small models)."""
    sd = {
        k: v.detach().cpu().float().numpy() for k, v in model.state_dict().items()
    }
    return convert_hf_encoder_state_dict(
        sd.__getitem__, cfg, dtype, has=sd.__contains__
    )
