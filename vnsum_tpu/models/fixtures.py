"""Real-format tiny HF Llama checkpoints for offline parity runs.

The quality-parity chain (safetensors → convert → TpuBackend(HF tokenizer)
→ strategy → ROUGE; reference quality gate
evaluation_results/first_dataset/mapreduce/llama3_2_3b_results.json) needs a
real HF checkpoint to exercise. Air-gapped hosts have no pretrained weights,
so this module builds one: a genuine ``transformers.LlamaForCausalLM``
saved via ``save_pretrained`` (config.json + model.safetensors) with a
genuine BPE tokenizer *trained on the target corpus* (tokenizer.json via the
``tokenizers`` library) — every file format identical to a hub checkpoint,
just small. ``train_steps > 0`` additionally fits the LM on the corpus
(torch CPU) so greedy decoding emits corpus-like Vietnamese instead of
random bytes.

For a real pretrained model (e.g. Llama-3.2-3B) none of this is needed:
point ``--weights-dir`` at its checkout (see pipeline.cli).
"""
from __future__ import annotations

from pathlib import Path
from typing import Iterable, Sequence

_BOS, _EOS, _PAD = "<|bos|>", "<|eos|>", "<|pad|>"


def train_bpe_tokenizer(corpus: Iterable[str], vocab_size: int = 1024):
    """Train a byte-level BPE tokenizer; returns PreTrainedTokenizerFast."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers
    from transformers import PreTrainedTokenizerFast

    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=True)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size,
        special_tokens=[_PAD, _BOS, _EOS],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        show_progress=False,
    )
    tok.train_from_iterator(corpus, trainer)
    return PreTrainedTokenizerFast(
        tokenizer_object=tok, bos_token=_BOS, eos_token=_EOS, pad_token=_PAD
    )


def make_tiny_hf_checkpoint(
    out_dir: str | Path,
    corpus: Sequence[str],
    vocab_size: int = 1024,
    dim: int = 128,
    n_layers: int = 2,
    n_heads: int = 4,
    n_kv_heads: int = 2,
    intermediate: int = 256,
    max_seq_len: int = 1024,
    seed: int = 0,
    train_steps: int = 0,
    train_seq_len: int = 128,
    train_batch: int = 16,
    lr: float = 3e-3,
) -> dict:
    """Build (and optionally train) a tiny HF Llama checkpoint at out_dir.

    Returns {"loss_first", "loss_last", "vocab_size"} for logging.
    """
    import torch
    import transformers

    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)

    hf_tok = train_bpe_tokenizer(corpus, vocab_size=vocab_size)
    vocab = len(hf_tok)

    torch.manual_seed(seed)
    cfg = transformers.LlamaConfig(
        vocab_size=vocab,
        hidden_size=dim,
        num_hidden_layers=n_layers,
        num_attention_heads=n_heads,
        num_key_value_heads=n_kv_heads,
        intermediate_size=intermediate,
        max_position_embeddings=max_seq_len,
        rms_norm_eps=1e-5,
        rope_theta=10_000.0,
        tie_word_embeddings=False,
        bos_token_id=hf_tok.bos_token_id,
        eos_token_id=hf_tok.eos_token_id,
        pad_token_id=hf_tok.pad_token_id,
    )
    model = transformers.LlamaForCausalLM(cfg)

    loss_first = loss_last = None
    if train_steps > 0:
        ids: list[int] = []
        for text in corpus:
            ids.extend(hf_tok.encode(text))
            ids.append(hf_tok.eos_token_id)
        n_windows = max(1, len(ids) // train_seq_len)
        data = torch.tensor(
            ids[: n_windows * train_seq_len], dtype=torch.long
        ).view(n_windows, train_seq_len)

        model.train()
        opt = torch.optim.AdamW(model.parameters(), lr=lr)
        gen = torch.Generator().manual_seed(seed)
        for step in range(train_steps):
            rows = torch.randint(
                0, data.shape[0], (min(train_batch, data.shape[0]),),
                generator=gen,
            )
            batch = data[rows]
            loss = model(input_ids=batch, labels=batch).loss
            opt.zero_grad()
            loss.backward()
            opt.step()
            if step == 0:
                loss_first = float(loss.detach())
            loss_last = float(loss.detach())
        model.eval()

    model.save_pretrained(out, safe_serialization=True)
    hf_tok.save_pretrained(out)
    return {
        "loss_first": loss_first,
        "loss_last": loss_last,
        "vocab_size": vocab,
    }


def train_wordpiece_tokenizer(corpus: Iterable[str], vocab_size: int = 2048):
    """Train a BERT-style WordPiece tokenizer; returns BertTokenizerFast
    semantics via PreTrainedTokenizerFast ([CLS]/[SEP]/[PAD]/[UNK]/[MASK])."""
    from tokenizers import Tokenizer, models, normalizers, pre_tokenizers, trainers
    from tokenizers.processors import TemplateProcessing
    from transformers import PreTrainedTokenizerFast

    specials = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    tok = Tokenizer(models.WordPiece(unk_token="[UNK]"))
    tok.normalizer = normalizers.NFC()
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    tok.train_from_iterator(
        corpus,
        trainers.WordPieceTrainer(
            vocab_size=vocab_size, special_tokens=specials, show_progress=False
        ),
    )
    cls_id, sep_id = tok.token_to_id("[CLS]"), tok.token_to_id("[SEP]")
    tok.post_processor = TemplateProcessing(
        single="[CLS] $A [SEP]",
        pair="[CLS] $A [SEP] $B [SEP]",
        special_tokens=[("[CLS]", cls_id), ("[SEP]", sep_id)],
    )
    return PreTrainedTokenizerFast(
        tokenizer_object=tok,
        pad_token="[PAD]", unk_token="[UNK]", cls_token="[CLS]",
        sep_token="[SEP]", mask_token="[MASK]",
    )


def make_tiny_hf_encoder_checkpoint(
    out_dir: str | Path,
    corpus: Sequence[str],
    vocab_size: int = 2048,
    dim: int = 64,
    n_layers: int = 2,
    n_heads: int = 4,
    intermediate: int = 128,
    max_len: int = 256,
    seed: int = 0,
) -> dict:
    """Build a tiny HF BERT checkpoint (config.json + model.safetensors +
    WordPiece tokenizer) at out_dir — the MiniLM/mBERT-shaped fixture for the
    embedding-metric parity chain (reference models:
    evaluate/evaluate_summaries_semantic.py:128-133, :577-582). For the real
    pretrained encoders, point EmbeddingModel.from_hf at their checkout."""
    import torch
    import transformers

    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)

    hf_tok = train_wordpiece_tokenizer(corpus, vocab_size=vocab_size)
    vocab = len(hf_tok)

    torch.manual_seed(seed)
    cfg = transformers.BertConfig(
        vocab_size=vocab,
        hidden_size=dim,
        num_hidden_layers=n_layers,
        num_attention_heads=n_heads,
        intermediate_size=intermediate,
        max_position_embeddings=max_len,
        pad_token_id=hf_tok.pad_token_id,
    )
    model = transformers.BertModel(cfg).eval()
    model.save_pretrained(out, safe_serialization=True)
    hf_tok.save_pretrained(out)
    return {"vocab_size": vocab}


# -- four-family trained fixtures (shared by parity tests and quality A/Bs) --

GEN_CORPUS = [
    "Quốc hội đã thông qua nghị quyết về phát triển kinh tế xã hội. "
    "Chính phủ sẽ triển khai các giải pháp trọng tâm trong năm nay.",
    "Tòa án nhân dân xét xử vụ án theo đúng quy định của pháp luật. "
    "Bản án được tuyên sau khi hội đồng nghị án.",
    "Nhà trường tổ chức kỳ thi tốt nghiệp cho học sinh khối mười hai. "
    "Kết quả sẽ được công bố trong tuần tới.",
] * 6

# family -> (HF model class name, HF config class name, config kwargs).
# One entry per reference model family (run_full_evaluation_pipeline.py:
# 960-962): Llama GQA, Qwen3 QK-norm, Gemma3 sandwich-norm + sliding
# interleave, Phi fused projections.
TRAINED_FAMILIES = {
    "llama": (
        "LlamaForCausalLM", "LlamaConfig",
        dict(hidden_size=64, intermediate_size=128, num_hidden_layers=2,
             num_attention_heads=4, num_key_value_heads=2, head_dim=16,
             max_position_embeddings=256, rope_theta=10000.0,
             rms_norm_eps=1e-5, tie_word_embeddings=True),
    ),
    "qwen3": (
        "Qwen3ForCausalLM", "Qwen3Config",
        dict(hidden_size=64, intermediate_size=128, num_hidden_layers=2,
             num_attention_heads=4, num_key_value_heads=2, head_dim=16,
             max_position_embeddings=256, rope_theta=10000.0,
             rms_norm_eps=1e-6, tie_word_embeddings=True),
    ),
    "gemma3": (
        "Gemma3ForCausalLM", "Gemma3TextConfig",
        dict(hidden_size=64, intermediate_size=128, num_hidden_layers=4,
             num_attention_heads=4, num_key_value_heads=2, head_dim=16,
             max_position_embeddings=256, rope_theta=10000.0,
             rope_local_base_freq=5000.0, rms_norm_eps=1e-6,
             tie_word_embeddings=True, query_pre_attn_scalar=32,
             sliding_window=8,
             layer_types=["sliding_attention", "sliding_attention",
                          "full_attention", "sliding_attention"]),
    ),
    "phi": (
        "Phi3ForCausalLM", "Phi3Config",
        dict(hidden_size=64, intermediate_size=128, num_hidden_layers=2,
             num_attention_heads=4, num_key_value_heads=2,
             max_position_embeddings=256, rope_theta=10000.0,
             rms_norm_eps=1e-5, tie_word_embeddings=False),
    ),
}

# overrides producing Pallas-kernel-compatible shapes (head_dim 128 is the
# lane-alignment gate, engine._decode_settings): the lossy-knob quality A/B
# (scripts/make_quality_lossy_ab.py) measures the PRODUCTION fast path —
# flash kernels + int8 KV — so its fixtures must be able to take it.
# Phi3Config derives head_dim = hidden/heads, so it omits the explicit key.
KERNEL_SHAPE_OVERRIDES = dict(
    hidden_size=256, intermediate_size=512, num_attention_heads=2,
    num_key_value_heads=1, head_dim=128,
)


def train_tiny_family(
    family: str,
    out_dir,
    steps: int = 40,
    overrides: dict | None = None,
    corpus: Sequence[str] | None = None,
):
    """Train a tiny HF model of ``family`` on ``corpus`` (torch CPU) and
    save_pretrained it with its BPE tokenizer. Returns (model, tokenizer).

    Lifted from the four-family string-parity test so artifact scripts can
    train the same checkpoints (VERDICT r4 #2: the lossy-knob quality A/B
    runs on these)."""
    import torch
    import transformers

    corpus = list(corpus) if corpus is not None else GEN_CORPUS
    model_name, cfg_name, kw = TRAINED_FAMILIES[family]
    if overrides:
        kw = dict(kw)
        kw.update(overrides)
        if cfg_name == "Phi3Config":
            kw.pop("head_dim", None)
    hf_tok = train_bpe_tokenizer(corpus, vocab_size=384)
    torch.manual_seed(0)
    cfg = getattr(transformers, cfg_name)(
        vocab_size=len(hf_tok),
        bos_token_id=hf_tok.bos_token_id,
        eos_token_id=hf_tok.eos_token_id,
        pad_token_id=hf_tok.pad_token_id,
        **kw,
    )
    model = getattr(transformers, model_name)(cfg)

    ids: list[int] = []
    for text in corpus:
        ids.extend(hf_tok.encode(text))
        ids.append(hf_tok.eos_token_id)
    seq = 64
    n = len(ids) // seq
    data = torch.tensor(ids[: n * seq], dtype=torch.long).view(n, seq)
    opt = torch.optim.AdamW(model.parameters(), lr=3e-3)
    gen = torch.Generator().manual_seed(0)
    model.train()
    for _ in range(steps):
        rows = torch.randint(0, n, (min(8, n),), generator=gen)
        batch = data[rows]
        loss = model(input_ids=batch, labels=batch).loss
        opt.zero_grad()
        loss.backward()
        opt.step()
    model.eval()
    model.save_pretrained(out_dir, safe_serialization=True)
    hf_tok.save_pretrained(out_dir)
    return model, hf_tok
