"""Real-format tiny HF Llama checkpoints for offline parity runs.

The quality-parity chain (safetensors → convert → TpuBackend(HF tokenizer)
→ strategy → ROUGE; reference quality gate
evaluation_results/first_dataset/mapreduce/llama3_2_3b_results.json) needs a
real HF checkpoint to exercise. Air-gapped hosts have no pretrained weights,
so this module builds one: a genuine ``transformers.LlamaForCausalLM``
saved via ``save_pretrained`` (config.json + model.safetensors) with a
genuine BPE tokenizer *trained on the target corpus* (tokenizer.json via the
``tokenizers`` library) — every file format identical to a hub checkpoint,
just small. ``train_steps > 0`` additionally fits the LM on the corpus
(torch CPU) so greedy decoding emits corpus-like Vietnamese instead of
random bytes.

For a real pretrained model (e.g. Llama-3.2-3B) none of this is needed:
point ``--weights-dir`` at its checkout (see pipeline.cli).
"""
from __future__ import annotations

from pathlib import Path
from typing import Iterable, Sequence

_BOS, _EOS, _PAD = "<|bos|>", "<|eos|>", "<|pad|>"


def train_bpe_tokenizer(corpus: Iterable[str], vocab_size: int = 1024):
    """Train a byte-level BPE tokenizer; returns PreTrainedTokenizerFast."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers
    from transformers import PreTrainedTokenizerFast

    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=True)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size,
        special_tokens=[_PAD, _BOS, _EOS],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        show_progress=False,
    )
    tok.train_from_iterator(corpus, trainer)
    return PreTrainedTokenizerFast(
        tokenizer_object=tok, bos_token=_BOS, eos_token=_EOS, pad_token=_PAD
    )


def make_tiny_hf_checkpoint(
    out_dir: str | Path,
    corpus: Sequence[str],
    vocab_size: int = 1024,
    dim: int = 128,
    n_layers: int = 2,
    n_heads: int = 4,
    n_kv_heads: int = 2,
    intermediate: int = 256,
    max_seq_len: int = 1024,
    seed: int = 0,
    train_steps: int = 0,
    train_seq_len: int = 128,
    train_batch: int = 16,
    lr: float = 3e-3,
) -> dict:
    """Build (and optionally train) a tiny HF Llama checkpoint at out_dir.

    Returns {"loss_first", "loss_last", "vocab_size"} for logging.
    """
    import torch
    import transformers

    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)

    hf_tok = train_bpe_tokenizer(corpus, vocab_size=vocab_size)
    vocab = len(hf_tok)

    torch.manual_seed(seed)
    cfg = transformers.LlamaConfig(
        vocab_size=vocab,
        hidden_size=dim,
        num_hidden_layers=n_layers,
        num_attention_heads=n_heads,
        num_key_value_heads=n_kv_heads,
        intermediate_size=intermediate,
        max_position_embeddings=max_seq_len,
        rms_norm_eps=1e-5,
        rope_theta=10_000.0,
        tie_word_embeddings=False,
        bos_token_id=hf_tok.bos_token_id,
        eos_token_id=hf_tok.eos_token_id,
        pad_token_id=hf_tok.pad_token_id,
    )
    model = transformers.LlamaForCausalLM(cfg)

    loss_first = loss_last = None
    if train_steps > 0:
        ids: list[int] = []
        for text in corpus:
            ids.extend(hf_tok.encode(text))
            ids.append(hf_tok.eos_token_id)
        n_windows = max(1, len(ids) // train_seq_len)
        data = torch.tensor(
            ids[: n_windows * train_seq_len], dtype=torch.long
        ).view(n_windows, train_seq_len)

        model.train()
        opt = torch.optim.AdamW(model.parameters(), lr=lr)
        gen = torch.Generator().manual_seed(seed)
        for step in range(train_steps):
            rows = torch.randint(
                0, data.shape[0], (min(train_batch, data.shape[0]),),
                generator=gen,
            )
            batch = data[rows]
            loss = model(input_ids=batch, labels=batch).loss
            opt.zero_grad()
            loss.backward()
            opt.step()
            if step == 0:
                loss_first = float(loss.detach())
            loss_last = float(loss.detach())
        model.eval()

    model.save_pretrained(out, safe_serialization=True)
    hf_tok.save_pretrained(out)
    return {
        "loss_first": loss_first,
        "loss_last": loss_last,
        "vocab_size": vocab,
    }


def train_wordpiece_tokenizer(corpus: Iterable[str], vocab_size: int = 2048):
    """Train a BERT-style WordPiece tokenizer; returns BertTokenizerFast
    semantics via PreTrainedTokenizerFast ([CLS]/[SEP]/[PAD]/[UNK]/[MASK])."""
    from tokenizers import Tokenizer, models, normalizers, pre_tokenizers, trainers
    from tokenizers.processors import TemplateProcessing
    from transformers import PreTrainedTokenizerFast

    specials = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    tok = Tokenizer(models.WordPiece(unk_token="[UNK]"))
    tok.normalizer = normalizers.NFC()
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    tok.train_from_iterator(
        corpus,
        trainers.WordPieceTrainer(
            vocab_size=vocab_size, special_tokens=specials, show_progress=False
        ),
    )
    cls_id, sep_id = tok.token_to_id("[CLS]"), tok.token_to_id("[SEP]")
    tok.post_processor = TemplateProcessing(
        single="[CLS] $A [SEP]",
        pair="[CLS] $A [SEP] $B [SEP]",
        special_tokens=[("[CLS]", cls_id), ("[SEP]", sep_id)],
    )
    return PreTrainedTokenizerFast(
        tokenizer_object=tok,
        pad_token="[PAD]", unk_token="[UNK]", cls_token="[CLS]",
        sep_token="[SEP]", mask_token="[MASK]",
    )


def make_tiny_hf_encoder_checkpoint(
    out_dir: str | Path,
    corpus: Sequence[str],
    vocab_size: int = 2048,
    dim: int = 64,
    n_layers: int = 2,
    n_heads: int = 4,
    intermediate: int = 128,
    max_len: int = 256,
    seed: int = 0,
) -> dict:
    """Build a tiny HF BERT checkpoint (config.json + model.safetensors +
    WordPiece tokenizer) at out_dir — the MiniLM/mBERT-shaped fixture for the
    embedding-metric parity chain (reference models:
    evaluate/evaluate_summaries_semantic.py:128-133, :577-582). For the real
    pretrained encoders, point EmbeddingModel.from_hf at their checkout."""
    import torch
    import transformers

    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)

    hf_tok = train_wordpiece_tokenizer(corpus, vocab_size=vocab_size)
    vocab = len(hf_tok)

    torch.manual_seed(seed)
    cfg = transformers.BertConfig(
        vocab_size=vocab,
        hidden_size=dim,
        num_hidden_layers=n_layers,
        num_attention_heads=n_heads,
        intermediate_size=intermediate,
        max_position_embeddings=max_len,
        pad_token_id=hf_tok.pad_token_id,
    )
    model = transformers.BertModel(cfg).eval()
    model.save_pretrained(out, safe_serialization=True)
    hf_tok.save_pretrained(out)
    return {"vocab_size": vocab}
