"""Token sampling under jit: greedy, temperature, top-k, top-p.

Greedy matches the reference's do_sample=False baseline
(runners/run_summarization.py:44); Ollama's default sampling is approximated
by temperature/top-k/top-p knobs (GenerationConfig).

Also home to the speculative-decoding acceptance rule
(:func:`draft_acceptance_rows`): the verify step (backend/engine.py spec
path) scores k+1 positions in one forward and this module decides, per row,
how many drafted tokens the model keeps — exact argmax matching for greedy
(bit-identical to plain decode by construction), rejection-style acceptance
against the filtered distribution for temperature sampling (the drafter is
a deterministic point-mass proposal, so accept-with-prob-p / resample-from-
residual is the lossless scheme of arXiv:2304.04487 §2.2).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def filter_logits(
    logits: jax.Array,      # [..., V] float32
    temperature: float,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """Temperature-scale then apply top-k / top-p cutoffs (blocked ids get
    float32 min). ONE copy of the filtering algebra shared by sample_logits
    and the speculative acceptance rule — the two must agree on what
    distribution "the model would sample from" means. Caller guarantees
    temperature > 0."""
    logits = logits / jnp.float32(temperature)

    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, jnp.finfo(jnp.float32).min, logits)

    if top_p < 1.0:
        sorted_logits = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob > top_p; keep at least one token
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        cutoff = jnp.take_along_axis(
            sorted_logits, cutoff_idx[..., None], axis=-1
        )
        logits = jnp.where(logits < cutoff, jnp.finfo(jnp.float32).min, logits)

    return logits


def sample_logits(
    logits: jax.Array,      # [B, V] float32
    key: jax.Array,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """Returns sampled token ids [B]. temperature==0 -> argmax (greedy)."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = filter_logits(logits, temperature, top_k, top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def sample_logits_rows(
    logits: jax.Array,      # [B, V] float32
    keys: jax.Array,        # [B] PRNG keys, one per row
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """Per-row-keyed sampling: row i draws only from keys[i], so a row's
    sampled stream is invariant to its position in the batch. This is what
    lets the continuous scheduler compact a sampled batch mid-decode without
    changing any surviving row's output (engine.py derives keys[i] from
    (seed, row_uid, step) — counter-based, like per-request generators in
    continuous-batching servers)."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.vmap(
        lambda l, k: sample_logits(l[None], k, temperature, top_k, top_p)[0]
    )(logits, keys)


def draft_acceptance_rows(
    logits: jax.Array,      # [B, K+1, V] float32 — verify-step logits
    drafts: jax.Array,      # [B, K] int32 — proposed continuation tokens
    n_draft: jax.Array,     # [B] int32 — how many of drafts are real
    keys: jax.Array,        # [B, K+1] PRNG keys (ignored for greedy)
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> tuple[jax.Array, jax.Array]:
    """Decide per row how many drafted tokens survive verification.

    Position i's logits are conditioned on the current token plus drafts
    d_1..d_i, so logits[:, i] IS the model's next-token distribution after
    accepting i drafts. Returns ``(m [B], next_token [B])``: the row keeps
    drafts d_1..d_m and ``next_token`` is the model's own token after them —
    always well-defined, so every verify step retires at least one token.

    Greedy: accept while argmax(logits[:, i-1]) == d_i (exact prefix match
    — the spec stream is provably identical to plain greedy decode).
    Sampled: accept d_i with probability p_i-1(d_i) under the filtered
    distribution; on rejection sample from the residual (p with the
    rejected draft masked out, renormalized — exact for a point-mass
    proposal); when every draft survives, sample position m freely."""
    K = drafts.shape[1]
    real = jnp.arange(K)[None, :] < n_draft[:, None]          # [B, K]

    if temperature <= 0.0:
        g = jnp.argmax(logits, axis=-1).astype(jnp.int32)     # [B, K+1]
        ok = (g[:, :K] == drafts) & real
        m = jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(axis=1)
        nxt = jnp.take_along_axis(g, m[:, None], axis=1)[:, 0]
        return m.astype(jnp.int32), nxt

    f = filter_logits(logits, temperature, top_k, top_p)      # [B, K+1, V]
    probs = jax.nn.softmax(f, axis=-1)
    p_draft = jnp.take_along_axis(
        probs[:, :K], drafts[..., None], axis=-1
    )[..., 0]                                                 # [B, K]
    u = jax.vmap(jax.vmap(lambda k: jax.random.uniform(jax.random.fold_in(k, 0))))(
        keys[:, :K]
    )
    ok = (u < p_draft) & real
    m = jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(axis=1).astype(jnp.int32)

    # candidate "next" tokens at EVERY position, gathered at m afterwards:
    # free sample (used when all real drafts survived) and residual sample
    # (used at the rejection point — the rejected draft is excluded)
    free = jax.vmap(
        jax.vmap(
            lambda l, k: jax.random.categorical(jax.random.fold_in(k, 2), l)
        )
    )(f, keys).astype(jnp.int32)                              # [B, K+1]
    neg = jnp.finfo(jnp.float32).min
    f_resid = jnp.where(
        jax.nn.one_hot(drafts, f.shape[-1], dtype=bool), neg, f[:, :K]
    )
    resid = jax.vmap(
        jax.vmap(
            lambda l, k: jax.random.categorical(jax.random.fold_in(k, 1), l)
        )
    )(f_resid, keys[:, :K]).astype(jnp.int32)                 # [B, K]
    resid = jnp.concatenate([resid, free[:, -1:]], axis=1)    # pad pos K
    rejected = m < n_draft  # m == n_draft means the chain never broke
    nxt = jnp.where(
        rejected,
        jnp.take_along_axis(resid, m[:, None], axis=1)[:, 0],
        jnp.take_along_axis(free, m[:, None], axis=1)[:, 0],
    )
    return m, nxt
