"""Token sampling under jit: greedy, temperature, top-k, top-p.

Greedy matches the reference's do_sample=False baseline
(runners/run_summarization.py:44); Ollama's default sampling is approximated
by temperature/top-k/top-p knobs (GenerationConfig).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_logits(
    logits: jax.Array,      # [B, V] float32
    key: jax.Array,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """Returns sampled token ids [B]. temperature==0 -> argmax (greedy)."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    logits = logits / jnp.float32(temperature)

    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, jnp.finfo(jnp.float32).min, logits)

    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob > top_p; keep at least one token
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff, jnp.finfo(jnp.float32).min, logits)

    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def sample_logits_rows(
    logits: jax.Array,      # [B, V] float32
    keys: jax.Array,        # [B] PRNG keys, one per row
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """Per-row-keyed sampling: row i draws only from keys[i], so a row's
    sampled stream is invariant to its position in the batch. This is what
    lets the continuous scheduler compact a sampled batch mid-decode without
    changing any surviving row's output (engine.py derives keys[i] from
    (seed, row_uid, step) — counter-based, like per-request generators in
    continuous-batching servers)."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.vmap(
        lambda l, k: sample_logits(l[None], k, temperature, top_k, top_p)[0]
    )(logits, keys)
