"""Bidirectional transformer encoder in functional JAX.

Fills the slot of the reference's sentence-transformers MiniLM + multilingual
BERT (evaluate/evaluate_summaries_semantic.py:128-133, :150-166): one encoder
architecture serves both the sentence-embedding cosine metric (mean pooling)
and the BERTScore token-embedding pass — batched on device instead of
per-pair host encodes (the reference re-encodes every pair serially, :561-575).

Same stacked-layer + lax.scan design as models.llama; weights random-init by
default (metrics are then self-consistent rather than pretrained-calibrated)
or converted from a HF BERT-family checkpoint via models.convert_encoder
(token_type embeddings folded into tok_embed, post-LN residuals, biased
projections — exact architecture match, parity-tested vs transformers).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class EncoderConfig:
    vocab_size: int = 384
    dim: int = 256
    n_layers: int = 4
    n_heads: int = 4
    intermediate: int = 1024
    max_len: int = 512
    norm_eps: float = 1e-12
    dtype: Any = field(default=jnp.float32)

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


def minilm_like(**kw) -> EncoderConfig:
    """Shape-compatible with all-MiniLM-L6-v2 (6 layers, 384 dim)."""
    base = dict(dim=384, n_layers=6, n_heads=12, intermediate=1536)
    base.update(kw)
    return EncoderConfig(**base)


def tiny_encoder(**kw) -> EncoderConfig:
    base = dict(dim=64, n_layers=2, n_heads=4, intermediate=128, max_len=128)
    base.update(kw)
    return EncoderConfig(**base)


def init_encoder_params(key: jax.Array, cfg: EncoderConfig) -> dict:
    L, D, I = cfg.n_layers, cfg.dim, cfg.intermediate
    ks = iter(jax.random.split(key, 12))

    def norm(shape, k, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(cfg.dtype)

    return {
        "tok_embed": norm((cfg.vocab_size, D), next(ks)),
        "pos_embed": norm((cfg.max_len, D), next(ks)),
        "embed_norm": {"w": jnp.ones((D,), cfg.dtype), "b": jnp.zeros((D,), cfg.dtype)},
        "layers": {
            "wq": norm((L, D, D), next(ks)),
            "bq": jnp.zeros((L, D), cfg.dtype),
            "wk": norm((L, D, D), next(ks)),
            "bk": jnp.zeros((L, D), cfg.dtype),
            "wv": norm((L, D, D), next(ks)),
            "bv": jnp.zeros((L, D), cfg.dtype),
            "wo": norm((L, D, D), next(ks)),
            "bo": jnp.zeros((L, D), cfg.dtype),
            "attn_norm_w": jnp.ones((L, D), cfg.dtype),
            "attn_norm_b": jnp.zeros((L, D), cfg.dtype),
            "w_up": norm((L, D, I), next(ks)),
            "b_up": jnp.zeros((L, I), cfg.dtype),
            "w_down": norm((L, I, D), next(ks)),
            "b_down": jnp.zeros((L, D), cfg.dtype),
            "mlp_norm_w": jnp.ones((L, D), cfg.dtype),
            "mlp_norm_b": jnp.zeros((L, D), cfg.dtype),
        },
    }


def _layernorm(x, w, b, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w + b


def encode(
    params: dict, cfg: EncoderConfig, tokens: jax.Array, mask: jax.Array
) -> jax.Array:
    """tokens [B, S] int32, mask [B, S] bool -> token embeddings [B, S, D]."""
    B, S = tokens.shape
    x = jnp.take(params["tok_embed"], tokens, axis=0)
    x = x + params["pos_embed"][None, :S]
    x = _layernorm(x, params["embed_norm"]["w"], params["embed_norm"]["b"], cfg.norm_eps)

    attn_mask = mask[:, None, None, :]  # [B, 1, 1, S] keys
    H, hd = cfg.n_heads, cfg.head_dim

    def layer_step(x, lp):
        q = (x @ lp["wq"] + lp["bq"]).reshape(B, S, H, hd)
        k = (x @ lp["wk"] + lp["bk"]).reshape(B, S, H, hd)
        v = (x @ lp["wv"] + lp["bv"]).reshape(B, S, H, hd)
        scores = jnp.einsum("bshd,bthd->bhst", q, k, preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(hd))
        scores = jnp.where(attn_mask, scores, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        attn = jnp.einsum("bhst,bthd->bshd", probs, v).reshape(B, S, cfg.dim)
        x = _layernorm(
            x + attn @ lp["wo"] + lp["bo"],
            lp["attn_norm_w"],
            lp["attn_norm_b"],
            cfg.norm_eps,
        )
        h = jax.nn.gelu(x @ lp["w_up"] + lp["b_up"])
        x = _layernorm(
            x + h @ lp["w_down"] + lp["b_down"],
            lp["mlp_norm_w"],
            lp["mlp_norm_b"],
            cfg.norm_eps,
        )
        return x, None

    x, _ = jax.lax.scan(layer_step, x, params["layers"])
    return x


def mean_pool(token_embs: jax.Array, mask: jax.Array) -> jax.Array:
    """Masked mean pooling + L2 normalize -> sentence embeddings [B, D]."""
    m = mask[..., None].astype(token_embs.dtype)
    summed = jnp.sum(token_embs * m, axis=1)
    counts = jnp.maximum(jnp.sum(m, axis=1), 1.0)
    pooled = summed / counts
    norm = jnp.linalg.norm(pooled, axis=-1, keepdims=True)
    return pooled / jnp.maximum(norm, 1e-9)
