"""Weight-only int8 quantization for decode throughput.

Single-token decode on a 3B model is HBM-bandwidth-bound: every step streams
the full weight set. Storing matmul weights as int8 with per-output-channel
float scales halves that traffic. The matmul runs on the raw int8 values
(converted to the activation dtype on the way into the MXU — a fusion XLA
always does) and the scale is applied to the matmul OUTPUT, which is exactly
equivalent because each scale multiplies only channels that never mix in the
contraction:

- ``wq/wk/wv [L, D, H, hd]``  (contract d)      -> scale ``[L, H, hd]``
- ``wo [L, H, hd, D]``        (contract h, k)   -> scale ``[L, D]``
- ``w_gate/w_up [L, D, I]``   (contract d)      -> scale ``[L, I]``
- ``w_down [L, I, D]``        (contract i)      -> scale ``[L, D]``
- ``embed [V, D]``            row-wise          -> scale ``[V]`` (works for
  both the gather and the tied LM head, whose output channel IS the row)
- ``lm_head [D, V]``          (contract d)      -> scale ``[V]``

Norm weights stay in full precision (tiny, and numerically sensitive).

The reference has no quantization support at all — its nearest analog is
running 4-bit Ollama builds like ``gemma3:4b-it-qat``
(run_full_evaluation_pipeline.py:960-962) as a black box.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

# weight name -> axes that are CONTRACTED in its matmul (reduced over for the
# scale max) ; remaining axes are output channels and keep per-channel scales
_CONTRACT_AXES = {
    "wq": (0,), "wk": (0,), "wv": (0,),   # [D, H, hd] contract D
    "wo": (0, 1),                          # [H, hd, D] contract H, hd
    "w_gate": (0,), "w_up": (0,),          # [D, I] contract D
    "w_down": (0,),                        # [I, D] contract I
}


def _quantize(w: jax.Array, contract_axes: tuple[int, ...]) -> dict:
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=contract_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": jnp.squeeze(scale, axis=contract_axes)}


def quantize_params(params: dict) -> dict:
    """Params pytree -> same tree with matmul weights as {"q": int8, "s": f32}.

    Layer weights have a leading stacked L dim, so their contract axes shift
    by one; the scale keeps the L dim for the layer scan.
    """
    layers = {}
    for name, w in params["layers"].items():
        if name in _CONTRACT_AXES:
            axes = tuple(a + 1 for a in _CONTRACT_AXES[name])
            layers[name] = _quantize(w, axes)
        else:  # norms
            layers[name] = w

    out = {
        "embed": _quantize(params["embed"], (1,)),  # row max -> scale [V]
        "layers": layers,
        "final_norm": params["final_norm"],
    }
    if "lm_head" in params:
        out["lm_head"] = _quantize(params["lm_head"], (0,))  # scale [V]
    return out


def init_params_quantized(key: jax.Array, cfg) -> dict:
    """Random-init a params tree DIRECTLY in quantize_params' int8 layout.

    The usual path (bf16 init, then on-device quantize) keeps both trees
    resident — 3x the int8 bytes — which can never fit phi4:14b (~14.2 GB
    int8) on one 16 GB chip. This builds the int8 tree without a bf16 one
    ever existing: random int8 weights with a constant ~1/(sqrt(fan_in)*127)
    scale, so dequantized magnitudes sit in the usual init range. Shapes
    come from jax.eval_shape over init_params — the two layouts cannot
    drift. Perf-sweep tool (real memory/compute shape, untrained values);
    jit via models.jitted_init like init_params.
    """
    from .llama import init_params

    shapes = jax.eval_shape(lambda k: init_params(k, cfg), key)
    n_leaves = len(jax.tree.leaves(shapes, is_leaf=lambda x: x is None))
    keys = iter(jax.random.split(key, max(n_leaves, 8)))

    def qinit(k, spec, contract_axes):
        q = jax.random.randint(k, spec.shape, -127, 128, dtype=jnp.int8)
        fan = 1
        for a in contract_axes:
            fan *= spec.shape[a]
        s_shape = tuple(
            d for i, d in enumerate(spec.shape) if i not in contract_axes
        )
        s = jnp.full(s_shape, (fan ** -0.5) / 127.0, jnp.float32)
        return {"q": q, "s": s}

    layers = {}
    for name, spec in shapes["layers"].items():
        if name in _CONTRACT_AXES:
            axes = tuple(a + 1 for a in _CONTRACT_AXES[name])
            layers[name] = qinit(next(keys), spec, axes)
        else:  # norm vectors
            layers[name] = jnp.ones(spec.shape, spec.dtype)
    out = {
        "embed": qinit(next(keys), shapes["embed"], (1,)),
        "layers": layers,
        "final_norm": jnp.ones(
            shapes["final_norm"].shape, shapes["final_norm"].dtype
        ),
    }
    if "lm_head" in shapes:
        out["lm_head"] = qinit(next(keys), shapes["lm_head"], (0,))
    return out


def dequantize_params(qparams: dict) -> dict:
    """Inverse transform (tests / round-trip checks)."""

    def deq(leaf, contract_axes):
        s = leaf["s"]
        for a in sorted(contract_axes):
            s = jnp.expand_dims(s, a)
        return leaf["q"].astype(jnp.float32) * s

    layers = {}
    for name, w in qparams["layers"].items():
        if name in _CONTRACT_AXES:
            axes = tuple(a + 1 for a in _CONTRACT_AXES[name])
            layers[name] = deq(w, axes)
        else:
            layers[name] = w
    out = {
        "embed": deq(qparams["embed"], (1,)),
        "layers": layers,
        "final_norm": qparams["final_norm"],
    }
    if "lm_head" in qparams:
        out["lm_head"] = deq(qparams["lm_head"], (0,))
    return out


def is_quantized(params: dict) -> bool:
    return isinstance(params.get("embed"), dict)
