"""Llama-3.2 family in functional JAX, designed for the MXU.

This fills the architectural slot of the reference's LLM execution layer (the
OllamaLLM HTTP wrapper, runners/run_summarization_ollama_mapreduce.py:23-60,
and the torch path in runners/run_summarization.py:54-62) with an on-device
implementation:

- params are a plain pytree with a stacked leading layer dim, so the decoder
  runs as one `lax.scan` over layers (fast XLA compiles, clean TP shardings);
- GQA attention with RoPE (llama3 frequency scaling), RMSNorm, SwiGLU;
- a preallocated KV cache written with `lax.dynamic_update_slice` so prefill
  and single-token decode share one code path and static shapes;
- bfloat16 storage/matmuls with float32 softmax and norms.

No HF/torch code is used on the compute path; weights can be randomly
initialized (benchmarks, tests) or converted from safetensors offline.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    dim: int = 3072
    n_layers: int = 28
    n_heads: int = 24
    n_kv_heads: int = 8
    head_dim: int = 128
    intermediate: int = 8192
    rope_theta: float = 500_000.0
    use_llama3_rope_scaling: bool = True
    rope_scale_factor: float = 32.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_len: int = 8192
    norm_eps: float = 1e-5
    max_seq_len: int = 16_384
    tie_embeddings: bool = True
    # Qwen3-style per-head RMSNorm on Q/K before RoPE — the one structural
    # delta between the Llama and Qwen3 decoder stacks; everything else
    # (GQA, SwiGLU, pre-norm residuals) is shared, so both families run
    # through this module (reference sweeps qwen3:8b alongside llama3.2:3b,
    # run_full_evaluation_pipeline.py:960-962)
    qk_norm: bool = False
    # --- Gemma3 deltas (reference sweeps gemma3:4b) — all default-off so
    # the Llama/Qwen traces are unchanged ---
    act: str = "silu"              # "silu" | "gelu_tanh" (GeGLU)
    sandwich_norms: bool = False   # post-attention + pre/post-FFW norms
    norm_plus_one: bool = False    # RMSNorm scale is (1 + w), zero-init w
    embed_scale: bool = False      # hidden states scaled by sqrt(dim)
    query_scale: float = 0.0       # 0 => 1/sqrt(head_dim); else 1/sqrt(this)
    sliding_window: int = 0        # 0 => every layer attends globally
    # per-layer attention kind when sliding_window > 0: True = global.
    # Gemma3 interleaves 5 sliding : 1 global
    layer_is_global: tuple = ()
    rope_local_theta: float = 10_000.0  # RoPE base for sliding layers
    rope_linear_factor: float = 0.0     # linear position scaling (Gemma3 global)
    # W8A8 prefill: dynamically int8-quantize ACTIVATIONS (per-token absmax)
    # into the int8-weight matmuls during multi-token forwards, hitting the
    # MXU's double-rate s8xs8 path (measured 132.7 vs 83.1 TFLOP/s on v5e).
    # LOSSY (~1/127 relative rounding per matmul input) and opt-in; decode
    # (single-token) keeps the exact mixed path — it is HBM-bound, not
    # MXU-bound. Requires int8-quantized weights to do anything.
    w8a8_prefill: bool = False
    dtype: Any = field(default=jnp.bfloat16)

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads


def llama32_3b(**kw) -> LlamaConfig:
    return LlamaConfig(**kw)


def llama32_1b(**kw) -> LlamaConfig:
    base = dict(
        dim=2048, n_layers=16, n_heads=32, n_kv_heads=8, head_dim=64,
        intermediate=8192,
    )
    base.update(kw)
    return LlamaConfig(**base)


def qwen3_8b(**kw) -> LlamaConfig:
    base = dict(
        vocab_size=151_936, dim=4096, n_layers=36, n_heads=32, n_kv_heads=8,
        head_dim=128, intermediate=12_288, rope_theta=1_000_000.0,
        use_llama3_rope_scaling=False, norm_eps=1e-6, max_seq_len=32_768,
        tie_embeddings=False, qk_norm=True,
    )
    base.update(kw)
    return LlamaConfig(**base)


def qwen3_0p6b(**kw) -> LlamaConfig:
    base = dict(
        vocab_size=151_936, dim=1024, n_layers=28, n_heads=16, n_kv_heads=8,
        head_dim=128, intermediate=3072, rope_theta=1_000_000.0,
        use_llama3_rope_scaling=False, norm_eps=1e-6, max_seq_len=32_768,
        tie_embeddings=True, qk_norm=True,
    )
    base.update(kw)
    return LlamaConfig(**base)


def gemma3_4b(**kw) -> LlamaConfig:
    """Gemma3-4B text decoder (reference model family #3,
    run_full_evaluation_pipeline.py:960-962 `gemma3:4b`)."""
    n_layers = 34
    base = dict(
        vocab_size=262_208, dim=2560, n_layers=n_layers, n_heads=8,
        n_kv_heads=4, head_dim=256, intermediate=10_240,
        rope_theta=1_000_000.0, use_llama3_rope_scaling=False,
        rope_linear_factor=8.0, norm_eps=1e-6, max_seq_len=32_768,
        tie_embeddings=True, qk_norm=True, act="gelu_tanh",
        sandwich_norms=True, norm_plus_one=True, embed_scale=True,
        query_scale=256.0, sliding_window=1024,
        layer_is_global=tuple((i + 1) % 6 == 0 for i in range(n_layers)),
        rope_local_theta=10_000.0,
    )
    base.update(kw)
    return LlamaConfig(**base)


def phi4_14b(**kw) -> LlamaConfig:
    """Phi-4 decoder (reference model family #4, `phi4:14b`): Llama math
    with fused-projection checkpoints (models.convert._phi_fused_getter)."""
    base = dict(
        vocab_size=100_352, dim=5120, n_layers=40, n_heads=40,
        n_kv_heads=10, head_dim=128, intermediate=17_920,
        rope_theta=250_000.0, use_llama3_rope_scaling=False,
        norm_eps=1e-5, max_seq_len=16_384, tie_embeddings=False,
    )
    base.update(kw)
    return LlamaConfig(**base)


def tiny_llama(**kw) -> LlamaConfig:
    """Small config for hermetic CPU tests."""
    base = dict(
        vocab_size=384, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        head_dim=16, intermediate=128, max_seq_len=256,
        use_llama3_rope_scaling=False, rope_theta=10_000.0,
        dtype=jnp.float32,
    )
    base.update(kw)
    return LlamaConfig(**base)


# -- parameters -------------------------------------------------------------


def init_params(key: jax.Array, cfg: LlamaConfig) -> dict:
    """Random init; layer weights are stacked on a leading L dim."""
    L, D, H, KV, hd, I = (
        cfg.n_layers, cfg.dim, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim,
        cfg.intermediate,
    )
    keys = iter(jax.random.split(key, 16))

    def norm(shape, k, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(cfg.dtype)

    # plus-one norms (Gemma) are zero-centered: w=0 means identity scale
    norm_init = jnp.zeros if cfg.norm_plus_one else jnp.ones
    params = {
        "embed": norm((cfg.vocab_size, D), next(keys)),
        "layers": {
            "attn_norm": norm_init((L, D), cfg.dtype),
            "wq": norm((L, D, H, hd), next(keys)),
            "wk": norm((L, D, KV, hd), next(keys)),
            "wv": norm((L, D, KV, hd), next(keys)),
            "wo": norm((L, H, hd, D), next(keys)),
            "mlp_norm": norm_init((L, D), cfg.dtype),
            "w_gate": norm((L, D, I), next(keys)),
            "w_up": norm((L, D, I), next(keys)),
            "w_down": norm((L, I, D), next(keys)),
        },
        "final_norm": norm_init((D,), cfg.dtype),
    }
    if cfg.qk_norm:
        params["layers"]["q_norm"] = norm_init((L, hd), cfg.dtype)
        params["layers"]["k_norm"] = norm_init((L, hd), cfg.dtype)
    if cfg.sandwich_norms:
        params["layers"]["post_attn_norm"] = norm_init((L, D), cfg.dtype)
        params["layers"]["post_ffw_norm"] = norm_init((L, D), cfg.dtype)
    if not cfg.tie_embeddings:
        params["lm_head"] = norm((D, cfg.vocab_size), next(keys))
    return params


def init_kv_cache(
    cfg: LlamaConfig, batch: int, cache_len: int, *, quantized: bool = False
) -> dict:
    """Stacked cache [L, B, KV, C, hd] — KV heads BEFORE the sequence dim.

    This is the layout the attention einsums consume directly ((b, kv) as
    batch dims, hd/c as the minor contraction dims). With the sequence dim
    ahead of the heads, XLA inserts whole-cache layout-conversion copies plus
    per-layer extraction copies inside the decode loop — measured ~19 GB of
    pure copy traffic per decode step on a 48×1088 cache, 3× the mandatory
    weight+cache reads.

    ``quantized=True`` stores K/V as int8 with per-(token, head) float32
    scales ``ks``/``vs`` [L, B, KV, C] — decode attention streams the whole
    cache every step, so this halves its HBM traffic (decode attention is
    the largest decode-phase cost once weights are int8)."""
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, cache_len, cfg.head_dim)
    if not quantized:
        return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}
    return {
        "k": jnp.zeros(shape, jnp.int8),
        "v": jnp.zeros(shape, jnp.int8),
        "ks": jnp.zeros(shape[:-1], jnp.float32),
        "vs": jnp.zeros(shape[:-1], jnp.float32),
    }


def is_quantized_cache(cache: dict) -> bool:
    return "ks" in cache


def _quantize_kv(x: jax.Array):
    """x [B, KV, S, hd] -> (int8 values, f32 scales [B, KV, S])."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
    return q, scale[..., 0]


def dequantize_cache_layer(cache: dict, layer_idx) -> tuple[jax.Array, jax.Array]:
    """Extract layer `layer_idx` as dense float K/V [B, KV, C, hd]."""
    k = jax.lax.dynamic_index_in_dim(cache["k"], layer_idx, 0, keepdims=False)
    v = jax.lax.dynamic_index_in_dim(cache["v"], layer_idx, 0, keepdims=False)
    if not is_quantized_cache(cache):
        return k, v
    ks = jax.lax.dynamic_index_in_dim(cache["ks"], layer_idx, 0, keepdims=False)
    vs = jax.lax.dynamic_index_in_dim(cache["vs"], layer_idx, 0, keepdims=False)
    return (
        k.astype(jnp.float32) * ks[..., None],
        v.astype(jnp.float32) * vs[..., None],
    )


# -- building blocks --------------------------------------------------------


def _proj(sub: str, x: jax.Array, w, act_quant: bool = False) -> jax.Array:
    """Einsum against a weight that may be int8-quantized ({"q", "s"}).

    The int8 values go straight into the matmul (the dtype convert fuses into
    the MXU tile load, so HBM sees int8); the per-output-channel scale
    multiplies the result, which is exact because scales never cross the
    contraction (models/quant.py layout).

    ``act_quant=True`` (cfg.w8a8_prefill) additionally quantizes x per token
    (absmax over its contracted — trailing — dims) and runs the s8xs8->s32
    MXU dot at double rate; the activation scale factors out of the
    contraction exactly like the weight scale, so the ONLY loss is the int8
    rounding of x."""
    if not isinstance(w, dict):
        return jnp.einsum(sub, x, w)
    if act_quant:
        xs, rest = sub.split(",")
        ws, out = rest.split("->")
        n_contract = sum(c in ws and c not in out for c in xs)
        axes = tuple(range(x.ndim - n_contract, x.ndim))
        amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axes,
                       keepdims=True)
        s_act = jnp.maximum(amax, 1e-8) / 127.0
        q = jnp.clip(
            jnp.round(x.astype(jnp.float32) / s_act), -127, 127
        ).astype(jnp.int8)
        y = jnp.einsum(
            sub, q, w["q"], preferred_element_type=jnp.int32
        ).astype(jnp.float32)
        # broadcast the per-token scale over the weight's output dims
        n_out = len(out) - (len(xs) - n_contract)
        s_act = s_act.reshape(s_act.shape[: x.ndim - n_contract] + (1,) * n_out)
        return (y * s_act * w["s"]).astype(x.dtype)
    y = jnp.einsum(sub, x, w["q"].astype(x.dtype))
    return (y.astype(jnp.float32) * w["s"]).astype(x.dtype)


def _embed_lookup(embed, tokens: jax.Array, dtype) -> jax.Array:
    if isinstance(embed, dict):
        rows = jnp.take(embed["q"], tokens, axis=0).astype(jnp.float32)
        scales = jnp.take(embed["s"], tokens, axis=0)
        return (rows * scales[..., None]).astype(dtype)
    return jnp.take(embed, tokens, axis=0)


def _lm_head_logits(x: jax.Array, params: dict, cfg: "LlamaConfig") -> jax.Array:
    """Final projection in float32 (sampling wants full-precision logits)."""
    if cfg.tie_embeddings:
        w = params["embed"]
        sub = "bsd,vd->bsv"  # tied head contracts the embed row dim
    else:
        w = params["lm_head"]
        sub = "bsd,dv->bsv"
    if isinstance(w, dict):
        y = jnp.einsum(
            sub, x, w["q"].astype(x.dtype), preferred_element_type=jnp.float32
        )
        return y * w["s"]
    return jnp.einsum(sub, x, w, preferred_element_type=jnp.float32)


def _rmsnorm(
    x: jax.Array, w: jax.Array, eps: float, plus_one: bool = False
) -> jax.Array:
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    if plus_one:
        # Gemma-family RMSNorm: zero-centered weight, applied in float32
        return ((x32 * scale) * (1.0 + w.astype(jnp.float32))).astype(x.dtype)
    return (x32 * scale).astype(x.dtype) * w


def _mlp_act(x: jax.Array, act: str) -> jax.Array:
    if act == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def _rope_inv_freq(cfg: LlamaConfig) -> jax.Array:
    half = cfg.head_dim // 2
    inv = 1.0 / (cfg.rope_theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    if not cfg.use_llama3_rope_scaling:
        return inv
    # llama3 long-context frequency scaling: low-frequency bands divided by
    # `factor`, high-frequency bands kept, smooth ramp between.
    lo_wavelen = cfg.rope_original_max_len / cfg.rope_low_freq_factor
    hi_wavelen = cfg.rope_original_max_len / cfg.rope_high_freq_factor
    wavelen = 2.0 * jnp.pi / inv
    ramp = (cfg.rope_original_max_len / wavelen - cfg.rope_low_freq_factor) / (
        cfg.rope_high_freq_factor - cfg.rope_low_freq_factor
    )
    ramp = jnp.clip(ramp, 0.0, 1.0)
    scaled = inv / cfg.rope_scale_factor
    smooth = (1.0 - ramp) * scaled + ramp * inv
    out = jnp.where(wavelen > lo_wavelen, scaled, inv)
    between = (wavelen <= lo_wavelen) & (wavelen >= hi_wavelen)
    return jnp.where(between, smooth, out)


def _rope_cos_sin(cfg: LlamaConfig, positions: jax.Array):
    """positions [B, S] -> cos/sin [B, S, hd/2] (float32)."""
    pos = positions[..., None].astype(jnp.float32)
    if cfg.rope_linear_factor:
        pos = pos / cfg.rope_linear_factor
    angles = pos * _rope_inv_freq(cfg)
    return jnp.cos(angles), jnp.sin(angles)


def _apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x [B, S, H, hd]; rotate-half convention (pairs are [..:half],[half:..])."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out1 = x1f * c - x2f * s
    out2 = x2f * c + x1f * s
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


def _attention(
    q: jax.Array,        # [B, S, H, hd]
    k: jax.Array,        # [B, KV, C, hd]
    v: jax.Array,        # [B, KV, C, hd]
    mask: jax.Array,     # [B, S, C] bool — True = attend
    q_per_kv: int,
) -> jax.Array:
    B, S, H, hd = q.shape
    KV = k.shape[1]
    # (b, kv) are batch dims of both einsums and lead both operands; the
    # contractions run over the minor dims (hd, then c) — no cache transpose
    qg = q.reshape(B, S, KV, q_per_kv, hd).transpose(0, 2, 3, 1, 4)
    scores = jnp.einsum(
        "bkgsh,bkch->bkgsc", qg, k, preferred_element_type=jnp.float32
    )
    scores = scores / jnp.sqrt(jnp.float32(hd))
    neg = jnp.finfo(jnp.float32).min
    scores = jnp.where(mask[:, None, None, :, :], scores, neg)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgsc,bkch->bkgsh", probs, v)
    return out.transpose(0, 3, 1, 2, 4).reshape(B, S, H, hd)


def _cache_write(buf, val, layer_idx, write_index):
    """Write a per-layer K/V (or scale) slab into the stacked cache.

    ``buf`` [L, B, KV, C(, hd)], ``val`` [B, KV, S(, hd)]. ``write_index``
    is the cache slot of val's first token — a scalar (prefill/decode: every
    row writes at the same slot) or a [B] vector (the speculative verify
    step: rows sit at different fills after ragged draft acceptance, so each
    row writes at its own slot via a vmapped per-row update)."""
    tail = (0,) * (buf.ndim - 4)  # hd present on k/v, absent on ks/vs
    if jnp.ndim(write_index) == 0:
        return jax.lax.dynamic_update_slice(
            buf, val[None], (layer_idx, 0, 0, write_index) + tail
        )
    return jax.vmap(
        # per row: buf slice [L, KV, C(, hd)], update [1, KV, S(, hd)]
        lambda c, u, w: jax.lax.dynamic_update_slice(
            c, u[None], (layer_idx, 0, w) + tail
        ),
        in_axes=(1, 0, 0),
        out_axes=1,
    )(buf, val, write_index)


def _block(
    x, lp, layer_idx, rope, mask, is_global, cache, write_index,
    cfg: LlamaConfig, attention_fn=None, stacked_attention_fn=None,
):
    """One decoder layer.

    ``cache`` holds the FULL stacked caches [L, B, KV, C, hd] (plus
    per-token scales when int8-quantized); only the [S]-token slice of layer
    ``layer_idx`` is written (a tiny in-place dynamic_update_slice on the
    scan carry). Carrying the whole cache and writing the small slice keeps
    decode HBM traffic at weights+cache-read — emitting per-layer caches as
    scan outputs would re-materialize the whole ~GB cache every decode
    step. ``write_index`` may be a [B] vector (see _cache_write) for the
    speculative verify step's per-row fills."""
    P1 = cfg.norm_plus_one
    cos, sin = rope[0]
    if cfg.sliding_window:
        # per-layer global/sliding select: rope pair 1 and the windowed
        # mask apply on sliding layers (is_global is a traced per-layer
        # scalar from the scan xs). Static-gated: the Llama/Qwen traces
        # never build these selects.
        (cos_l, sin_l) = rope[1]
        cos = jnp.where(is_global, cos, cos_l)
        sin = jnp.where(is_global, sin, sin_l)
        C = mask.shape[-1]
        S = x.shape[1]
        k_slot = jnp.arange(C)
        if jnp.ndim(write_index) == 0:
            q_slot = write_index + jnp.arange(S)
            in_window = (
                k_slot[None, :] > q_slot[:, None] - cfg.sliding_window
            )[None]
        else:  # per-row write slots (spec verify): [B, S] query slots
            q_slot = write_index[:, None] + jnp.arange(S)[None, :]
            in_window = (
                k_slot[None, None, :]
                > q_slot[:, :, None] - cfg.sliding_window
            )
        mask = mask & (is_global | in_window)

    # W8A8 only on MULTI-token forwards (prefill): decode's single-token
    # matmuls are HBM-bound and S is trace-static, so this gate adds no
    # device control flow. The spec VERIFY forward is multi-token but
    # decode-phase (per-row write_index is its signature): it must stay
    # exact — speculation promises greedy outputs identical to plain
    # decode, and plain decode scores these positions unquantized
    aq = cfg.w8a8_prefill and x.shape[1] > 1 and jnp.ndim(write_index) == 0
    h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps, P1)
    q = _proj("bsd,dhk->bshk", h, lp["wq"], aq)
    k = _proj("bsd,dhk->bshk", h, lp["wk"], aq)
    v = _proj("bsd,dhk->bshk", h, lp["wv"], aq)
    if cfg.qk_norm:
        # Qwen3/Gemma3: RMSNorm over each head's hd dim before RoPE
        q = _rmsnorm(q, lp["q_norm"], cfg.norm_eps, P1)
        k = _rmsnorm(k, lp["k_norm"], cfg.norm_eps, P1)
    if cfg.query_scale:
        # fold a non-default score scale (Gemma's query_pre_attn_scalar)
        # into q so every attention implementation (dense, ring, Pallas)
        # keeps its built-in 1/sqrt(head_dim)
        q = q * jnp.asarray(
            (cfg.head_dim ** 0.5) / (cfg.query_scale ** 0.5), q.dtype
        )
    q = _apply_rope(q, cos, sin)
    k = _apply_rope(k, cos, sin)

    kt = k.transpose(0, 2, 1, 3)  # [B, KV, S, hd] — cache-native
    vt = v.transpose(0, 2, 1, 3)
    if is_quantized_cache(cache):
        k8, ks = _quantize_kv(kt)
        v8, vs = _quantize_kv(vt)
        cache = dict(
            cache,
            k=_cache_write(cache["k"], k8, layer_idx, write_index),
            v=_cache_write(cache["v"], v8, layer_idx, write_index),
            ks=_cache_write(cache["ks"], ks, layer_idx, write_index),
            vs=_cache_write(cache["vs"], vs, layer_idx, write_index),
        )
    else:
        cache = dict(
            cache,
            k=_cache_write(cache["k"], kt, layer_idx, write_index),
            v=_cache_write(cache["v"], vt, layer_idx, write_index),
        )

    if stacked_attention_fn is not None:
        # reads the stacked cache in place (Pallas kernels): no per-layer
        # extraction copy materializes
        attn = stacked_attention_fn(q, cache, layer_idx)
    else:
        k_cache, v_cache = dequantize_cache_layer(cache, layer_idx)
        k_cache = k_cache.astype(q.dtype)
        v_cache = v_cache.astype(q.dtype)
        if attention_fn is None:
            attn = _attention(q, k_cache, v_cache, mask, cfg.q_per_kv)
        else:
            attn = attention_fn(q, k_cache, v_cache, mask, cfg.q_per_kv)
    attn_out = _proj("bshk,hkd->bsd", attn, lp["wo"], aq)
    if cfg.sandwich_norms:
        attn_out = _rmsnorm(attn_out, lp["post_attn_norm"], cfg.norm_eps, P1)
    x = x + attn_out

    h = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps, P1)
    gate = _proj("bsd,di->bsi", h, lp["w_gate"], aq)
    up = _proj("bsd,di->bsi", h, lp["w_up"], aq)
    mlp_out = _proj(
        "bsi,id->bsd", _mlp_act(gate, cfg.act) * up, lp["w_down"], aq
    )
    if cfg.sandwich_norms:
        mlp_out = _rmsnorm(mlp_out, lp["post_ffw_norm"], cfg.norm_eps, P1)
    return x + mlp_out, cache


def forward(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,       # [B, S] int32
    positions: jax.Array,    # [B, S] int32 (RoPE positions, pad rows clipped)
    kv_cache: dict,          # {"k","v": [L, B, KV, C, hd]}
    write_index,             # cache slot of tokens[:, 0]: scalar, or [B]
    #                          vector for per-row slots (spec verify)
    mask: jax.Array,         # [B, S, C] bool over cache slots
    *,
    remat: bool = False,
    last_only: bool = False,
    attention_fn=None,
    stacked_attention_fn=None,
) -> tuple[jax.Array, dict]:
    """Run the decoder; returns (logits [B, S, vocab] f32, updated cache).

    ``last_only=True`` projects only the final position through the LM head
    (prefill sampling needs just that; a full [B, S, vocab] f32 tensor at
    S=2048 would be ~8 GB on the 128k vocab).

    ``attention_fn(q, k_cache, v_cache, mask, q_per_kv)`` overrides the
    dense cache attention on the extracted (dequantized) layer cache;
    ``stacked_attention_fn(q, cache, layer_idx)`` overrides it with a
    consumer of the FULL stacked cache dict (the Pallas kernels) and takes
    precedence."""
    x = _embed_lookup(params["embed"], tokens, cfg.dtype)
    if cfg.embed_scale:
        # Gemma scales hidden states by sqrt(dim), rounded through the
        # model dtype like the HF implementation's normalizer
        x = x * jnp.asarray(cfg.dim ** 0.5, cfg.dtype)
    rope = (_rope_cos_sin(cfg, positions),)
    if cfg.sliding_window:
        import dataclasses as _dc

        local_cfg = _dc.replace(
            cfg, rope_theta=cfg.rope_local_theta,
            use_llama3_rope_scaling=False, rope_linear_factor=0.0,
        )
        rope = rope + (_rope_cos_sin(local_cfg, positions),)
    flags = _layer_global_flags(cfg)

    block = _block
    if remat:
        block = jax.checkpoint(_block, static_argnums=(8, 9, 10))

    def layer_step(carry, xs):
        h, cache = carry
        lp, li, is_global = xs
        h, cache = block(
            h, lp, li, rope, mask, is_global, cache, write_index, cfg,
            attention_fn, stacked_attention_fn,
        )
        return (h, cache), None

    (x, new_cache), _ = jax.lax.scan(
        layer_step,
        (x, kv_cache),
        (params["layers"], jnp.arange(cfg.n_layers), flags),
    )

    if last_only:
        x = x[:, -1:, :]
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps, cfg.norm_plus_one)
    logits = _lm_head_logits(x, params, cfg)
    return logits, new_cache


def _layer_global_flags(cfg: LlamaConfig) -> jax.Array:
    """[L] bool — which layers attend globally.

    With sliding_window set and no explicit layer_is_global, EVERY layer is
    sliding (Mistral-style) — a silent all-global fallback would make the
    window a no-op while still paying its dense-path costs."""
    if not cfg.sliding_window:
        return jnp.ones((cfg.n_layers,), dtype=bool)
    if not cfg.layer_is_global:
        return jnp.zeros((cfg.n_layers,), dtype=bool)
    if len(cfg.layer_is_global) != cfg.n_layers:
        raise ValueError(
            f"layer_is_global has {len(cfg.layer_is_global)} entries "
            f"for {cfg.n_layers} layers"
        )
    return jnp.asarray(cfg.layer_is_global, dtype=bool)


def dense_causal_attention(q: jax.Array, k: jax.Array, v: jax.Array, q_per_kv: int):
    """Full causal attention without a cache (training path).

    k/v arrive projection-shaped [B, S, KV, hd]; _attention consumes the
    cache-native head-major layout, so transpose here (cheap next to the
    training matmuls)."""
    B, S = q.shape[0], q.shape[1]
    i = jnp.arange(S)[None, :, None]
    j = jnp.arange(S)[None, None, :]
    mask = jnp.broadcast_to(j <= i, (B, S, S))
    return _attention(
        q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3), mask, q_per_kv
    )


def cache_free_block(x, lp, cos, sin, cfg: LlamaConfig, attention_fn):
    """One cache-free decoder layer; returns (x, (k, v)) with k/v
    projection-shaped [B, S, KV, hd]. Shared by forward_train (which
    discards the k/v) and the long-context ring prefill (which stacks them
    into the frozen prefill cache) — ONE copy of the block math.

    Sliding-window (Gemma local) layers are NOT supported on this path —
    ring attention streams global K/V blocks; callers gate on
    cfg.sliding_window."""
    P1 = cfg.norm_plus_one
    h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps, P1)
    q = _proj("bsd,dhk->bshk", h, lp["wq"])
    k = _proj("bsd,dhk->bshk", h, lp["wk"])
    v = _proj("bsd,dhk->bshk", h, lp["wv"])
    if cfg.qk_norm:
        # Qwen3/Gemma3: RMSNorm over each head's hd dim before RoPE
        q = _rmsnorm(q, lp["q_norm"], cfg.norm_eps, P1)
        k = _rmsnorm(k, lp["k_norm"], cfg.norm_eps, P1)
    if cfg.query_scale:
        q = q * jnp.asarray(
            (cfg.head_dim ** 0.5) / (cfg.query_scale ** 0.5), q.dtype
        )
    q = _apply_rope(q, cos, sin)
    k = _apply_rope(k, cos, sin)
    attn = attention_fn(q, k, v, cfg.q_per_kv)
    attn_out = _proj("bshk,hkd->bsd", attn, lp["wo"])
    if cfg.sandwich_norms:
        attn_out = _rmsnorm(attn_out, lp["post_attn_norm"], cfg.norm_eps, P1)
    x = x + attn_out
    h = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps, P1)
    gate = _proj("bsd,di->bsi", h, lp["w_gate"])
    up = _proj("bsd,di->bsi", h, lp["w_up"])
    mlp_out = _proj("bsi,id->bsd", _mlp_act(gate, cfg.act) * up, lp["w_down"])
    if cfg.sandwich_norms:
        mlp_out = _rmsnorm(mlp_out, lp["post_ffw_norm"], cfg.norm_eps, P1)
    return x + mlp_out, (k, v)


def forward_train(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,        # [B, S] int32
    *,
    attention_fn=None,        # (q, k, v, q_per_kv) -> out; default dense causal
    remat: bool = True,
) -> jax.Array:
    """Cache-free causal forward for training; returns logits [B, S, V] f32.

    ``attention_fn`` is the sequence-parallelism seam: pass
    parallel.ring.ring_attention (wrapped over a mesh) to run blockwise ring
    attention over a sharded sequence axis instead of dense attention.
    """
    B, S = tokens.shape
    if cfg.sliding_window:
        raise NotImplementedError(
            "sliding-window (Gemma local) layers are not supported on the "
            "cache-free train/ring path; use the KV-cache forward"
        )
    attention_fn = attention_fn or dense_causal_attention
    x = _embed_lookup(params["embed"], tokens, cfg.dtype)
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.dim ** 0.5, cfg.dtype)
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    cos, sin = _rope_cos_sin(cfg, positions)

    def block(x, lp):
        x, _ = cache_free_block(x, lp, cos, sin, cfg, attention_fn)
        return x

    if remat:
        block = jax.checkpoint(block)

    def layer_step(carry, lp):
        return block(carry, lp), None

    x, _ = jax.lax.scan(layer_step, x, params["layers"])
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps, cfg.norm_plus_one)
    return _lm_head_logits(x, params, cfg)


# -- mask / position helpers (host-independent, shape-static) ----------------


def prefill_attention_mask(pad_lens: jax.Array, seq_len: int, cache_len: int):
    """Left-padded causal mask: query i attends cache slot j iff
    pad_b <= j <= i. [B, S, C]."""
    i = jnp.arange(seq_len)[None, :, None]
    j = jnp.arange(cache_len)[None, None, :]
    pad = pad_lens[:, None, None]
    return (j >= pad) & (j <= i)


def decode_attention_mask(pad_lens: jax.Array, fill: jax.Array, cache_len: int):
    """Single-token step: attend j iff pad_b <= j <= fill. [B, 1, C]."""
    j = jnp.arange(cache_len)[None, None, :]
    pad = pad_lens[:, None, None]
    return (j >= pad) & (j <= fill)


def prefill_positions(pad_lens: jax.Array, seq_len: int) -> jax.Array:
    """RoPE positions for left-padded prompts: max(0, i - pad). [B, S]."""
    i = jnp.arange(seq_len)[None, :]
    return jnp.maximum(0, i - pad_lens[:, None])


def verify_attention_mask(
    pad_lens: jax.Array, fills: jax.Array, num_q: int, cache_len: int
):
    """Speculative verify step: ``num_q`` query tokens per row sit at
    per-row cache slots fills_b .. fills_b + num_q - 1; query i attends
    j iff pad_b <= j <= fills_b + i. [B, num_q, C]. With num_q=1 and a
    shared fill this degenerates to decode_attention_mask."""
    j = jnp.arange(cache_len)[None, None, :]
    pad = pad_lens[:, None, None]
    limit = (fills[:, None] + jnp.arange(num_q)[None, :])[:, :, None]
    return (j >= pad) & (j <= limit)


def verify_positions(
    pad_lens: jax.Array, fills: jax.Array, num_q: int
) -> jax.Array:
    """RoPE positions of the verify queries: (fills_b - pad_b) + i. [B, S]."""
    return (fills - pad_lens)[:, None] + jnp.arange(num_q)[None, :]
