"""Demo core: run the registered approaches side-by-side over one document and
score each against an optional reference — the compute behind both demo
frontends (web server + streamlit), mirroring the reference's
streamlit_demo.py:61-161 (_summarise_async dispatch + compute_metrics).

Unlike the reference (one fixed Ollama model, approaches run serially over a
sync-over-async shim, streamlit_demo.py:164-180), the approaches here share
one Backend, and each approach's map rounds batch all chunks into single
device calls already, so no async juggling is needed.
"""
from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass, field

from ..backend.base import Backend
from ..core.config import APPROACHES, PipelineConfig, approach_defaults
from ..eval.rouge import RougeScorer
from ..strategies import get_strategy
from ..text import clean_thinking_tokens


@dataclass
class ApproachRun:
    approach: str
    summary: str = ""
    num_chunks: int = 0
    llm_calls: int = 0
    seconds: float = 0.0
    status: str = "success"
    error: str | None = None
    metrics: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def compute_metrics(summary: str, reference: str) -> dict:
    """ROUGE-1/2/L F1 vs the reference summary (streamlit_demo.py:61-79;
    BERTScore is left to the full evaluator — the demo stays encoder-free so
    it answers interactively)."""
    scorer = RougeScorer(["rouge1", "rouge2", "rougeL"])
    scores = scorer.score(reference, summary)
    return {name: s.fmeasure for name, s in scores.items()}


def run_approaches(
    text: str,
    backend: Backend,
    *,
    approaches: list[str] | None = None,
    reference: str | None = None,
    base_config: PipelineConfig | None = None,
    progress=None,
) -> list[ApproachRun]:
    """Run each approach on `text`; `progress(i, n, name)` is called before
    each one (the reference's progress bar hook, streamlit_demo.py:230-240)."""
    chosen = list(approaches or APPROACHES)
    runs: list[ApproachRun] = []
    for i, name in enumerate(chosen):
        if progress:
            progress(i, len(chosen), name)
        run = ApproachRun(approach=name)
        t0 = time.time()
        try:
            if base_config is not None:
                cfg = dataclasses.replace(base_config, approach=name)
            else:
                cfg = PipelineConfig(approach=name, **approach_defaults(name))
            strategy = get_strategy(name, backend, cfg)
            result = strategy.summarize(text)
            run.summary = clean_thinking_tokens(result.summary)
            run.num_chunks = result.num_chunks
            run.llm_calls = result.llm_calls
            if reference:
                run.metrics = compute_metrics(run.summary, reference)
        except Exception as e:  # one approach failing must not kill the rest
            run.status = "failed"
            run.error = str(e)
        run.seconds = time.time() - t0
        runs.append(run)
    return runs
