"""Dependency-free web demo — the interactive side-by-side comparison the
reference provides via Streamlit (streamlit_demo.py:183-287, SURVEY.md §2
C14), served from the stdlib so it runs on TPU hosts without extra packages.

Single page: paste or pick a document, choose approaches, submit; the page
renders each approach's summary with chunk/LLM-call/time stats and ROUGE vs
the reference summary when one is given.

Rebased onto vnsum_tpu.serve: summarize requests used to serialize whole
runs behind a lock (the backend is not thread-safe); now every approach's
LLM rounds are submitted through the micro-batching scheduler, so engine
access still serializes — per BATCH, in the scheduler thread — while
concurrent demo requests coalesce into shared device batches instead of
queueing behind each other.

    python -m vnsum_tpu.demo.server --backend fake --port 8900
    python -m vnsum_tpu.demo.server --backend tpu --model llama3.2:3b
"""
from __future__ import annotations

import argparse
import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from ..backend.base import Backend, get_backend
from ..core.config import APPROACHES
from ..core.logging import get_logger
from ..data import DocumentDataset
from ..serve.scheduler import MicroBatchScheduler
from .core import run_approaches

logger = get_logger("vnsum.demo")

_PAGE = """<!DOCTYPE html>
<html lang="vi"><head><meta charset="utf-8">
<title>VN-LongSum TPU demo</title>
<style>
body{font-family:system-ui,sans-serif;max-width:960px;margin:2rem auto;padding:0 1rem;color:#222}
textarea{width:100%;font-family:inherit}
.approach{border:1px solid #ccc;border-radius:8px;padding:1rem;margin:1rem 0}
.approach h3{margin-top:0}
.meta{color:#666;font-size:.85rem}
.failed{border-color:#c00}
button{padding:.5rem 1.5rem;font-size:1rem}
label{margin-right:1rem}
#status{color:#06c}
</style></head><body>
<h1>VN-LongSum TPU — so sánh 5 chiến lược tóm tắt</h1>
<p>Dán văn bản (hoặc chọn tài liệu mẫu nếu server có dataset), chọn chiến
lược, bấm <b>Tóm tắt</b>.</p>
<div id="picker"></div>
<p><textarea id="doc" rows="10" placeholder="Văn bản cần tóm tắt…"></textarea></p>
<p><textarea id="ref" rows="3" placeholder="Tóm tắt tham chiếu (tuỳ chọn, để tính ROUGE)…"></textarea></p>
<p id="boxes"></p>
<p><button onclick="run()">Tóm tắt</button> <span id="status"></span></p>
<div id="out"></div>
<script>
const APPROACHES = %APPROACHES%;
const esc = s => String(s).replace(/[&<>"']/g,
  c => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
document.getElementById('boxes').innerHTML = APPROACHES.map(a =>
  `<label><input type="checkbox" name="ap" value="${esc(a)}" checked>${esc(a)}</label>`).join('');
fetch('api/docs').then(r=>r.json()).then(d=>{
  if(!d.docs.length) return;
  document.getElementById('picker').innerHTML =
    '<select id="docsel"><option value="">— tài liệu mẫu —</option>'+
    d.docs.map(n=>`<option>${esc(n)}</option>`).join('')+'</select>';
  document.getElementById('docsel').onchange = e=>{
    if(!e.target.value) return;
    fetch('api/doc?name='+encodeURIComponent(e.target.value)).then(r=>r.json())
      .then(d=>{document.getElementById('doc').value=d.text;
                document.getElementById('ref').value=d.reference||'';});
  };
});
function run(){
  const text = document.getElementById('doc').value.trim();
  if(!text){alert('Chưa có văn bản');return;}
  const approaches=[...document.querySelectorAll('input[name=ap]:checked')].map(c=>c.value);
  document.getElementById('status').textContent='Đang tóm tắt…';
  document.getElementById('out').innerHTML='';
  fetch('api/summarize',{method:'POST',headers:{'Content-Type':'application/json'},
    body:JSON.stringify({text,reference:document.getElementById('ref').value.trim(),approaches})})
  .then(r=>r.json()).then(d=>{
    document.getElementById('status').textContent='';
    document.getElementById('out').innerHTML=d.runs.map(r=>{
      const m=r.metrics&&Object.keys(r.metrics).length?
        '<div class="meta">ROUGE-1/2/L: '+['rouge1','rouge2','rougeL']
          .map(k=>r.metrics[k].toFixed(4)).join(' / ')+'</div>':'';
      const body=r.status==='success'?`<p>${esc(r.summary)}</p>`:`<p>Lỗi: ${esc(r.error)}</p>`;
      return `<div class="approach ${r.status==='failed'?'failed':''}">
        <h3>${esc(r.approach)}</h3>${body}
        <div class="meta">${r.num_chunks} chunks · ${r.llm_calls} LLM calls · ${r.seconds.toFixed(1)}s</div>${m}</div>`;
    }).join('');
  }).catch(e=>{document.getElementById('status').textContent='Lỗi: '+e;});
}
</script></body></html>"""


class DemoState:
    def __init__(
        self,
        backend: Backend,
        dataset: DocumentDataset | None = None,
        *,
        max_batch: int = 8,
        max_wait_s: float = 0.005,
    ):
        self.backend = backend
        self.dataset = dataset
        # backends are not thread-safe (jit caches, stats, torch modules) —
        # the serve scheduler owns the only thread that touches the engine,
        # and concurrent summarize requests coalesce into its batches
        self.scheduler = MicroBatchScheduler(
            backend, max_batch=max_batch, max_wait_s=max_wait_s
        )

    def serving_backend(self):
        """A fresh per-request view: QueuedBackend accumulates per-request
        observability records, so sharing one across a server's lifetime
        would grow without bound."""
        return self.scheduler.backend_view()

    def close(self) -> None:
        self.scheduler.close(drain=True)


def make_handler(state: DemoState):
    class Handler(BaseHTTPRequestHandler):
        def _json(self, payload: dict, status: int = 200) -> None:
            body = json.dumps(payload, ensure_ascii=False).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self) -> None:  # noqa: N802 (stdlib API)
            path, _, query = self.path.partition("?")
            if path in ("/", "/index.html"):
                page = _PAGE.replace("%APPROACHES%", json.dumps(list(APPROACHES)))
                body = page.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/api/docs":
                names = state.dataset.filenames() if state.dataset else []
                self._json({"docs": names})
            elif path == "/api/doc":
                params = dict(
                    p.split("=", 1) for p in query.split("&") if "=" in p
                )
                from urllib.parse import unquote

                name = unquote(params.get("name", ""))
                if state.dataset is None or name not in state.dataset.filenames():
                    self._json({"error": "unknown document"}, 404)
                    return
                ref = ""
                if state.dataset.has_reference(name):
                    ref = state.dataset.read_reference(name)
                self._json(
                    {"text": state.dataset.read_doc(name), "reference": ref}
                )
            else:
                self._json({"error": "not found"}, 404)

        def do_POST(self) -> None:  # noqa: N802 (stdlib API)
            if self.path != "/api/summarize":
                self._json({"error": "not found"}, 404)
                return
            length = int(self.headers.get("Content-Length", "0"))
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(req, dict) or not isinstance(
                    req.get("text", ""), str
                ):
                    self._json({"error": "malformed request"}, 400)
                    return
                text = req.get("text", "")
                if not text.strip():
                    self._json({"error": "empty document"}, 400)
                    return
                approaches = req.get("approaches")  # None/absent = all
                if approaches == []:
                    self._json({"error": "no approaches selected"}, 400)
                    return
                if approaches is not None:
                    bad = [a for a in approaches if a not in APPROACHES]
                    if bad:
                        self._json({"error": f"unknown approaches: {bad}"}, 400)
                        return
                runs = run_approaches(
                    text,
                    state.serving_backend(),
                    approaches=approaches,
                    reference=req.get("reference") or None,
                )
                self._json({"runs": [r.to_dict() for r in runs]})
            except json.JSONDecodeError:
                self._json({"error": "invalid JSON"}, 400)
            except Exception as e:  # surface, don't crash the server
                logger.exception("summarize failed")
                self._json({"error": str(e)}, 500)

        def log_message(self, fmt, *args):  # route through our logger
            logger.info("%s %s", self.address_string(), fmt % args)

    return Handler


def make_server(
    state: DemoState, host: str = "127.0.0.1", port: int = 8900
) -> ThreadingHTTPServer:
    return ThreadingHTTPServer((host, port), make_handler(state))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="vnsum-demo")
    p.add_argument("--backend", choices=["tpu", "ollama", "hf", "fake"],
                   default="fake")
    p.add_argument("--model", default="llama3.2:3b")
    p.add_argument("--docs-dir", default="data_1/doc")
    p.add_argument("--summary-dir", default="data_1/summary")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8900)
    args = p.parse_args(argv)

    if args.backend == "tpu":
        from ..models import MODEL_REGISTRY

        backend = get_backend("tpu", model_config=MODEL_REGISTRY[args.model]())
    elif args.backend == "ollama":
        backend = get_backend("ollama", model=args.model)
    elif args.backend == "hf":
        backend = get_backend("hf", model_name_or_path=args.model)
    else:
        backend = get_backend("fake")

    dataset = None
    if Path(args.docs_dir).is_dir():
        dataset = DocumentDataset(args.docs_dir, args.summary_dir)
    state = DemoState(backend, dataset)
    server = make_server(state, args.host, args.port)
    logger.info("demo serving on http://%s:%d/", args.host, args.port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        state.close()  # drain in-flight scheduler batches
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
