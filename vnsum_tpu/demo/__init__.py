from .core import ApproachRun, run_approaches

__all__ = ["ApproachRun", "run_approaches"]
