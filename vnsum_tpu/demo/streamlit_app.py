"""Streamlit variant of the demo (the reference's streamlit_demo.py:183-287),
for hosts that have streamlit installed — the stdlib server in
`vnsum_tpu.demo.server` is the primary frontend on TPU images, which ship
without streamlit.

    streamlit run vnsum_tpu/demo/streamlit_app.py -- --backend fake
"""
from __future__ import annotations

import argparse
import sys
import threading
from pathlib import Path

try:
    import streamlit as st
except ImportError as e:  # pragma: no cover - exercised only sans streamlit
    raise SystemExit(
        "streamlit is not installed on this host; use the stdlib demo instead:\n"
        "  python -m vnsum_tpu.demo.server --backend fake"
    ) from e

from vnsum_tpu.backend.base import get_backend
from vnsum_tpu.core.config import APPROACHES
from vnsum_tpu.data import DocumentDataset
from vnsum_tpu.demo.core import run_approaches


def _args() -> argparse.Namespace:
    p = argparse.ArgumentParser()
    p.add_argument("--backend", default="fake",
                   choices=["tpu", "ollama", "hf", "fake"])
    p.add_argument("--model", default="llama3.2:3b")
    p.add_argument("--docs-dir", default="data_1/doc")
    p.add_argument("--summary-dir", default="data_1/summary")
    return p.parse_args(sys.argv[1:])


@st.cache_resource
def _generate_lock() -> threading.Lock:
    # backends are not thread-safe (jit caches, stats, torch modules); each
    # streamlit session runs in its own thread but shares the cached backend
    return threading.Lock()


@st.cache_resource
def _backend(spec: str, model: str):
    if spec == "tpu":
        from vnsum_tpu.models import MODEL_REGISTRY

        return get_backend("tpu", model_config=MODEL_REGISTRY[model]())
    if spec == "ollama":
        return get_backend("ollama", model=model)
    if spec == "hf":
        return get_backend("hf", model_name_or_path=model)
    return get_backend("fake")


def main() -> None:
    args = _args()
    st.set_page_config(page_title="VN-LongSum TPU demo", layout="wide")
    st.title("VN-LongSum TPU — so sánh 5 chiến lược tóm tắt")

    text, reference = "", ""
    if Path(args.docs_dir).is_dir():
        ds = DocumentDataset(args.docs_dir, args.summary_dir)
        choice = st.selectbox("Tài liệu mẫu", ["—", *ds.filenames()])
        if choice != "—":
            text = ds.read_doc(choice)
            reference = ds.read_reference(choice) or ""
    uploaded = st.file_uploader("…hoặc tải lên file .txt", type="txt")
    if uploaded is not None:
        text = uploaded.read().decode("utf-8")

    text = st.text_area("Văn bản", value=text, height=240)
    reference = st.text_area("Tóm tắt tham chiếu (tuỳ chọn)", value=reference,
                             height=100)
    chosen = st.multiselect("Chiến lược", list(APPROACHES), default=list(APPROACHES))

    if st.button("Tóm tắt") and text.strip():
        bar = st.progress(0.0)
        with _generate_lock():
            runs = run_approaches(
                text,
                _backend(args.backend, args.model),
                approaches=chosen,
                reference=reference.strip() or None,
                progress=lambda i, n, name: bar.progress(i / n, text=name),
            )
        bar.progress(1.0, text="xong")
        tabs = st.tabs([r.approach for r in runs])
        for tab, r in zip(tabs, runs):
            with tab:
                if r.status == "failed":
                    st.error(r.error)
                    continue
                st.write(r.summary)
                st.caption(
                    f"{r.num_chunks} chunks · {r.llm_calls} LLM calls · "
                    f"{r.seconds:.1f}s"
                )
                if r.metrics:
                    st.table({k: [f"{v:.4f}"] for k, v in r.metrics.items()})


main()
