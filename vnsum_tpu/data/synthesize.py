"""Synthetic VN-LongSum-shaped corpus generator.

The reference's datasets live on Google Drive (README.md:25-26) and only
their metadata is committed (metadata/doc_metadata.json: 150 docs, avg
54,566 tokens/doc; summary_metadata.json: avg 714 tokens). On an air-gapped
TPU host the pipeline still needs a corpus with the same *shape* — long
multi-section Vietnamese documents with reference summaries and a document
structure tree — for end-to-end runs, benchmarks, and the hierarchical
strategy. This module builds one deterministically.

Documents are assembled from a Vietnamese sentence grammar (topic subjects ×
predicates × numeric variations, full diacritics) into titled sections, so
they are ragged, non-repetitive enough to exercise tokenizers/ROUGE, and
carry real structure for the tree JSON ({type, text, children} — reference
runners/run_summarization_ollama_mapreduce_hierarchical.py:202-239).
Reference summaries take each section's lead sentences, mirroring how the
real summaries compress per-topic content.
"""
from __future__ import annotations

import argparse
import json
import random
from pathlib import Path

from ..text.tokenizer import whitespace_token_count

_TOPICS = [
    ("kinh tế", [
        "nền kinh tế Việt Nam", "ngành xuất khẩu thủy sản", "thị trường bất động sản",
        "khu vực doanh nghiệp nhỏ và vừa", "ngành du lịch trong nước",
    ]),
    ("môi trường", [
        "chất lượng không khí tại các đô thị lớn", "hệ sinh thái rừng ngập mặn",
        "nguồn nước sông Mê Kông", "công tác xử lý rác thải nhựa",
        "đa dạng sinh học ở Tây Nguyên",
    ]),
    ("giáo dục", [
        "chương trình giáo dục phổ thông mới", "hệ thống trường nghề",
        "việc dạy và học ngoại ngữ", "chuyển đổi số trong nhà trường",
        "chính sách học phí đại học",
    ]),
    ("y tế", [
        "mạng lưới y tế cơ sở", "công tác tiêm chủng mở rộng",
        "tình trạng quá tải bệnh viện tuyến trung ương", "bảo hiểm y tế toàn dân",
        "nguồn nhân lực ngành điều dưỡng",
    ]),
    ("pháp luật", [
        "dự thảo luật đất đai sửa đổi", "quy định về an toàn giao thông",
        "chính sách thuế thu nhập cá nhân", "công tác phòng chống tham nhũng",
        "thủ tục hành chính công trực tuyến",
    ]),
]

_PREDICATES = [
    "đã có những chuyển biến tích cực trong {period}",
    "đang đối mặt với nhiều thách thức lớn về nguồn lực",
    "được dự báo sẽ tăng trưởng khoảng {pct} phần trăm trong năm tới",
    "cần thêm các giải pháp đồng bộ từ trung ương đến địa phương",
    "thu hút sự quan tâm đặc biệt của dư luận xã hội",
    "ghi nhận mức đầu tư hơn {num} tỷ đồng trong {period}",
    "chịu ảnh hưởng rõ rệt từ biến động kinh tế toàn cầu",
    "đạt kết quả vượt chỉ tiêu đề ra với {pct} phần trăm kế hoạch",
    "còn tồn tại không ít hạn chế cần khắc phục sớm",
    "sẽ được rà soát toàn diện theo chỉ đạo của Chính phủ",
    "đóng vai trò then chốt trong chiến lược phát triển bền vững",
    "tiếp tục là điểm sáng được các chuyên gia đánh giá cao",
]

_PERIODS = [
    "quý một", "quý hai", "sáu tháng đầu năm", "giai đoạn vừa qua",
    "năm năm gần đây", "thập kỷ qua",
]

_CONNECTORS = [
    "Bên cạnh đó,", "Theo báo cáo mới nhất,", "Trong khi đó,",
    "Đáng chú ý,", "Về lâu dài,", "Tuy nhiên,", "Trên thực tế,",
    "Theo các chuyên gia,",
]


def _sentence(rng: random.Random, subjects: list[str]) -> str:
    subj = rng.choice(subjects)
    pred = rng.choice(_PREDICATES).format(
        pct=rng.randint(2, 95), num=rng.randint(10, 900),
        period=rng.choice(_PERIODS),
    )
    lead = rng.choice(_CONNECTORS) + " " if rng.random() < 0.4 else ""
    s = f"{lead}{subj} {pred}."
    return s[0].upper() + s[1:]


def _section(
    rng: random.Random, topic: str, subjects: list[str], target_tokens: int
) -> tuple[str, list[str], str]:
    """Returns (header, paragraphs, lead_sentence_for_summary)."""
    header = f"Phần về {topic} ({rng.choice(_PERIODS)})"
    paragraphs: list[str] = []
    lead = _sentence(rng, subjects)
    tokens = whitespace_token_count(lead)
    current = [lead]
    while tokens < target_tokens:
        s = _sentence(rng, subjects)
        tokens += whitespace_token_count(s)
        current.append(s)
        if len(current) >= rng.randint(4, 8):
            paragraphs.append(" ".join(current))
            current = []
    if current:
        paragraphs.append(" ".join(current))
    return header, paragraphs, lead


def synthesize_corpus(
    out_dir: str | Path,
    n_docs: int = 10,
    tokens_per_doc: int = 2000,
    summary_tokens: int = 120,
    seed: int = 0,
    ragged: float = 0.5,
) -> dict:
    """Write doc/, summary/, document_tree.json, metadata/ under ``out_dir``.

    ``tokens_per_doc`` is a whitespace-token target; actual lengths are
    ragged by ±``ragged``/2 (VN-LongSum docs vary widely around their 54k
    mean). Returns corpus stats (doc/summary token totals).
    """
    out = Path(out_dir)
    (out / "doc").mkdir(parents=True, exist_ok=True)
    (out / "summary").mkdir(parents=True, exist_ok=True)
    (out / "metadata").mkdir(parents=True, exist_ok=True)
    rng = random.Random(seed)

    tree_entries = []
    doc_meta, sum_meta = [], []
    for i in range(n_docs):
        name = f"doc_{i:03d}.txt"
        target = max(
            80, int(tokens_per_doc * (1 + ragged * (rng.random() - 0.5)))
        )
        n_sections = max(2, min(8, target // 400 + 2))
        topics = rng.sample(_TOPICS, k=min(n_sections, len(_TOPICS)))
        while len(topics) < n_sections:
            topics.append(rng.choice(_TOPICS))

        title = f"Báo cáo tổng hợp số {i + 1} về tình hình {topics[0][0]} và {topics[1][0]}"
        sections, leads = [], []
        for topic, subjects in topics:
            header, paragraphs, lead = _section(
                rng, topic, subjects, target // n_sections
            )
            sections.append((header, paragraphs))
            leads.append(lead)

        body = [title, ""]
        for header, paragraphs in sections:
            body.append(header)
            body.extend(paragraphs)
            body.append("")
        doc_text = "\n\n".join(body).strip()

        # summary: section leads + a closing sentence, clipped near target
        closing = (
            "Nhìn chung, báo cáo cho thấy các lĩnh vực trên cần được theo dõi "
            "sát sao và điều phối chặt chẽ trong thời gian tới."
        )
        summary_parts: list[str] = []
        tokens = 0
        for lead in leads + [closing]:
            t = whitespace_token_count(lead)
            if summary_parts and tokens + t > summary_tokens:
                break
            summary_parts.append(lead)
            tokens += t
        summary_text = " ".join(summary_parts)

        (out / "doc" / name).write_text(doc_text, encoding="utf-8")
        (out / "summary" / name).write_text(summary_text, encoding="utf-8")

        tree_entries.append({
            "filename": name,
            "tree": {
                "type": "Document",
                "text": title,
                "children": [
                    {
                        "type": "Header",
                        "text": header,
                        "children": [
                            {"type": "Paragraph", "text": p, "children": []}
                            for p in paragraphs
                        ],
                    }
                    for header, paragraphs in sections
                ],
            },
        })
        doc_meta.append({
            "filename": name,
            "tokens": whitespace_token_count(doc_text),
            "chars": len(doc_text),
        })
        sum_meta.append({
            "filename": name,
            "tokens": whitespace_token_count(summary_text),
            "chars": len(summary_text),
        })

    (out / "document_tree.json").write_text(
        json.dumps(tree_entries, ensure_ascii=False), encoding="utf-8"
    )

    def _meta(rows: list[dict]) -> dict:
        total = sum(r["tokens"] for r in rows)
        return {
            "total_files": len(rows),
            "total_tokens": total,
            "avg_tokens_per_file": total / len(rows) if rows else 0.0,
            "files": rows,
        }

    stats = {"documents": _meta(doc_meta), "summaries": _meta(sum_meta)}
    (out / "metadata" / "doc_metadata.json").write_text(
        json.dumps(stats["documents"], ensure_ascii=False, indent=1),
        encoding="utf-8",
    )
    (out / "metadata" / "summary_metadata.json").write_text(
        json.dumps(stats["summaries"], ensure_ascii=False, indent=1),
        encoding="utf-8",
    )
    return stats


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(
        description="Synthesize a VN-LongSum-shaped corpus "
        "(docs + summaries + tree JSON + metadata)"
    )
    ap.add_argument("--out", required=True, help="output corpus dir")
    ap.add_argument("--docs", type=int, default=150)
    ap.add_argument(
        "--tokens-per-doc", type=int, default=54_000,
        help="whitespace-token target per doc (VN-LongSum avg 54,566)",
    )
    ap.add_argument(
        "--summary-tokens", type=int, default=714,
        help="reference-summary token target (VN-LongSum avg 714)",
    )
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    stats = synthesize_corpus(
        args.out, args.docs, args.tokens_per_doc, args.summary_tokens,
        args.seed,
    )
    print(json.dumps({
        "docs": stats["documents"]["total_files"],
        "doc_tokens": stats["documents"]["total_tokens"],
        "avg_doc_tokens": round(stats["documents"]["avg_tokens_per_file"]),
        "avg_summary_tokens": round(stats["summaries"]["avg_tokens_per_file"]),
    }))


if __name__ == "__main__":
    main()
