"""Filesystem datasets: docs + reference summaries keyed by filename
(ref L0 layer, SURVEY.md §1: data_1/doc/*.txt ↔ data_1/summary/*.txt, plus
the document tree JSON for the hierarchical approach).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterator

from ..core.logging import get_logger

logger = get_logger("vnsum.data")


@dataclass
class DocStats:
    """Per-corpus stats (ref count_documents, run_full_evaluation_pipeline.py
    :235-322 — WITHOUT its indentation bug that left doc_info empty, SURVEY.md
    §7 'known reference bugs')."""

    total_documents: int = 0
    total_tokens: int = 0
    total_chars: int = 0
    estimated_chunks: int = 0
    per_document: list[dict] = field(default_factory=list)

    @property
    def avg_tokens_per_doc(self) -> float:
        return self.total_tokens / self.total_documents if self.total_documents else 0.0

    def to_dict(self) -> dict:
        return {
            "total_documents": self.total_documents,
            "total_tokens": self.total_tokens,
            "total_chars": self.total_chars,
            "estimated_chunks": self.estimated_chunks,
            "avg_tokens_per_doc": self.avg_tokens_per_doc,
            "per_document": self.per_document,
        }


class DocumentDataset:
    """Paired iteration over a docs dir and a reference-summary dir."""

    def __init__(self, docs_dir: str | Path, summary_dir: str | Path | None = None):
        self.docs_dir = Path(docs_dir)
        self.summary_dir = Path(summary_dir) if summary_dir else None
        if not self.docs_dir.is_dir():
            raise FileNotFoundError(f"docs dir not found: {self.docs_dir}")

    def filenames(self, max_samples: int | None = None) -> list[str]:
        names = sorted(p.name for p in self.docs_dir.glob("*.txt"))
        return names[:max_samples] if max_samples else names

    def read_doc(self, name: str) -> str:
        return (self.docs_dir / name).read_text(encoding="utf-8")

    def has_reference(self, name: str) -> bool:
        return self.summary_dir is not None and (self.summary_dir / name).is_file()

    def read_reference(self, name: str) -> str | None:
        if self.summary_dir is None:
            return None
        p = self.summary_dir / name
        return p.read_text(encoding="utf-8") if p.is_file() else None

    def __iter__(self) -> Iterator[tuple[str, str, str | None]]:
        for name in self.filenames():
            yield name, self.read_doc(name), self.read_reference(name)

    def __len__(self) -> int:
        return len(self.filenames())


def analyze_documents(
    dataset: DocumentDataset,
    count_tokens: Callable[[str], int],
    chunk_size: int | None = None,
    max_samples: int | None = None,
) -> DocStats:
    stats = DocStats()
    for name in dataset.filenames(max_samples):
        text = dataset.read_doc(name)
        tokens = count_tokens(text)
        chunks = max(1, -(-tokens // chunk_size)) if chunk_size else 1
        stats.total_documents += 1
        stats.total_tokens += tokens
        stats.total_chars += len(text)
        stats.estimated_chunks += chunks
        stats.per_document.append(
            {"filename": name, "tokens": tokens, "chars": len(text), "est_chunks": chunks}
        )
    return stats
