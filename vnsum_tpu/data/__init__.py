from .dataset import DocumentDataset, DocStats, analyze_documents

__all__ = ["DocumentDataset", "DocStats", "analyze_documents"]
