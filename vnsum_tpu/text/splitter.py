"""Token-aware recursive text splitting.

Re-implements the splitting semantics the reference gets from langchain's
RecursiveCharacterTextSplitter with keep_separator=True
(construction at run_full_evaluation_pipeline.py:356-361; Vietnamese-friendly
separator ladder ["\\n\\n", "\\n", ".", "!", "?", ";", " ", ""]) so that
chunk boundaries match the reference runs. The length function is pluggable;
the reference passes HF `tokenizer.encode` (:348-349).
"""
from __future__ import annotations

import re
from typing import Callable, Sequence

VIETNAMESE_SEPARATORS: tuple[str, ...] = ("\n\n", "\n", ".", "!", "?", ";", " ", "")


class RecursiveTokenSplitter:
    """Recursively split text on a separator ladder, then greedily merge
    pieces into chunks of at most ``chunk_size`` (per ``length_function``)
    with ``chunk_overlap`` carry-over between consecutive chunks.

    Separators are kept and attached to the *following* piece (langchain's
    keep_separator=True behavior), so no characters are lost except the
    strip() at chunk joins.
    """

    def __init__(
        self,
        chunk_size: int,
        chunk_overlap: int = 0,
        length_function: Callable[[str], int] = len,
        separators: Sequence[str] = VIETNAMESE_SEPARATORS,
        length_batch_function: Callable[[Sequence[str]], list[int]] | None = None,
    ) -> None:
        if chunk_overlap >= chunk_size:
            raise ValueError("chunk_overlap must be smaller than chunk_size")
        self.chunk_size = chunk_size
        self.chunk_overlap = chunk_overlap
        self.length_function = length_function
        # one tokenizer call per split level instead of one per PIECE: a
        # reference-scale doc splits into thousands of sentence pieces, and
        # per-piece HF encode calls dominated the pipeline's host time.
        # Semantics are identical — batch(l) must equal [length(p) for p]
        self.length_batch = length_batch_function or (
            lambda texts: [length_function(t) for t in texts]
        )
        self.separators = list(separators)

    # -- public API --------------------------------------------------------

    def split_text(self, text: str) -> list[str]:
        if not text:
            return []
        return self._split(text, self.separators)

    # -- internals ---------------------------------------------------------

    def _split_on(self, text: str, separator: str) -> list[str]:
        """Split keeping the separator glued to the following piece."""
        if separator == "":
            return [c for c in text]
        parts = re.split(f"({re.escape(separator)})", text)
        out: list[str] = []
        if parts[0]:
            out.append(parts[0])
        for i in range(1, len(parts) - 1, 2):
            merged = parts[i] + parts[i + 1]
            if merged:
                out.append(merged)
        return [p for p in out if p]

    def _split(self, text: str, separators: Sequence[str]) -> list[str]:
        # pick the first separator present in the text (or the terminal "")
        separator = separators[-1]
        next_separators: Sequence[str] = []
        for i, sep in enumerate(separators):
            if sep == "":
                separator = sep
                break
            if sep in text:
                separator = sep
                next_separators = separators[i + 1 :]
                break

        splits = self._split_on(text, separator)
        lens = self.length_batch(splits)  # counted ONCE per level

        chunks: list[str] = []
        small: list[tuple[str, int]] = []
        for piece, plen in zip(splits, lens):
            if plen < self.chunk_size:
                small.append((piece, plen))
            else:
                if small:
                    chunks.extend(self._merge_counted(small))
                    small = []
                if not next_separators:
                    chunks.append(piece)
                else:
                    chunks.extend(self._split(piece, next_separators))
        if small:
            chunks.extend(self._merge_counted(small))
        return chunks

    def _merge_counted(self, counted: list[tuple[str, int]]) -> list[str]:
        """Greedy merge of already-small (piece, length) pairs into
        ≤chunk_size chunks, keeping a chunk_overlap-sized tail of pieces
        between chunks. Lengths arrive precomputed from the per-level
        batch count in _split — never recounted here."""
        pieces = [p for p, _ in counted]
        lengths = [n for _, n in counted]
        chunks: list[str] = []
        window: list[str] = []
        window_lens: list[int] = []
        total = 0
        for piece, plen in zip(pieces, lengths):
            if total + plen > self.chunk_size and window:
                joined = "".join(window).strip()
                if joined:
                    chunks.append(joined)
                # drop from the front until within overlap budget (and room
                # for the incoming piece)
                while window and (
                    total > self.chunk_overlap
                    or (total + plen > self.chunk_size and total > 0)
                ):
                    total -= window_lens[0]
                    window.pop(0)
                    window_lens.pop(0)
            window.append(piece)
            window_lens.append(plen)
            total += plen
        joined = "".join(window).strip()
        if joined:
            chunks.append(joined)
        return chunks
