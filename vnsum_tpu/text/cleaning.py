"""Model-output sanitization.

Behavioral parity with the reference's clean_thinking_tokens
(run_full_evaluation_pipeline.py:34-63; duplicated with drift at
runners/..._critique.py:26-46, ..._iterative.py:19-47, ..._hierarchical.py:20-40).
This is the single canonical copy; the hierarchical variant's
collapse-all-whitespace behavior (:39) is available via `collapse_whitespace=True`.
"""
from __future__ import annotations

import re

_TAG_PATTERNS = [
    re.compile(r"<think>.*?</think>", re.DOTALL | re.IGNORECASE),
    re.compile(r"<thinking>.*?</thinking>", re.DOTALL | re.IGNORECASE),
    re.compile(r"<thought>.*?</thought>", re.DOTALL | re.IGNORECASE),
    re.compile(r"<reasoning>.*?</reasoning>", re.DOTALL | re.IGNORECASE),
    re.compile(r"<analysis>.*?</analysis>", re.DOTALL | re.IGNORECASE),
]
_TRIPLE_NEWLINE = re.compile(r"\n\s*\n\s*\n")
_ALL_WS = re.compile(r"\s+")


def clean_thinking_tokens(text: str, *, collapse_whitespace: bool = False) -> str:
    """Strip <think>/<thinking>/<thought>/<reasoning>/<analysis> blocks and
    normalize leftover whitespace."""
    if not text:
        return text
    cleaned = text
    for pat in _TAG_PATTERNS:
        cleaned = pat.sub("", cleaned)
    if collapse_whitespace:
        cleaned = _ALL_WS.sub(" ", cleaned)
    else:
        cleaned = _TRIPLE_NEWLINE.sub("\n\n", cleaned)
    return cleaned.strip()
