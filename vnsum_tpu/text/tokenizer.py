"""Tokenizers.

The reference uses two token metrics: real HF tokenizer counts for chunking
(run_full_evaluation_pipeline.py:348-349, meta-llama/Llama-3.2-3b at :344-345)
and whitespace-split word counts for collapse gating
(runners/run_summarization_ollama_mapreduce.py:58-60). Both are exposed here;
the framework uses ONE tokenizer consistently (SURVEY.md §7.2) and keeps
`whitespace_token_count` available for reference-parity gating.

Because pretrained vocabularies may not be present on an air-gapped TPU host,
the default is a self-contained byte-level tokenizer (lossless UTF-8 round
trip, zero downloads); `HFTokenizer` wraps any locally available HuggingFace
tokenizer for exact reference parity when its files exist.
"""
from __future__ import annotations

from functools import lru_cache
from typing import Protocol, Sequence


class Tokenizer(Protocol):
    vocab_size: int
    bos_id: int
    eos_id: int
    pad_id: int

    def encode(self, text: str, *, add_bos: bool = False) -> list[int]: ...
    def encode_batch(
        self, texts: Sequence[str], *, add_bos: bool = False
    ) -> list[list[int]]: ...
    def decode(self, ids: Sequence[int], *, skip_special_tokens: bool = True) -> str: ...
    def count(self, text: str) -> int: ...
    def count_batch(self, texts: Sequence[str]) -> list[int]: ...


def whitespace_token_count(text: str) -> int:
    """The reference backend's token estimate: len(text.split())
    (runners/run_summarization_ollama_mapreduce.py:58-60)."""
    return len(text.split())


class ByteTokenizer:
    """Lossless UTF-8 byte tokenizer with special tokens.

    ids 0..255 are raw bytes; BOS/EOS/PAD follow. vocab_size is padded to a
    multiple of 128 so the embedding table tiles cleanly on the MXU lane
    dimension.
    """

    def __init__(self) -> None:
        self.bos_id = 256
        self.eos_id = 257
        self.pad_id = 258
        self.vocab_size = 384  # 259 rounded up to a multiple of 128
        # ids that decode() can render as text: the 256 raw bytes. BOS/EOS/
        # PAD terminate or vanish, and 259..383 are MXU-tiling filler —
        # sampling any of them produces no text (see decodable_vocab_limit)
        self.decodable_vocab_size = 256

    def encode(self, text: str, *, add_bos: bool = False) -> list[int]:
        ids = list(text.encode("utf-8"))
        if add_bos:
            ids = [self.bos_id] + ids
        return ids

    def encode_batch(
        self, texts: Sequence[str], *, add_bos: bool = False
    ) -> list[list[int]]:
        return [self.encode(t, add_bos=add_bos) for t in texts]

    _SPECIAL_NAMES = {256: "<|bos|>", 257: "<|eos|>", 258: "<|pad|>"}

    def decode(self, ids: Sequence[int], *, skip_special_tokens: bool = True) -> str:
        if skip_special_tokens:
            raw = bytes(i for i in ids if i < 256)
            return raw.decode("utf-8", errors="ignore")
        out: list[str] = []
        run: list[int] = []
        for i in ids:
            if i < 256:
                run.append(i)
            else:
                if run:
                    out.append(bytes(run).decode("utf-8", errors="ignore"))
                    run = []
                out.append(self._SPECIAL_NAMES.get(i, f"<|{i}|>"))
        if run:
            out.append(bytes(run).decode("utf-8", errors="ignore"))
        return "".join(out)

    def count(self, text: str) -> int:
        return len(text.encode("utf-8"))

    def count_batch(self, texts: Sequence[str]) -> list[int]:
        return [len(t.encode("utf-8")) for t in texts]


class HFTokenizer:
    """Wrapper over a locally available HuggingFace tokenizer (the reference's
    chunking metric, run_full_evaluation_pipeline.py:344-349)."""

    def __init__(self, name_or_path: str) -> None:
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(name_or_path)
        self.vocab_size = len(self._tok)
        # encoder-only tokenizers (BERT/MiniLM WordPiece) have no EOS; their
        # SEP plays the terminator role. The generation engine still needs a
        # real terminator, so raise only when neither exists.
        eos = self._tok.eos_token_id
        if eos is None:
            eos = self._tok.sep_token_id
        if eos is None:
            raise ValueError(
                f"tokenizer {name_or_path!r} has neither eos nor sep token; "
                "the engine needs one to terminate generation"
            )
        self.eos_id = eos
        self.bos_id = self._tok.bos_token_id  # may be None (no BOS prepended)
        self.cls_id = self._tok.cls_token_id  # BERT-family only (else None)
        self.sep_id = self._tok.sep_token_id
        pad = self._tok.pad_token_id
        self.pad_id = pad if pad is not None else self.eos_id

    def encode(self, text: str, *, add_bos: bool = False) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        return ids

    def encode_batch(
        self, texts: Sequence[str], *, add_bos: bool = False
    ) -> list[list[int]]:
        """One call into the Rust fast-tokenizer for the whole list: it
        releases the GIL and parallelizes across cores, and even
        single-core it skips the per-call Python overhead (measured 1.4x
        on reference-scale prompt lists — the engine's tokenize_host
        phase and the splitter's length function both ride this)."""
        out = self._tok(list(texts), add_special_tokens=False)["input_ids"]
        if add_bos and self.bos_id is not None:
            out = [[self.bos_id] + ids for ids in out]
        return out

    def decode(self, ids: Sequence[int], *, skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def count(self, text: str) -> int:
        return len(self._tok.encode(text, add_special_tokens=False))

    def count_batch(self, texts: Sequence[str]) -> list[int]:
        return [len(ids) for ids in self.encode_batch(texts)]


@lru_cache(maxsize=8)
def get_tokenizer(spec: str = "byte") -> Tokenizer:
    """Factory: "byte" or "hf:<name-or-path>"."""
    if spec == "byte":
        return ByteTokenizer()
    if spec.startswith("hf:"):
        return HFTokenizer(spec[3:])
    raise ValueError(f"unknown tokenizer spec {spec!r} (use 'byte' or 'hf:<path>')")
