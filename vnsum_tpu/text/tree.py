"""Document structure trees for the hierarchical strategy.

Node schema {type: Document|Header|Paragraph, text, children} and operations
match the reference's DFS helpers
(runners/run_summarization_ollama_mapreduce_hierarchical.py:202-239), plus a
loader for data_1/document_tree.json keyed by filename
(run_full_evaluation_pipeline.py:505-530).
"""
from __future__ import annotations

import copy
import json
from pathlib import Path
from typing import Callable, Optional

Node = dict


def depth_first_traverse(
    node: Node,
    callback: Callable[[Node, int, Optional[Node]], None],
    depth: int = 0,
    parent: Optional[Node] = None,
) -> None:
    callback(node, depth, parent)
    for child in node.get("children", []) or []:
        depth_first_traverse(child, callback, depth + 1, node)


def tree_depth(node: Node) -> int:
    children = node.get("children") or []
    if not children:
        return 0
    return 1 + max(tree_depth(c) for c in children)


def collect_nodes_at_depth(root: Node, target_depth: int) -> list[Node]:
    """Non-Paragraph nodes at exactly ``target_depth``."""
    out: list[Node] = []

    def _cb(n: Node, d: int, _p: Optional[Node]) -> None:
        if d == target_depth and n.get("type") != "Paragraph":
            out.append(n)

    depth_first_traverse(root, _cb)
    return out


def extract_descendant_paragraph_text(node: Node) -> str:
    """Concatenate all descendant Paragraph texts, joined by blank lines."""
    texts: list[str] = []

    def _cb(n: Node, _d: int, _p: Optional[Node]) -> None:
        if n.get("type") == "Paragraph":
            texts.append(n.get("text", ""))

    depth_first_traverse(node, _cb)
    return "\n\n".join(texts)


def replace_node_with_paragraph(node: Node, summary_text: str) -> None:
    """Mutate ``node`` in place into a Paragraph leaf holding ``summary_text``."""
    node.pop("children", None)
    node.clear()
    node["type"] = "Paragraph"
    node["text"] = summary_text


class DocumentTree:
    """Map of filename -> Document node, loaded from a tree JSON file."""

    def __init__(self, mapping: dict[str, Node]) -> None:
        self._trees = mapping

    @classmethod
    def load(cls, path: str | Path) -> "DocumentTree":
        data = json.loads(Path(path).read_text(encoding="utf-8"))
        if isinstance(data, list):
            mapping = {}
            for i, entry in enumerate(data):
                name = entry.get("filename") or entry.get("name")
                if not name:
                    raise ValueError(
                        f"tree JSON list entry {i} has no 'filename'/'name' key"
                    )
                mapping[name] = entry.get("tree", entry)
        else:
            mapping = data
        return cls(mapping)

    def get(self, filename: str) -> Optional[Node]:
        """Deep copy — strategies mutate trees in place during collapse."""
        node = self._trees.get(filename)
        return copy.deepcopy(node) if node is not None else None

    def __contains__(self, filename: str) -> bool:
        return filename in self._trees

    def __len__(self) -> int:
        return len(self._trees)
