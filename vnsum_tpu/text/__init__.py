from .cleaning import clean_thinking_tokens
from .splitter import RecursiveTokenSplitter
from .tokenizer import (
    ByteTokenizer,
    HFTokenizer,
    Tokenizer,
    get_tokenizer,
    whitespace_token_count,
)
from .tree import (
    DocumentTree,
    collect_nodes_at_depth,
    depth_first_traverse,
    extract_descendant_paragraph_text,
    replace_node_with_paragraph,
    tree_depth,
)

__all__ = [
    "clean_thinking_tokens",
    "RecursiveTokenSplitter",
    "ByteTokenizer",
    "HFTokenizer",
    "Tokenizer",
    "get_tokenizer",
    "whitespace_token_count",
    "DocumentTree",
    "collect_nodes_at_depth",
    "depth_first_traverse",
    "extract_descendant_paragraph_text",
    "replace_node_with_paragraph",
    "tree_depth",
]
