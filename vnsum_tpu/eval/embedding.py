"""On-device embedding metrics: sentence cosine similarity and BERTScore.

Replaces the reference's sentence-transformers per-pair encode loop
(evaluate/evaluate_summaries_semantic.py:561-575 — re-encodes every pair,
no batching) and the external bert-score package (:577-582) with batched
JAX passes over one encoder.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..models import jitted_init
from ..models.encoder import (
    EncoderConfig,
    encode,
    init_encoder_params,
    mean_pool,
    minilm_like,
)
from ..text.tokenizer import Tokenizer, get_tokenizer


@dataclass(frozen=True)
class BertScore:
    precision: float
    recall: float
    f1: float


class EmbeddingModel:
    """Tokenize → encode on device, with fixed-length batches."""

    def __init__(
        self,
        config: EncoderConfig | None = None,
        tokenizer: str | Tokenizer = "byte",
        params=None,
        max_len: int | None = None,
        batch_size: int = 32,
        seed: int = 0,
    ) -> None:
        from ..core.jax_cache import enable_compilation_cache

        enable_compilation_cache()
        self.cfg = config or minilm_like()
        self.tok = get_tokenizer(tokenizer) if isinstance(tokenizer, str) else tokenizer
        self.max_len = max_len or self.cfg.max_len
        self.batch_size = batch_size
        if self.tok.vocab_size > self.cfg.vocab_size:
            raise ValueError(
                f"tokenizer vocab ({self.tok.vocab_size}) exceeds encoder "
                f"vocab ({self.cfg.vocab_size}); ids would clamp to garbage "
                "embeddings — use an EncoderConfig sized for this tokenizer"
            )
        if params is None:
            params = jitted_init(init_encoder_params, self.cfg, seed)
        self.params = params
        # BERT-family tokenizers carry [CLS]/[SEP]; pretrained encoders were
        # trained with them, so wrap every sequence the way
        # sentence-transformers does (mean pooling then includes both, per
        # its attention-mask pooling)
        self._cls = getattr(self.tok, "cls_id", None)
        self._sep = getattr(self.tok, "sep_id", None)
        self._encode = jax.jit(partial(encode, cfg=self.cfg))

    @classmethod
    def from_hf(cls, model_dir: str, batch_size: int = 32, dtype=None):
        """Load a converted HF BERT-family checkpoint + its tokenizer from a
        local dir — makes the metrics pretrained-calibrated (comparable to
        the reference's all-MiniLM-L6-v2 / mBERT numbers,
        evaluate/evaluate_summaries_semantic.py:128-133, :577-582)."""
        from ..models.convert_encoder import load_hf_encoder

        config, params = load_hf_encoder(model_dir, dtype=dtype)
        return cls(
            config=config,
            tokenizer=f"hf:{model_dir}",
            params=params,
            batch_size=batch_size,
        )

    def _batch_tokens(self, texts: list[str]) -> tuple[np.ndarray, np.ndarray]:
        S = self.max_len
        special = int(self._cls is not None) + int(self._sep is not None)
        toks = np.full((len(texts), S), self.tok.pad_id, dtype=np.int32)
        mask = np.zeros((len(texts), S), dtype=bool)
        for i, t in enumerate(texts):
            ids = self.tok.encode(t)[: S - special]
            if self._cls is not None:
                ids = [self._cls] + ids
            if self._sep is not None:
                ids = ids + [self._sep]
            toks[i, : len(ids)] = ids
            mask[i, : len(ids)] = True
        return toks, mask

    def token_embeddings(self, texts: list[str]) -> tuple[jax.Array, np.ndarray]:
        """Returns (embs [N, S, D] ON DEVICE, mask [N, S] host) in
        fixed-size batches.

        The embeddings stay device-resident deliberately: downstream
        consumers (mean pooling, BERTScore greedy matching) run on device,
        and only their small [N] / [N, D] results cross to the host. The
        earlier host round trip of the full [N, S, D] tensor dominated the
        evaluation pass on a slow device link (~25 MB per batch each way)."""
        embs, masks = [], []
        for start in range(0, len(texts), self.batch_size):
            chunk = texts[start : start + self.batch_size]
            # pad the trailing partial batch to the full batch size so the
            # jitted encode sees one shape
            toks, mask = self._batch_tokens(
                chunk + [""] * (self.batch_size - len(chunk))
            )
            out = self._encode(self.params, tokens=toks, mask=mask)
            embs.append(out[: len(chunk)])
            masks.append(mask[: len(chunk)])
        return jnp.concatenate(embs), np.concatenate(masks)

    def sentence_embeddings(self, texts: list[str]) -> np.ndarray:
        """L2-normalized mean-pooled embeddings [N, D]."""
        embs, mask = self.token_embeddings(texts)
        return np.asarray(mean_pool(embs, jnp.asarray(mask)))


def cosine_similarities(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Row-wise cosine of two [N, D] arrays (already normalized or not)."""
    an = a / np.maximum(np.linalg.norm(a, axis=-1, keepdims=True), 1e-9)
    bn = b / np.maximum(np.linalg.norm(b, axis=-1, keepdims=True), 1e-9)
    return np.sum(an * bn, axis=-1)


@jax.jit
def _greedy_match(c_embs, c_mask, r_embs, r_mask):
    """BERTScore greedy matching for one pair batch:
    c_embs [N, Sc, D], r_embs [N, Sr, D] -> (P, R) [N]."""
    cn = c_embs / jnp.maximum(
        jnp.linalg.norm(c_embs, axis=-1, keepdims=True), 1e-9
    )
    rn = r_embs / jnp.maximum(
        jnp.linalg.norm(r_embs, axis=-1, keepdims=True), 1e-9
    )
    sim = jnp.einsum("ncd,nrd->ncr", cn, rn)
    valid = c_mask[:, :, None] & r_mask[:, None, :]
    sim = jnp.where(valid, sim, -jnp.inf)
    c_best = jnp.max(sim, axis=2)  # [N, Sc]
    r_best = jnp.max(sim, axis=1)  # [N, Sr]
    # tokens with no valid counterpart (empty other side) contribute 0, and
    # padding contributes 0 — keeps empty texts finite instead of -inf/NaN
    c_best = jnp.where(c_mask & jnp.isfinite(c_best), c_best, 0.0)
    r_best = jnp.where(r_mask & jnp.isfinite(r_best), r_best, 0.0)
    c_count = jnp.maximum(jnp.sum(c_mask, axis=1), 1)
    r_count = jnp.maximum(jnp.sum(r_mask, axis=1), 1)
    P = jnp.sum(c_best, axis=1) / c_count
    R = jnp.sum(r_best, axis=1) / r_count
    return P, R


def bert_scores(
    model: EmbeddingModel, candidates: list[str], references: list[str]
) -> list[BertScore]:
    """Corpus BERTScore (no IDF weighting, like bert_score defaults the
    reference relies on at evaluate/evaluate_summaries_semantic.py:577-582)."""
    if len(candidates) != len(references):
        raise ValueError("candidates and references must align")
    out: list[BertScore] = []
    # chunk the matching pass with the encode batch size so the [n, S, S]
    # similarity tensor stays bounded regardless of corpus size
    bs = model.batch_size
    for start in range(0, len(candidates), bs):
        cands = candidates[start : start + bs]
        refs = references[start : start + bs]
        n = len(cands)
        # pad the trailing partial chunk to the full batch size so
        # _greedy_match compiles exactly ONE shape per corpus (a second
        # trace of the [n, S, S] einsum costs more than the padded rows)
        cands = cands + [""] * (bs - n)
        refs = refs + [""] * (bs - n)
        c_embs, c_mask = model.token_embeddings(cands)
        r_embs, r_mask = model.token_embeddings(refs)
        P, R = _greedy_match(
            jnp.asarray(c_embs), jnp.asarray(c_mask),
            jnp.asarray(r_embs), jnp.asarray(r_mask),
        )
        for p, r in zip(
            np.asarray(P)[:n].tolist(), np.asarray(R)[:n].tolist()
        ):
            f1 = 2 * p * r / (p + r) if (p + r) else 0.0
            out.append(BertScore(p, r, f1))
    return out
