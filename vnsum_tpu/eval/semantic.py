"""Semantic evaluator: per-pair sentence cosine + ROUGE, corpus BERTScore,
optional LLM G-Eval — returning structured results in the reference's results
JSON schema (evaluate/evaluate_summaries_semantic.py:125-180, :674-696:
summary_statistics{semantic_similarity, rouge_scores, bert_scores,
llm_scores} + detailed_results). Metrics travel as data; there is no stdout
scraping step (contrast run_full_evaluation_pipeline.py:729-784).
"""
from __future__ import annotations

import contextlib
import json
from pathlib import Path

import numpy as np

from ..core.logging import get_logger
from .embedding import EmbeddingModel, bert_scores, cosine_similarities
from .rouge import RougeScorer

logger = get_logger("vnsum.eval")


def load_summary_dir(path: str | Path) -> dict[str, str]:
    """filename -> text for every .txt in a directory
    (ref :521-544 folder loading)."""
    out: dict[str, str] = {}
    p = Path(path)
    if not p.is_dir():
        raise FileNotFoundError(f"summary directory not found: {p}")
    for f in sorted(p.glob("*.txt")):
        out[f.name] = f.read_text(encoding="utf-8")
    return out


def match_pairs(
    generated: dict[str, str],
    references: dict[str, str],
    max_samples: int | None = None,
) -> list[str]:
    """Sorted filenames present on both sides (ref :521-544 intersection);
    logs what was dropped and raises when nothing matches."""
    common = sorted(set(generated) & set(references))
    unpaired = (set(generated) | set(references)) - set(common)
    if unpaired:
        logger.info("skipping %d unpaired files", len(unpaired))
    if max_samples:
        common = common[:max_samples]
    if not common:
        raise ValueError("no common filenames between generated and references")
    return common


class SemanticEvaluator:
    def __init__(
        self,
        embedding_model: EmbeddingModel | None = None,
        use_stemmer: bool = True,
        include_llm_eval: bool = False,
        llm_judge=None,
        tracer=None,
    ) -> None:
        self.embedder = embedding_model or EmbeddingModel()
        self.rouge = RougeScorer(["rouge1", "rouge2", "rougeL"], use_stemmer)
        self.include_llm_eval = include_llm_eval
        self.llm_judge = llm_judge
        self.tracer = tracer

    def _span(self, name: str):
        if self.tracer is None:
            return contextlib.nullcontext()
        return self.tracer.span(name)

    def evaluate_pairs(
        self,
        generated: dict[str, str],
        references: dict[str, str],
        max_samples: int | None = None,
    ) -> dict:
        """Evaluate matching filenames; returns the results-JSON dict."""
        common = match_pairs(generated, references, max_samples)

        gen_texts = [generated[f] for f in common]
        ref_texts = [references[f] for f in common]

        # batched on-device embedding passes (one per side, not per pair)
        with self._span("embed"):
            gen_emb = self.embedder.sentence_embeddings(gen_texts)
            ref_emb = self.embedder.sentence_embeddings(ref_texts)
            sims = cosine_similarities(gen_emb, ref_emb)

        with self._span("bertscore"):
            bert = bert_scores(self.embedder, gen_texts, ref_texts)

        detailed = []
        r1, r2, rl = [], [], []
        for fname, g, r, sim in zip(common, gen_texts, ref_texts, sims):
            with self._span("rouge"):
                scores = self.rouge.score(r, g)
            r1.append(scores["rouge1"].fmeasure)
            r2.append(scores["rouge2"].fmeasure)
            rl.append(scores["rougeL"].fmeasure)
            detailed.append(
                {
                    "semantic_similarity": float(sim),
                    "rouge1_f": scores["rouge1"].fmeasure,
                    "rouge2_f": scores["rouge2"].fmeasure,
                    "rougeL_f": scores["rougeL"].fmeasure,
                    "filename": fname,
                }
            )

        stats = {
            "semantic_similarity": {
                "mean": float(np.mean(sims)),
                "std": float(np.std(sims)),
                "min": float(np.min(sims)),
                "max": float(np.max(sims)),
            },
            "rouge_scores": {
                "rouge1_f1": float(np.mean(r1)),
                "rouge2_f1": float(np.mean(r2)),
                "rougeL_f1": float(np.mean(rl)),
            },
            "bert_scores": {
                "bert_precision": float(np.mean([b.precision for b in bert])),
                "bert_recall": float(np.mean([b.recall for b in bert])),
                "bert_f1": float(np.mean([b.f1 for b in bert])),
            },
        }

        if self.include_llm_eval and self.llm_judge is not None:
            stats["llm_scores"] = self.llm_judge.evaluate(
                {f: generated[f] for f in common},
                {f: references[f] for f in common},
            )

        return {"summary_statistics": stats, "detailed_results": detailed}

    def evaluate_folders(
        self,
        generated_dir: str | Path,
        reference_dir: str | Path,
        max_samples: int | None = None,
        output: str | Path | None = None,
    ) -> dict:
        results = self.evaluate_pairs(
            load_summary_dir(generated_dir),
            load_summary_dir(reference_dir),
            max_samples=max_samples,
        )
        if output:
            Path(output).parent.mkdir(parents=True, exist_ok=True)
            Path(output).write_text(
                json.dumps(results, indent=2, ensure_ascii=False), encoding="utf-8"
            )
        return results
