from .embedding import BertScore, EmbeddingModel, bert_scores, cosine_similarities
from .geval import LLMJudge
from .rouge import RougeScorer, Score, tokenize
from .semantic import SemanticEvaluator, load_summary_dir

__all__ = [
    "BertScore",
    "EmbeddingModel",
    "bert_scores",
    "cosine_similarities",
    "LLMJudge",
    "RougeScorer",
    "Score",
    "tokenize",
    "SemanticEvaluator",
    "load_summary_dir",
]
