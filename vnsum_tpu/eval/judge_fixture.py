"""Trainable judge fixture: a tiny model that scores summaries 1-5.

VERDICT r4 missing #4 asked for an engine-as-judge G-Eval path that
produces real scores; round 5's constrained choice scorer
(``TpuBackend.score_choices``) made every case parse, but on an UNTRAINED
fixture the chosen digit is whatever byte the random logits favor —
degenerate 5/5 everywhere. This module closes the remaining caveat: it
builds a judging curriculum a 2-layer model can actually learn, so the
device-judge arm yields CONTENT-DEPENDENT scores with sane distributions
(reference judge loop: evaluate/evaluate_summaries_semantic.py:203-433).

The curriculum: reference summaries are sentences over a small Vietnamese
content lexicon; a "generated" summary at corruption level p has a
fraction p of its words replaced by tokens from a disjoint noise lexicon.
The supervised digit is 5 at p=0 down to 1 at p=1 — so the learnable
shortcut is noise-token density in the Generated-summary section, a
signal tiny attention heads can read. Prompts are built with the EXACT
``geval._JUDGE_TEMPLATE`` + ``LLMJudge._FORCED_PREFIX`` the production
judge sends, and the supervised token is ``encode(digit)[0]`` appended to
``encode(prompt)`` — the same first-token rule ``score_choices`` applies,
so training and inference agree positionally by construction.
"""
from __future__ import annotations

import random
from dataclasses import dataclass

from .geval import (
    COHERENCE_CRITERIA,
    CORRECTNESS_CRITERIA,
    _JUDGE_TEMPLATE,
    LLMJudge,
)

# Content lexicon: plausible Vietnamese summary vocabulary. Noise lexicon:
# tokens that never appear in clean summaries (the learnable marker).
CONTENT_WORDS = (
    "việt nam phát triển kinh tế xã hội văn hóa giáo dục khoa học công nghệ "
    "nông nghiệp du lịch thành phố nông thôn người dân chính phủ đầu tư "
    "tăng trưởng bền vững môi trường năng lượng sản xuất xuất khẩu thị "
    "trường lao động y tế cộng đồng truyền thống lịch sử tương lai"
).split()
NOISE_WORDS = (
    "zqxv kplw brzt fjdn xcvq wmzk qpgh vbnx ztrl hjkq "
    "drwp mnqz xlft qzvb wkrp"
).split()

LEVELS = (0.0, 0.25, 0.5, 0.75, 1.0)


def level_digit(p: float) -> int:
    """Corruption level -> supervised score digit (5 clean .. 1 garbage)."""
    return 5 - round(4 * p)


def make_summary(rng: random.Random, sentences: int = 3,
                 words_per_sentence: int = 8) -> str:
    out = []
    for _ in range(sentences):
        ws = [rng.choice(CONTENT_WORDS) for _ in range(words_per_sentence)]
        out.append(" ".join(ws).capitalize() + ".")
    return " ".join(out)


def corrupt(rng: random.Random, summary: str, p: float) -> str:
    words = summary.split()
    n_bad = round(p * len(words))
    idx = rng.sample(range(len(words)), n_bad)
    for i in idx:
        words[i] = rng.choice(NOISE_WORDS)
    return " ".join(words)


@dataclass
class JudgeCase:
    prompt: str  # full judge prompt incl. the forced '{"score": ' prefix
    digit: int  # supervised 1-5 verdict
    kind: str  # "correctness" | "coherence"
    level: float


def build_cases(n_per_level: int, seed: int = 0) -> list[JudgeCase]:
    """Balanced curriculum: for each corruption level, correctness prompts
    (generated vs reference) and coherence prompts (generated alone), built
    with the production template + forced prefix."""
    rng = random.Random(seed)
    cases: list[JudgeCase] = []
    for p in LEVELS:
        for _ in range(n_per_level):
            ref = make_summary(rng)
            gen = corrupt(rng, make_summary(rng) if p > 0 else ref, p)
            # p=0 uses gen == ref so "5" means verbatim-faithful; higher
            # levels corrupt an unrelated-but-in-lexicon summary
            corr = _JUDGE_TEMPLATE.format(
                criteria=CORRECTNESS_CRITERIA,
                body=f"Generated summary:\n{gen}\n\nReference summary:\n{ref}",
            ) + LLMJudge._FORCED_PREFIX
            coh = _JUDGE_TEMPLATE.format(
                criteria=COHERENCE_CRITERIA,
                body=f"Generated summary:\n{gen}",
            ) + LLMJudge._FORCED_PREFIX
            d = level_digit(p)
            cases.append(JudgeCase(corr, d, "correctness", p))
            cases.append(JudgeCase(coh, d, "coherence", p))
    rng.shuffle(cases)
    return cases


def curriculum_corpus(cases: list[JudgeCase]) -> list[str]:
    """Texts for BPE training: the full verdict lines ensure the ' <digit>'
    merges exist so the five choices have distinct first tokens
    (score_choices' single-token constraint)."""
    texts = [c.prompt + f'{c.digit}, "reason": "đánh giá"}}' for c in cases]
    # digit bigrams, repeated so BPE rank-orders the ' d' merges early
    texts += ['{"score": 1 {"score": 2 {"score": 3 {"score": 4 {"score": 5 '] * 8
    return texts


def train_judge_fixture(
    out_dir,
    n_per_level: int = 24,
    steps: int = 800,
    seed: int = 0,
    vocab_size: int = 512,
    lr: float = 2e-3,
    progress=None,
):
    """Train the tiny llama-family judge on the curriculum and
    save_pretrained it (HF checkpoint + tokenizer) to ``out_dir``.

    Loss is masked to the digit position only: the model learns exactly the
    mapping ``score_choices`` will query (next-token logits over the five
    digit tokens after the forced prefix). Returns (model, tokenizer,
    digit_token_ids)."""
    import torch
    import transformers

    from ..models.fixtures import KERNEL_SHAPE_OVERRIDES, train_bpe_tokenizer

    cases = build_cases(n_per_level, seed=seed)
    hf_tok = train_bpe_tokenizer(curriculum_corpus(cases), vocab_size=vocab_size)

    digit_ids = []
    for d in "12345":
        enc = hf_tok.encode(d)
        digit_ids.append(enc[0])
    if len(set(digit_ids)) != len(digit_ids):
        raise RuntimeError(
            f"digit choices collide in their first token: {digit_ids} — "
            "BPE did not learn distinct ' <digit>' merges"
        )

    # sequences: encode(prompt) + digit first-token, labels masked to the
    # digit (and the engine adds BOS at inference, so add it here too)
    bos = hf_tok.bos_token_id
    seqs = []
    for c in cases:
        ids = [bos] + hf_tok.encode(c.prompt)
        seqs.append((ids, digit_ids[c.digit - 1]))
    max_len = max(len(ids) + 1 for ids, _ in seqs)

    cfg = transformers.LlamaConfig(
        vocab_size=len(hf_tok),
        bos_token_id=hf_tok.bos_token_id,
        eos_token_id=hf_tok.eos_token_id,
        pad_token_id=hf_tok.pad_token_id,
        max_position_embeddings=max(1024, max_len),
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=True,
        num_hidden_layers=2,
        **KERNEL_SHAPE_OVERRIDES,
    )
    torch.manual_seed(seed)
    model = transformers.LlamaForCausalLM(cfg)

    pad = hf_tok.pad_token_id
    input_ids = torch.full((len(seqs), max_len), pad, dtype=torch.long)
    labels = torch.full((len(seqs), max_len), -100, dtype=torch.long)
    attn = torch.zeros((len(seqs), max_len), dtype=torch.long)
    for i, (ids, digit_tok) in enumerate(seqs):
        L = len(ids)
        input_ids[i, :L] = torch.tensor(ids)
        input_ids[i, L] = digit_tok
        labels[i, L] = digit_tok  # HF shifts internally: position L-1 predicts L
        attn[i, : L + 1] = 1

    opt = torch.optim.AdamW(model.parameters(), lr=lr)
    sched = torch.optim.lr_scheduler.CosineAnnealingLR(
        opt, T_max=steps, eta_min=lr / 10
    )
    gen = torch.Generator().manual_seed(seed)
    model.train()
    n = len(seqs)
    for step in range(steps):
        rows = torch.randint(0, n, (min(24, n),), generator=gen)
        out = model(
            input_ids=input_ids[rows],
            attention_mask=attn[rows],
            labels=labels[rows],
        )
        opt.zero_grad()
        out.loss.backward()
        opt.step()
        sched.step()
        if progress is not None and (step % 50 == 0 or step == steps - 1):
            progress(step, float(out.loss.detach()))
    model.eval()
    model.save_pretrained(out_dir, safe_serialization=True)
    hf_tok.save_pretrained(out_dir)
    return model, hf_tok, digit_ids
