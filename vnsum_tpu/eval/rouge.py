"""Self-contained ROUGE-1/2/L scorer.

Exact behavioral port of the google-research `rouge_score` package's scoring
path as the reference uses it (evaluate/evaluate_summaries_semantic.py:132-143:
RougeScorer(['rouge1','rouge2','rougeL'], use_stemmer=True)), including its
ASCII-only tokenization (lowercase, non-[a-z0-9] stripped — which is what the
reference's committed Vietnamese numbers were produced with) and the Porter
stemmer applied to tokens longer than 3 chars. Golden-tested against
rouge_score + NLTK in tests/test_eval_rouge.py.
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Sequence

_NON_ALNUM = re.compile(r"[^a-z0-9]+")
# keep_unicode alphabet: any Unicode word char except underscore
_NON_WORD_UNI = re.compile(r"[^\w]+|_+", re.UNICODE)


@dataclass(frozen=True)
class Score:
    precision: float
    recall: float
    fmeasure: float


def _fmeasure(p: float, r: float) -> float:
    return 2 * p * r / (p + r) if p + r else 0.0


# -- Porter stemmer ---------------------------------------------------------
# Behavioral match for NLTK's PorterStemmer in its default NLTK_EXTENSIONS
# mode — the mode rouge_score actually constructs — including the irregular
# pool, the ies/ied 4-letter rules, the consonant-y rule in step 1c, the
# alli-first recursion and logi/fulli rules in step 2, and the 2-letter vc
# case of *o. Fuzz-tested against nltk in tests/test_eval_rouge.py.

_IRREGULAR = {
    "skies": "sky", "sky": "sky", "dying": "die", "lying": "lie",
    "tying": "tie", "news": "news", "innings": "inning", "inning": "inning",
    "outings": "outing", "outing": "outing", "cannings": "canning",
    "canning": "canning", "howe": "howe", "proceed": "proceed",
    "exceed": "exceed", "succeed": "succeed",
}


class PorterStemmer:
    _VOWELS = frozenset("aeiou")

    def _is_cons(self, word: str, i: int) -> bool:
        ch = word[i]
        if ch in self._VOWELS:
            return False
        if ch == "y":
            return True if i == 0 else not self._is_cons(word, i - 1)
        return True

    def _measure(self, stem: str) -> int:
        seq = "".join(
            "c" if self._is_cons(stem, i) else "v" for i in range(len(stem))
        )
        return seq.count("vc")

    def _m_gt0(self, stem: str) -> bool:
        return self._measure(stem) > 0

    def _m_gt1(self, stem: str) -> bool:
        return self._measure(stem) > 1

    def _has_vowel(self, stem: str) -> bool:
        return any(not self._is_cons(stem, i) for i in range(len(stem)))

    def _ends_double_cons(self, word: str) -> bool:
        return (
            len(word) >= 2
            and word[-1] == word[-2]
            and self._is_cons(word, len(word) - 1)
        )

    def _cvc(self, word: str) -> bool:
        if (
            len(word) >= 3
            and self._is_cons(word, len(word) - 3)
            and not self._is_cons(word, len(word) - 2)
            and self._is_cons(word, len(word) - 1)
            and word[-1] not in "wxy"
        ):
            return True
        # NLTK extension: 2-letter vc counts as *o
        return (
            len(word) == 2
            and not self._is_cons(word, 0)
            and self._is_cons(word, 1)
        )

    def _apply_rules(self, word: str, rules) -> str:
        """First rule whose suffix matches wins; a failed condition on a
        matched suffix stops the whole step (NLTK _apply_rule_list)."""
        for suffix, repl, cond in rules:
            if suffix == "*d":
                if self._ends_double_cons(word):
                    stem = word[:-2]
                    return stem + repl if cond is None or cond(stem) else word
                continue
            if word.endswith(suffix):
                stem = word[: len(word) - len(suffix)] if suffix else word
                return stem + repl if cond is None or cond(stem) else word
        return word

    def _step1a(self, w: str) -> str:
        if w.endswith("ies") and len(w) == 4:
            return w[:-3] + "ie"
        return self._apply_rules(
            w,
            [("sses", "ss", None), ("ies", "i", None), ("ss", "ss", None),
             ("s", "", None)],
        )

    def _step1b(self, w: str) -> str:
        if w.endswith("ied"):
            return w[:-3] + ("ie" if len(w) == 4 else "i")
        if w.endswith("eed"):
            stem = w[:-3]
            return stem + "ee" if self._m_gt0(stem) else w
        inter = None
        for suffix in ("ed", "ing"):
            if w.endswith(suffix):
                stem = w[: len(w) - len(suffix)]
                if self._has_vowel(stem):
                    inter = stem
                break
        if inter is None:
            return w
        return self._apply_rules(
            inter,
            [
                ("at", "ate", None),
                ("bl", "ble", None),
                ("iz", "ize", None),
                ("*d", inter[-1], lambda s: inter[-1] not in ("l", "s", "z")),
                ("", "e", lambda s: self._measure(s) == 1 and self._cvc(s)),
            ],
        )

    def _step1c(self, w: str) -> str:
        # y -> i only when preceded by a consonant in a >1-char stem
        return self._apply_rules(
            w,
            [("y", "i",
              lambda s: len(s) > 1 and self._is_cons(s, len(s) - 1))],
        )

    def _step2(self, w: str) -> str:
        if w.endswith("alli") and self._m_gt0(w[:-4]):
            return self._step2(w[:-4] + "al")
        rules = [
            ("ational", "ate", self._m_gt0), ("tional", "tion", self._m_gt0),
            ("enci", "ence", self._m_gt0), ("anci", "ance", self._m_gt0),
            ("izer", "ize", self._m_gt0), ("bli", "ble", self._m_gt0),
            ("alli", "al", self._m_gt0), ("entli", "ent", self._m_gt0),
            ("eli", "e", self._m_gt0), ("ousli", "ous", self._m_gt0),
            ("ization", "ize", self._m_gt0), ("ation", "ate", self._m_gt0),
            ("ator", "ate", self._m_gt0), ("alism", "al", self._m_gt0),
            ("iveness", "ive", self._m_gt0), ("fulness", "ful", self._m_gt0),
            ("ousness", "ous", self._m_gt0), ("aliti", "al", self._m_gt0),
            ("iviti", "ive", self._m_gt0), ("biliti", "ble", self._m_gt0),
            ("fulli", "ful", self._m_gt0),
            # the 'l' of 'logi' stays with the stem
            ("logi", "log", lambda s: self._m_gt0(w[:-3])),
        ]
        return self._apply_rules(w, rules)

    def _step3(self, w: str) -> str:
        return self._apply_rules(
            w,
            [
                ("icate", "ic", self._m_gt0), ("ative", "", self._m_gt0),
                ("alize", "al", self._m_gt0), ("iciti", "ic", self._m_gt0),
                ("ical", "ic", self._m_gt0), ("ful", "", self._m_gt0),
                ("ness", "", self._m_gt0),
            ],
        )

    def _step4(self, w: str) -> str:
        return self._apply_rules(
            w,
            [
                ("al", "", self._m_gt1), ("ance", "", self._m_gt1),
                ("ence", "", self._m_gt1), ("er", "", self._m_gt1),
                ("ic", "", self._m_gt1), ("able", "", self._m_gt1),
                ("ible", "", self._m_gt1), ("ant", "", self._m_gt1),
                ("ement", "", self._m_gt1), ("ment", "", self._m_gt1),
                ("ent", "", self._m_gt1),
                ("ion", "",
                 lambda s: self._m_gt1(s) and bool(s) and s[-1] in ("s", "t")),
                ("ou", "", self._m_gt1), ("ism", "", self._m_gt1),
                ("ate", "", self._m_gt1), ("iti", "", self._m_gt1),
                ("ous", "", self._m_gt1), ("ive", "", self._m_gt1),
                ("ize", "", self._m_gt1),
            ],
        )

    def _step5a(self, w: str) -> str:
        if w.endswith("e"):
            stem = w[:-1]
            if self._m_gt1(stem):
                return stem
            if self._measure(stem) == 1 and not self._cvc(stem):
                return stem
        return w

    def _step5b(self, w: str) -> str:
        return self._apply_rules(
            w, [("ll", "l", lambda s: self._m_gt1(w[:-1]))]
        )

    def stem(self, word: str) -> str:
        w = word.lower()
        if w in _IRREGULAR:
            return _IRREGULAR[w]
        if len(word) <= 2:
            return w
        for step in (
            self._step1a, self._step1b, self._step1c, self._step2,
            self._step3, self._step4, self._step5a, self._step5b,
        ):
            w = step(w)
        return w


_STEMMER = PorterStemmer()


def tokenize(
    text: str, use_stemmer: bool = True, keep_unicode: bool = False
) -> list[str]:
    """rouge_score tokenization: lowercase, strip non-[a-z0-9], stem len>3.

    ``keep_unicode=False`` (default) reproduces rouge_score EXACTLY —
    including its ASCII-only alphabet, which shreds Vietnamese words into
    diacritic-free fragments ('tóm tắt' → ['t','m','t','t']). The
    reference's ROUGE numbers are computed this way (its evaluate stack
    imports rouge_score verbatim), so parity demands it stay the default.
    ``keep_unicode=True`` keeps any Unicode word character instead, scoring
    Vietnamese on whole words; the Porter stemmer (English-only) is then
    applied only to pure-ASCII tokens."""
    text = text.lower()
    if keep_unicode:
        # NFC first: Python's \w does not match combining marks (Mn), so
        # NFD input ('o' + U+0301) would shred at every diacritic — the
        # exact failure this mode exists to avoid. The parity path is NOT
        # normalized: rouge_score doesn't, and parity means byte-for-byte
        import unicodedata

        text = unicodedata.normalize("NFC", text)
        text = _NON_WORD_UNI.sub(" ", text)
        tokens = [t for t in text.split() if t]
        if use_stemmer:
            tokens = [
                _STEMMER.stem(t) if len(t) > 3 and t.isascii() else t
                for t in tokens
            ]
        return tokens
    text = _NON_ALNUM.sub(" ", text)
    tokens = [t for t in text.split() if t]
    if use_stemmer:
        tokens = [_STEMMER.stem(t) if len(t) > 3 else t for t in tokens]
    return tokens


def _ngram_counts(tokens: Sequence[str], n: int) -> dict:
    counts: dict = {}
    for i in range(len(tokens) - n + 1):
        g = tuple(tokens[i : i + n])
        counts[g] = counts.get(g, 0) + 1
    return counts


def _score_ngrams(target: Sequence[str], prediction: Sequence[str], n: int) -> Score:
    t_counts = _ngram_counts(target, n)
    p_counts = _ngram_counts(prediction, n)
    overlap = sum(min(c, p_counts.get(g, 0)) for g, c in t_counts.items())
    t_total = max(sum(t_counts.values()), 0)
    p_total = max(sum(p_counts.values()), 0)
    precision = overlap / p_total if p_total else 0.0
    recall = overlap / t_total if t_total else 0.0
    return Score(precision, recall, _fmeasure(precision, recall))


def _lcs_len(a: Sequence[str], b: Sequence[str]) -> int:
    if not a or not b:
        return 0
    prev = [0] * (len(b) + 1)
    for i in range(1, len(a) + 1):
        cur = [0] * (len(b) + 1)
        ai = a[i - 1]
        for j in range(1, len(b) + 1):
            if ai == b[j - 1]:
                cur[j] = prev[j - 1] + 1
            else:
                cur[j] = max(prev[j], cur[j - 1])
        prev = cur
    return prev[len(b)]


def _score_lcs(target: Sequence[str], prediction: Sequence[str]) -> Score:
    if not target or not prediction:
        return Score(0.0, 0.0, 0.0)
    lcs = _lcs_len(target, prediction)
    precision = lcs / len(prediction)
    recall = lcs / len(target)
    return Score(precision, recall, _fmeasure(precision, recall))


class RougeScorer:
    """API-compatible subset of rouge_score.rouge_scorer.RougeScorer.

    Scoring runs through the C++ core (vnsum_tpu.native) when the library is
    available — the O(n·m) ROUGE-L LCS dominates host-side eval cost — and
    falls back to the pure-Python path with identical results otherwise
    (equality fuzz-tested in tests/test_native.py)."""

    def __init__(
        self,
        rouge_types: Sequence[str],
        use_stemmer: bool = True,
        use_native: bool | None = None,
        keep_unicode: bool = False,
    ):
        for rt in rouge_types:
            if rt not in ("rouge1", "rouge2", "rougeL"):
                raise ValueError(f"unsupported rouge type {rt!r}")
        self.rouge_types = list(rouge_types)
        self.use_stemmer = use_stemmer
        # keep_unicode scores on whole Unicode words (see tokenize); the C++
        # core implements the ASCII rouge_score tokenizer, so this mode runs
        # the Python path
        self.keep_unicode = keep_unicode
        if use_native is None:
            from ..native import available

            use_native = available() and not keep_unicode
        elif use_native and keep_unicode:
            raise ValueError(
                "keep_unicode tokenization is Python-only (the native core "
                "implements rouge_score's ASCII tokenizer)"
            )
        self.use_native = use_native

    def score(self, target: str, prediction: str) -> dict[str, Score]:
        if self.use_native:
            from ..native import rouge_score_native

            try:
                raw = rouge_score_native(target, prediction, self.use_stemmer)
                return {rt: Score(*raw[rt]) for rt in self.rouge_types}
            except ValueError:
                pass  # embedded NUL: score this pair on the Python path
        t = tokenize(target, self.use_stemmer, self.keep_unicode)
        p = tokenize(prediction, self.use_stemmer, self.keep_unicode)
        out: dict[str, Score] = {}
        for rt in self.rouge_types:
            if rt == "rouge1":
                out[rt] = _score_ngrams(t, p, 1)
            elif rt == "rouge2":
                out[rt] = _score_ngrams(t, p, 2)
            else:
                out[rt] = _score_lcs(t, p)
        return out
