"""LLM-judge G-Eval (correctness vs reference, coherence standalone).

Port of the reference's DeepEval + OpenRouter path
(evaluate/evaluate_summaries_semantic.py:203-433) without the deepeval
dependency: the judge prompt asks for a 1-5 rating which is normalized to
0-1 like G-Eval does; criteria texts are verbatim (:275-300). Works against
any OpenAI-compatible chat endpoint, or a local Backend for offline judging.
Per-case failures are contained (:318-376) so one bad call never voids a run.
"""
from __future__ import annotations

import json
import re

import numpy as np

from ..core.logging import get_logger

logger = get_logger("vnsum.geval")

CORRECTNESS_CRITERIA = """
        Correctness (1-5): Measures how accurately the generated summary captures the key information and main points from the reference summary.
        Criteria:
        - How much correct information does the generated summary contain compare to the reference summary?
        - Does the generated summay contains contradictions with the source document?
        - How well does the generated summary cover key points and main themes (or events) with respect to the reference?
        """

COHERENCE_CRITERIA = """
        Coherence (1-5): Measures the logical flow, structure, and organization of the generated summary.
        The summary should:
        - Have a clear and logical structure that flows from sentence to sentence
        - Be well-organized with coherent progression of ideas
        - Maintain consistency in style and tone throughout
        - Not be just a collection of random facts, but a cohesive narrative
        - Use appropriate transitions and connections between concepts
        """

_JUDGE_TEMPLATE = """You are an expert evaluator of text summaries.

Evaluation criteria:
{criteria}

{body}

Respond with ONLY a JSON object: {{"score": <number 1-5>, "reason": "<short reason>"}}
"""

_SCORE_RE = re.compile(r'"score"\s*:\s*([0-9.]+)')


def _parse_score(text: str) -> float | None:
    m = _SCORE_RE.search(text)
    if not m:
        m = re.search(r"\b([1-5](?:\.\d+)?)\b", text)
    if not m:
        return None
    raw = float(m.group(1))
    if not 1.0 <= raw <= 5.0:
        return None
    return (raw - 1.0) / 4.0  # normalize 1-5 -> 0-1 like G-Eval


class LLMJudge:
    """Judge over a Backend-protocol generator (local) or an OpenAI-compatible
    HTTP endpoint (set api_base/api_key/model, e.g. OpenRouter)."""

    def __init__(
        self,
        backend=None,
        api_base: str | None = None,
        api_key: str | None = None,
        model: str = "openai/gpt-4o-mini",
        max_new_tokens: int = 256,
        constrained: bool = False,
    ) -> None:
        if backend is None and api_base is None:
            raise ValueError("LLMJudge needs a local backend or an api_base")
        if constrained and not hasattr(backend, "score_choices"):
            raise ValueError(
                "constrained=True needs a backend with score_choices "
                "(TpuBackend's constrained choice scorer)"
            )
        self.backend = backend
        self.api_base = api_base.rstrip("/") if api_base else None
        self.api_key = api_key
        self.model = model
        self.max_new_tokens = max_new_tokens
        # constrained mode: instead of free-decoding the verdict JSON, the
        # judge prompt is extended with the forced prefix `{"score": ` and
        # the engine picks the score digit by next-token logits over
        # {"1".."5"} (TpuBackend.score_choices). The device chooses the
        # score; the host assembles the JSON — parse failures become
        # structurally impossible, which is what lets the engine-as-judge
        # path produce real llm_scores (VERDICT r4 missing #4)
        self.constrained = constrained

    _FORCED_PREFIX = '\n{"score": '

    def _complete(self, prompts: list[str]) -> list[str]:
        if self.backend is not None:
            if self.constrained:
                idx = self.backend.score_choices(
                    [p + self._FORCED_PREFIX for p in prompts],
                    ["1", "2", "3", "4", "5"],
                )
                return [
                    f'{{"score": {i + 1}, '
                    f'"reason": "constrained single-token choice"}}'
                    for i in idx
                ]
            return self.backend.generate(prompts, max_new_tokens=self.max_new_tokens)
        import requests

        outs = []
        for p in prompts:
            resp = requests.post(
                f"{self.api_base}/chat/completions",
                headers={"Authorization": f"Bearer {self.api_key}"},
                json={
                    "model": self.model,
                    "messages": [{"role": "user", "content": p}],
                    "max_tokens": self.max_new_tokens,
                },
                timeout=120,
            )
            resp.raise_for_status()
            outs.append(resp.json()["choices"][0]["message"]["content"])
        return outs

    def evaluate(
        self, generated: dict[str, str], references: dict[str, str]
    ) -> dict:
        """Returns the llm_scores stats block of the results schema."""
        files = sorted(set(generated) & set(references))
        correctness: list[float] = []
        coherence: list[float] = []
        failed = 0
        for fname in files:
            try:
                corr_prompt = _JUDGE_TEMPLATE.format(
                    criteria=CORRECTNESS_CRITERIA,
                    body=(
                        f"Generated summary:\n{generated[fname]}\n\n"
                        f"Reference summary:\n{references[fname]}"
                    ),
                )
                coh_prompt = _JUDGE_TEMPLATE.format(
                    criteria=COHERENCE_CRITERIA,
                    body=f"Generated summary:\n{generated[fname]}",
                )
                corr_out, coh_out = self._complete([corr_prompt, coh_prompt])
                c1, c2 = _parse_score(corr_out), _parse_score(coh_out)
                if c1 is None or c2 is None:
                    raise ValueError("judge returned no parseable score")
                correctness.append(c1)
                coherence.append(c2)
            except Exception as e:  # per-case containment (ref :373-376)
                failed += 1
                logger.warning("G-Eval failed for %s: %s", fname, e)

        def _stats(prefix: str, vals: list[float]) -> dict:
            if not vals:
                return {f"{prefix}_mean": 0.0, f"{prefix}_std": 0.0,
                        f"{prefix}_min": 0.0, f"{prefix}_max": 0.0}
            return {
                f"{prefix}_mean": float(np.mean(vals)),
                f"{prefix}_std": float(np.std(vals)),
                f"{prefix}_min": float(np.min(vals)),
                f"{prefix}_max": float(np.max(vals)),
            }

        return {
            **_stats("llm_correctness", correctness),
            **_stats("llm_coherence", coherence),
            "llm_successful_cases": len(correctness),
            "llm_failed_cases": failed,
            "llm_total_cases_processed": len(files),
        }
