"""Typed configuration for the pipeline and strategies.

Mirrors the semantics of the reference's dict-based config
(run_full_evaluation_pipeline.py:973-1027) — same knob names and defaults —
but as dataclasses with validation, serialization, and per-approach defaults,
so every run record embeds the exact config it ran with.
"""
from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Literal

ApproachName = Literal[
    "mapreduce",
    "mapreduce_critique",
    "iterative",
    "truncated",
    "mapreduce_hierarchical",
    "skeleton",
]

APPROACHES: tuple[str, ...] = (
    "mapreduce",
    "mapreduce_critique",
    "iterative",
    "truncated",
    "mapreduce_hierarchical",
    "skeleton",
)


@dataclass(frozen=True)
class GenerationConfig:
    """Decoding parameters for one backend.generate() call."""

    # None = inherit the backend's constructor default; a config passed only
    # to set temperature/eos must not silently override the decode budget
    max_new_tokens: int | None = None
    temperature: float = 0.0  # 0.0 => greedy (ref: run_summarization.py:44)
    top_k: int = 0            # 0 => disabled
    top_p: float = 1.0
    eos_ids: tuple[int, ...] = ()
    seed: int = 0
    # reference-guided speculative decoding (vnsum_tpu.spec): propose up to
    # spec_k continuation tokens per row by n-gram matching the emitted
    # stream against the request's reference text (backend.generate's
    # per-prompt `references`), verified in one batched forward. 0 = off —
    # the default engine decode path is untouched and outputs are
    # bit-identical to pre-spec builds. Greedy outputs are identical at ANY
    # spec_k (acceptance is exact argmax prefix match); sampling stays
    # distribution-lossless but consumes randomness differently.
    spec_k: int = 0
    # longest emitted-stream suffix the drafter tries to match (>=1)
    spec_ngram: int = 3

    def with_(self, **kw) -> "GenerationConfig":
        return dataclasses.replace(self, **kw)


@dataclass
class EvalConfig:
    """Evaluation stack settings (ref run_full_evaluation_pipeline.py:984-990)."""

    embedding_model: str = "all-MiniLM-L6-v2"
    # local HF BERT-family checkpoint dir (config.json + safetensors +
    # tokenizer); when set, BERTScore/semsim run with converted pretrained
    # weights (comparable to BASELINE.md) instead of random init
    embedding_dir: str | None = None
    include_llm_eval: bool = False
    use_openrouter: bool = True
    llm_model: str = "openai/gpt-4o-mini"
    # local judge: run G-Eval through the Backend protocol instead of an
    # HTTP endpoint — the offline path for air-gapped hosts. Forms:
    # "fake" (CI), "ollama:<model>", "tpu:<registry-name>" (random weights —
    # plumbing/containment only). Takes precedence over API keys.
    judge_backend: str | None = None
    max_samples: int | None = None
    bert_batch_size: int = 32


@dataclass
class PipelineConfig:
    """Full pipeline configuration.

    Defaults follow the reference base_config + per-approach configs
    (run_full_evaluation_pipeline.py:973-1027); `approach_defaults()` applies
    the per-approach overrides.
    """

    approach: str = "mapreduce"
    models: list[str] = field(default_factory=lambda: ["llama3.2-3b"])
    backend: str = "tpu"  # tpu | ollama | fake
    ollama_url: str = "http://localhost:11434"
    max_new_tokens: int = 1024
    docs_dir: str = "data_1/doc"
    summary_dir: str = "data_1/summary"
    generated_summaries_dir: str = "data_1/generated_summaries"
    results_dir: str = "evaluation_results"
    logs_dir: str = "logs"
    max_samples: int | None = None

    # chunking (mapreduce / critique / hierarchical)
    chunk_size: int = 12000
    chunk_overlap: int = 200
    token_max: int = 10000

    # iterative
    iterative_chunk_size: int = 12000
    iterative_chunk_overlap: int = 200

    # truncated
    max_context: int = 16384

    # critique
    max_critique_iterations: int = 2

    # hierarchical
    max_depth: int = 1
    tree_json_path: str = "data_1/document_tree.json"

    # failure containment: re-submit a failed document batch this many extra
    # times before recording its documents as failed (reference: none —
    # SURVEY.md §5 "No retries anywhere")
    max_batch_retries: int = 1
    retry_backoff: float = 1.0

    # engine
    batch_size: int = 8
    # documents submitted to the strategy per round; 0 = auto (4x batch_size).
    # Bigger groups pack map/collapse/reduce calls into fuller device batches
    # (a group of batch_size docs leaves reduce rounds running B=2/B=4
    # half-empty dispatches — each a fresh bucket compile); the cost is
    # coarser resume granularity (summaries write per group)
    doc_group_size: int = 0
    tokenizer: str = "byte"  # byte | hf:<name-or-path>
    mesh_shape: dict[str, int] = field(default_factory=dict)
    # opt-in: when mesh_shape needs more devices than the default platform
    # has, rebuild the mesh on host CPU devices (tests, dry runs, artifact
    # scripts). Off by default so a production TPU run with an oversized
    # --mesh fails loudly instead of silently running ~100x slower on CPU
    allow_cpu_mesh: bool = False
    # ring-attention prefill + seq-sharded decode (backend/long_context.py):
    # prompts run UN-truncated up to seq_axis × the one-chip limit; requires
    # backend=tpu and a mesh with a seq axis > 1
    long_context: bool = False
    # int8-quantize the long-context prefill KV cache. LOSSY (per-position
    # int8 round-trip on cached K/V) but halves ring-decode HBM traffic —
    # the dominant cost of long-context decode. Off by default because
    # `quantize` alone promises exact weight-only quantization
    long_context_quantize_kv: bool = False
    # int8 weight-only quantization (per-output-channel scales — exact
    # w.r.t. the quantized weights; models/quant.py). The engine's decode is
    # weight-bandwidth-bound, so this is most of the single-chip speedup
    quantize: bool = False
    # W8A8 prefill: ALSO int8-quantize activations (per-token absmax) into
    # the prefill matmuls — double-rate s8xs8 MXU dots. LOSSY (activation
    # rounding ~1/127 per matmul input), so off by default; quality runs
    # should A/B it. Requires quantize=True
    quantize_act: bool = False
    dtype: str = "bfloat16"
    # local HF checkpoint dir (config.json + *.safetensors + tokenizer files)
    # for the tpu backend: weights are converted via models.convert and the
    # checkpoint's tokenizer is used unless `tokenizer` is explicitly hf:<..>
    weights_dir: str | None = None

    evaluation: EvalConfig = field(default_factory=EvalConfig)

    def __post_init__(self) -> None:
        if self.approach not in APPROACHES:
            raise ValueError(
                f"unknown approach {self.approach!r}; expected one of {APPROACHES}"
            )
        if self.long_context_quantize_kv and not self.long_context:
            raise ValueError(
                "long_context_quantize_kv requires long_context=True — the "
                "one-chip engine ignores it, so the run would claim an int8 "
                "prefill cache while using the exact one"
            )
        if self.chunk_overlap >= self.chunk_size:
            raise ValueError("chunk_overlap must be smaller than chunk_size")
        if self.iterative_chunk_overlap >= self.iterative_chunk_size:
            raise ValueError(
                "iterative_chunk_overlap must be smaller than iterative_chunk_size"
            )
        if self.weights_dir and len(self.models) > 1:
            raise ValueError(
                "weights_dir points at ONE checkpoint; with multiple models "
                "every entry would silently run the same weights — run one "
                "model per weights_dir"
            )
        if self.weights_dir and self.backend != "tpu":
            raise ValueError(
                f"weights_dir requires backend='tpu' (got {self.backend!r}); "
                "other backends would silently ignore the checkpoint and "
                "evaluate a different model"
            )
        if self.quantize and self.backend != "tpu":
            raise ValueError(
                f"quantize requires backend='tpu' (got {self.backend!r}); "
                "other backends would silently run full-precision while the "
                "run record claims int8"
            )
        if self.quantize_act and not self.quantize:
            raise ValueError(
                "quantize_act (W8A8 prefill) requires quantize=True — "
                "without int8 weights there is no s8xs8 matmul to run"
            )
        if self.quantize_act and self.long_context:
            raise ValueError(
                "quantize_act is one-chip-engine only; the long-context "
                "ring prefill would silently run weight-only while the run "
                "record claims W8A8"
            )
        if self.long_context:
            if self.backend != "tpu":
                raise ValueError(
                    f"long_context requires backend='tpu' (got {self.backend!r})"
                )
            if self.mesh_shape.get("seq", 1) < 2:
                raise ValueError(
                    "long_context requires a mesh with a seq axis > 1 "
                    "(e.g. --mesh seq=4,data=2) — the seq axis is what "
                    "multiplies the context ceiling"
                )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, ensure_ascii=False)

    @classmethod
    def from_dict(cls, d: dict) -> "PipelineConfig":
        d = dict(d)
        ev = d.pop("evaluation", None)
        known = {f.name for f in dataclasses.fields(cls)}
        extra = {k: v for k, v in d.items() if k not in known}
        if extra:
            raise ValueError(f"unknown config keys: {sorted(extra)}")
        cfg = cls(**d)
        if ev is not None:
            cfg.evaluation = EvalConfig(**ev) if isinstance(ev, dict) else ev
        return cfg


def approach_defaults(approach: str) -> dict:
    """Per-approach config overrides, matching the reference's approach_config
    blocks (run_full_evaluation_pipeline.py:993-1027)."""
    if approach == "mapreduce":
        return {"chunk_size": 12000, "chunk_overlap": 200, "token_max": 10000}
    if approach == "iterative":
        return {"iterative_chunk_size": 12000, "iterative_chunk_overlap": 200}
    if approach == "truncated":
        return {"max_context": 16384}
    if approach == "mapreduce_critique":
        return {
            "chunk_size": 12000,
            "chunk_overlap": 200,
            "token_max": 10000,
            "max_critique_iterations": 2,
            "max_new_tokens": 2048,
        }
    if approach == "mapreduce_hierarchical":
        return {"chunk_size": 12000, "chunk_overlap": 200, "max_depth": 1}
    if approach == "skeleton":
        # Skeleton-of-Thought (arXiv 2307.15337): same context contract as
        # truncated — the outline/expand fan-out runs over what fits
        return {"max_context": 16384}
    raise ValueError(f"unknown approach: {approach}")
