"""Crash-safe artifact writes: write-temp + fsync + ``os.replace``.

Every bench/runbook artifact in this repo is a JSON file some later run (or
the CI no-worse guard) reads back; a plain ``Path.write_text`` interrupted
by a crash leaves a truncated file that poisons the next resume (the
north-star runner checkpoints after every approach exactly to survive
crashes — a torn checkpoint would defeat it). These helpers make the write
atomic: the complete new content lands in a temp file in the SAME directory
(``os.replace`` is only atomic within a filesystem), is fsynced, and then
renamed over the target — a reader sees the old file or the new file, never
a prefix.

The ``# durable`` markers are load-bearing: the ``durable-write`` analysis
rule (vnsum_tpu/analysis/rules/durable.py) verifies each marked function
carries the full write+flush+fsync+replace sequence, so the crash-safety
claim is machine-checked rather than a comment that can rot.
"""
from __future__ import annotations

import json
import os
import tempfile
from pathlib import Path


# durable
def atomic_write_text(path: str | Path, text: str,
                      encoding: str = "utf-8") -> Path:
    """Atomically replace ``path`` with ``text``; parents are created."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=str(path.parent), prefix=path.name + ".", suffix=".tmp"
    )
    try:
        with os.fdopen(fd, "w", encoding=encoding) as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(path.parent)
    return path


def atomic_write_json(path: str | Path, obj, indent: int | None = 2) -> Path:
    """Atomically write ``obj`` as JSON (trailing newline, like the benches
    have always committed their artifacts)."""
    return atomic_write_text(
        path, json.dumps(obj, indent=indent, ensure_ascii=False) + "\n"
    )


def fsync_dir(directory: str | Path) -> None:
    """Make a rename in ``directory`` itself durable; best-effort on
    platforms whose directories can't be opened (Windows). Shared by the
    atomic writers here and the journal's compaction (serve/journal.py)."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
