from .config import (
    ApproachName,
    EvalConfig,
    GenerationConfig,
    PipelineConfig,
    approach_defaults,
)
from .faults import (
    FaultInjectingBackend,
    FaultPlan,
    FaultRule,
    RetryingBackend,
    call_with_retries,
)
from .logging import get_logger, setup_run_logging
from .profiling import Tracer, annotate, device_profile
from .results import DocumentRecord, ModelRunRecord, PipelineResults

__all__ = [
    "Tracer",
    "annotate",
    "device_profile",
    "ApproachName",
    "EvalConfig",
    "GenerationConfig",
    "PipelineConfig",
    "approach_defaults",
    "FaultInjectingBackend",
    "FaultPlan",
    "FaultRule",
    "RetryingBackend",
    "call_with_retries",
    "get_logger",
    "setup_run_logging",
    "DocumentRecord",
    "ModelRunRecord",
    "PipelineResults",
]
