"""Tracing / profiling subsystem.

The reference has no profiler integration — only LangSmith `@traceable` on one
driver (runners/run_summarization_ollama_mapreduce_critique.py:21,403, active
only when LangSmith env vars are set) and manual wall-clock spans stored in the
run record (run_full_evaluation_pipeline.py:439,572-591). This module keeps
those capabilities and makes them first-class:

- `Tracer.span(name)` — nested wall-clock spans with aggregated statistics,
  thread-safe (strategy batches may fan out over a thread pool), persisted in
  the structured run record instead of log lines. Rebased onto the obs span
  model (`obs/trace.SpanRecorder`): pipeline runs and the serving layer now
  share ONE span primitive, so a pipeline run can export the same
  Perfetto-loadable Chrome trace the serving `/debug/trace` endpoint serves
  (`Tracer.chrome_trace()`, written next to results by pipeline/runner.py
  when profiling is armed).
- `device_profile(log_dir)` — `jax.profiler.trace` wrapper producing TensorBoard
  / Perfetto traces of the on-device work (the TPU-native analog of the
  reference's LangSmith tracing). Gated: no-op unless a directory is given or
  `VNSUM_PROFILE_DIR` is set, mirroring the reference's env-gated LangSmith
  activation (...critique.py:22-23).
- `annotate(name)` — `jax.profiler.TraceAnnotation` passthrough so host-side
  phases show up inside device traces.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass

from ..obs.trace import Span, SpanRecorder


@dataclass
class SpanStats:
    count: int = 0
    total_s: float = 0.0
    min_s: float = float("inf")
    max_s: float = 0.0

    def add(self, duration: float) -> None:
        self.count += 1
        self.total_s += duration
        self.min_s = min(self.min_s, duration)
        self.max_s = max(self.max_s, duration)

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "total_s": self.total_s,
            "mean_s": self.total_s / self.count if self.count else 0.0,
            "min_s": self.min_s if self.count else 0.0,
            "max_s": self.max_s,
        }


class Tracer:
    """Aggregating wall-clock tracer over the shared obs span model.

    Span names are hierarchical: nested spans get `parent/child` keys, so the
    run record shows e.g. `summarize/batch` under `summarize`. One Tracer is
    shared per pipeline run; use `reset()` between runs.

    Two views of the same spans: `stats()` aggregates per name (bounded
    state, any run length — what lands in the run record), and `timeline()`
    keeps the first `timeline_maxlen` raw spans for `chrome_trace()` export.
    The recorder's `on_close` hook feeds aggregation, so the two views can
    never disagree about a span's duration.
    """

    def __init__(self, timeline_maxlen: int = 4096) -> None:
        self._stats: dict[str, SpanStats] = {}
        self._lock = threading.Lock()
        self._rec = SpanRecorder(maxlen=timeline_maxlen,
                                 on_close=self._aggregate)

    def _aggregate(self, full_name: str, duration: float) -> None:
        with self._lock:
            self._stats.setdefault(full_name, SpanStats()).add(duration)

    def span(self, name: str):
        return self._rec.span(name)

    def record(self, name: str, duration: float) -> None:
        """Record an externally-timed span (e.g. a device-side step time)."""
        self._aggregate(name, duration)
        self._rec.add(name, time.monotonic() - duration, duration)

    def stats(self) -> dict[str, dict]:
        with self._lock:
            return {k: v.to_dict() for k, v in sorted(self._stats.items())}

    def timeline(self) -> list[Span]:
        """Raw spans in completion order (bounded by timeline_maxlen)."""
        return self._rec.spans()

    def chrome_trace(self, process_name: str = "pipeline") -> dict:
        """Perfetto-loadable Chrome trace-event JSON of the timeline — the
        offline twin of the serving layer's /debug/trace dump."""
        from ..obs.export import spans_to_chrome

        return spans_to_chrome(self.timeline(), process_name)

    def to_dict(self) -> dict:
        return {"spans": self.stats()}

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
        self._rec.clear()


@contextlib.contextmanager
def device_profile(log_dir: str | None = None):
    """Capture a JAX device profile for the enclosed block.

    `log_dir` falls back to `$VNSUM_PROFILE_DIR`; when neither is set this is
    a no-op, so production paths can wrap their hot sections unconditionally.
    View with TensorBoard (`tensorboard --logdir <dir>`) or Perfetto.
    """
    log_dir = log_dir or os.environ.get("VNSUM_PROFILE_DIR")
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def annotate(name: str):
    """Named region inside a device trace (XPlane TraceMe annotation)."""
    try:
        import jax

        cm = jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - jax always present in this image
        cm = contextlib.nullcontext()
    with cm:
        yield
