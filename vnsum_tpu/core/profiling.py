"""Tracing / profiling subsystem.

The reference has no profiler integration — only LangSmith `@traceable` on one
driver (runners/run_summarization_ollama_mapreduce_critique.py:21,403, active
only when LangSmith env vars are set) and manual wall-clock spans stored in the
run record (run_full_evaluation_pipeline.py:439,572-591). This module keeps
those capabilities and makes them first-class:

- `Tracer.span(name)` — nested wall-clock spans with aggregated statistics,
  thread-safe (strategy batches may fan out over a thread pool), persisted in
  the structured run record instead of log lines.
- `device_profile(log_dir)` — `jax.profiler.trace` wrapper producing TensorBoard
  / Perfetto traces of the on-device work (the TPU-native analog of the
  reference's LangSmith tracing). Gated: no-op unless a directory is given or
  `VNSUM_PROFILE_DIR` is set, mirroring the reference's env-gated LangSmith
  activation (...critique.py:22-23).
- `annotate(name)` — `jax.profiler.TraceAnnotation` passthrough so host-side
  phases show up inside device traces.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass, field


@dataclass
class SpanStats:
    count: int = 0
    total_s: float = 0.0
    min_s: float = float("inf")
    max_s: float = 0.0

    def add(self, duration: float) -> None:
        self.count += 1
        self.total_s += duration
        self.min_s = min(self.min_s, duration)
        self.max_s = max(self.max_s, duration)

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "total_s": self.total_s,
            "mean_s": self.total_s / self.count if self.count else 0.0,
            "min_s": self.min_s if self.count else 0.0,
            "max_s": self.max_s,
        }


@dataclass
class Tracer:
    """Aggregating wall-clock tracer.

    Span names are hierarchical: nested spans get `parent/child` keys, so the
    run record shows e.g. `summarize/batch` under `summarize`. One Tracer is
    shared per pipeline run; use `reset()` between runs.
    """

    _stats: dict[str, SpanStats] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)
    _local: threading.local = field(default_factory=threading.local)

    def _stack(self) -> list[str]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    @contextlib.contextmanager
    def span(self, name: str):
        stack = self._stack()
        full = "/".join([*stack, name])
        stack.append(name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            duration = time.perf_counter() - t0
            stack.pop()
            with self._lock:
                self._stats.setdefault(full, SpanStats()).add(duration)

    def record(self, name: str, duration: float) -> None:
        """Record an externally-timed span (e.g. a device-side step time)."""
        with self._lock:
            self._stats.setdefault(name, SpanStats()).add(duration)

    def stats(self) -> dict[str, dict]:
        with self._lock:
            return {k: v.to_dict() for k, v in sorted(self._stats.items())}

    def to_dict(self) -> dict:
        return {"spans": self.stats()}

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


@contextlib.contextmanager
def device_profile(log_dir: str | None = None):
    """Capture a JAX device profile for the enclosed block.

    `log_dir` falls back to `$VNSUM_PROFILE_DIR`; when neither is set this is
    a no-op, so production paths can wrap their hot sections unconditionally.
    View with TensorBoard (`tensorboard --logdir <dir>`) or Perfetto.
    """
    log_dir = log_dir or os.environ.get("VNSUM_PROFILE_DIR")
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def annotate(name: str):
    """Named region inside a device trace (XPlane TraceMe annotation)."""
    try:
        import jax

        cm = jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - jax always present in this image
        cm = contextlib.nullcontext()
    with cm:
        yield
