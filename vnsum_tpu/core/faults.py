"""Fault injection + retry policy for the backend seam.

The reference has no fault injection and no retries anywhere (SURVEY.md §5
"Failure detection": try/except per model, nothing else). A batched TPU
engine concentrates risk — one failed device batch takes a whole group of
documents with it — so the framework provides:

- `FaultPlan` / `FaultInjectingBackend`: a deterministic chaos wrapper for
  any Backend, used by the test suite to prove containment (and available
  under `--backend fake+faults`-style manual runs). Faults are by call
  index, every-N, or seeded probability; they raise or corrupt output.
- `RetryingBackend`: generic retry-with-exponential-backoff around any
  backend's `generate` (the Ollama backend additionally retries per-HTTP
  request below this seam).
- `call_with_retries`: host-side helper the pipeline uses to re-submit a
  failed document batch before recording its documents as failed.
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass, field

from .logging import get_logger

logger = get_logger("vnsum.faults")


@dataclass
class FaultRule:
    """One injection rule, matched against the 1-based generate() call index.

    kind: "raise" (throw `error`) or "corrupt" (replace outputs with
    `corruption`). Exactly one of `on_call`, `every_n`, `probability` selects
    when the rule fires.
    """

    kind: str = "raise"
    on_call: int | None = None
    every_n: int | None = None
    probability: float | None = None
    error: Exception | None = None
    corruption: str = ""

    def fires(self, call_index: int, rng: random.Random) -> bool:
        if self.on_call is not None:
            return call_index == self.on_call
        if self.every_n is not None:
            return call_index % self.every_n == 0
        if self.probability is not None:
            return rng.random() < self.probability
        return False


@dataclass
class FaultPlan:
    rules: list[FaultRule] = field(default_factory=list)
    seed: int = 0

    def __post_init__(self):
        self._rng = random.Random(self.seed)
        self._calls = 0

    def check(self) -> FaultRule | None:
        """Advance the call counter; return the first firing rule, if any."""
        self._calls += 1
        for rule in self.rules:
            if rule.fires(self._calls, self._rng):
                return rule
        return None

    @property
    def calls(self) -> int:
        return self._calls


class FaultInjectingBackend:
    """Wrap a Backend; inject faults per the plan on each generate() call.

    ``name`` is preserved from the wrapped backend (pipeline preflight
    dispatches on it); ``label`` carries the decorated form for logs."""

    def __init__(self, inner, plan: FaultPlan) -> None:
        self.inner = inner
        self.plan = plan
        self.name = inner.name
        self.label = f"{inner.name}+faults"

    def generate(self, prompts, **kw):
        rule = self.plan.check()
        if rule is not None:
            if rule.kind == "raise":
                err = rule.error or RuntimeError(
                    f"injected fault on call {self.plan.calls}"
                )
                logger.warning("injecting %r on call %d", err, self.plan.calls)
                raise err
            if rule.kind == "corrupt":
                logger.warning("corrupting output of call %d", self.plan.calls)
                return [rule.corruption for _ in prompts]
            raise ValueError(f"unknown fault kind {rule.kind!r}")
        return self.inner.generate(prompts, **kw)

    def count_tokens(self, text: str) -> int:
        return self.inner.count_tokens(text)

    def __getattr__(self, item):
        return getattr(self.inner, item)


def call_with_retries(
    fn,
    *,
    max_retries: int,
    backoff: float = 1.0,
    max_backoff: float = 60.0,
    jitter: float = 0.0,
    rng: random.Random | None = None,
    retryable: tuple[type[BaseException], ...] = (Exception,),
    should_retry=None,
    what: str = "call",
):
    """Run fn(); on a retryable failure wait min(backoff * 2^attempt,
    max_backoff) * (1 + jitter * U[0,1)) and rerun, up to max_retries extra
    attempts (negative clamps to 0 — fn always runs at least once).
    ``jitter`` desynchronizes concurrent retriers (thundering-herd control;
    pass a seeded ``rng`` for deterministic tests). ``should_retry(exc) ->
    bool`` refines the class filter (e.g. retry only 5xx HTTP errors); a
    non-retryable failure re-raises immediately. Re-raises the last
    failure."""
    max_retries = max(max_retries, 0)
    rng = rng or random
    for attempt in range(max_retries + 1):
        try:
            return fn()
        except retryable as e:
            if should_retry is not None and not should_retry(e):
                raise
            if attempt >= max_retries:
                raise
            delay = min(backoff * (2 ** attempt), max_backoff)
            if jitter:
                delay *= 1.0 + jitter * rng.random()
            logger.warning(
                "%s failed (%s: %s); retry %d/%d in %.1fs",
                what, type(e).__name__, e, attempt + 1, max_retries, delay,
            )
            time.sleep(delay)


# error classes a retry can never fix (programming or input errors, not
# transient device/network state) — shared fail-fast filter for every retry
# seam (RetryingBackend, pipeline batch retry)
PERMANENT_ERRORS = (
    FileNotFoundError, TypeError, ValueError, KeyError, AttributeError,
    IndexError, NotImplementedError,
)


def is_retryable(e: BaseException) -> bool:
    """The shared retry predicate: fail fast on PERMANENT_ERRORS, except
    json.JSONDecodeError — it subclasses ValueError but is a garbled-body
    transient (the ollama seam retries it too, ollama.py:86-123)."""
    import json

    return isinstance(e, json.JSONDecodeError) or not isinstance(
        e, PERMANENT_ERRORS
    )


class RetryingBackend:
    """Generic retry wrapper for any Backend's generate().

    Permanent errors (bad config/input — see PERMANENT_ERRORS) fail fast
    instead of burning backoff, mirroring the ollama and pipeline seams; pass
    `should_retry` to override."""

    def __init__(
        self,
        inner,
        max_retries: int = 2,
        backoff: float = 1.0,
        should_retry=None,
    ) -> None:
        self.inner = inner
        self.max_retries = max_retries
        self.backoff = backoff
        self.should_retry = should_retry or is_retryable
        self.name = inner.name  # preflight dispatches on the backend kind
        self.label = f"{inner.name}+retry"

    def generate(self, prompts, **kw):
        return call_with_retries(
            lambda: self.inner.generate(prompts, **kw),
            max_retries=self.max_retries,
            backoff=self.backoff,
            should_retry=self.should_retry,
            what=f"{self.inner.name}.generate({len(prompts)} prompts)",
        )

    def count_tokens(self, text: str) -> int:
        return self.inner.count_tokens(text)

    def __getattr__(self, item):
        return getattr(self.inner, item)
