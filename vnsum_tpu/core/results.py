"""Structured run records.

Keeps the reference's pipeline_results JSON schema
(run_full_evaluation_pipeline.py:927-947: pipeline_info / config / results
{document_stats, summarization, evaluation}) so downstream tooling that read
the reference's result files keeps working — but metrics travel as structured
objects end to end, never via stdout scraping
(the reference's parse_evaluation_output, :729-784, is deliberately absent).
"""
from __future__ import annotations

import dataclasses
import json
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any


@dataclass
class DocumentRecord:
    """Per-document processing details (ref :575-582).

    `num_chunks` and `llm_calls` are TRUE per-document counts (each prompt in
    a shared batch belongs to exactly one document). `processing_time` is the
    document's even share of its batch's wall-clock — device batches serve
    many documents at once, so per-doc wall time is not separable; the parent
    ModelRunRecord declares this via ``time_basis``."""

    filename: str
    num_chunks: int
    processing_time: float
    summary_length_chars: int
    llm_calls: int = 0
    status: str = "success"
    error: str | None = None


@dataclass
class ModelRunRecord:
    """Per-model summarization stats (ref :586-607)."""

    model: str
    approach: str
    total_documents: int = 0
    successful: int = 0
    failed: int = 0
    total_chunks: int = 0
    total_time: float = 0.0
    status: str = "success"
    error: str | None = None
    # how per-doc processing_time was measured: "batch_amortized" (even share
    # of the shared device batch) vs the reference's serial "per_document"
    time_basis: str = "batch_amortized"
    processing_details: list[DocumentRecord] = field(default_factory=list)

    @property
    def avg_processing_time_per_doc(self) -> float:
        return self.total_time / self.total_documents if self.total_documents else 0.0

    @property
    def chunks_per_second(self) -> float:
        return self.total_chunks / self.total_time if self.total_time else 0.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["avg_processing_time_per_doc"] = self.avg_processing_time_per_doc
        d["chunks_per_second"] = self.chunks_per_second
        return d


@dataclass
class ServeRequestRecord:
    """Per-request online-serving observability (serve/scheduler.py).

    One record per request DISPATCHED to the engine — completed or errored.
    Shed requests never reach a batch and are counted per-reason in
    ServingStats.shed instead (their typed RequestShed carries the reason
    to the caller). The serving HTTP layer returns these inline with
    responses and the load generator (scripts/bench_serving.py) aggregates
    them, so the same fields serve live debugging and committed benchmark
    evidence.

    ``trace_id`` is the END-TO-END correlation id (vnsum_tpu.obs): the same
    string rides the X-Request-Id response header, the /debug/trace dump's
    request track, and log lines — a summarize request's fanned-out prompts
    all share its trace_id while keeping distinct queue-level request_ids."""

    request_id: int
    status: str = "ok"  # ok | error
    trace_id: str = ""
    queue_wait_s: float = 0.0  # submit -> engine dispatch
    engine_s: float = 0.0      # wall clock of the shared engine batch
    total_s: float = 0.0       # submit -> completion
    # submit -> first token: queue wait + the batch's prefill phase when the
    # backend emitted one (obs.BatchTrace.first_token_at), else the whole
    # engine call — the fused one-shot program has no observable midpoint.
    # ttft_anchored says which: only anchored values feed the
    # vnsum_serve_ttft_seconds histogram (an unanchored fallback is just
    # e2e relabeled and would poison the quantiles)
    ttft_s: float = 0.0
    ttft_anchored: bool = False
    batch_size: int = 0        # occupancy of the engine batch it rode
    prompt_tokens: int = 0
    generated_tokens: int = 0
    # reference-guided speculative decoding (vnsum_tpu.spec): per-request
    # drafting/acceptance, attributed from the backend's take_spec_report
    # hook (all zero when speculation was off for the batch). spec_steps
    # counts the verify forwards the row was live for — accepted/steps feeds
    # the vnsum_serve_spec_accepted_per_step histogram
    draft_tokens: int = 0
    accepted_tokens: int = 0
    spec_steps: int = 0
    # radix prefix KV cache (vnsum_tpu.cache): prompt tokens whose prefill
    # was served from cached prefix blocks, attributed from the backend's
    # take_cache_report hook (0 when the cache is off or the prompt missed)
    cached_prompt_tokens: int = 0

    @property
    def acceptance_rate(self) -> float:
        return self.accepted_tokens / self.draft_tokens if self.draft_tokens else 0.0

    @property
    def cache_hit_rate(self) -> float:
        """Fraction of this request's prompt tokens served from the prefix
        cache."""
        if not self.prompt_tokens:
            return 0.0
        return min(self.cached_prompt_tokens / self.prompt_tokens, 1.0)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["acceptance_rate"] = round(self.acceptance_rate, 6)
        d["cache_hit_rate"] = round(self.cache_hit_rate, 6)
        return d


@dataclass
class ServingStats:
    """Aggregate serving counters — the snapshot form of serve.ServeMetrics,
    embeddable in run records (PipelineResults.serving) and bench JSON."""

    submitted: int = 0
    completed: int = 0
    errors: int = 0
    shed: dict[str, int] = field(default_factory=dict)  # reason -> count
    batches: int = 0
    batch_occupancy_sum: int = 0
    engine_seconds: float = 0.0
    queue_wait_seconds: float = 0.0
    prompt_tokens: int = 0
    generated_tokens: int = 0
    # speculative decoding aggregates (sums of the per-request fields)
    draft_tokens: int = 0
    accepted_tokens: int = 0
    # prefix KV cache aggregate: prompt tokens served from cached blocks
    cache_hit_tokens: int = 0
    # in-flight batching (serve/inflight.py): decode segments dispatched by
    # the slot loop, and requests admitted into a RUNNING decode batch at a
    # segment boundary (0 for the batch-dispatch scheduler)
    segments: int = 0
    refills: int = 0
    # fused multi-step decode: host dispatches of the slot loop (each
    # covers up to --fused-segments on-device segments; == segments at N=1)
    fused_dispatches: int = 0
    # fault tolerance (serve/supervisor.py): classified dispatch failures,
    # retries scheduled, bisection splits, requests quarantined as poison,
    # total backoff slept, and degradation-ladder transitions
    failures: dict[str, int] = field(default_factory=dict)  # class -> count
    retries: int = 0
    bisects: int = 0
    quarantined: int = 0
    backoff_seconds: float = 0.0
    degraded_steps: int = 0
    degraded_recoveries: int = 0
    # multi-tenant QoS (serve/qos.py): batch-tier slot evictions for
    # interactive work, their matching requeues, per-tenant admitted
    # requests, and per-tenant token-rate quota sheds
    preemptions: int = 0
    requeues: int = 0
    tenant_requests: dict[str, int] = field(default_factory=dict)
    quota_sheds: dict[str, int] = field(default_factory=dict)
    # SSE streaming (serve/stream.py): streamed requests admitted, SSE
    # events written, and streams open right now (the scrape-time gauge)
    stream_requests: int = 0
    stream_events: int = 0
    streams_open: int = 0
    # request cancellation (serve/scheduler.py cancel paths): terminal
    # cancels by lifecycle stage (queued / dispatched / resident), plus how
    # many were triggered by a client disconnect or idle-consumer timeout
    # rather than an explicit DELETE
    cancelled: dict[str, int] = field(default_factory=dict)  # stage -> count
    cancel_disconnects: int = 0
    # stream hardening (serve/stream.py): pending events collapsed by the
    # bounded channel's coalesce-on-full, Last-Event-ID reattaches served,
    # and keepalive heartbeat frames written
    stream_coalesced: int = 0
    stream_resumes: int = 0
    stream_heartbeats: int = 0
    # structured jobs (serve/gang.py): gangs admitted through the one-pass
    # request-level gate, fan-out children recorded into groups, take-path
    # batches where the affinity pick co-scheduled siblings, whole-gang
    # slot evictions, and gangs degraded to a partial result
    gang_admitted: int = 0
    gang_members: int = 0
    gang_affinity_picks: int = 0
    gang_preemptions: int = 0
    gang_partials: int = 0

    @property
    def shed_total(self) -> int:
        return sum(self.shed.values())

    @property
    def acceptance_rate(self) -> float:
        return self.accepted_tokens / self.draft_tokens if self.draft_tokens else 0.0

    @property
    def cache_hit_rate(self) -> float:
        if not self.prompt_tokens:
            return 0.0
        return min(self.cache_hit_tokens / self.prompt_tokens, 1.0)

    @property
    def avg_batch_occupancy(self) -> float:
        return self.batch_occupancy_sum / self.batches if self.batches else 0.0

    @property
    def tokens_per_second(self) -> float:
        total = self.prompt_tokens + self.generated_tokens
        return total / self.engine_seconds if self.engine_seconds else 0.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["shed_total"] = self.shed_total
        d["avg_batch_occupancy"] = self.avg_batch_occupancy
        d["tokens_per_second"] = self.tokens_per_second
        d["acceptance_rate"] = round(self.acceptance_rate, 6)
        d["cache_hit_rate"] = round(self.cache_hit_rate, 6)
        return d


@dataclass
class PipelineResults:
    """Top-level run record, persisted as
    evaluation_results/pipeline_results_<ts>.json (ref :927-947)."""

    config: dict
    start_time: float = field(default_factory=time.time)
    document_stats: dict = field(default_factory=dict)
    summarization: dict[str, Any] = field(default_factory=dict)
    evaluation: dict[str, Any] = field(default_factory=dict)
    tracing: dict[str, Any] = field(default_factory=dict)
    # online-serving counters (ServingStats.to_dict) when the run went
    # through vnsum_tpu.serve; empty for offline pipeline runs
    serving: dict[str, Any] = field(default_factory=dict)

    def add_summarization(self, record: ModelRunRecord) -> None:
        self.summarization[record.model] = record.to_dict()

    def add_evaluation(self, model: str, metrics: dict) -> None:
        self.evaluation[model] = metrics

    def to_dict(self) -> dict:
        end = time.time()
        return {
            "pipeline_info": {
                "timestamp": time.strftime(
                    "%Y-%m-%dT%H:%M:%S", time.localtime(self.start_time)
                ),
                "duration_seconds": end - self.start_time,
                "approach": self.config.get("approach"),
                "framework": "vnsum_tpu",
            },
            "config": self.config,
            "results": {
                "document_stats": self.document_stats,
                "summarization": self.summarization,
                "evaluation": self.evaluation,
                "tracing": self.tracing,
                "serving": self.serving,
            },
        }

    def save(self, results_dir: str | Path) -> Path:
        out = Path(results_dir)
        out.mkdir(parents=True, exist_ok=True)
        ts = time.strftime("%Y%m%d_%H%M%S")
        path = out / f"pipeline_results_{ts}.json"
        n = 1
        while path.exists():
            path = out / f"pipeline_results_{ts}_{n}.json"
            n += 1
        path.write_text(
            json.dumps(self.to_dict(), indent=2, ensure_ascii=False, default=str),
            encoding="utf-8",
        )
        return path
