"""Structured run records.

Keeps the reference's pipeline_results JSON schema
(run_full_evaluation_pipeline.py:927-947: pipeline_info / config / results
{document_stats, summarization, evaluation}) so downstream tooling that read
the reference's result files keeps working — but metrics travel as structured
objects end to end, never via stdout scraping
(the reference's parse_evaluation_output, :729-784, is deliberately absent).
"""
from __future__ import annotations

import dataclasses
import json
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any


@dataclass
class DocumentRecord:
    """Per-document processing details (ref :575-582).

    `num_chunks` and `llm_calls` are TRUE per-document counts (each prompt in
    a shared batch belongs to exactly one document). `processing_time` is the
    document's even share of its batch's wall-clock — device batches serve
    many documents at once, so per-doc wall time is not separable; the parent
    ModelRunRecord declares this via ``time_basis``."""

    filename: str
    num_chunks: int
    processing_time: float
    summary_length_chars: int
    llm_calls: int = 0
    status: str = "success"
    error: str | None = None


@dataclass
class ModelRunRecord:
    """Per-model summarization stats (ref :586-607)."""

    model: str
    approach: str
    total_documents: int = 0
    successful: int = 0
    failed: int = 0
    total_chunks: int = 0
    total_time: float = 0.0
    status: str = "success"
    error: str | None = None
    # how per-doc processing_time was measured: "batch_amortized" (even share
    # of the shared device batch) vs the reference's serial "per_document"
    time_basis: str = "batch_amortized"
    processing_details: list[DocumentRecord] = field(default_factory=list)

    @property
    def avg_processing_time_per_doc(self) -> float:
        return self.total_time / self.total_documents if self.total_documents else 0.0

    @property
    def chunks_per_second(self) -> float:
        return self.total_chunks / self.total_time if self.total_time else 0.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["avg_processing_time_per_doc"] = self.avg_processing_time_per_doc
        d["chunks_per_second"] = self.chunks_per_second
        return d


@dataclass
class PipelineResults:
    """Top-level run record, persisted as
    evaluation_results/pipeline_results_<ts>.json (ref :927-947)."""

    config: dict
    start_time: float = field(default_factory=time.time)
    document_stats: dict = field(default_factory=dict)
    summarization: dict[str, Any] = field(default_factory=dict)
    evaluation: dict[str, Any] = field(default_factory=dict)
    tracing: dict[str, Any] = field(default_factory=dict)

    def add_summarization(self, record: ModelRunRecord) -> None:
        self.summarization[record.model] = record.to_dict()

    def add_evaluation(self, model: str, metrics: dict) -> None:
        self.evaluation[model] = metrics

    def to_dict(self) -> dict:
        end = time.time()
        return {
            "pipeline_info": {
                "timestamp": time.strftime(
                    "%Y-%m-%dT%H:%M:%S", time.localtime(self.start_time)
                ),
                "duration_seconds": end - self.start_time,
                "approach": self.config.get("approach"),
                "framework": "vnsum_tpu",
            },
            "config": self.config,
            "results": {
                "document_stats": self.document_stats,
                "summarization": self.summarization,
                "evaluation": self.evaluation,
                "tracing": self.tracing,
            },
        }

    def save(self, results_dir: str | Path) -> Path:
        out = Path(results_dir)
        out.mkdir(parents=True, exist_ok=True)
        ts = time.strftime("%Y%m%d_%H%M%S")
        path = out / f"pipeline_results_{ts}.json"
        n = 1
        while path.exists():
            path = out / f"pipeline_results_{ts}_{n}.json"
            n += 1
        path.write_text(
            json.dumps(self.to_dict(), indent=2, ensure_ascii=False, default=str),
            encoding="utf-8",
        )
        return path
