"""Run logging: timestamped file + stdout, like the reference's setup_logging
(run_full_evaluation_pipeline.py:137-163), without mutating global state twice.

The stream handler is installed IDEMPOTENTLY on the "vnsum" root logger and
nowhere else: a previous version skipped installation whenever the GLOBAL
root logger had handlers, so any process that configured root logging first
(pytest's capture handler, absl's init, a user basicConfig) silently
suppressed every vnsum log line. Now the handler is keyed by a marker
attribute — repeated get_logger() calls never stack duplicates, and an
already-configured root cannot veto vnsum's own stream.

``VNSUM_LOG_JSON=1`` switches the stream handler to a structured JSONL
formatter (one JSON object per line: ts, level, logger, msg, plus exc_info
when present) for log pipelines that ingest structured events; the run-file
handler keeps the human-readable format either way.
"""
from __future__ import annotations

import json
import logging
import os
import sys
import time
from pathlib import Path

_FORMAT = "%(asctime)s - %(name)s - %(levelname)s - %(message)s"
_MARKER = "_vnsum_stream_handler"


class JsonFormatter(logging.Formatter):
    """One JSON object per record — stable keys, ISO-ish local timestamps."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.localtime(record.created)
            ),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, ensure_ascii=False)


def _stream_formatter() -> logging.Formatter:
    if os.environ.get("VNSUM_LOG_JSON") == "1":
        return JsonFormatter()
    return logging.Formatter(_FORMAT)


def get_logger(name: str = "vnsum") -> logging.Logger:
    """Child loggers propagate to the single handler on the "vnsum" root."""
    root = logging.getLogger("vnsum")
    if not any(getattr(h, _MARKER, False) for h in root.handlers):
        h = logging.StreamHandler(sys.stdout)
        h.setFormatter(_stream_formatter())
        setattr(h, _MARKER, True)
        root.addHandler(h)
        root.setLevel(logging.INFO)
        # vnsum owns its emission: without this, a process whose GLOBAL
        # root is also configured (basicConfig, absl) would print every
        # line twice — once here, once propagated to the root handler
        root.propagate = False
    return logging.getLogger(name)


_active_file_handler: logging.FileHandler | None = None


def setup_run_logging(logs_dir: str | Path, run_name: str = "pipeline_run") -> Path:
    """Attach a timestamped file handler to the root vnsum logger, replacing
    the handler from any previous run in this process.

    Returns the log file path (logs/<run_name>_<ts>.log).
    """
    global _active_file_handler
    logs = Path(logs_dir)
    logs.mkdir(parents=True, exist_ok=True)
    ts = time.strftime("%Y%m%d_%H%M%S")
    path = logs / f"{run_name}_{ts}.log"
    logger = logging.getLogger("vnsum")
    if _active_file_handler is not None:
        logger.removeHandler(_active_file_handler)
        _active_file_handler.close()
    fh = logging.FileHandler(path, encoding="utf-8")
    fh.setFormatter(logging.Formatter(_FORMAT))
    logger.addHandler(fh)
    logger.setLevel(logging.INFO)
    _active_file_handler = fh
    return path
