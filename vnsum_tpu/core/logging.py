"""Run logging: timestamped file + stdout, like the reference's setup_logging
(run_full_evaluation_pipeline.py:137-163), without mutating global state twice.
"""
from __future__ import annotations

import logging
import sys
import time
from pathlib import Path

_FORMAT = "%(asctime)s - %(name)s - %(levelname)s - %(message)s"


def get_logger(name: str = "vnsum") -> logging.Logger:
    """Child loggers propagate to the single handler on the "vnsum" root."""
    root = logging.getLogger("vnsum")
    if not root.handlers and not logging.getLogger().handlers:
        h = logging.StreamHandler(sys.stdout)
        h.setFormatter(logging.Formatter(_FORMAT))
        root.addHandler(h)
        root.setLevel(logging.INFO)
    return logging.getLogger(name)


_active_file_handler: logging.FileHandler | None = None


def setup_run_logging(logs_dir: str | Path, run_name: str = "pipeline_run") -> Path:
    """Attach a timestamped file handler to the root vnsum logger, replacing
    the handler from any previous run in this process.

    Returns the log file path (logs/<run_name>_<ts>.log).
    """
    global _active_file_handler
    logs = Path(logs_dir)
    logs.mkdir(parents=True, exist_ok=True)
    ts = time.strftime("%Y%m%d_%H%M%S")
    path = logs / f"{run_name}_{ts}.log"
    logger = logging.getLogger("vnsum")
    if _active_file_handler is not None:
        logger.removeHandler(_active_file_handler)
        _active_file_handler.close()
    fh = logging.FileHandler(path, encoding="utf-8")
    fh.setFormatter(logging.Formatter(_FORMAT))
    logger.addHandler(fh)
    logger.setLevel(logging.INFO)
    _active_file_handler = fh
    return path
