"""Persistent XLA compilation cache.

The engine's per-bucket programs cost 5-60s each to compile (a 3B prefill at
S=8192 is the worst), and the reference has nothing comparable to pay — its
"backend" is an HTTP call. Enabling JAX's persistent compilation cache makes
every program a one-time cost per machine instead of per process: measured on
the attached TPU, a cross-process recompile drops from seconds to ~20ms.

Opt-out via VNSUM_JAX_CACHE_DIR=off. Every device-touching entry point
(TpuBackend, LongContextBackend, EmbeddingModel, Trainer, bench.py) calls
:func:`enable_compilation_cache` before building programs.
"""
from __future__ import annotations

import os

# None = never configured; "" = explicitly disabled; else the active dir.
# The disabled sentinel matters: an explicit opt-out must survive the
# library-internal no-arg ensure-enabled calls backends make.
_state: str | None = None


def _apply(directory: str | None) -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", directory)
    if directory is not None:
        # cache every program that takes meaningful compile time; the tiny
        # eager helpers stay uncached to keep the directory small
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # JAX binds its cache object at the FIRST cached compile and a run-once
    # guard then ignores config changes — drop it so the new directory (or
    # the disable) actually takes effect for subsequent compiles
    from jax.experimental.compilation_cache import compilation_cache

    compilation_cache.reset_cache()


def enable_compilation_cache(cache_dir: str | None = None) -> bool:
    """Point JAX at a persistent on-disk compilation cache.

    Returns True when the cache is active. Resolution order: explicit
    argument > $VNSUM_JAX_CACHE_DIR > ~/.cache/vnsum_jax. The values
    "off"/"0"/"" disable it.

    Calls are idempotent for the same resolved directory. A later call with
    a DIFFERENT *explicit* cache_dir re-points JAX at it — programs compiled
    under the old directory stay there, new compiles land in the new one —
    and an explicit "off" disables it. No-arg calls (the library-internal
    ensure-enabled calls every device-touching entry point makes) never
    override an explicit earlier choice, enable or disable.
    """
    global _state
    if cache_dir is None and _state is not None:
        return _state != ""
    resolved = (
        cache_dir
        if cache_dir is not None
        else os.environ.get(
            "VNSUM_JAX_CACHE_DIR", os.path.expanduser("~/.cache/vnsum_jax")
        )
    )
    if resolved in ("", "0", "off"):
        if _state not in (None, ""):
            _apply(None)
        _state = ""
        return False
    if resolved == _state:
        return True
    os.makedirs(resolved, exist_ok=True)
    _apply(resolved)
    _state = resolved
    return True
