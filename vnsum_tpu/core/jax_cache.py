"""Persistent XLA compilation cache.

The engine's per-bucket programs cost 5-60s each to compile (a 3B prefill at
S=8192 is the worst), and the reference has nothing comparable to pay — its
"backend" is an HTTP call. Enabling JAX's persistent compilation cache makes
every program a one-time cost per machine instead of per process: measured on
the attached TPU, a cross-process recompile drops from seconds to ~20ms.

Opt-out via VNSUM_JAX_CACHE_DIR=off. Every device-touching entry point
(TpuBackend, LongContextBackend, EmbeddingModel, Trainer, bench.py) calls
:func:`enable_compilation_cache` before building programs.
"""
from __future__ import annotations

import os

_enabled = False


def enable_compilation_cache(cache_dir: str | None = None) -> bool:
    """Idempotently point JAX at a persistent on-disk compilation cache.

    Returns True when the cache is active. Resolution order: explicit
    argument > $VNSUM_JAX_CACHE_DIR > ~/.cache/vnsum_jax. The values
    "off"/"0"/"" disable it.
    """
    global _enabled
    if _enabled:
        return True
    resolved = cache_dir or os.environ.get(
        "VNSUM_JAX_CACHE_DIR", os.path.expanduser("~/.cache/vnsum_jax")
    )
    if resolved in ("", "0", "off"):
        return False
    import jax

    os.makedirs(resolved, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", resolved)
    # cache every program that takes meaningful compile time; the tiny eager
    # helpers stay uncached to keep the directory small
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    _enabled = True
    return True
