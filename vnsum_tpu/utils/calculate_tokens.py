"""Token statistics tool (capability match for utils/calculate_tokens.py in
the reference: per-file token/char/word counts over a folder → JSON, which
produced metadata/doc_metadata.json & summary_metadata.json).

The tokenizer is any framework tokenizer spec ("byte" or "hf:<name>") rather
than a hard HF dependency (ref default Qwen/Qwen3-4B, :7-19).
"""
from __future__ import annotations

import argparse
import json
from pathlib import Path

from ..text.tokenizer import get_tokenizer, whitespace_token_count


def count_stats(text: str, tok) -> dict:
    return {
        "tokens": tok.count(text),
        "characters": len(text),
        "words": whitespace_token_count(text),
    }


def process_folder(folder: str | Path, tokenizer: str = "byte") -> dict:
    tok = get_tokenizer(tokenizer)
    folder = Path(folder)
    files = {}
    totals = {"tokens": 0, "characters": 0, "words": 0}
    for f in sorted(folder.glob("*.txt")):
        stats = count_stats(f.read_text(encoding="utf-8"), tok)
        files[f.name] = stats
        for k in totals:
            totals[k] += stats[k]
    n = len(files)
    return {
        "summary": {
            "total_files": n,
            **{f"total_{k}": v for k, v in totals.items()},
            **{f"avg_{k}": (v / n if n else 0.0) for k, v in totals.items()},
            "tokenizer": tokenizer,
        },
        "files": files,
    }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="vnsum-tokens")
    p.add_argument("folder")
    p.add_argument("--tokenizer", default="byte")
    p.add_argument("--output", default=None)
    args = p.parse_args(argv)
    result = process_folder(args.folder, args.tokenizer)
    text = json.dumps(result, indent=2, ensure_ascii=False)
    if args.output:
        Path(args.output).write_text(text, encoding="utf-8")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
