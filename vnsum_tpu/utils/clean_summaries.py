"""Batch summary cleaner (capability match for utils/clean_summaries.py in
the reference: strip <think>-style blocks from saved summaries, in place or
into a new directory, with --preview).
"""
from __future__ import annotations

import argparse
from pathlib import Path

from ..text.cleaning import clean_thinking_tokens


def clean_summaries(
    input_dir: str | Path,
    output_dir: str | Path | None = None,
    preview: bool = False,
) -> dict:
    src = Path(input_dir)
    if not src.is_dir():
        raise FileNotFoundError(f"input dir not found: {src}")
    dst = Path(output_dir) if output_dir else src
    changed, unchanged = [], []
    for f in sorted(src.glob("*.txt")):
        text = f.read_text(encoding="utf-8")
        cleaned = clean_thinking_tokens(text)
        if cleaned != text:
            changed.append(f.name)
            if not preview:
                dst.mkdir(parents=True, exist_ok=True)
                (dst / f.name).write_text(cleaned, encoding="utf-8")
        else:
            unchanged.append(f.name)
            if not preview and dst != src:
                dst.mkdir(parents=True, exist_ok=True)
                (dst / f.name).write_text(text, encoding="utf-8")
    return {"changed": changed, "unchanged": unchanged, "preview": preview}


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="vnsum-clean")
    p.add_argument("input_dir")
    p.add_argument("--output-dir", default=None)
    p.add_argument("--preview", action="store_true")
    args = p.parse_args(argv)
    result = clean_summaries(args.input_dir, args.output_dir, args.preview)
    print(
        f"{'would clean' if args.preview else 'cleaned'} "
        f"{len(result['changed'])} files; {len(result['unchanged'])} unchanged"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
