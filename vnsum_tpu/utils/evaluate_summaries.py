"""Simple evaluator CLI — capability match for the reference's
`utils/evaluate_summaries.py:27-106`: folder-vs-folder ROUGE-1/2/L + BERTScore
with per-file and aggregate numbers, no embeddings/LLM judge.

Differences by design: ROUGE uses the framework's exact-parity port
(vnsum_tpu.eval.rouge) on the host's native text core when available, and
BERTScore runs batched on-device through the JAX encoder instead of the
`bert_score` package's per-corpus torch pass — and results are emitted as
structured JSON (`--output`), never scraped from stdout.
"""
from __future__ import annotations

import argparse
import json
from pathlib import Path

from ..core.logging import get_logger
from ..eval.embedding import EmbeddingModel, bert_scores
from ..eval.rouge import RougeScorer
from ..eval.semantic import load_summary_dir, match_pairs

logger = get_logger("vnsum.utils.evaluate")


def evaluate_summaries(
    generated_dir: str | Path,
    reference_dir: str | Path,
    *,
    max_samples: int | None = None,
    use_stemmer: bool = True,
    skip_bert: bool = False,
    embedding_model: EmbeddingModel | None = None,
) -> dict:
    """Folder-vs-folder ROUGE (+ optional BERTScore) over matching filenames
    (ref utils/evaluate_summaries.py:27-106)."""
    generated = load_summary_dir(generated_dir)
    references = load_summary_dir(reference_dir)
    common = match_pairs(generated, references, max_samples)

    scorer = RougeScorer(["rouge1", "rouge2", "rougeL"], use_stemmer)
    per_file: dict[str, dict] = {}
    for name in common:
        scores = scorer.score(references[name], generated[name])
        per_file[name] = {
            k: {"precision": s.precision, "recall": s.recall, "f1": s.fmeasure}
            for k, s in scores.items()
        }

    def mean(metric: str, field: str) -> float:
        return sum(per_file[n][metric][field] for n in common) / len(common)

    aggregate = {
        m: {f: mean(m, f) for f in ("precision", "recall", "f1")}
        for m in ("rouge1", "rouge2", "rougeL")
    }

    if not skip_bert:
        model = embedding_model or EmbeddingModel()
        bert = bert_scores(
            model, [generated[n] for n in common], [references[n] for n in common]
        )
        for name, b in zip(common, bert):
            per_file[name]["bert"] = {
                "precision": b.precision, "recall": b.recall, "f1": b.f1,
            }
        aggregate["bert"] = {
            "precision": sum(b.precision for b in bert) / len(bert),
            "recall": sum(b.recall for b in bert) / len(bert),
            "f1": sum(b.f1 for b in bert) / len(bert),
        }

    return {
        "num_pairs": len(common),
        "aggregate": aggregate,
        "per_file": per_file,
    }


def format_report(results: dict) -> str:
    lines = [f"Evaluated {results['num_pairs']} summary pairs", ""]
    for metric, vals in results["aggregate"].items():
        lines.append(
            f"{metric:8s}  P={vals['precision']:.4f}  "
            f"R={vals['recall']:.4f}  F1={vals['f1']:.4f}"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="vnsum-evaluate",
        description="Folder-vs-folder ROUGE + BERTScore evaluation",
    )
    p.add_argument("generated_dir")
    p.add_argument("reference_dir")
    p.add_argument("--max-samples", type=int, default=None)
    p.add_argument("--no-stemmer", action="store_true")
    p.add_argument("--skip-bert", action="store_true",
                   help="ROUGE only (no encoder / device work)")
    p.add_argument("--output", default=None, help="write full results JSON here")
    args = p.parse_args(argv)

    results = evaluate_summaries(
        args.generated_dir,
        args.reference_dir,
        max_samples=args.max_samples,
        use_stemmer=not args.no_stemmer,
        skip_bert=args.skip_bert,
    )
    print(format_report(results))
    if args.output:
        out = Path(args.output)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(
            json.dumps(results, indent=2, ensure_ascii=False), encoding="utf-8"
        )
        logger.info("results written to %s", out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
