"""Draft-model-free n-gram reference drafter.

Summarization output overlaps its source document far more than free-form
generation does — map/collapse/refine calls largely re-emit spans of the
text they were handed. That is the regime where reference-guided speculation
("Inference with Reference", arXiv:2304.04487) is lossless and cheap: instead
of a draft model, the drafter suffix-matches the tokens already emitted
against the request's source-document tokens and proposes the continuation
that follows the longest match. Verification (backend/engine.py spec path)
feeds the k proposed tokens through ONE batched forward and accepts the
longest prefix the model itself would have produced, so greedy outputs are
bit-identical to plain decode by construction — speculation only changes how
many tokens each dispatch retires.

Two implementations of the same contract:

- :func:`propose_drafts` — pure jnp on fixed shapes, so it runs inside the
  engine's jitted spec step (no host sync on the decode path);
- :func:`propose_drafts_host` — plain numpy mirror for host-side callers
  (FakeBackend-style doubles, debugging, and the equivalence tests that pin
  the jnp version's semantics).

Both return, per batch row, up to ``k`` draft tokens and the count actually
proposed. Rows with no reference, no match, or an exhausted reference
propose zero drafts — the verify step then degrades to one token per step,
exactly plain decode.
"""
from __future__ import annotations

import numpy as np

# sentinel for "no token here" in history tails / reference padding; never a
# valid token id, so it can never produce a spurious match
NO_TOKEN = -1


def encode_references(
    tok,
    references: list[str | None],
    max_ref_tokens: int = 4096,
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side packing of per-request reference texts into fixed-shape
    buffers: (ref_tokens [B, R] int32 padded with NO_TOKEN, ref_lens [B]).

    ``R`` is the longest encoded reference clamped to ``max_ref_tokens``
    (references are matched, not attended — truncating one only costs draft
    coverage of its tail, never correctness). ``None`` entries get length 0:
    those rows never draft."""
    encoded: list[list[int]] = []
    for r in references:
        if not r:
            encoded.append([])
            continue
        ids = tok.encode(r, add_bos=False)
        encoded.append(ids[:max_ref_tokens])
    R = max((len(e) for e in encoded), default=0)
    R = max(R, 1)  # zero-width buffers make degenerate jit shapes
    out = np.full((len(encoded), R), NO_TOKEN, dtype=np.int32)
    lens = np.zeros((len(encoded),), dtype=np.int32)
    for i, ids in enumerate(encoded):
        out[i, : len(ids)] = ids
        lens[i] = len(ids)
    return out, lens


def propose_drafts(ref, ref_lens, tail, k: int):
    """Batched n-gram suffix-match drafting, pure jnp (jit-safe).

    ref       [B, R] int32 — reference tokens, NO_TOKEN-padded
    ref_lens  [B]    int32 — valid prefix length of each row's reference
    tail      [B, N] int32 — the last N tokens of each row's emitted stream
                             (tail[:, -1] is the most recent, i.e. the token
                             about to be fed to the model), NO_TOKEN where
                             the stream is shorter than N
    k         static int   — max draft tokens to propose

    Returns (drafts [B, k] int32, n_draft [B] int32). drafts[:, i] for
    i >= n_draft are 0-filled (valid-but-ignored ids: the verify step masks
    them out of acceptance, they only pad the fixed-shape forward).

    Match rule: for every reference position p, the match length m(p) is the
    number of trailing emitted tokens that equal ref[p - i] walking
    backwards (capped at N). The winner maximizes (m, p) — longest suffix
    match first, latest occurrence to break ties (later spans tend to carry
    the continuation the model is currently producing). Rows whose best
    m == 0 or whose winning position has no continuation left propose
    nothing."""
    import jax
    import jax.numpy as jnp

    B, R = ref.shape
    N = tail.shape[1]
    tail_rev = tail[:, ::-1]  # tail_rev[:, i] = i-th most recent token

    # idx[p, i] = p - i: reference position holding the i-th most recent
    # token if the match ends at p
    p_idx = jnp.arange(R)[:, None] - jnp.arange(N)[None, :]  # [R, N]
    valid = p_idx >= 0
    gathered = jnp.take(ref, jnp.clip(p_idx, 0, R - 1), axis=1)  # [B, R, N]
    eq = (
        (gathered == tail_rev[:, None, :])
        & valid[None]
        & (tail_rev[:, None, :] != NO_TOKEN)
        & (gathered != NO_TOKEN)
    )
    # consecutive-match length along the suffix axis
    m = jnp.cumprod(eq.astype(jnp.int32), axis=2).sum(axis=2)  # [B, R]
    # a position only counts inside the row's real reference AND with at
    # least one continuation token left — a match ending the reference
    # proposes nothing, so it must not shadow a drafting-capable match
    pos = jnp.arange(R)[None, :]
    usable = (pos + 1) < ref_lens[:, None]
    m = jnp.where(usable, m, 0)
    score = m * (R + 1) + pos
    best = jnp.argmax(score, axis=1).astype(jnp.int32)        # [B]
    best_m = jnp.take_along_axis(m, best[:, None], axis=1)[:, 0]

    # continuation after the match, clamped at the reference end
    start = best + 1
    avail = jnp.maximum(ref_lens - start, 0)
    n_draft = jnp.where(best_m > 0, jnp.minimum(avail, k), 0)

    ref_pad = jnp.concatenate(
        [ref, jnp.zeros((B, k), dtype=ref.dtype)], axis=1
    )
    drafts = jax.vmap(
        lambda row, s: jax.lax.dynamic_slice(row, (s,), (k,))
    )(ref_pad, jnp.minimum(start, R))
    # zero the unproposed tail so NO_TOKEN padding never reaches the forward
    drafts = jnp.where(
        jnp.arange(k)[None, :] < n_draft[:, None], drafts, 0
    ).astype(jnp.int32)
    return drafts, n_draft.astype(jnp.int32)


def propose_drafts_host(
    ref: np.ndarray, ref_lens: np.ndarray, tail: np.ndarray, k: int
) -> tuple[np.ndarray, np.ndarray]:
    """Numpy mirror of :func:`propose_drafts` — identical semantics, host
    execution. The straightforward per-row loop doubles as executable
    documentation of the match rule; tests assert the two agree."""
    B, R = ref.shape
    N = tail.shape[1]
    drafts = np.zeros((B, k), dtype=np.int32)
    n_draft = np.zeros((B,), dtype=np.int32)
    for b in range(B):
        L = int(ref_lens[b])
        best_m, best_p = 0, -1
        for p in range(L - 1):  # p = L-1 has no continuation: never usable
            m = 0
            for i in range(N):
                if p - i < 0:
                    break
                t = int(tail[b, N - 1 - i])
                if t == NO_TOKEN or int(ref[b, p - i]) != t:
                    break
                m += 1
            if m >= best_m and m > 0:  # ties break toward the later p
                best_m, best_p = m, p
        if best_m == 0:
            continue
        n = min(k, L - (best_p + 1))
        drafts[b, :n] = ref[b, best_p + 1 : best_p + 1 + n]
        n_draft[b] = n
    return drafts, n_draft


def history_tail(out: np.ndarray, out_lens: np.ndarray, cur: np.ndarray,
                 n: int) -> np.ndarray:
    """Host helper: the last ``n`` tokens of each row's emitted stream —
    out[b, :out_lens[b]] followed by cur[b] — NO_TOKEN-padded on the left.
    The jitted spec step computes the same thing on-device; this exists for
    host-side drafting (propose_drafts_host callers)."""
    B = out.shape[0]
    tail = np.full((B, n), NO_TOKEN, dtype=np.int32)
    for b in range(B):
        hist = list(out[b, : int(out_lens[b])]) + [int(cur[b])]
        take = hist[-n:]
        tail[b, n - len(take):] = take
    return tail
