"""Reference-guided speculative decoding (draft-model-free).

The package splits along the draft/verify seam:

- :mod:`drafter` — n-gram suffix matching against the request's source
  document proposes up to ``k`` continuation tokens per row (jnp for the
  jitted engine path, numpy for host callers);
- the batched verify step lives in ``backend/engine.py`` (it is a decode
  variant of TpuBackend, entangled with its cache/bucketing machinery);
  the multi-position attention it needs is ``models.llama`` (dense) and
  ``ops.decode_attention.flash_spec_verify_attention`` (Pallas);
- :class:`SpecRecord` is the per-prompt observability unit the serving
  layer attributes to requests (core/results.py, serve/metrics.py).

Enabled per call via ``GenerationConfig(spec_k=K)`` plus per-prompt
``references`` on ``backend.generate``; ``spec_k=0`` (the default) leaves
every existing path untouched.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass

from .drafter import (  # noqa: F401
    NO_TOKEN,
    encode_references,
    history_tail,
    propose_drafts,
    propose_drafts_host,
)


@dataclass
class SpecRecord:
    """Per-prompt speculative-decoding accounting for ONE generate call.

    ``draft_tokens`` counts tokens proposed by the drafter and fed to
    verification; ``accepted_tokens`` counts those the model kept (emitted);
    ``verify_steps`` counts batched verify forwards the row was live for.
    Mean emitted-per-step is ``(accepted_tokens + verify_steps) /
    verify_steps`` — every step retires at least the model's own token."""

    draft_tokens: int = 0
    accepted_tokens: int = 0
    verify_steps: int = 0

    @property
    def acceptance_rate(self) -> float:
        return (
            self.accepted_tokens / self.draft_tokens if self.draft_tokens else 0.0
        )

    @property
    def tokens_per_step(self) -> float:
        if not self.verify_steps:
            return 0.0
        return (self.accepted_tokens + self.verify_steps) / self.verify_steps

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["acceptance_rate"] = round(self.acceptance_rate, 6)
        d["tokens_per_step"] = round(self.tokens_per_step, 6)
        return d


__all__ = [
    "NO_TOKEN",
    "SpecRecord",
    "encode_references",
    "history_tail",
    "propose_drafts",
    "propose_drafts_host",
]
