"""Token-id radix index over fixed-size blocks, with ref-counts + LRU.

The index is pure host state: a trie whose edges are BLOCK-sized runs of
token ids (``block_tokens`` per node), each node owning one block id in the
device pool (cache/store.py). Matching walks whole blocks only — a prefix is
reusable at block granularity, the standard paged-KV compromise (vLLM /
SGLang RadixAttention) that keeps device copies rectangular.

Concurrency contract (mirrors the serving layer's single engine thread,
serve/scheduler.py): ALL mutation — pinning matches, inserting chains,
eviction (which only happens inside an insert's allocation) — runs on the
one engine thread; other threads may only :meth:`probe` for admission
accounting. Everything still locks, so a probe can never observe a
half-linked chain, but the no-pin window between ``insert`` and the pool
write is safe only because no other allocator exists.

Eviction: leaves (no children) with refcount 0, least-recently-used first —
recency IS the ``_evictable`` dict's insertion order (refreshes move a node
to the MRU end); there are no timestamps.
A pinned block can never be reallocated while a live batch's gather might
still read it — the acceptance property tests/test_cache_radix.py pins.

Token ids are any hashable scalars: ints for the real tokenizers,
whitespace words for FakeBackend's synthetic mirror.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Hashable, Sequence

from ..analysis.sanitizers import make_lock


@dataclass
class CacheStats:
    """Host-side accounting; the serve layer re-exports these on /metrics
    (vnsum_serve_cache_* — see serve/metrics.py)."""

    lookups: int = 0
    hit_tokens: int = 0
    miss_tokens: int = 0
    inserted_blocks: int = 0
    evictions: int = 0

    def to_dict(self) -> dict:
        import dataclasses

        return dataclasses.asdict(self)


class _Node:
    __slots__ = ("key", "block", "parent", "children", "refs")

    def __init__(self, key: tuple, block: int, parent: "_Node | None") -> None:
        self.key = key          # the block_tokens ids this node spans
        self.block = block      # device pool block id (-1 on the root)
        self.parent = parent
        self.children: dict[tuple, _Node] = {}
        self.refs = 0


@dataclass
class Match:
    """A pinned chain of matched blocks. ``blocks`` are pool ids in prefix
    order; ``tokens`` == len(blocks) * block_tokens. Hold it across the
    device gather, then :meth:`RadixIndex.release` it exactly once."""

    blocks: list[int] = field(default_factory=list)
    tokens: int = 0
    nodes: list = field(default_factory=list, repr=False)
    released: bool = False


class RadixIndex:
    def __init__(self, num_blocks: int, block_tokens: int) -> None:
        if num_blocks < 1:
            raise ValueError("num_blocks must be >= 1")
        if block_tokens < 1:
            raise ValueError("block_tokens must be >= 1")
        self.num_blocks = num_blocks
        self.block_tokens = block_tokens
        self.stats = CacheStats()               # guarded by: _lock
        self._root = _Node((), -1, None)        # guarded by: _lock
        # pop() -> 0 first
        self._free: list[int] = list(range(num_blocks - 1, -1, -1))  # guarded by: _lock
        # LRU queue of evictable nodes (linked leaves with refcount 0), kept
        # in insertion order: refreshing moves a node to the MRU end, so
        # eviction is an O(1) front pop instead of a full-trie scan under
        # the lock (which would serialize HTTP-thread probes behind
        # O(nodes) insert churn at pool saturation)
        self._evictable: dict[_Node, None] = {}  # guarded by: _lock
        # lock-order-sanitizer hook: plain threading.Lock in production
        self._lock = make_lock("cache.radix")

    # -- introspection ---------------------------------------------------

    @property
    def blocks_used(self) -> int:
        with self._lock:
            return self.num_blocks - len(self._free)

    @property
    def pinned_blocks(self) -> int:
        """Blocks with a live refcount — what an un-released Match leaks.
        O(nodes) trie walk; a debug/assertion surface (the fault-injection
        tests pin that a crashed dispatch returns this to its pre-batch
        level), never on the serving path."""
        with self._lock:
            return self._pinned_locked()

    def _pinned_locked(self) -> int:
        count = 0
        stack = [self._root]
        while stack:
            node = stack.pop()
            if node.refs > 0:
                count += 1
            stack.extend(node.children.values())
        return count

    def stats_dict(self) -> dict:
        with self._lock:
            d = self.stats.to_dict()
            d["blocks_used"] = self.num_blocks - len(self._free)
            d["blocks_total"] = self.num_blocks
            # scrape-time pin-leak probe (vnsum_serve_cache_pinned_blocks):
            # O(nodes), fine at scrape cadence — the churn chaos soak
            # asserts this returns to baseline after client churn
            d["pinned_blocks"] = self._pinned_locked()
            return d

    # -- matching --------------------------------------------------------

    def _walk_locked(self, tokens: Sequence[Hashable], max_tokens: int) -> list[_Node]:
        BLK = self.block_tokens
        limit = min(len(tokens), max_tokens)
        chain: list[_Node] = []
        node = self._root
        off = 0
        while off + BLK <= limit:
            child = node.children.get(tuple(tokens[off : off + BLK]))
            if child is None:
                break
            chain.append(child)
            node = child
            off += BLK
        return chain

    def match(
        self, tokens: Sequence[Hashable], max_tokens: int | None = None
    ) -> Match:
        """Longest block-aligned cached prefix of ``tokens``, PINNED: every
        matched node's refcount is bumped so eviction cannot reallocate its
        block before :meth:`release`. ``max_tokens`` caps the match (the
        engine passes len-1 so at least one suffix token remains to produce
        first-token logits)."""
        if max_tokens is None:
            max_tokens = len(tokens)
        with self._lock:
            chain = self._walk_locked(tokens, max_tokens)
            for n in chain:
                n.refs += 1
                self._evictable.pop(n, None)  # pinned: off the LRU queue
            matched = len(chain) * self.block_tokens
            self.stats.lookups += 1
            self.stats.hit_tokens += matched
            self.stats.miss_tokens += max(len(tokens) - matched, 0)
            return Match(
                blocks=[n.block for n in chain], tokens=matched, nodes=chain
            )

    def probe(self, tokens: Sequence[Hashable], max_tokens: int | None = None) -> int:
        """Read-only match length in tokens — admission-control accounting
        from other threads. No pin, no stats, no LRU touch."""
        if max_tokens is None:
            max_tokens = len(tokens)
        with self._lock:
            return len(self._walk_locked(tokens, max_tokens)) * self.block_tokens

    def release(self, match: Match) -> None:
        with self._lock:
            if match.released:
                return
            match.released = True
            for n in match.nodes:
                n.refs -= 1
                self._refresh_evictable_locked(n)

    # -- insertion / eviction -------------------------------------------

    def _refresh_evictable_locked(self, node: _Node) -> None:
        """Re-derive a node's LRU-queue membership after a refs/children
        change: linked leaves with refcount 0 sit in the queue, moved to
        the MRU end on refresh (a parent freshly exposed by a tail eviction
        re-enters at the MRU end too — a mild LRU approximation that only
        delays, never corrupts, its turn)."""
        self._evictable.pop(node, None)
        if node.parent is not None and node.refs == 0 and not node.children:
            self._evictable[node] = None

    def _evict_one_locked(self) -> int | None:
        """Reclaim the LRU unpinned LEAF's block; None when everything is
        pinned or interior (chains are evicted tail-first). O(1): the
        evictable queue is maintained incrementally."""
        victim = next(iter(self._evictable), None)
        if victim is None:
            return None
        del self._evictable[victim]
        parent = victim.parent
        parent.children.pop(victim.key, None)
        victim.parent = None  # unlinked: a late refresh can never re-queue it
        self.stats.evictions += 1
        # the unlink may expose the parent as a new evictable leaf
        self._refresh_evictable_locked(parent)
        return victim.block

    def insert(
        self, tokens: Sequence[Hashable], upto: int
    ) -> list[tuple[int, int]]:
        """Extend the trie to cover ``tokens[:upto]`` (block-truncated),
        reusing existing nodes; allocates pool blocks for the missing tail,
        evicting LRU leaves as needed. Returns [(block_id, token_offset)]
        for NEWLY allocated blocks only — the caller must fill those pool
        slots before the next engine-thread match can hand them out (safe by
        the single-allocator contract in the module docstring). Stops early
        (possibly empty) when nothing is evictable."""
        BLK = self.block_tokens
        limit = min(len(tokens), upto) // BLK * BLK
        new: list[tuple[int, int]] = []
        path: list[_Node] = []
        with self._lock:
            node = self._root
            off = 0
            while off + BLK <= limit:
                key = tuple(tokens[off : off + BLK])
                child = node.children.get(key)
                if child is None:
                    block = self._free.pop() if self._free else self._evict_one_locked()
                    if block is None:
                        break
                    child = _Node(key, block, node)
                    node.children[key] = child
                    self._evictable.pop(node, None)  # parent is no leaf now
                    self.stats.inserted_blocks += 1
                    new.append((block, off))
                # transient pin: a later allocation in THIS insert must not
                # evict a node of the chain being built (a fresh leaf has
                # refs 0 and would otherwise be fair game under a full pool)
                child.refs += 1
                self._evictable.pop(child, None)
                path.append(child)
                node = child
                off += BLK
            for n in path:
                n.refs -= 1
                self._refresh_evictable_locked(n)
        return new
