"""Paged KV block store + the engine-facing PrefixCache facade.

The pool mirrors the stacked cache layout the attention kernels consume
(models/llama.py init_kv_cache: [L, B, KV, C, hd], scales [L, B, KV, C]):
one pool row per block, [L, KV, BLK, hd] (and [L, KV, BLK] for int8-KV
scales), so extraction and gather are pure layout-preserving copies — no
transpose ever materializes on device.

Blocks are POSITION-CONTIGUOUS: a block holds the KV of BLK consecutive
prompt tokens at RoPE positions [off, off + BLK), independent of where the
row sat in its producer batch. Left-padded batches place token position p of
a row at cache slot pad + p (models/llama.py prefill_positions), so a block
extracted at slot pad_src + off pastes into any consumer row at slot
pad_dst + off — the positions line up by construction, which is what makes
cross-request, cross-bucket reuse sound.

Two device ops, both jitted per cache-shape bucket:

- :meth:`BlockStore.write_block` — copy one block slab out of a batch row
  into the pool (insertion after prefill); one dispatch per block keeps the
  copies clamp-free for any slot alignment.
- :meth:`BlockStore.gather` — vmapped per-row ``dynamic_update_slice`` of up
  to NB blocks into a fresh batch cache at per-row slot offsets (the same
  per-row ragged-write shape as llama._cache_write's vector path). Rows
  needing fewer blocks pad with the scratch block id; those writes land at
  slots the suffix prefill overwrites (or a filler row nobody reads), so
  padding is harmless by construction — see backend/engine.py's resume path
  for the slot arithmetic that guarantees it.
"""
from __future__ import annotations

import numpy as np

from .radix import Match, RadixIndex


def _pow2_at_least(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


class BlockStore:
    """Device pool of ``num_blocks`` KV blocks (+1 scratch row used as the
    padding target for ragged gathers; the radix index never hands it out).

    Under a ``mesh`` the pool shards its KV-head dim over `model` —
    mirroring ``parallel.sharding.cache_specs`` so gather/extract copies are
    head-local (no resharding collective on the hot path) — and stays
    replicated over `data`: a block is position-contiguous KV shared by ALL
    batch rows, so every data replica must see every block."""

    def __init__(
        self,
        num_blocks: int,
        block_tokens: int,
        *,
        n_layers: int,
        n_kv_heads: int,
        head_dim: int,
        dtype,
        quantized: bool = False,
        mesh=None,
    ) -> None:
        import jax.numpy as jnp

        self.block_tokens = block_tokens
        self.scratch_id = num_blocks
        self.mesh = mesh
        N = num_blocks + 1
        shape = (N, n_layers, n_kv_heads, block_tokens, head_dim)
        # [N, L, KV(, BLK, hd)]: KV heads over `model`, rest replicated —
        # allocated DIRECTLY into the sharding (a production pool is sized
        # against the mesh's combined HBM; materializing it on one chip
        # first would OOM at exactly the scale the mesh exists for)
        placement = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.mesh import AXES

            model_size = mesh.shape.get(AXES.model, 1)
            if n_kv_heads % max(model_size, 1):
                raise ValueError(
                    f"n_kv_heads={n_kv_heads} is not divisible by mesh axis "
                    f"'{AXES.model}' ({model_size}); shrink that axis or "
                    "pick a TP-compatible model config"
                )

            def placement(ndim):
                return NamedSharding(
                    mesh,
                    P(*((None, None, AXES.model) + (None,) * (ndim - 3))),
                )

        def zeros(shp, dt):
            if placement is None:
                return jnp.zeros(shp, dt)
            return jnp.zeros(shp, dt, device=placement(len(shp)))

        if quantized:
            self.pool = {
                "k": zeros(shape, jnp.int8),
                "v": zeros(shape, jnp.int8),
                "ks": zeros(shape[:-1], jnp.float32),
                "vs": zeros(shape[:-1], jnp.float32),
            }
        else:
            self.pool = {
                "k": zeros(shape, dtype),
                "v": zeros(shape, dtype),
            }
        self._write_fns: dict = {}
        self._gather_fns: dict = {}

    @property
    def hbm_bytes(self) -> int:
        return sum(v.size * v.dtype.itemsize for v in self.pool.values())

    @staticmethod
    def _shape_sig(cache: dict) -> tuple:
        return tuple(sorted((k, v.shape, str(v.dtype)) for k, v in cache.items()))

    def _constrain_batch_cache(self, cache: dict) -> dict:
        """Pin a [L, B, KV, C(, hd)] batch cache to the engine's (data,
        model) layout inside a traced gather — without this the seeded
        cache's layout is left to GSPMD propagation and the resume prefill
        pays a re-layout on its first touch. Identity off-mesh."""
        if self.mesh is None:
            return cache
        import jax
        from jax.sharding import NamedSharding

        from ..parallel.sharding import cache_specs

        specs = cache_specs(quantized="ks" in cache)
        return {
            name: jax.lax.with_sharding_constraint(
                buf, NamedSharding(self.mesh, specs[name])
            )
            for name, buf in cache.items()
        }

    # -- insertion -------------------------------------------------------

    def write_block(self, cache: dict, row: int, slot: int, block_id: int) -> None:
        """Copy the [slot, slot+BLK) slab of batch ``row`` into pool block
        ``block_id``. One small device-to-device copy; per-block dispatch
        means no padded slice can ever clamp onto neighbouring slots."""
        import jax
        import jax.numpy as jnp

        BLK = self.block_tokens
        key = self._shape_sig(cache)
        fn = self._write_fns.get(key)
        if fn is None:

            def write(pool, cache, row, slot, bid):
                out = {}
                for name, buf in cache.items():
                    # [L, B, KV, C(, hd)] -> slab [L, KV, BLK(, hd)]
                    L, _, KV = buf.shape[:3]
                    tail = buf.shape[4:]
                    sizes = (L, 1, KV, BLK) + tail
                    starts = (0, row, 0, slot) + (0,) * len(tail)
                    slab = jax.lax.dynamic_slice(buf, starts, sizes)[:, 0]
                    out[name] = jax.lax.dynamic_update_slice(
                        pool[name], slab[None],
                        (bid,) + (0,) * (pool[name].ndim - 1),
                    )
                return out

            fn = jax.jit(write, donate_argnums=(0,))
            self._write_fns[key] = fn
        self.pool = fn(
            self.pool, cache,
            jnp.int32(row), jnp.int32(slot), jnp.int32(block_id),
        )

    # -- gather ----------------------------------------------------------

    def gather(self, cache: dict, block_ids: np.ndarray, starts: np.ndarray) -> dict:
        """Seed ``cache`` (a fresh [L, B, KV, C, hd] batch cache) with pool
        blocks: row b gets block_ids[b, i] written at slot starts[b] + i*BLK.
        ``block_ids`` is [B, NB'] (any NB'); it is padded to a power-of-two
        NB with the scratch id to bound compiled-program fan-out."""
        import jax
        import jax.numpy as jnp

        BLK = self.block_tokens
        B, nb = block_ids.shape
        NB = _pow2_at_least(max(nb, 1))
        ids = np.full((B, NB), self.scratch_id, dtype=np.int32)
        ids[:, :nb] = block_ids
        key = (B, NB, self._shape_sig(cache))
        fn = self._gather_fns.get(key)
        if fn is None:

            def per_row(pool, row_cache, row_ids, start):
                for i in range(NB):
                    for name in row_cache:
                        blk = pool[name][row_ids[i]]  # [L, KV, BLK(, hd)]
                        nd = row_cache[name].ndim
                        idx = (0, 0, start + i * BLK) + (0,) * (nd - 3)
                        row_cache[name] = jax.lax.dynamic_update_slice(
                            row_cache[name], blk, idx
                        )
                return row_cache

            def gather_fn(pool, cache, ids, starts):
                out = jax.vmap(
                    per_row, in_axes=(None, 1, 0, 0), out_axes=1
                )(pool, cache, ids, starts)
                return self._constrain_batch_cache(out)

            fn = jax.jit(gather_fn, donate_argnums=(1,))
            self._gather_fns[key] = fn
        return fn(
            self.pool, cache, jnp.asarray(ids),
            jnp.asarray(starts, dtype=jnp.int32),
        )


class PrefixCache:
    """Radix index + block store, the one object the engine talks to.

    Single engine thread does all mutation (match-with-pin, gather, insert);
    other threads may only :meth:`probe` — the contract inherited from
    cache/radix.py."""

    def __init__(
        self,
        num_blocks: int,
        block_tokens: int,
        *,
        n_layers: int,
        n_kv_heads: int,
        head_dim: int,
        dtype,
        quantized: bool = False,
        mesh=None,
    ) -> None:
        self.block_tokens = block_tokens
        self.index = RadixIndex(num_blocks, block_tokens)
        self.store = BlockStore(
            num_blocks, block_tokens, n_layers=n_layers,
            n_kv_heads=n_kv_heads, head_dim=head_dim, dtype=dtype,
            quantized=quantized, mesh=mesh,
        )

    def match(self, ids, max_tokens: int | None = None) -> Match:
        return self.index.match(ids, max_tokens)

    def release(self, match: Match) -> None:
        self.index.release(match)

    def probe(self, ids, max_tokens: int | None = None) -> int:
        return self.index.probe(ids, max_tokens)

    def gather(self, cache: dict, block_ids, starts) -> dict:
        return self.store.gather(cache, block_ids, starts)

    def insert(self, cache: dict, row: int, slot_base: int, ids, upto: int) -> int:
        """Index tokens[:upto] of a freshly prefilled row and copy the newly
        allocated blocks' KV out of ``cache`` (whose row sits left-padded at
        ``slot_base``). Returns the number of new blocks written."""
        new = self.index.insert(ids, upto)
        for block, off in new:
            self.store.write_block(cache, row, slot_base + off, block)
        return len(new)

    def stats_dict(self) -> dict:
        d = self.index.stats_dict()
        d["block_tokens"] = self.block_tokens
        d["hbm_bytes"] = self.store.hbm_bytes
        return d
