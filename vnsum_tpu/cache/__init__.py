"""Radix prefix KV cache — cross-request prompt reuse for prefill.

Every prompt this system prefills is prefix-redundant by construction: map
chunks share a template header (strategies/prompts.py), iterative refinement
re-feeds the prior summary, hierarchical collapse re-feeds child summaries.
This package caches the KV of already-prefilled token prefixes so later
requests prefill only their suffix (survey arXiv:2405.13019 §KV-cache reuse):

- :mod:`radix` — the host-side token-id radix index at block granularity,
  with ref-counting (live batches pin their matched blocks) and LRU eviction
  under a fixed block budget;
- :mod:`store` — the device-side paged block pool (one [L, KV, BLK, hd]
  slab per block, mirroring the stacked cache layout of models/llama.py)
  plus :class:`~vnsum_tpu.cache.store.PrefixCache`, the engine-facing facade
  combining both.

Greedy outputs on the resume-prefill path are byte-identical to the uncached
path on same-shape replays (the same caveat as decode compaction,
backend/engine.py): cached K/V are bitwise copies of what full prefill wrote,
and the suffix forward computes the same math over the same cache length.
"""
from .radix import CacheStats, Match, RadixIndex
from .store import BlockStore, PrefixCache

__all__ = ["BlockStore", "CacheStats", "Match", "PrefixCache", "RadixIndex"]
