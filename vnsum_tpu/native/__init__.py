"""ctypes bindings for the C++ host core (native/vnsum_native.cpp).

Loads libvnsum_native.so from the repo's native/ dir (building it on demand
with `make` when a compiler is available) and exposes:

- rouge_score_native / rouge_corpus_native — C++ ROUGE-1/2/L with the same
  tokenizer+stemmer semantics as eval/rouge.py;
- porter_stem_native — the NLTK-mode Porter stemmer;
- split_text_bytes — the recursive byte-budget splitter;
- count_words — whitespace word count.

Everything degrades gracefully: `available()` is False when the library
can't be built/loaded, and callers fall back to the Python implementations.
"""
from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path

from ..core.logging import get_logger

logger = get_logger("vnsum.native")

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libvnsum_native.so"
_lib: ctypes.CDLL | None = None
_load_attempted = False


def _try_build() -> bool:
    if not (_NATIVE_DIR / "vnsum_native.cpp").is_file():
        return False
    try:
        subprocess.run(
            ["make", "-s"], cwd=_NATIVE_DIR, check=True,
            capture_output=True, timeout=120,
        )
        return _LIB_PATH.is_file()
    except Exception as e:
        logger.info("native build unavailable: %s", e)
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    # always run make: no-op when fresh, rebuilds a stale .so after source edits
    if not _try_build() and not _LIB_PATH.is_file():
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError as e:
        logger.info("native library load failed: %s", e)
        return None
    lib.vn_rouge_score.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.vn_rouge_corpus.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_double),
    ]
    lib.vn_porter_stem.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.vn_porter_stem.restype = ctypes.c_int
    lib.vn_count_words.argtypes = [ctypes.c_char_p]
    lib.vn_count_words.restype = ctypes.c_int
    lib.vn_split_bytes.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_long,
        ctypes.POINTER(ctypes.c_int), ctypes.c_int,
    ]
    lib.vn_split_bytes.restype = ctypes.c_int
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _c_text(s: str) -> bytes:
    """Encode for a NUL-terminated char*; embedded NULs would silently
    truncate, so callers must fall back to Python for such strings."""
    b = s.encode("utf-8")
    if b"\x00" in b:
        raise ValueError("text contains NUL; use the Python path")
    return b


def rouge_score_native(target: str, prediction: str, use_stemmer: bool = True):
    """Returns {"rouge1"|"rouge2"|"rougeL": (precision, recall, fmeasure)}.
    Raises ValueError for strings with embedded NULs (fall back to Python)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    out = (ctypes.c_double * 9)()
    lib.vn_rouge_score(
        _c_text(target), _c_text(prediction), int(use_stemmer), out,
    )
    vals = list(out)
    return {
        "rouge1": tuple(vals[0:3]),
        "rouge2": tuple(vals[3:6]),
        "rougeL": tuple(vals[6:9]),
    }


def rouge_corpus_native(
    targets: list[str], predictions: list[str], use_stemmer: bool = True
):
    """Batched scoring: returns a list of per-pair dicts like
    rouge_score_native."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(targets)
    if n != len(predictions):
        raise ValueError("targets and predictions must align")
    t_arr = (ctypes.c_char_p * n)(*[_c_text(t) for t in targets])
    p_arr = (ctypes.c_char_p * n)(*[_c_text(p) for p in predictions])
    out = (ctypes.c_double * (9 * n))()
    lib.vn_rouge_corpus(t_arr, p_arr, n, int(use_stemmer), out)
    results = []
    for i in range(n):
        v = out[9 * i : 9 * i + 9]
        results.append(
            {
                "rouge1": tuple(v[0:3]),
                "rouge2": tuple(v[3:6]),
                "rougeL": tuple(v[6:9]),
            }
        )
    return results


def porter_stem_native(word: str) -> str:
    """Lowercases like PorterStemmer.stem; non-ASCII words take the Python
    path (the rouge tokenizer never produces them, but the public API must
    agree with the Python stemmer)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    lowered = word.lower()
    try:
        encoded = lowered.encode("ascii")
    except UnicodeEncodeError:
        from ..eval.rouge import PorterStemmer

        return PorterStemmer().stem(word)
    buf = ctypes.create_string_buffer(len(encoded) + 8)
    n = lib.vn_porter_stem(encoded, buf, len(buf))
    return buf.raw[:n].decode("ascii")


def count_words(text: str) -> int:
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    return lib.vn_count_words(_c_text(text))


def split_text_bytes(text: str, chunk_size: int, chunk_overlap: int) -> list[str]:
    """Native equivalent of RecursiveTokenSplitter(...).split_text for the
    byte-count length function. Raises ValueError for NUL-containing text."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    data = _c_text(text)
    if not data:
        return []
    # overlap carry-over inflates total output; grow the buffer on demand
    cap = max(len(data) * 2 + 4096, 1 << 16)
    max_chunks = max(len(data), 1024)
    for _ in range(8):
        buf = ctypes.create_string_buffer(cap)
        lens = (ctypes.c_int * max_chunks)()
        n = lib.vn_split_bytes(
            data, chunk_size, chunk_overlap, buf, cap, lens, max_chunks
        )
        if n >= 0:
            raw = buf.raw
            chunks = []
            start = 0
            for i in range(n):
                chunks.append(raw[start : start + lens[i]].decode("utf-8"))
                start += lens[i]
            return chunks
        cap *= 2
        max_chunks *= 2
    raise RuntimeError("native splitter buffer overflow after retries")
