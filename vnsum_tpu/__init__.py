"""vnsum_tpu — TPU-native Vietnamese long-document summarization framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
pipeline `Duy1230/Map-Reduced-Approach-for-Vietnamese-Long-Document-
Summarization` (see SURVEY.md): five summarization strategies (truncated,
map-reduce, map-reduce + self-critique, iterative refinement, hierarchical
tree-collapse), a full evaluation stack (ROUGE / BERTScore / semantic
similarity / G-Eval), and a batch pipeline with resume + structured results —
all executing against a batched, mesh-sharded on-device generation engine
instead of serial HTTP calls.

Layer map (mirrors SURVEY.md §1, inverted per §7):

    pipeline/    batch runner, CLI, reports           (ref L6)
    eval/        metrics, on-device embeddings        (ref L5)
    strategies/  the five approaches as host drivers  (ref L4+L3)
    text/        tokenizers, splitter, cleaner, tree  (ref L2)
    backend/     Backend protocol + generation engine (ref L1)
    models/      Llama-3.2-3B + encoder in JAX        (new)
    ops/         Pallas TPU kernels                   (new)
    parallel/    mesh, shardings, collectives         (new)
    train/       sharded training step                 (new)
    data/        datasets, document trees             (ref L0)
    core/        config, logging, run records
"""

__version__ = "0.1.0"
