"""Pallas TPU decode attention over the full stacked KV cache.

Single-token decode attention is pure HBM streaming, but the XLA lowering of
the naive formulation adds ~3x traffic on top of the mandatory cache read
(measured on a 48x1088 Llama-3.2-3B cache, 25.3 GB touched per step vs ~9 GB
mandatory):

- `dynamic_index_in_dim(cache, layer)` materializes a per-layer cache copy
  inside the layer scan (107 MB x 2 x 28 layers per step);
- XLA pins the while-loop cache carry to one layout while the attention
  einsum prefers another, inserting TWO whole-cache layout-conversion copies
  (3.1 GB each) per step, in each direction.

This kernel sidesteps both by consuming the stacked [L, B, KV, C, hd] cache
directly: the layer index arrives via scalar prefetch and only steers the
BlockSpec index_map, so exactly the needed blocks are DMA'd — no extraction,
no conversion.

Block geometry matters more than anything here: a first cut that gridded
over (B, KV, C/BK) issued tens-of-KB DMAs and ran 3x SLOWER than the XLA
path (92 ms/step) because the pipeline never got deep enough. This version
grids over (B/BB, ceil(C/BK)) with each block carrying all KV heads and BB
batch rows (~MB-scale DMAs); the BB x KV attention groups are computed as an
unrolled loop of small MXU dots against VMEM-resident tiles.

Blocks past the current fill position are elided by clamping the index_map
(Pallas skips the DMA when consecutive grid steps address the same block)
and `pl.when` skips their compute, so a step at fill=600 in a C=1152 cache
reads only ~half the cache.

int8 KV caches (models.llama.init_kv_cache(quantized=True)) stream half the
bytes again: the kernel loads int8 K/V blocks plus per-(token, head) f32
scales and folds dequantization into the softmax algebra — scores multiply
by the K scale per cache slot, and probabilities multiply by the V scale
before the PV dot (diag-scale commutes through both contractions).

Inference-only (no VJP). The reference has no analog — its decode happens
inside Ollama (SURVEY.md §1 L1).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _LANES, _NEG


def _kernel(
    lidx_ref,  # [1] int32 (SMEM) — layer to read
    fill_ref,  # [1] int32 (SMEM) — last valid cache slot (inclusive)
    win_ref,   # [1] int32 (SMEM) — sliding window; 0 = global
    *refs,
    block_b: int,
    block_k: int,
    n_kv: int,
    scale: float,
    quantized: bool,
    return_partials: bool = False,
):
    if return_partials:
        # outputs are the UNNORMALIZED online-softmax state (acc, m, l) —
        # the shard-local form the long-context path LSE-merges across the
        # seq axis (backend.long_context make_long_decode_attention)
        if quantized:
            (q_ref, pads_ref, k_ref, v_ref, ks_ref, vs_ref,
             o_ref, mo_ref, lo_ref, acc_ref, m_ref, l_ref) = refs
        else:
            (q_ref, pads_ref, k_ref, v_ref,
             o_ref, mo_ref, lo_ref, acc_ref, m_ref, l_ref) = refs
            ks_ref = vs_ref = None
    elif quantized:
        q_ref, pads_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs
        mo_ref = lo_ref = None
    else:
        q_ref, pads_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
        ks_ref = vs_ref = mo_ref = lo_ref = None
    # q_ref/o_ref [1, BB*KV, G, hd] (host pre-merges the batch/head dims —
    # Mosaic supports MERGING leading dims in-kernel but not splitting them,
    # and tpu.matmul takes a single batch dim); pads_ref [1, BB*KV, 1, BK]
    # (per-row left-pads pre-broadcast on host: SMEM scalars can't be
    # stacked into a vector in-kernel); k_ref/v_ref [1, BB, KV, BK, hd];
    # ks_ref/vs_ref [1, BB, KV, BK]; scratch acc [BB*KV, G, hd],
    # m/l [BB*KV, G, LANES]

    j = pl.program_id(1)
    nj = pl.num_programs(1)
    fill = fill_ref[0]
    win = win_ref[0]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    # blocks wholly past the fill point — or, with a sliding window, wholly
    # below the window floor — were never DMA'd (clamped index_map); skip
    # their compute so the clamped duplicate block isn't double-counted
    @pl.when(
        (j * block_k <= fill)
        & ((win == 0) | (j * block_k + block_k - 1 >= fill - win + 1))
    )
    def _compute():
        G = q_ref.shape[2]
        hd = q_ref.shape[3]
        BKV = block_b * n_kv
        # one batched dot over the merged (BB, KV) dim instead of BBxKV
        # unrolled small dots: the unrolled form was VPU-bound (its softmax
        # bookkeeping ran once per head) and an int8 cache gave no speedup
        qb = q_ref[0].astype(jnp.float32)                       # [BKV, G, hd]
        kb = k_ref[0].astype(jnp.float32).reshape(BKV, block_k, hd)
        vb = v_ref[0].astype(jnp.float32).reshape(BKV, block_k, hd)

        s = jax.lax.dot_general(
            qb, kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale  # [BKV, G, BK]
        if quantized:
            s = s * ks_ref[0].reshape(BKV, 1, block_k)

        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (BKV, 1, block_k), 2
        )
        mask = (k_pos >= pads_ref[0]) & (k_pos <= fill)  # [BKV, 1, BK]
        # window in slot space, matching the dense path's k_slot > fill - win
        mask = mask & ((win == 0) | (k_pos > fill - win))
        s = jnp.where(mask, s, _NEG)

        m_prev = m_ref[:, :, :1]                         # [BKV, G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)

        l_ref[...] = jnp.broadcast_to(
            l_ref[:, :, :1] * corr + jnp.sum(p, axis=2, keepdims=True),
            l_ref.shape,
        )
        if quantized:
            p = p * vs_ref[0].reshape(BKV, 1, block_k)
        pv = jax.lax.dot_general(
            p, vb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [BKV, G, hd]
        acc_ref[...] = acc_ref[...] * corr + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == nj - 1)
    def _finalize():
        if return_partials:
            o_ref[0] = acc_ref[...].astype(o_ref.dtype)
            mo_ref[0] = m_ref[...]
            lo_ref[0] = l_ref[...]
        else:
            l = jnp.maximum(l_ref[:, :, :1], 1e-30)
            o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _pick_block_b(batch: int) -> int:
    for b in (8, 4, 2, 1):
        if batch % b == 0:
            return b
    return 1


def supports_decode(cache_len: int, head_dim: int) -> bool:
    """Ceil-div grid handles any C; only lane-aligned head dims matter."""
    return head_dim % _LANES == 0


@functools.partial(
    jax.jit,
    static_argnames=("q_per_kv", "block_k", "interpret", "return_partials"),
)
def flash_decode_attention(
    q: jax.Array,          # [B, 1, H, hd]
    cache: dict,           # stacked {"k","v"[, "ks","vs"]} (llama.init_kv_cache)
    layer_idx: jax.Array,  # scalar int32
    pad_lens: jax.Array,   # [B] int32
    fill: jax.Array,       # scalar int32 — last valid slot (inclusive)
    q_per_kv: int,
    window: jax.Array | None = None,  # scalar int32; 0/None = global
    *,
    block_k: int = 128,
    interpret: bool = False,
    return_partials: bool = False,
) -> jax.Array:
    """Semantics match _attention(q, dequantized cache[layer],
    mask=pad<=j<=fill); returns [B, 1, H, hd]. ``window`` > 0 restricts to
    the last ``window`` slots (Gemma sliding layers): below-window blocks
    are compute-skipped and DMA-elided like past-fill blocks, so a sliding
    layer's step reads only ~window worth of cache however long the fill.

    ``return_partials=True`` returns the unnormalized online-softmax state
    ``(o [B, H, hd] f32, m [B, H] f32, l [B, H] f32)`` instead — the
    shard-local partial the long-context decode LSE-merges across the seq
    axis (same contract as backend.long_context._prefill_partial_local)."""
    k_all, v_all = cache["k"], cache["v"]
    quantized = "ks" in cache
    B, S, H, hd = q.shape
    L, _, KV, C, _ = k_all.shape
    if S != 1:
        raise ValueError(f"decode kernel is single-token (S=1), got S={S}")
    if hd % _LANES and not interpret:
        raise ValueError(f"unsupported decode head_dim={hd}")
    bk = min(block_k, C)
    bb = _pick_block_b(B)

    qg = q.reshape(B // bb, bb * KV, q_per_kv, hd)
    # per-row left-pads, pre-broadcast to the merged-row block shape (the
    # kernel can't assemble a vector out of SMEM scalars)
    pads = jnp.broadcast_to(
        pad_lens.astype(jnp.int32).reshape(B // bb, bb, 1, 1, 1),
        (B // bb, bb, KV, 1, bk),
    ).reshape(B // bb, bb * KV, 1, bk)
    grid = (B // bb, pl.cdiv(C, bk))

    def visible_j(j, fill, win, blk=bk):
        # clamp past-fill (and, under a window, below-window) blocks onto
        # the nearest visible block: consecutive grid steps then address the
        # same block and Pallas elides the DMA
        lo = jnp.where(
            win[0] > 0, jnp.maximum(fill[0] - win[0] + 1, 0) // blk, 0
        )
        return jnp.clip(j, lo, fill[0] // blk)

    def kv_index(b, j, lidx, fill, win):
        return (lidx[0], b, 0, visible_j(j, fill, win), 0)

    def scale_index(b, j, lidx, fill, win):
        return (lidx[0], b, 0, visible_j(j, fill, win))

    in_specs = [
        pl.BlockSpec(
            (1, bb * KV, q_per_kv, hd),
            lambda b, j, lidx, fill, win: (b, 0, 0, 0),
        ),
        pl.BlockSpec(
            (1, bb * KV, 1, bk), lambda b, j, lidx, fill, win: (b, 0, 0, 0)
        ),
        pl.BlockSpec((1, bb, KV, bk, hd), kv_index),
        pl.BlockSpec((1, bb, KV, bk, hd), kv_index),
    ]
    operands = [qg, pads, k_all, v_all]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, bb, KV, bk), scale_index),
            pl.BlockSpec((1, bb, KV, bk), scale_index),
        ]
        operands += [cache["ks"], cache["vs"]]

    kernel = functools.partial(
        _kernel, block_b=bb, block_k=bk, n_kv=KV, scale=1.0 / (hd ** 0.5),
        quantized=quantized, return_partials=return_partials,
    )
    out_block = lambda shape: pl.BlockSpec(  # noqa: E731
        (1, *shape), lambda b, j, lidx, fill, win: (b,) + (0,) * len(shape)
    )
    if return_partials:
        out_specs = (
            out_block((bb * KV, q_per_kv, hd)),
            out_block((bb * KV, q_per_kv, _LANES)),
            out_block((bb * KV, q_per_kv, _LANES)),
        )
        out_shape = (
            jax.ShapeDtypeStruct((B // bb, bb * KV, q_per_kv, hd), jnp.float32),
            jax.ShapeDtypeStruct((B // bb, bb * KV, q_per_kv, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((B // bb, bb * KV, q_per_kv, _LANES), jnp.float32),
        )
    else:
        out_specs = out_block((bb * KV, q_per_kv, hd))
        out_shape = jax.ShapeDtypeStruct(
            (B // bb, bb * KV, q_per_kv, hd), q.dtype
        )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((bb * KV, q_per_kv, hd), jnp.float32),
                pltpu.VMEM((bb * KV, q_per_kv, _LANES), jnp.float32),
                pltpu.VMEM((bb * KV, q_per_kv, _LANES), jnp.float32),
            ],
        ),
        out_shape=out_shape,
        interpret=interpret,
    )(
        jnp.asarray(layer_idx, jnp.int32).reshape(1),
        jnp.asarray(fill, jnp.int32).reshape(1),
        jnp.asarray(0 if window is None else window, jnp.int32).reshape(1),
        *operands,
    )
    if return_partials:
        o, m, l = out
        return (
            o.reshape(B, H, hd),
            m[..., 0].reshape(B, H),
            l[..., 0].reshape(B, H),
        )
    return out.reshape(B, 1, H, hd)
