"""Pallas TPU decode attention over the full stacked KV cache.

Single-token decode attention is pure HBM streaming, but the XLA lowering of
the naive formulation adds ~3x traffic on top of the mandatory cache read
(measured on a 48x1088 Llama-3.2-3B cache, 25.3 GB touched per step vs ~9 GB
mandatory):

- `dynamic_index_in_dim(cache, layer)` materializes a per-layer cache copy
  inside the layer scan (107 MB x 2 x 28 layers per step);
- XLA pins the while-loop cache carry to one layout while the attention
  einsum prefers another, inserting TWO whole-cache layout-conversion copies
  (3.1 GB each) per step, in each direction.

This kernel sidesteps both by consuming the stacked [L, B, KV, C, hd] cache
directly: the layer index arrives via scalar prefetch and only steers the
BlockSpec index_map, so exactly the needed blocks are DMA'd — no extraction,
no conversion.

Block geometry matters more than anything here: a first cut that gridded
over (B, KV, C/BK) issued tens-of-KB DMAs and ran 3x SLOWER than the XLA
path (92 ms/step) because the pipeline never got deep enough. This version
grids over (B/BB, C/BK) with each block carrying all KV heads and BB batch
rows (~MB-scale DMAs); the BB x KV attention groups are computed as an
unrolled loop of small MXU dots against VMEM-resident tiles.

Blocks past the current fill position are elided by clamping the index_map
(Pallas skips the DMA when consecutive grid steps address the same block)
and `pl.when` skips their compute, so a step at fill=600 in a C=1152 cache
reads only ~half the cache.

Inference-only (no VJP). The reference has no analog — its decode happens
inside Ollama (SURVEY.md §1 L1).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _LANES, _NEG


def _kernel(
    lidx_ref,  # [1] int32 (SMEM) — layer to read
    pad_ref,   # [B] int32 (SMEM) — left-pad per row
    fill_ref,  # [1] int32 (SMEM) — last valid cache slot (inclusive)
    q_ref,     # [1, BB, KV, G, hd]
    k_ref,     # [1, BB, KV, BK, hd]
    v_ref,     # [1, BB, KV, BK, hd]
    o_ref,     # [1, BB, KV, G, hd]
    acc_ref,   # [BB, KV * G, hd] f32
    m_ref,     # [BB, KV * G, LANES] f32
    l_ref,     # [BB, KV * G, LANES] f32
    *,
    block_b: int,
    block_k: int,
    n_kv: int,
    scale: float,
):
    bb = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    fill = fill_ref[0]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    # blocks wholly past the fill point were never DMA'd (clamped index_map);
    # skip their compute so the clamped duplicate block isn't double-counted
    @pl.when(j * block_k <= fill)
    def _compute():
        G = q_ref.shape[3]
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (G, block_k), 1
        )
        for i in range(block_b):  # static unroll over the row block
            row_mask = (k_pos >= pad_ref[bb * block_b + i]) & (k_pos <= fill)
            for h in range(n_kv):  # static unroll over KV heads
                qb = q_ref[0, i, h].astype(jnp.float32)   # [G, hd]
                kb = k_ref[0, i, h].astype(jnp.float32)   # [BK, hd]
                vb = v_ref[0, i, h].astype(jnp.float32)

                s = jax.lax.dot_general(
                    qb, kb, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * scale  # [G, BK]
                s = jnp.where(row_mask, s, _NEG)

                g0 = h * G
                m_prev = m_ref[i, g0 : g0 + G, :1]
                m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
                corr = jnp.exp(m_prev - m_new)
                p = jnp.exp(s - m_new)
                p = jnp.where(row_mask, p, 0.0)

                l_ref[i, g0 : g0 + G] = jnp.broadcast_to(
                    l_ref[i, g0 : g0 + G, :1] * corr
                    + jnp.sum(p, axis=1, keepdims=True),
                    (G, l_ref.shape[2]),
                )
                acc_ref[i, g0 : g0 + G] = acc_ref[
                    i, g0 : g0 + G
                ] * corr + jax.lax.dot_general(
                    p, vb, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                m_ref[i, g0 : g0 + G] = jnp.broadcast_to(
                    m_new, (G, m_ref.shape[2])
                )

    @pl.when(j == nj - 1)
    def _finalize():
        G = o_ref.shape[3]
        for i in range(block_b):
            for h in range(n_kv):
                g0 = h * G
                l = jnp.maximum(l_ref[i, g0 : g0 + G, :1], 1e-30)
                o_ref[0, i, h] = (
                    acc_ref[i, g0 : g0 + G] / l
                ).astype(o_ref.dtype)


def _pick_block_b(batch: int) -> int:
    for b in (8, 4, 2, 1):
        if batch % b == 0:
            return b
    return 1


def supports_decode(cache_len: int, head_dim: int) -> bool:
    """Ceil-div grid handles any C; only lane-aligned head dims matter."""
    return head_dim % _LANES == 0


@functools.partial(
    jax.jit, static_argnames=("q_per_kv", "block_k", "interpret")
)
def flash_decode_attention(
    q: jax.Array,          # [B, 1, H, hd]
    k_all: jax.Array,      # [L, B, KV, C, hd] — FULL stacked cache
    v_all: jax.Array,      # [L, B, KV, C, hd]
    layer_idx: jax.Array,  # scalar int32
    pad_lens: jax.Array,   # [B] int32
    fill: jax.Array,       # scalar int32 — last valid slot (inclusive)
    q_per_kv: int,
    *,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Semantics match _attention(q, cache[layer], mask=pad<=j<=fill);
    returns [B, 1, H, hd]."""
    B, S, H, hd = q.shape
    L, _, KV, C, _ = k_all.shape
    if S != 1:
        raise ValueError(f"decode kernel is single-token (S=1), got S={S}")
    if hd % _LANES and not interpret:
        raise ValueError(f"unsupported decode head_dim={hd}")
    bk = min(block_k, C)
    bb = _pick_block_b(B)

    qg = q.reshape(B // bb, bb, KV, q_per_kv, hd)
    grid = (B // bb, pl.cdiv(C, bk))

    def kv_index(b, j, lidx, pad, fill, blk=bk):
        # clamp past-fill blocks onto the fill block: consecutive grid steps
        # then address the same block and Pallas elides the DMA
        return (lidx[0], b, 0, jnp.minimum(j, fill[0] // blk), 0)

    kernel = functools.partial(
        _kernel, block_b=bb, block_k=bk, n_kv=KV, scale=1.0 / (hd ** 0.5)
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, bb, KV, q_per_kv, hd),
                    lambda b, j, lidx, pad, fill: (b, 0, 0, 0, 0),
                ),
                pl.BlockSpec((1, bb, KV, bk, hd), kv_index),
                pl.BlockSpec((1, bb, KV, bk, hd), kv_index),
            ],
            out_specs=pl.BlockSpec(
                (1, bb, KV, q_per_kv, hd),
                lambda b, j, lidx, pad, fill: (b, 0, 0, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((bb, KV * q_per_kv, hd), jnp.float32),
                pltpu.VMEM((bb, KV * q_per_kv, _LANES), jnp.float32),
                pltpu.VMEM((bb, KV * q_per_kv, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B // bb, bb, KV, q_per_kv, hd), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(layer_idx, jnp.int32).reshape(1),
        pad_lens.astype(jnp.int32),
        jnp.asarray(fill, jnp.int32).reshape(1),
        qg,
        k_all,
        v_all,
    )
    return out.reshape(B, 1, H, hd)
