"""Pallas TPU decode attention over the full stacked KV cache.

Single-token decode attention is pure HBM streaming, but the XLA lowering of
the naive formulation adds ~3x traffic on top of the mandatory cache read
(measured on a 48x1088 Llama-3.2-3B cache, 25.3 GB touched per step vs ~9 GB
mandatory):

- `dynamic_index_in_dim(cache, layer)` materializes a per-layer cache copy
  inside the layer scan (107 MB x 2 x 28 layers per step);
- XLA pins the while-loop cache carry to one layout while the attention
  einsum prefers another, inserting TWO whole-cache layout-conversion copies
  (3.1 GB each) per step, in each direction.

This kernel sidesteps both by consuming the stacked [L, B, KV, C, hd] cache
directly: the layer index arrives via scalar prefetch and only steers the
BlockSpec index_map, so exactly the needed blocks are DMA'd — no extraction,
no conversion.

Block geometry matters more than anything here: a first cut that gridded
over (B, KV, C/BK) issued tens-of-KB DMAs and ran 3x SLOWER than the XLA
path (92 ms/step) because the pipeline never got deep enough. This version
grids over (B/BB, ceil(C/BK)) with each block carrying all KV heads and BB
batch rows (~MB-scale DMAs); the BB x KV attention groups are computed as an
unrolled loop of small MXU dots against VMEM-resident tiles.

Blocks past the current fill position are elided by clamping the index_map
(Pallas skips the DMA when consecutive grid steps address the same block)
and `pl.when` skips their compute, so a step at fill=600 in a C=1152 cache
reads only ~half the cache.

int8 KV caches (models.llama.init_kv_cache(quantized=True)) stream half the
bytes again: the kernel loads int8 K/V blocks plus per-(token, head) f32
scales and folds dequantization into the softmax algebra — scores multiply
by the K scale per cache slot, and probabilities multiply by the V scale
before the PV dot (diag-scale commutes through both contractions).

Inference-only (no VJP). The reference has no analog — its decode happens
inside Ollama (SURVEY.md §1 L1).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _LANES, _NEG


def _kernel(
    lidx_ref,  # [1] int32 (SMEM) — layer to read
    fill_ref,  # [1] int32 (SMEM) — last valid cache slot (inclusive)
    win_ref,   # [1] int32 (SMEM) — sliding window; 0 = global
    *refs,
    block_b: int,
    block_k: int,
    n_kv: int,
    scale: float,
    quantized: bool,
    return_partials: bool = False,
):
    if return_partials:
        # outputs are the UNNORMALIZED online-softmax state (acc, m, l) —
        # the shard-local form the long-context path LSE-merges across the
        # seq axis (backend.long_context make_long_decode_attention)
        if quantized:
            (q_ref, pads_ref, k_ref, v_ref, ks_ref, vs_ref,
             o_ref, mo_ref, lo_ref, acc_ref, m_ref, l_ref) = refs
        else:
            (q_ref, pads_ref, k_ref, v_ref,
             o_ref, mo_ref, lo_ref, acc_ref, m_ref, l_ref) = refs
            ks_ref = vs_ref = None
    elif quantized:
        q_ref, pads_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs
        mo_ref = lo_ref = None
    else:
        q_ref, pads_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
        ks_ref = vs_ref = mo_ref = lo_ref = None
    # q_ref/o_ref [1, BB*KV, G, hd] (host pre-merges the batch/head dims —
    # Mosaic supports MERGING leading dims in-kernel but not splitting them,
    # and tpu.matmul takes a single batch dim); pads_ref [1, BB*KV, 1, BK]
    # (per-row left-pads pre-broadcast on host: SMEM scalars can't be
    # stacked into a vector in-kernel); k_ref/v_ref [1, BB, KV, BK, hd];
    # ks_ref/vs_ref [1, BB, KV, BK]; scratch acc [BB*KV, G, hd],
    # m/l [BB*KV, G, LANES]

    j = pl.program_id(1)
    nj = pl.num_programs(1)
    fill = fill_ref[0]
    win = win_ref[0]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    # blocks wholly past the fill point — or, with a sliding window, wholly
    # below the window floor — were never DMA'd (clamped index_map); skip
    # their compute so the clamped duplicate block isn't double-counted
    @pl.when(
        (j * block_k <= fill)
        & ((win == 0) | (j * block_k + block_k - 1 >= fill - win + 1))
    )
    def _compute():
        G = q_ref.shape[2]
        hd = q_ref.shape[3]
        BKV = block_b * n_kv
        # one batched dot over the merged (BB, KV) dim instead of BBxKV
        # unrolled small dots: the unrolled form was VPU-bound (its softmax
        # bookkeeping ran once per head) and an int8 cache gave no speedup
        qb = q_ref[0].astype(jnp.float32)                       # [BKV, G, hd]
        kb = k_ref[0].astype(jnp.float32).reshape(BKV, block_k, hd)
        vb = v_ref[0].astype(jnp.float32).reshape(BKV, block_k, hd)

        s = jax.lax.dot_general(
            qb, kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale  # [BKV, G, BK]
        if quantized:
            s = s * ks_ref[0].reshape(BKV, 1, block_k)

        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (BKV, 1, block_k), 2
        )
        mask = (k_pos >= pads_ref[0]) & (k_pos <= fill)  # [BKV, 1, BK]
        # window in slot space, matching the dense path's k_slot > fill - win
        mask = mask & ((win == 0) | (k_pos > fill - win))
        s = jnp.where(mask, s, _NEG)

        m_prev = m_ref[:, :, :1]                         # [BKV, G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)

        l_ref[...] = jnp.broadcast_to(
            l_ref[:, :, :1] * corr + jnp.sum(p, axis=2, keepdims=True),
            l_ref.shape,
        )
        if quantized:
            p = p * vs_ref[0].reshape(BKV, 1, block_k)
        pv = jax.lax.dot_general(
            p, vb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [BKV, G, hd]
        acc_ref[...] = acc_ref[...] * corr + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == nj - 1)
    def _finalize():
        if return_partials:
            o_ref[0] = acc_ref[...].astype(o_ref.dtype)
            mo_ref[0] = m_ref[...]
            lo_ref[0] = l_ref[...]
        else:
            l = jnp.maximum(l_ref[:, :, :1], 1e-30)
            o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _verify_kernel(
    lidx_ref,   # [1] int32 (SMEM) — layer to read
    fmax_ref,   # [1] int32 (SMEM) — max over rows of (fill + Sq - 1)
    fmin_ref,   # [1] int32 (SMEM) — min over rows of fill
    win_ref,    # [1] int32 (SMEM) — sliding window; 0 = global
    *refs,
    block_b: int,
    block_k: int,
    n_kv: int,
    n_q: int,
    scale: float,
    quantized: bool,
):
    """Multi-position decode ("verify") attention for speculative decoding.

    Same block geometry and online-softmax bookkeeping as _kernel, but each
    row carries Sq query positions at PER-ROW cache offsets: query (b, s)
    attends slots pad_b <= j <= fill_b + s. The per-(row, query) visibility
    limit arrives as a pre-broadcast VMEM operand (limits_ref) because the
    merged (bb*KV, Sq*G) row layout cannot be assembled from SMEM scalars
    in-kernel; the SCALAR fill bounds (fmax/fmin) only steer DMA elision."""
    if quantized:
        (q_ref, pads_ref, lim_ref, k_ref, v_ref, ks_ref, vs_ref,
         o_ref, acc_ref, m_ref, l_ref) = refs
    else:
        (q_ref, pads_ref, lim_ref, k_ref, v_ref,
         o_ref, acc_ref, m_ref, l_ref) = refs
        ks_ref = vs_ref = None
    # q_ref/o_ref [1, BB*KV, Sq*G, hd] (row index s*G + g: query position s,
    # group head g); pads_ref [1, BB*KV, 1, BK]; lim_ref [1, BB*KV, SqG,
    # LANES] (per-(row, query) last visible slot, lane-broadcast);
    # k_ref/v_ref [1, BB, KV, BK, hd]; scratch acc [BB*KV, SqG, hd],
    # m/l [BB*KV, SqG, LANES]

    j = pl.program_id(1)
    nj = pl.num_programs(1)
    fill_hi = fmax_ref[0]
    fill_lo = fmin_ref[0]
    win = win_ref[0]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    # blocks wholly past EVERY row's last visible slot — or, with a window,
    # wholly below every row's window floor — were never DMA'd (clamped
    # index_map); skip their compute so the duplicate block isn't counted
    @pl.when(
        (j * block_k <= fill_hi)
        & ((win == 0) | (j * block_k + block_k - 1 >= fill_lo - win + 1))
    )
    def _compute():
        hd = q_ref.shape[3]
        BKV = block_b * n_kv
        SG = q_ref.shape[2]
        qb = q_ref[0].astype(jnp.float32)                       # [BKV, SG, hd]
        kb = k_ref[0].astype(jnp.float32).reshape(BKV, block_k, hd)
        vb = v_ref[0].astype(jnp.float32).reshape(BKV, block_k, hd)

        s = jax.lax.dot_general(
            qb, kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale  # [BKV, SG, BK]
        if quantized:
            s = s * ks_ref[0].reshape(BKV, 1, block_k)

        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (BKV, 1, block_k), 2
        )
        limit = lim_ref[0, :, :, :1]                     # [BKV, SG, 1]
        mask = (k_pos >= pads_ref[0]) & (k_pos <= limit)
        # window in slot space per query: k_slot > (fill_b + s) - win
        mask = mask & ((win == 0) | (k_pos > limit - win))
        s = jnp.where(mask, s, _NEG)

        m_prev = m_ref[:, :, :1]                         # [BKV, SG, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)

        l_ref[...] = jnp.broadcast_to(
            l_ref[:, :, :1] * corr + jnp.sum(p, axis=2, keepdims=True),
            l_ref.shape,
        )
        if quantized:
            p = p * vs_ref[0].reshape(BKV, 1, block_k)
        pv = jax.lax.dot_general(
            p, vb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [BKV, SG, hd]
        acc_ref[...] = acc_ref[...] * corr + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == nj - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _pick_block_b(batch: int) -> int:
    for b in (8, 4, 2, 1):
        if batch % b == 0:
            return b
    return 1


def supports_decode(cache_len: int, head_dim: int) -> bool:
    """Ceil-div grid handles any C; only lane-aligned head dims matter."""
    return head_dim % _LANES == 0


@functools.partial(
    jax.jit,
    static_argnames=("q_per_kv", "block_k", "interpret", "return_partials"),
)
def flash_decode_attention(
    q: jax.Array,          # [B, 1, H, hd]
    cache: dict,           # stacked {"k","v"[, "ks","vs"]} (llama.init_kv_cache)
    layer_idx: jax.Array,  # scalar int32
    pad_lens: jax.Array,   # [B] int32
    fill: jax.Array,       # scalar int32 — last valid slot (inclusive)
    q_per_kv: int,
    window: jax.Array | None = None,  # scalar int32; 0/None = global
    *,
    block_k: int = 128,
    interpret: bool = False,
    return_partials: bool = False,
) -> jax.Array:
    """Semantics match _attention(q, dequantized cache[layer],
    mask=pad<=j<=fill); returns [B, 1, H, hd]. ``window`` > 0 restricts to
    the last ``window`` slots (Gemma sliding layers): below-window blocks
    are compute-skipped and DMA-elided like past-fill blocks, so a sliding
    layer's step reads only ~window worth of cache however long the fill.

    ``return_partials=True`` returns the unnormalized online-softmax state
    ``(o [B, H, hd] f32, m [B, H] f32, l [B, H] f32)`` instead — the
    shard-local partial the long-context decode LSE-merges across the seq
    axis (same contract as backend.long_context._prefill_partial_local)."""
    k_all, v_all = cache["k"], cache["v"]
    quantized = "ks" in cache
    B, S, H, hd = q.shape
    L, _, KV, C, _ = k_all.shape
    if S != 1:
        raise ValueError(f"decode kernel is single-token (S=1), got S={S}")
    if hd % _LANES and not interpret:
        raise ValueError(f"unsupported decode head_dim={hd}")
    bk = min(block_k, C)
    bb = _pick_block_b(B)

    qg = q.reshape(B // bb, bb * KV, q_per_kv, hd)
    # per-row left-pads, pre-broadcast to the merged-row block shape (the
    # kernel can't assemble a vector out of SMEM scalars)
    pads = jnp.broadcast_to(
        pad_lens.astype(jnp.int32).reshape(B // bb, bb, 1, 1, 1),
        (B // bb, bb, KV, 1, bk),
    ).reshape(B // bb, bb * KV, 1, bk)
    grid = (B // bb, pl.cdiv(C, bk))

    def visible_j(j, fill, win, blk=bk):
        # clamp past-fill (and, under a window, below-window) blocks onto
        # the nearest visible block: consecutive grid steps then address the
        # same block and Pallas elides the DMA
        lo = jnp.where(
            win[0] > 0, jnp.maximum(fill[0] - win[0] + 1, 0) // blk, 0
        )
        return jnp.clip(j, lo, fill[0] // blk)

    def kv_index(b, j, lidx, fill, win):
        return (lidx[0], b, 0, visible_j(j, fill, win), 0)

    def scale_index(b, j, lidx, fill, win):
        return (lidx[0], b, 0, visible_j(j, fill, win))

    in_specs = [
        pl.BlockSpec(
            (1, bb * KV, q_per_kv, hd),
            lambda b, j, lidx, fill, win: (b, 0, 0, 0),
        ),
        pl.BlockSpec(
            (1, bb * KV, 1, bk), lambda b, j, lidx, fill, win: (b, 0, 0, 0)
        ),
        pl.BlockSpec((1, bb, KV, bk, hd), kv_index),
        pl.BlockSpec((1, bb, KV, bk, hd), kv_index),
    ]
    operands = [qg, pads, k_all, v_all]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, bb, KV, bk), scale_index),
            pl.BlockSpec((1, bb, KV, bk), scale_index),
        ]
        operands += [cache["ks"], cache["vs"]]

    kernel = functools.partial(
        _kernel, block_b=bb, block_k=bk, n_kv=KV, scale=1.0 / (hd ** 0.5),
        quantized=quantized, return_partials=return_partials,
    )
    out_block = lambda shape: pl.BlockSpec(  # noqa: E731
        (1, *shape), lambda b, j, lidx, fill, win: (b,) + (0,) * len(shape)
    )
    if return_partials:
        out_specs = (
            out_block((bb * KV, q_per_kv, hd)),
            out_block((bb * KV, q_per_kv, _LANES)),
            out_block((bb * KV, q_per_kv, _LANES)),
        )
        out_shape = (
            jax.ShapeDtypeStruct((B // bb, bb * KV, q_per_kv, hd), jnp.float32),
            jax.ShapeDtypeStruct((B // bb, bb * KV, q_per_kv, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((B // bb, bb * KV, q_per_kv, _LANES), jnp.float32),
        )
    else:
        out_specs = out_block((bb * KV, q_per_kv, hd))
        out_shape = jax.ShapeDtypeStruct(
            (B // bb, bb * KV, q_per_kv, hd), q.dtype
        )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((bb * KV, q_per_kv, hd), jnp.float32),
                pltpu.VMEM((bb * KV, q_per_kv, _LANES), jnp.float32),
                pltpu.VMEM((bb * KV, q_per_kv, _LANES), jnp.float32),
            ],
        ),
        out_shape=out_shape,
        interpret=interpret,
    )(
        jnp.asarray(layer_idx, jnp.int32).reshape(1),
        jnp.asarray(fill, jnp.int32).reshape(1),
        jnp.asarray(0 if window is None else window, jnp.int32).reshape(1),
        *operands,
    )
    if return_partials:
        o, m, l = out
        return (
            o.reshape(B, H, hd),
            m[..., 0].reshape(B, H),
            l[..., 0].reshape(B, H),
        )
    return out.reshape(B, 1, H, hd)


@functools.partial(
    jax.jit,
    static_argnames=("q_per_kv", "block_k", "interpret"),
)
def flash_spec_verify_attention(
    q: jax.Array,          # [B, Sq, H, hd] — Sq = spec_k + 1 verify queries
    cache: dict,           # stacked {"k","v"[, "ks","vs"]} (llama.init_kv_cache)
    layer_idx: jax.Array,  # scalar int32
    pad_lens: jax.Array,   # [B] int32
    fills: jax.Array,      # [B] int32 — per-row cache slot of query 0
    q_per_kv: int,
    window: jax.Array | None = None,  # scalar int32; 0/None = global
    *,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Multi-position decode attention for the speculative verify step:
    query (b, s) sits at cache slot fills_b + s and attends
    pad_b <= j <= fills_b + s (models.llama.verify_attention_mask
    semantics). Returns [B, Sq, H, hd].

    This is the decode kernel generalized along two axes at once: several
    query positions per row (the Sq*G rows of one grid cell share each K/V
    block, so a verify step streams the cache ONCE for all k+1 positions —
    the whole point of batched verification) and PER-ROW fill offsets
    (after ragged draft acceptance, rows sit at different cache lengths).
    DMA elision clamps against the batch-max fill; masking uses the exact
    per-(row, query) limit."""
    k_all, v_all = cache["k"], cache["v"]
    quantized = "ks" in cache
    B, Sq, H, hd = q.shape
    L, _, KV, C, _ = k_all.shape
    if hd % _LANES and not interpret:
        raise ValueError(f"unsupported verify head_dim={hd}")
    G = q_per_kv
    if H != KV * G:
        raise ValueError(f"q_per_kv={q_per_kv} inconsistent with H/KV={H // KV}")
    bk = min(block_k, C)
    bb = _pick_block_b(B)
    SG = Sq * G

    # merged layout [B//bb, bb*KV, Sq*G, hd] with query position MAJOR over
    # the group heads (row s*G + g) so one limits row covers a position's
    # whole GQA group
    qg = (
        q.transpose(0, 2, 1, 3)               # [B, H, Sq, hd]
        .reshape(B, KV, G, Sq, hd)
        .transpose(0, 1, 3, 2, 4)             # [B, KV, Sq, G, hd]
        .reshape(B // bb, bb * KV, SG, hd)
    )
    pads = jnp.broadcast_to(
        pad_lens.astype(jnp.int32).reshape(B // bb, bb, 1, 1, 1),
        (B // bb, bb, KV, 1, bk),
    ).reshape(B // bb, bb * KV, 1, bk)
    # per-(row, query) last visible slot, lane-broadcast (the kernel cannot
    # assemble the merged-row vector from SMEM scalars)
    limits = fills.astype(jnp.int32)[:, None] + jnp.arange(Sq, dtype=jnp.int32)
    limits = jnp.broadcast_to(
        limits[:, None, :, None, None], (B, KV, Sq, G, _LANES)
    ).reshape(B // bb, bb * KV, SG, _LANES)
    fill_hi = jnp.max(fills) + Sq - 1
    fill_lo = jnp.min(fills)
    grid = (B // bb, pl.cdiv(C, bk))

    def visible_j(j, fmax, fmin, win, blk=bk):
        lo = jnp.where(
            win[0] > 0, jnp.maximum(fmin[0] - win[0] + 1, 0) // blk, 0
        )
        return jnp.clip(j, lo, fmax[0] // blk)

    def kv_index(b, j, lidx, fmax, fmin, win):
        return (lidx[0], b, 0, visible_j(j, fmax, fmin, win), 0)

    def scale_index(b, j, lidx, fmax, fmin, win):
        return (lidx[0], b, 0, visible_j(j, fmax, fmin, win))

    row_block = lambda shape: pl.BlockSpec(  # noqa: E731
        (1, *shape), lambda b, j, lidx, fmax, fmin, win: (b,) + (0,) * len(shape)
    )
    in_specs = [
        row_block((bb * KV, SG, hd)),
        row_block((bb * KV, 1, bk)),
        row_block((bb * KV, SG, _LANES)),
        pl.BlockSpec((1, bb, KV, bk, hd), kv_index),
        pl.BlockSpec((1, bb, KV, bk, hd), kv_index),
    ]
    operands = [qg, pads, limits, k_all, v_all]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, bb, KV, bk), scale_index),
            pl.BlockSpec((1, bb, KV, bk), scale_index),
        ]
        operands += [cache["ks"], cache["vs"]]

    kernel = functools.partial(
        _verify_kernel, block_b=bb, block_k=bk, n_kv=KV, n_q=Sq,
        scale=1.0 / (hd ** 0.5), quantized=quantized,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=in_specs,
            out_specs=row_block((bb * KV, SG, hd)),
            scratch_shapes=[
                pltpu.VMEM((bb * KV, SG, hd), jnp.float32),
                pltpu.VMEM((bb * KV, SG, _LANES), jnp.float32),
                pltpu.VMEM((bb * KV, SG, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B // bb, bb * KV, SG, hd), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(layer_idx, jnp.int32).reshape(1),
        jnp.asarray(fill_hi, jnp.int32).reshape(1),
        jnp.asarray(fill_lo, jnp.int32).reshape(1),
        jnp.asarray(0 if window is None else window, jnp.int32).reshape(1),
        *operands,
    )
    return (
        out.reshape(B, KV, Sq, G, hd)
        .transpose(0, 2, 1, 3, 4)             # [B, Sq, KV, G, hd]
        .reshape(B, Sq, H, hd)
    )
