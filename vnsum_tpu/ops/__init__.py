from .flash_attention import flash_prefill_attention, supports_flash

__all__ = ["flash_prefill_attention", "supports_flash"]
