from .decode_attention import flash_decode_attention, supports_decode
from .flash_attention import flash_prefill_attention, supports_flash

__all__ = [
    "flash_decode_attention",
    "flash_prefill_attention",
    "supports_decode",
    "supports_flash",
]
