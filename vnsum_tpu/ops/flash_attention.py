"""Pallas TPU flash attention for the prefill path.

The XLA attention in models.llama materializes the full [B, KV, G, S, C]
f32 score tensor — at S=8k, C=9k that alone is >30 GB, capping chunk sizes
far below the reference's 12k-token chunks (SURVEY.md §5). This kernel
computes attention blockwise with online-softmax scratch accumulators, so
VMEM holds only (BQ × BK) score tiles and HBM never sees a score tensor:

- grid (B, KV, ⌈S/BQ⌉, ⌈C/BK⌉), K-block innermost; the whole GQA GROUP
  (G = H/KV query heads) rides one grid cell — each K/V block is DMA'd
  ONCE per group instead of once per query head (the original (B, H, …)
  grid streamed every block G times; for Llama's 24:8 that was 3x the
  mandatory attention bytes). The causal/pad/window mask is also computed
  once per cell and shared by the G heads;
- scratch (acc, m, l) carries the running softmax across K blocks per
  head (static G-sliced rows of one scratch buffer — leading dims may
  MERGE in-kernel but never split, so per-head slices beat a reshape);
  output written on the last K block;
- **ceil-division grids with masked tails**: block sizes stay MXU-friendly
  for ANY S/C. An earlier divisor-only picker collapsed to 32-wide
  K blocks at C=2080 (8 KB DMAs) and the kernel ran 60% of total profile
  time — tail masking costs one wasted partial block instead;
- **wide K blocks, measured**: this kernel is DMA-granularity-bound, not
  MXU-bound (switching the dots bf16 moved nothing —
  artifacts/prefill_gap.json). For the group-major grid the measured-best
  default is bq=512 / bk=2048 at hd=128, G≤3 (30.8 ms/layer at the worst
  e2e chunk vs the per-head kernel's best 37.5; map shape 19.4 vs 20.7 —
  artifacts/flash_block_geometry.json holds the per-head history). bk
  shrinks with head_dim (hd=256 Gemma3 → 1024) AND with G (the unrolled
  per-head score temporaries stay live: G=4 at bk=2048 exceeds the 16 MB
  scoped-VMEM budget, so G·bk is capped at 3·2048 — phi-4's 4:1 groups
  resolve to bk=1024, measured working at 14.7 GB int8 on chip);
- **consumes the FULL stacked cache [L, B, KV, C, hd]** like the decode twin
  (ops/decode_attention.py): the layer index arrives via scalar prefetch and
  steers the index_map, eliminating the per-layer 2×(B·C·hd·KV) extraction
  copies XLA otherwise materializes inside the layer scan;
- causal + left-pad masking fused (same semantics as
  models.llama.prefill_attention_mask: pad_b <= j <= i);
- blocks strictly above the causal diagonal skip their FLOPs entirely.

Inference-only (no VJP); training uses dense or ring attention.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30  # python float: jnp constants would be captured by the kernel
_LANES = 128


def _kernel(
    lidx_ref,  # [1] int32 (scalar prefetch, SMEM) — layer to read
    pad_ref,   # [B] int32 (scalar prefetch, SMEM)
    win_ref,   # [1] int32 (scalar prefetch, SMEM) — sliding window; 0 = global
    off_ref,   # [1] int32 (scalar prefetch, SMEM) — cache slot of query 0
    *refs,
    block_q: int,
    block_k: int,
    seq_len: int,
    scale: float,
    quantized: bool,
    q_per_kv: int,
):
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
        ks_ref = vs_ref = None
    # q_ref/o_ref [1, 1, G, BQ, hd]; k_ref/v_ref [1, 1, 1, BK, hd];
    # ks_ref/vs_ref [1, 1, KV, BK] (full KV axis — Mosaic requires the
    # second-minor block dim be 8-divisible or whole; the group's row is
    # selected in-kernel); scratch acc [G*BQ, hd] f32, m/l [G*BQ, LANES]
    # f32 — per-head state lives in static G-slices of one buffer

    b = pl.program_id(0)
    kv = pl.program_id(1)
    i = pl.program_id(2)
    j = pl.program_id(3)
    nj = pl.num_programs(3)

    # chunked prefill: queries live at cache slots off..off+S-1 (chunk c of
    # a longer prompt); off = 0 is the classic whole-prompt prefill
    q_start = off_ref[0] + i * block_q
    k_start = j * block_k
    win = win_ref[0]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    # blocks strictly above the causal diagonal contribute nothing; with a
    # sliding window, neither do blocks wholly below the FIRST query row's
    # window floor (q_start - win + 1 — the least restrictive floor in the
    # block; later rows re-mask per element). Both sets were never DMA'd —
    # the index_map clamps them onto an in-range block, see kv_index.
    @pl.when(
        (k_start <= q_start + block_q - 1)
        & ((win == 0) | (k_start + block_k - 1 >= q_start - win + 1))
    )
    def _compute():
        # casts hoisted out of the G-unroll: one [BK, hd] conversion per
        # grid cell, not G (int8 cache values are exact in the query
        # dtype — see the dot comment below)
        kb = k_ref[0, 0, 0].astype(q_ref.dtype)
        vb = v_ref[0, 0, 0].astype(q_ref.dtype)

        # mask depends on positions only, not the head — ONE copy serves
        # the whole GQA group (a third of the old per-head VPU bookkeeping)
        q_pos = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        pad = pad_ref[b]
        # k_pos <= q_pos also kills the masked tail of a partial K block
        # (those slots have k_pos > any valid q_pos); q_pos of a partial
        # Q-block tail produces garbage rows the caller never reads.
        # Window semantics in SLOT space match the dense path
        # (models.llama._block: k_slot > q_slot - window) — left pad shifts
        # q and k slots identically, so the token-space window is preserved
        mask = (
            (k_pos <= q_pos) & (k_pos >= pad)
            & (q_pos < off_ref[0] + seq_len)
        )
        mask = mask & ((win == 0) | (k_pos > q_pos - win))

        for g in range(q_per_kv):  # static unroll over the GQA group
            lo, hi = g * block_q, (g + 1) * block_q
            # MXU inputs stay in the QUERY dtype with f32 accumulation
            # (preferred_element_type): f32 parity tests keep exact f32
            # dots, the engine's bf16 takes the native-rate MXU path.
            # Measured NEUTRAL on wall (the kernel is DMA-bound — the
            # block geometry and the once-per-group K/V stream are the
            # wins); kept because f32 dots waste MXU headroom for nothing
            # the f32 oracle tests need. int8 cache values (-128..127)
            # are exactly representable in bf16, so the dequant algebra
            # is unchanged.
            qg = q_ref[0, 0, g]
            s = jax.lax.dot_general(
                qg, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [BQ, BK] f32
            if quantized:
                s = s * ks_ref[0, 0, kv][None, :]
            s = jnp.where(mask, s, _NEG)

            m_prev = m_ref[lo:hi, :1]                   # [BQ, 1]
            m_cur = jnp.max(s, axis=1, keepdims=True)   # [BQ, 1]
            m_new = jnp.maximum(m_prev, m_cur)
            corr = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            p = jnp.where(mask, p, 0.0)                 # dead rows stay dead

            l_new = l_ref[lo:hi, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
            if quantized:
                p = p * vs_ref[0, 0, kv][None, :]
            # probabilities drop to the query dtype for the PV dot (bf16
            # adds ~0.4% relative rounding — same class as the int8 V
            # scale already applied above); accumulation stays f32
            acc_ref[lo:hi] = acc_ref[lo:hi] * corr + jax.lax.dot_general(
                p.astype(qg.dtype), vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[lo:hi] = jnp.broadcast_to(m_new, (block_q, m_ref.shape[1]))
            l_ref[lo:hi] = jnp.broadcast_to(l_new, (block_q, l_ref.shape[1]))

    @pl.when(j == nj - 1)
    def _finalize():
        for g in range(q_per_kv):
            lo, hi = g * block_q, (g + 1) * block_q
            l = jnp.maximum(l_ref[lo:hi, :1], 1e-30)
            o_ref[0, 0, g] = (acc_ref[lo:hi] / l).astype(o_ref.dtype)


def supports_flash(seq_len: int, cache_len: int, head_dim: int) -> bool:
    """Ceil-div grids handle any S/C; only the lane-aligned head dim is
    load-bearing on real hardware."""
    return head_dim % _LANES == 0


@functools.partial(
    jax.jit,
    static_argnames=("q_per_kv", "block_q", "block_k", "interpret"),
)
def flash_prefill_attention(
    q: jax.Array,          # [B, S, H, hd]
    cache: dict,           # stacked {"k","v"[, "ks","vs"]} (llama.init_kv_cache)
    layer_idx: jax.Array,  # scalar int32
    pad_lens: jax.Array,   # [B] int32 — left-pad per sequence
    q_per_kv: int,
    window: jax.Array | None = None,  # scalar int32; 0/None = global
    q_offset: jax.Array | None = None,  # scalar int32; cache slot of query 0
    *,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Returns [B, S, H, hd]; semantics match _attention with the prefill
    mask (pad_b <= j <= i over cache slots) on the (dequantized) cache layer
    ``layer_idx``. ``window`` > 0 additionally restricts each query to the
    last ``window`` slots (Gemma sliding layers — the per-layer value is a
    runtime scalar, so one compiled program serves global and local layers).
    ``q_offset`` places the S queries at cache slots
    [q_offset, q_offset + S) — chunk c of a CHUNKED prefill (the engine's
    prefill_chunk_tokens path, which halves/quarters prefill transients so
    bigger decode batches fit); 0/None is the classic whole-prompt prefill.

    K/V blocks a query block can never see — strictly above the causal
    diagonal, or wholly below the window floor — are both compute-skipped
    AND DMA-elided: the index_map clamps their block index onto the nearest
    visible block, and Pallas skips the copy when consecutive grid steps
    address the same block."""
    k_all, v_all = cache["k"], cache["v"]
    quantized = "ks" in cache
    B, S, H, hd = q.shape
    L, _, KV, C, _ = k_all.shape
    if hd % _LANES and not interpret:
        raise ValueError(f"unsupported flash head_dim={hd}")
    G = H // KV
    if q_per_kv != G:
        # the group-major grid derives G from the shapes; a mismatched
        # caller value would silently change the head->KV mapping
        raise ValueError(f"q_per_kv={q_per_kv} inconsistent with H/KV={G}")
    # measured-best geometry for the GROUP-major grid (worst e2e chunk,
    # B=16/S=2048@off=6144/C=8320 int8: 512/2048 = 30.8 ms/layer vs the
    # per-head kernel's best 37.5; map shape 19.4 vs 20.7). Two VMEM
    # scaling rules keep the ~16 MB scoped budget at the measured G=3,
    # hd=128 level: the K width shrinks with head_dim (hd=256 Gemma3 →
    # bk 1024), AND with the group size — the per-head loop is a static
    # unroll whose [bq, bk] f32 score temporaries stay live per head, so
    # G=4 at bk=2048 exceeds scoped vmem by ~2 MB (measured compile OOM;
    # G*bk is held ≤ 3*2048). bq stays 512: the q tile already carries
    # G*512 rows, and bq=1024 geometries fail to compile at G=3.
    default_bk = max(512, 2048 * _LANES // max(hd, 1))
    while G * default_bk > 3 * 2048 and default_bk > 512:
        default_bk //= 2
    bq = min(block_q or 512, S)
    # scratch is G-sliced at multiples of bq — keep the slice offsets
    # sublane-aligned when S is small and not 8-divisible
    bq = -(-bq // 8) * 8
    bk = min(block_k or default_bk, C)
    # the bk guard above bottoms out at 512; very wide GQA groups (G > 12)
    # can still blow the scoped-VMEM score budget there, so continue the
    # scaling on bq (the q tile and the per-head [bq, bk] f32 temporaries
    # both shrink with it). G*bq*bk <= 3*2048*512 is the measured-working
    # ceiling at the default geometry (G=3, bq=512, bk=2048).
    _VMEM_CELLS = 3 * 2048 * 512
    if block_q is None:
        while G * bq * bk > _VMEM_CELLS and bq > 8:
            bq = max(-(-(bq // 2) // 8) * 8, 8)
    if G * bq * bk > _VMEM_CELLS and not interpret:
        # an explicit block_q/block_k overrode the autoscaler into a
        # geometry that will OOM in Mosaic — fail with the numbers instead
        # of a compile-time scoped-vmem error naming none of them
        raise ValueError(
            f"flash prefill geometry exceeds the ~16 MB scoped-VMEM "
            f"budget: G={G} (H={H}/KV={KV}), head_dim={hd}, bq={bq}, "
            f"bk={bk} (G*bq*bk={G * bq * bk} > {_VMEM_CELLS}) — pass a "
            f"smaller block_q/block_k or drop to the dense path"
        )

    # group-major query layout: [B, KV, G, S, hd] — the grid walks KV
    # heads, so one grid cell computes the whole GQA group against each
    # K/V block (DMA'd once, not G times)
    qt = q.transpose(0, 2, 1, 3).reshape(B, KV, G, S, hd)

    def visible_j(i, j, win, off):
        # causal: last block any row sees (rows start at off + i*bq)
        j_hi = (off[0] + i * bq + bq - 1) // bk
        # window: first block any row sees — the FIRST query row's floor
        lo = jnp.where(
            win[0] > 0,
            jnp.maximum(off[0] + i * bq - win[0] + 1, 0) // bk,
            0,
        )
        return jnp.clip(j, lo, j_hi)

    def kv_index(b, kv, i, j, lidx, pad, win, off):
        return (lidx[0], b, kv, visible_j(i, j, win, off), 0)

    def scale_index(b, kv, i, j, lidx, pad, win, off):
        return (lidx[0], b, 0, visible_j(i, j, win, off))

    in_specs = [
        pl.BlockSpec(
            (1, 1, G, bq, hd),
            lambda b, kv, i, j, lidx, pad, win, off: (b, kv, 0, i, 0),
        ),
        pl.BlockSpec((1, 1, 1, bk, hd), kv_index),
        pl.BlockSpec((1, 1, 1, bk, hd), kv_index),
    ]
    operands = [qt, k_all, v_all]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, KV, bk), scale_index),
            pl.BlockSpec((1, 1, KV, bk), scale_index),
        ]
        operands += [cache["ks"], cache["vs"]]

    grid = (B, KV, pl.cdiv(S, bq), pl.cdiv(C, bk))
    kernel = functools.partial(
        _kernel, block_q=bq, block_k=bk, seq_len=S, scale=1.0 / (hd ** 0.5),
        quantized=quantized, q_per_kv=G,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, G, bq, hd),
                lambda b, kv, i, j, lidx, pad, win, off: (b, kv, 0, i, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((G * bq, hd), jnp.float32),
                pltpu.VMEM((G * bq, _LANES), jnp.float32),
                pltpu.VMEM((G * bq, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, KV, G, S, hd), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(layer_idx, jnp.int32).reshape(1),
        pad_lens.astype(jnp.int32),
        jnp.asarray(0 if window is None else window, jnp.int32).reshape(1),
        jnp.asarray(0 if q_offset is None else q_offset, jnp.int32).reshape(1),
        *operands,
    )
    return out.reshape(B, H, S, hd).transpose(0, 2, 1, 3)
