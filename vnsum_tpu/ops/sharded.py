"""shard_map wrappers that keep the Pallas attention kernels under a mesh.

Without these, a meshed engine had to fall back to the dense XLA attention
path (whose per-step whole-cache copies are exactly what the kernels remove
— see ops/decode_attention.py). The wrapping is collective-free: batch rows
live on the `data` axis and heads on the `model` axis, so every (row, head)
softmax is complete within one shard — each chip just runs the same kernel
on its local q/cache blocks. GSPMD continues to partition the rest of the
forward around these calls.

The reference has no analog (its only "distribution" is HTTP to Ollama,
SURVEY.md §2.2); this is the scaling-book recipe: pick a mesh, keep the hot
kernel local, let the compiler move everything else.
"""
from __future__ import annotations

from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import AXES, shard_map
from ..parallel.sharding import cache_specs
from .decode_attention import flash_decode_attention
from .flash_attention import flash_prefill_attention

_Q_SPEC = P(AXES.data, None, AXES.model, None)  # [B, S|1, H, hd]


def _cache_specs(cache: dict) -> dict:
    return cache_specs(quantized="ks" in cache)


def sharded_flash_prefill(
    mesh: Mesh,
    q,
    cache: dict,
    layer_idx,
    pad_lens,
    q_per_kv: int,
    window=None,
    q_offset=None,
    *,
    interpret: bool = False,
):
    """flash_prefill_attention with q/cache sharded over (data, model).
    ``window`` and ``q_offset`` are replicated scalars (0/None = global
    layer / whole-prompt prefill)."""
    import jax.numpy as jnp

    fn = shard_map(
        lambda qs, cs, li, pads, win, off: flash_prefill_attention(
            qs, cs, li, pads, q_per_kv, win, off, interpret=interpret
        ),
        mesh=mesh,
        in_specs=(_Q_SPEC, _cache_specs(cache), P(), P(AXES.data), P(), P()),
        out_specs=_Q_SPEC,
        check_vma=False,
    )
    win = jnp.asarray(0 if window is None else window, jnp.int32)
    off = jnp.asarray(0 if q_offset is None else q_offset, jnp.int32)
    return fn(q, cache, layer_idx, pad_lens, win, off)


def sharded_flash_decode(
    mesh: Mesh,
    q,
    cache: dict,
    layer_idx,
    pad_lens,
    fill,
    q_per_kv: int,
    window=None,
    *,
    interpret: bool = False,
):
    """flash_decode_attention with q/cache sharded over (data, model).
    ``window`` is a replicated scalar (0/None = global layer)."""
    import jax.numpy as jnp

    fn = shard_map(
        lambda qs, cs, li, pads, fl, win: flash_decode_attention(
            qs, cs, li, pads, fl, q_per_kv, win, interpret=interpret
        ),
        mesh=mesh,
        in_specs=(_Q_SPEC, _cache_specs(cache), P(), P(AXES.data), P(), P()),
        out_specs=_Q_SPEC,
        check_vma=False,
    )
    win = jnp.asarray(0 if window is None else window, jnp.int32)
    return fn(q, cache, layer_idx, pad_lens, fill, win)
