"""Domain static analysis + runtime sanitizers for the serving stack.

Four PRs in, correctness rests on hand-enforced invariants: lock discipline
across the serve/cache/obs modules, no host<->device syncs inside the engine
hot loops, donation safety on seeded-cache fns, and metrics/doc consistency.
This package machine-checks them, the way production continuous-batching
engines (Orca, OSDI '22) and SGLang's RadixAttention (arXiv:2312.07104 —
whose cache design cache/radix.py mirrors) lean on sanitizers to keep
scheduler/cache races out of serving:

- :mod:`core`  — the AST lint framework: rule registry, per-file source
  model (AST + comment map), ``# lint-allow[rule]: reason`` suppressions
  (a reason is mandatory), human + JSON output, and the
  ``python -m vnsum_tpu.analysis`` CLI (:mod:`__main__`);
- :mod:`rules` — the domain rules: ``guarded-by`` (fields annotated
  ``# guarded by: <lock>`` must only be touched under ``with self.<lock>``),
  ``host-sync-in-hot-path`` (``.item()`` / ``device_get`` / ``np.asarray`` /
  ``block_until_ready`` banned in functions marked ``# hot path``),
  ``donation-safety`` (reusing a binding after passing it to a
  ``donate_argnums`` position), ``jit-recompile-hazard`` (Python branching
  on traced args, f-strings inside jitted fns), and ``metrics-doc`` (the
  serve/metrics.py registry and the README observability table must match
  bidirectionally — absorbs scripts/check_metrics_doc.py);
- :mod:`sanitizers` — runtime detectors switchable via ``VNSUM_SANITIZERS``:
  a lockdep-style lock-order detector wrapping the serve/cache/obs locks
  (wait-for graph across threads, fails on cycles) and the
  ``jax.transfer_guard`` hot-loop wiring that turns implicit device->host
  transfers inside decode/prefill into errors. Both are constructed-away
  when disabled: ``make_lock`` returns a plain ``threading.Lock`` and
  ``hot_path_transfer_guard`` a ``nullcontext``, so production pays zero
  extra acquisitions (tests/test_analysis_sanitizers.py pins that).

Lint annotations are conventions, not syntax: ``# guarded by: <lock>[, alt]``
on a ``self.field = ...`` line, ``# hot path`` on (or directly above) a
``def`` line, and methods named ``*_locked`` are trusted to be called with
the lock already held (the repo's existing naming convention).
"""
from .core import Finding, Rule, all_rules, run_paths

__all__ = ["Finding", "Rule", "all_rules", "run_paths"]
