"""CLI: ``python -m vnsum_tpu.analysis [paths...]``.

Exit 0 when clean, 1 when any finding survives suppression — the contract
CI's named ``analysis`` step and scripts/tier1.sh rely on. ``--json`` emits
machine-readable findings for tooling; ``--rule`` narrows to one rule while
iterating on a fix.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .core import all_rules, render_findings, run_paths


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m vnsum_tpu.analysis",
        description="domain lint for the vnsum serving stack",
    )
    ap.add_argument(
        "paths", nargs="*", default=["vnsum_tpu"],
        help="files or directories to lint (default: vnsum_tpu)",
    )
    ap.add_argument(
        "--root", default=None,
        help="repo root for project-scope rules like metrics-doc "
        "(default: cwd)",
    )
    ap.add_argument(
        "--rule", action="append", default=None,
        help="run only this rule (repeatable)",
    )
    ap.add_argument("--json", action="store_true", help="JSON findings")
    ap.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for name, rule in sorted(all_rules().items()):
            scope = "project" if rule.project else "file"
            print(f"{name:24s} [{scope}] {rule.description}")
        return 0

    try:
        findings = run_paths(
            args.paths, root=Path(args.root) if args.root else None,
            rules=args.rule,
        )
    except (FileNotFoundError, ValueError) as e:
        # bad path or unknown --rule: fail the gate loudly (distinct from
        # exit 1 = findings), never lint an empty set and report ok
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(render_findings(findings, as_json=args.json))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
