"""guarded-by: annotated fields must only be touched under their lock.

The serving stack's shared state is documented today by prose ("everything
locks", cache/radix.py) — this rule turns the documentation into a check.
Annotate the field's assignment in ``__init__``::

    self._items: list = []          # guarded by: _cond, _lock
    self._queued_tokens = 0         # guarded by: _cond, _lock

and every ``self._items`` access anywhere else in the class must sit
lexically inside ``with self._cond:`` (or ``with self._lock:`` — a
comma-separated annotation lists every alias of the same underlying lock,
the RequestQueue's Condition-over-Lock pattern).

Two deliberate holes, both conventions this repo already uses:

- methods named ``*_locked`` (and ``__init__``/``__post_init__``) are
  exempt — they declare "caller holds the lock" in their name, which is
  exactly the contract the lint cannot see lexically;
- the check is self-scoped: a OTHER module reaching into
  ``obj.index.stats`` is invisible here (that is what the runtime
  lock-order sanitizer and the single-writer contracts are for).
"""
from __future__ import annotations

import ast
import re

from ..core import Finding, Rule, SourceFile, register

GUARD_RE = re.compile(r"#\s*guarded by:\s*([\w, ]+)")

_EXEMPT = {"__init__", "__post_init__"}


def _self_attr(node: ast.AST) -> str | None:
    """'x' for an ``self.x`` attribute node, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _guarded_fields(sf: SourceFile, cls: ast.ClassDef) -> dict[str, set[str]]:
    """field name -> allowed lock attribute names, from ``# guarded by:``
    comments on ``self.field = ...`` lines anywhere in the class."""
    fields: dict[str, set[str]] = {}
    for node in ast.walk(cls):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        m = GUARD_RE.search(sf.comment(node.lineno)) or GUARD_RE.search(
            sf.comment(node.end_lineno or node.lineno)
        )
        if not m:
            continue
        locks = {part.strip() for part in m.group(1).split(",") if part.strip()}
        for t in targets:
            name = _self_attr(t)
            if name:
                fields[name] = locks
    return fields


def _under_lock(sf: SourceFile, node: ast.AST, locks: set[str]) -> bool:
    for anc in sf.ancestors(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                lock = _self_attr(item.context_expr)
                if lock in locks:
                    return True
    return False


def _enclosing_function(sf: SourceFile, node: ast.AST):
    for anc in sf.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


@register
class GuardedByRule(Rule):
    name = "guarded-by"
    description = (
        "fields annotated '# guarded by: <lock>' must only be accessed "
        "inside 'with self.<lock>:' (methods named *_locked are trusted "
        "to be called with the lock held)"
    )

    def check(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            fields = _guarded_fields(sf, cls)
            if not fields:
                continue
            for node in ast.walk(cls):
                name = _self_attr(node)
                if name is None or name not in fields:
                    continue
                fn = _enclosing_function(sf, node)
                if fn is None or fn.name in _EXEMPT or fn.name.endswith("_locked"):
                    continue
                if _under_lock(sf, node, fields[name]):
                    continue
                locks = ", ".join(sorted(fields[name]))
                out.append(Finding(
                    self.name, sf.path, node.lineno,
                    f"self.{name} accessed in {cls.name}.{fn.name} outside "
                    f"'with self.{locks}' (annotated '# guarded by')",
                ))
        return out
