"""device-pinning: no hard-coded device-0 placement in backend/ or cache/.

The bug class the multi-chip serving refactor eliminated: an engine- or
cache-path array pinned to ``jax.devices()[0]`` (or placed by a bare
``jax.device_put(x)`` with no sharding/device) silently anchors state on one
chip, so the first mesh run either pays a re-layout on every dispatch or —
worse — commits a buffer single-device and fails jit's committed-device
consistency check in production. Device placement in those trees must be
expressed against the mesh (``NamedSharding`` / explicit device argument) or
left uncommitted for GSPMD to lay out.

Scoped to path components named ``backend`` or ``cache``: test fixtures,
the parallel helpers (which legitimately enumerate devices to BUILD meshes)
and scripts are out of scope. Intended pins carry a reasoned
``# lint-allow[device-pinning]: <why this placement is single-device>``.
"""
from __future__ import annotations

import ast
from pathlib import Path

from ..core import Finding, Rule, SourceFile, register

_SCOPE_PARTS = {"backend", "cache"}
_DEVICE_ENUMS = {"devices", "local_devices"}


def _in_scope(path: str) -> bool:
    return bool(_SCOPE_PARTS.intersection(Path(path).parts))


def _is_jax_attr(node: ast.AST, names: set[str]) -> str | None:
    """'jax.devices' / 'jax.local_devices' style attribute on the jax
    module alias; returns the attr name or None."""
    if (
        isinstance(node, ast.Attribute)
        and node.attr in names
        and isinstance(node.value, ast.Name)
        and node.value.id == "jax"
    ):
        return node.attr
    return None


@register
class DevicePinningRule(Rule):
    name = "device-pinning"
    description = (
        "jax.devices()[i] pins and bare jax.device_put(x) implicitly "
        "default-device-places — banned in backend/ and cache/; mesh "
        "placement or a reasoned lint-allow instead"
    )

    def check(self, sf: SourceFile) -> list[Finding]:
        if not _in_scope(sf.path):
            return []
        out: list[Finding] = []
        for node in ast.walk(sf.tree):
            # jax.devices(...)[i] / jax.local_devices(...)[i]
            if isinstance(node, ast.Subscript) and isinstance(
                node.value, ast.Call
            ):
                attr = _is_jax_attr(node.value.func, _DEVICE_ENUMS)
                if attr is not None:
                    out.append(Finding(
                        self.name, sf.path, node.lineno,
                        f"jax.{attr}()[...] hard-pins a device — engine/"
                        "cache state must be placed via the mesh "
                        "(NamedSharding) or left for GSPMD to lay out",
                    ))
            # jax.device_put(x) with no device/sharding: implicit default-
            # device placement (device_put(x, sharding) is the fix, so a
            # second positional arg or device= keyword clears it)
            if isinstance(node, ast.Call):
                if (
                    _is_jax_attr(node.func, {"device_put"})
                    and len(node.args) < 2
                    and not any(kw.arg == "device" for kw in node.keywords)
                ):
                    out.append(Finding(
                        self.name, sf.path, node.lineno,
                        "jax.device_put(x) without a sharding/device "
                        "places on the implicit default device — pass a "
                        "NamedSharding (or explicit device) instead",
                    ))
        return out
