"""metrics-doc: serve/metrics.py registry <-> README table, bidirectionally.

Absorbs scripts/check_metrics_doc.py (the script survives as a thin shim so
CI history stays comparable) and extends it: beyond "every registered metric
is documented", every ``vnsum_serve_*`` name the README mentions must match
a registered metric — a renamed or deleted metric can no longer leave a
stale row behind. Histogram series suffixes (``_bucket``/``_sum``/
``_count``) are accepted for registered histograms, since that is what the
Prometheus text format actually exports.

Like its predecessor this PARSES source (the registry keeps literal string
names in ``_reg("...")`` calls exactly for this), so it runs before
dependencies are installed and cannot be skewed by import-time failures.
Project-scope rule: runs once per invocation against the repo root, and
skips silently when the root has no serve/metrics.py (fixture trees).
"""
from __future__ import annotations

import re
from pathlib import Path

from ..core import Finding, Rule, register

_REG = re.compile(r'_reg\(\s*"([a-z0-9_]+)",\s*"([a-z]+)"')
_README_NAME = re.compile(r"vnsum_serve_([a-z0-9_]+)")
_PREFIX = "vnsum_serve_"
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")

METRICS_REL = Path("vnsum_tpu") / "serve" / "metrics.py"
README_REL = Path("README.md")


def registered_metrics(metrics_py: Path) -> dict[str, tuple[str, int]]:
    """short name -> (type, line) parsed from the _reg registry block."""
    out: dict[str, tuple[str, int]] = {}
    for i, line in enumerate(
        metrics_py.read_text(encoding="utf-8").splitlines(), start=1
    ):
        m = _REG.search(line)
        if m:
            out[m.group(1)] = (m.group(2), i)
    return out


@register
class MetricsDocRule(Rule):
    name = "metrics-doc"
    description = (
        "every metric registered in serve/metrics.py appears in README.md "
        "and every vnsum_serve_* name in README.md is a registered metric"
    )
    project = True

    def check_project(self, root: Path) -> list[Finding]:
        metrics_py = root / METRICS_REL
        readme = root / README_REL
        if not metrics_py.is_file() or not readme.is_file():
            return []  # fixture tree or partial checkout: nothing to check
        registry = registered_metrics(metrics_py)
        if not registry:
            return [Finding(
                self.name, str(metrics_py), 1,
                'no _reg("...") registrations found — registry moved? '
                "update analysis/rules/metrics_doc.py",
            )]
        readme_text = readme.read_text(encoding="utf-8")

        out: list[Finding] = []
        for short, (_typ, line) in registry.items():
            if _PREFIX + short not in readme_text:
                out.append(Finding(
                    self.name, str(metrics_py), line,
                    f"registered metric {_PREFIX}{short} is missing from "
                    "the README observability table",
                ))

        def known(short: str) -> bool:
            if short in registry:
                return True
            for suf in _HIST_SUFFIXES:
                base = short.removesuffix(suf)
                if short.endswith(suf) and registry.get(base, ("",))[0] == "histogram":
                    return True
            return False

        for i, line_text in enumerate(readme_text.splitlines(), start=1):
            for m in _README_NAME.finditer(line_text):
                if not known(m.group(1)):
                    out.append(Finding(
                        self.name, str(readme), i,
                        f"README mentions {_PREFIX}{m.group(1)} but no such "
                        "metric is registered in serve/metrics.py",
                    ))
        return out
