"""metric-label-cardinality: dynamic metric labels in serve/ must be
bounded.

A Prometheus metric family's cost is its label cardinality, and a label
value interpolated from request state (a tenant name, an arbitrary id) is
an unbounded-cardinality bug: a hostile caller cycling names grows the
scrape, the dashboards, and every downstream TSDB without limit. The
serving layer's answer is the capped :class:`~vnsum_tpu.serve.usage.
TenantLabelRegistry` — ``canonical(name)`` sanitizes and collapses
past-the-cap names into the ``other`` overflow label — and this rule makes
routing through it mandatory rather than conventional.

Mechanically: in ``vnsum_tpu/serve/``, every f-string that emits a label
value (a literal chunk ending ``<label>="`` immediately followed by an
interpolation — the repo's one metric-emission idiom) must interpolate a
BOUNDED expression:

- a call to ``canonical(...)`` (the registry helper, however reached);
- an enum's ``.value`` (the label set is the enum — bounded by the type);
- a loop variable iterating a literal tuple/list of constants (the label
  set is spelled out at the emission site).

Anything else — a raw name, a dict key, request state — is a finding:
route it through the registry or carry a reasoned
``# lint-allow[metric-label-cardinality]`` explaining why the value set is
bounded (the SLO gauges do exactly this: objective names are parse-time-
validated config tokens).

``worker=`` labels (the fleet router/federation series) are held to the
STRICT form: only a ``canonical(...)`` call qualifies. The worker label
set is the roster registry seeded at router construction; an enum or a
literal loop cannot prove an emission site agrees with that roster, and a
respawn/rename drifting off it must collapse into ``other``, not mint a
series.
"""
from __future__ import annotations

import ast
import re

from ..core import Finding, Rule, SourceFile, register

_SCOPE_RE = re.compile(r"(^|/)vnsum_tpu/serve/")
# a literal f-string chunk that opens a label value: ...{label="
_LABEL_OPEN_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="$')


def _canonical_call(expr: ast.expr) -> bool:
    """Is ``expr`` a call to the registry helper —
    ``<anything>.canonical(...)`` / ``canonical(...)``?"""
    if isinstance(expr, ast.Call):
        func = expr.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None
        )
        return name == "canonical"
    return False


def _bounded(sf: SourceFile, fstr: ast.JoinedStr, expr: ast.expr) -> bool:
    """Is the interpolated label value drawn from a bounded set?"""
    if _canonical_call(expr):
        return True
    # enum idiom: `for reason in ShedReason: ... {reason.value}` — the
    # label set is the enum's members
    if isinstance(expr, ast.Attribute) and expr.attr == "value":
        return True
    # literal loop: `for stage in ("queued", "resident"): ... {stage}`
    if isinstance(expr, ast.Name):
        for anc in sf.ancestors(fstr):
            if (
                isinstance(anc, ast.For)
                and isinstance(anc.target, ast.Name)
                and anc.target.id == expr.id
                and isinstance(anc.iter, (ast.Tuple, ast.List))
                and all(isinstance(e, ast.Constant) for e in anc.iter.elts)
            ):
                return True
    return False


@register
class LabelCardinalityRule(Rule):
    name = "metric-label-cardinality"
    description = (
        "in serve/, f-string metric label values (literal ending '<label>=\"' "
        "followed by an interpolation) must be bounded: the capped "
        "TenantLabelRegistry.canonical(...), an enum .value, or a literal "
        "loop variable"
    )

    def check(self, sf: SourceFile) -> list[Finding]:
        if not _SCOPE_RE.search(sf.path.replace("\\", "/")):
            return []
        out: list[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.JoinedStr):
                continue
            parts = node.values
            for i, part in enumerate(parts[:-1]):
                nxt = parts[i + 1]
                if not (
                    isinstance(part, ast.Constant)
                    and isinstance(part.value, str)
                    and isinstance(nxt, ast.FormattedValue)
                ):
                    continue
                m = _LABEL_OPEN_RE.search(part.value)
                if m is None:
                    continue
                if m.group(1) == "worker":
                    # fleet worker labels: ONLY the roster registry's
                    # canonical(...) proves agreement with the bounded
                    # worker set — enum/literal-loop escapes don't
                    if _canonical_call(nxt.value):
                        continue
                    out.append(Finding(
                        self.name, sf.path, nxt.value.lineno,
                        'metric label worker="..." must interpolate a '
                        "canonical(...) call on the bounded worker-roster "
                        "registry (enum values and literal loops do not "
                        "qualify for fleet worker labels)",
                    ))
                    continue
                if _bounded(sf, node, nxt.value):
                    continue
                out.append(Finding(
                    self.name, sf.path, nxt.value.lineno,
                    f'metric label {m.group(1)}="..." interpolates an '
                    "unbounded value — route it through the capped "
                    "TenantLabelRegistry.canonical(...) (or lint-allow "
                    "with the reason the value set is bounded)",
                ))
        return out
