"""jit-recompile-hazard: host-Python control flow on traced values.

Inside a jitted function, a Python ``if``/``while``/``assert`` on a traced
argument either raises ConcretizationTypeError at trace time or — when the
argument is accidentally static — silently recompiles per distinct value
(the per-K program fan-out backend/engine.py's resume path bounds with an
explicit grid is the *managed* version of this hazard). F-strings inside a
jitted body are the same trap in string form: interpolating a tracer
concretizes it, and even constant ones run per trace.

Detection: functions directly jitted in the SAME scope — decorated with
``@jax.jit`` / ``@partial(jax.jit, ...)`` or passed as ``jax.jit(fn, ...)``.
Parameters named in ``static_argnums``/``static_argnames`` literals are
excluded (branching on statics is the point of statics). ``x is None`` /
``x is not None`` tests are allowed — tracers are never None, so that is a
host-level structure check, not a value branch.
"""
from __future__ import annotations

import ast

from ..core import Finding, Rule, SourceFile, register


def _jit_call(call: ast.Call) -> bool:
    f = call.func
    return (
        (isinstance(f, ast.Attribute) and f.attr == "jit")
        or (isinstance(f, ast.Name) and f.id == "jit")
    )


def _static_params(call: ast.Call | None, fn: ast.FunctionDef) -> set[str]:
    """Parameter names made static by static_argnums/static_argnames."""
    if call is None:
        return set()
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    static: set[str] = set()
    for kw in call.keywords:
        vals: list = []
        v = kw.value
        if isinstance(v, ast.Constant):
            vals = [v.value]
        elif isinstance(v, (ast.Tuple, ast.List)):
            vals = [e.value for e in v.elts if isinstance(e, ast.Constant)]
        if kw.arg == "static_argnums":
            static.update(params[i] for i in vals
                          if isinstance(i, int) and i < len(params))
        elif kw.arg == "static_argnames":
            static.update(s for s in vals if isinstance(s, str))
    return static


def _jitted_functions(sf: SourceFile):
    """Yield (fn_def, jit_call | None) for directly-jitted functions."""
    defs: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef):
            defs[node.name] = node
            for dec in node.decorator_list:
                # @jax.jit / @jit
                if (isinstance(dec, ast.Attribute) and dec.attr == "jit") or (
                    isinstance(dec, ast.Name) and dec.id == "jit"
                ):
                    yield node, None
                # @jax.jit(...) / @partial(jax.jit, ...)
                elif isinstance(dec, ast.Call):
                    if _jit_call(dec):
                        yield node, dec
                    elif (
                        isinstance(dec.func, ast.Name)
                        and dec.func.id == "partial"
                        and dec.args
                        and isinstance(dec.args[0], (ast.Attribute, ast.Name))
                        and _jit_call(ast.Call(func=dec.args[0], args=[],
                                               keywords=[]))
                    ):
                        yield node, dec
        elif isinstance(node, ast.Call) and _jit_call(node) and node.args:
            target = node.args[0]
            if isinstance(target, ast.Name) and target.id in defs:
                yield defs[target.id], node


def _is_none_test(test: ast.expr) -> bool:
    return (
        isinstance(test, ast.Compare)
        and all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)
    )


def _names_in(expr: ast.expr) -> set[str]:
    return {
        n.id for n in ast.walk(expr)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


@register
class RecompileRule(Rule):
    name = "jit-recompile-hazard"
    description = (
        "Python if/while/assert on traced args and f-strings inside "
        "jitted functions concretize tracers or fan out recompiles"
    )

    def check(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        seen: set[tuple[int, str]] = set()
        for fn, jit_call in _jitted_functions(sf):
            static = _static_params(jit_call, fn)
            traced = {
                a.arg for a in fn.args.posonlyargs + fn.args.args
                + fn.args.kwonlyargs
            } - static - {"self"}
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While, ast.Assert)):
                    if _is_none_test(node.test):
                        continue
                    hit = _names_in(node.test) & traced
                    if hit:
                        kind = type(node).__name__.lower()
                        key = (node.lineno, kind)
                        if key in seen:
                            continue
                        seen.add(key)
                        out.append(Finding(
                            self.name, sf.path, node.lineno,
                            f"Python {kind} on traced arg(s) "
                            f"{sorted(hit)} inside jitted {fn.name!r} — "
                            "use lax.cond/where, or mark the arg static",
                        ))
                elif isinstance(node, ast.JoinedStr):
                    key = (node.lineno, "fstring")
                    if key in seen:
                        continue
                    seen.add(key)
                    out.append(Finding(
                        self.name, sf.path, node.lineno,
                        f"f-string inside jitted {fn.name!r} — interpolating "
                        "a tracer concretizes it; format on the host or use "
                        "jax.debug.print",
                    ))
        return out
