"""donation-safety: never reuse a binding after donating it to XLA.

``jax.jit(f, donate_argnums=(i,))`` hands argument *i*'s buffer to the
compiled program; the Python binding still points at it, but the array is
deleted — reading it later raises (or worse, on some backends, reads
garbage). The engine's segment/spec/seeded-cache programs all donate, and
their callers must rebind from the call's results (the pattern
``t, cur, cache, done, out = segment(..., cache, ..., out, ...)``).

Scope: intra-function dataflow, deliberately conservative. The rule tracks
``name = jax.jit(fn, donate_argnums=...)`` bindings and flags a *load* of a
donated positional argument's name after the call, unless the call's own
assignment (or a later store before the first load) rebinds it. Calls
through attributes, dict caches, or other scopes (the engine's
``_get_seg_fn`` indirection) are out of reach — for those the runtime check
is XLA's own donated-buffer error, which the engine test suites exercise.
Line-ordered, control-flow-insensitive: a fixture-honest approximation, not
an alias analysis.
"""
from __future__ import annotations

import ast

from ..core import Finding, Rule, SourceFile, register


def _donated_indices(call: ast.Call) -> list[int] | None:
    """donate_argnums literal of a jax.jit(...) call, else None."""
    f = call.func
    is_jit = (
        (isinstance(f, ast.Attribute) and f.attr == "jit")
        or (isinstance(f, ast.Name) and f.id == "jit")
    )
    if not is_jit:
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return [v.value]
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for elt in v.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                    out.append(elt.value)
            return out
    return None


def _assigned_names(stmt_targets: list[ast.expr]) -> set[str]:
    names: set[str] = set()
    for t in stmt_targets:
        for node in ast.walk(t):
            if isinstance(node, ast.Name):
                names.add(node.id)
    return names


@register
class DonationRule(Rule):
    name = "donation-safety"
    description = (
        "a binding passed at a donate_argnums position must not be read "
        "after the call unless the call's results rebind it"
    )

    def check(self, sf: SourceFile) -> list[Finding]:
        # nested defs are walked by their enclosing scope too — dedupe so a
        # closure-local violation reports once
        seen: dict[Finding, None] = {}
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for f in self._check_scope(sf, fn):
                seen.setdefault(f)
        return list(seen)

    def _check_scope(self, sf: SourceFile, fn: ast.AST) -> list[Finding]:
        # jitted-with-donation bindings created in THIS scope
        donating: dict[str, list[int]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                idx = _donated_indices(node.value)
                if idx:
                    for name in _assigned_names(node.targets):
                        donating[name] = idx
        if not donating:
            return []

        findings: list[Finding] = []
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
                continue
            idx = donating.get(node.func.id)
            if not idx:
                continue
            rebound = _assigned_names(
                getattr(getattr(node, "_lint_parent", None), "targets", [])
            )
            for i in idx:
                if i >= len(node.args) or not isinstance(node.args[i], ast.Name):
                    continue
                donated = node.args[i].id
                if donated in rebound:
                    continue
                findings.extend(self._reused_after(
                    sf, fn, donated, node, node.func.id
                ))
        return findings

    def _reused_after(self, sf, fn, name: str, call: ast.Call,
                      fn_name: str) -> list[Finding]:
        # "after the call" = after its LAST line: a multi-line call's own
        # argument occurrences are part of the donation, not a reuse
        call_line = call.end_lineno or call.lineno
        loads = []
        stores = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id == name \
                    and node.lineno > call_line:
                if isinstance(node.ctx, ast.Load):
                    loads.append(node.lineno)
                else:
                    stores.append(node.lineno)
        if not loads:
            return []
        first_load = min(loads)
        if stores and min(stores) <= first_load:
            return []  # rebound before any read
        return [Finding(
            self.name, sf.path, first_load,
            f"{name!r} is read after being donated to {fn_name}() at line "
            f"{call.lineno} (donate_argnums) — its buffer no longer exists; "
            "rebind from the call's results",
        )]
