"""The domain rule set. Importing this package registers every rule with
:mod:`vnsum_tpu.analysis.core`; add a module here and import it below to
ship a new rule."""
from . import (  # noqa: F401
    device_pinning,
    donation,
    durable,
    guarded_by,
    host_sync,
    label_cardinality,
    metrics_doc,
    recompile,
    swallowed,
    unbounded_wait,
)
