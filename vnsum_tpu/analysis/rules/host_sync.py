"""host-sync-in-hot-path: no host<->device syncs inside marked hot loops.

A ``.item()``, ``np.asarray(device_array)``, ``jax.device_get`` or
``block_until_ready`` inside the engine's decode/prefill loops forces the
host to wait on the device — exactly the per-segment stall PERF.md's
measurement-hygiene notes fight, and the silent way a refactor turns an
async dispatch pipeline into lockstep. Functions whose ``def`` line (or the
line directly above it) carries a ``# hot path`` comment are scanned; every
sync-shaped call inside must either go away or carry a
``# lint-allow[host-sync-in-hot-path]: <why this sync is load-bearing>``.

The ban is textual, not semantic: ``np.asarray`` on a host list is no sync,
but it reads identically to one in review — the suppression reason is where
the difference gets written down. Intended fetches should be EXPLICIT
``jax.device_get`` (suppressed with their reason): the runtime half of this
check, ``sanitizers.hot_path_transfer_guard``, errors on *implicit*
device->host transfers in sanitizer mode, so acknowledged syncs pass the
guard and unacknowledged ones fail it.
"""
from __future__ import annotations

import ast
import re

from ..core import Finding, Rule, SourceFile, register

HOT_RE = re.compile(r"#\s*hot path\b")

# attribute-call names that always read as a sync
_ATTR_CALLS = {"item", "block_until_ready"}
# (module alias, function) calls; bare names cover `from jax import device_get`
_FN_CALLS = {
    ("jax", "device_get"), ("np", "asarray"), ("numpy", "asarray"),
}
_BARE_CALLS = {"device_get"}


def _is_hot(sf: SourceFile, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    for line in (fn.lineno, fn.lineno - 1):
        if HOT_RE.search(sf.comment(line)):
            return True
    return False


def _sync_call(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr in _ATTR_CALLS:
            return f".{f.attr}()"
        if isinstance(f.value, ast.Name) and (f.value.id, f.attr) in _FN_CALLS:
            return f"{f.value.id}.{f.attr}()"
    elif isinstance(f, ast.Name) and f.id in _BARE_CALLS:
        return f"{f.id}()"
    return None


@register
class HostSyncRule(Rule):
    name = "host-sync-in-hot-path"
    description = (
        ".item()/device_get/np.asarray/block_until_ready are banned inside "
        "functions marked '# hot path'; intended syncs carry a reasoned "
        "lint-allow"
    )

    def check(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _is_hot(sf, fn):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                what = _sync_call(node)
                if what is not None:
                    out.append(Finding(
                        self.name, sf.path, node.lineno,
                        f"{what} inside hot-path function {fn.name!r} — "
                        "remove the sync or lint-allow it with the reason "
                        "it is load-bearing",
                    ))
        return out
