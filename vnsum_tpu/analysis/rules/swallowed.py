"""swallowed-exception: no silently-dropped errors in serve/ and backend/.

The serving stack's cardinal failure mode is a future nobody resolves: a
caller blocks on ``result()`` forever while ``/healthz`` keeps reporting ok.
Every ``except`` handler in ``vnsum_tpu/serve/`` and ``vnsum_tpu/backend/``
must therefore visibly do one of three things with the error:

- **re-raise** (any ``raise`` statement in the handler body);
- **resolve a future / answer the caller** — a call to ``set_exception`` /
  ``set_result``, a delegation to a resolver helper (terminal call name
  starting with ``_resolve``, ``_fail``, or ``_shed`` — the scheduler's
  convention), or the HTTP layer's typed error response ``self._json(...)``
  (responding IS resolving for a handler thread);
- **return a value** (``return expr`` — an explicit fallback result, e.g.
  the HF chat-template retry without ``enable_thinking``).

Anything else — ``pass``, a bare log-and-continue, an assignment — needs a
``# lint-allow[swallowed-exception]: reason`` on the ``except`` line or the
line above. The two historical log-and-continue handlers in
serve/scheduler.py carry exactly such reasons; the point of the rule is
that every NEW swallow is a written-down decision, not an accident.

Scope is deliberately the two packages where a dropped error strands a
future or a device batch; strategies/eval/pipeline code answers to the
pipeline's own failure accounting instead.
"""
from __future__ import annotations

import ast
import re

from ..core import Finding, Rule, SourceFile, register

_SCOPE_RE = re.compile(r"(^|/)vnsum_tpu/(serve|backend)/")

_RESOLVER_CALLS = {"set_exception", "set_result", "_json"}
_RESOLVER_PREFIXES = ("_resolve", "_fail", "_shed")


def _terminal_name(func: ast.expr) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _handler_resolves(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Return) and node.value is not None:
            return True
        if isinstance(node, ast.Call):
            name = _terminal_name(node.func)
            if name is None:
                continue
            if name in _RESOLVER_CALLS or name.startswith(_RESOLVER_PREFIXES):
                return True
    return False


@register
class SwallowedExceptionRule(Rule):
    name = "swallowed-exception"
    description = (
        "in serve/ and backend/, an except handler must re-raise, resolve "
        "a future (set_exception/set_result/_resolve*/_fail*/_shed*/_json), "
        "or return a value — otherwise it needs a reasoned lint-allow"
    )

    def check(self, sf: SourceFile) -> list[Finding]:
        if not _SCOPE_RE.search(sf.path.replace("\\", "/")):
            return []
        out: list[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _handler_resolves(node):
                continue
            what = (
                ast.unparse(node.type) if node.type is not None else "bare"
            )
            out.append(Finding(
                self.name, sf.path, node.lineno,
                f"except {what} neither re-raises, resolves a future, nor "
                "returns a value — a swallowed error can strand callers on "
                "futures forever; handle it or lint-allow with the reason",
            ))
        return out
