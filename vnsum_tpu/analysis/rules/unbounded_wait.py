"""unbounded-blocking-wait: no timeout-less blocking waits in serve/.

The watchdog (serve/watchdog.py) can detect a wedged thread, but the better
outcome is a thread that cannot wedge FOREVER in the first place: every
blocking primitive in the serving stack must carry a timeout so the waiting
loop periodically regains control — to beat its heartbeat, observe a close
flag, or shed expired work. A timeout-less ``Condition.wait()`` /
``Event.wait()`` / ``Future.result()`` / ``Queue.get()`` is the exact shape
of every historical serving wedge (a lost ``notify``, a future nobody
resolves, a producer that died), and none of them is observable from
outside without ``sys._current_frames`` spelunking.

The rule flags calls of those four names with no timeout — zero arguments,
an explicit ``timeout=None``, or a lone positional ``None``.
``dict.get(key)`` never matches (its argument is a key, not None);
``wait(0.1)`` / ``result(timeout=5)`` / ``get(timeout=...)`` pass. The few legitimate sites — an HTTP handler thread blocking on its
own request future, whose resolution every scheduler path guarantees —
carry reasoned ``# lint-allow[unbounded-blocking-wait]`` suppressions: the
point is that every new indefinite wait is a written-down decision, not an
accident the watchdog gets to meet in production.

Scope is ``vnsum_tpu/serve/`` — the package whose threads the liveness
contract covers; backends block inside device runtimes the lint cannot see
anyway, and offline pipeline code answers to its own timeouts.
"""
from __future__ import annotations

import ast
import re

from ..core import Finding, Rule, SourceFile, register

_SCOPE_RE = re.compile(r"(^|/)vnsum_tpu/serve/")

# the blocking-primitive method names the liveness contract bans bare
_BLOCKING_ATTRS = ("wait", "result", "get")


@register
class UnboundedBlockingWaitRule(Rule):
    name = "unbounded-blocking-wait"
    description = (
        "in serve/, Condition.wait() / Event.wait() / Future.result() / "
        "Queue.get() without a timeout can wedge a serving thread forever "
        "— pass a timeout (loop if you must wait indefinitely) or "
        "lint-allow with the reason the wait is externally bounded"
    )

    def check(self, sf: SourceFile) -> list[Finding]:
        if not _SCOPE_RE.search(sf.path.replace("\\", "/")):
            return []
        out: list[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr not in _BLOCKING_ATTRS:
                continue
            if node.args and not (
                len(node.args) == 1 and _is_none(node.args[0])
            ):
                # a positional arg is the timeout for wait()/result(), and
                # rules dict.get(key)/kwargs.get(k, d) out entirely — but a
                # lone positional None (ev.wait(None)) is spelled-out
                # unboundedness, same as timeout=None
                continue
            if any(kw.arg == "timeout" and not _is_none(kw.value)
                   for kw in node.keywords):
                continue
            out.append(Finding(
                self.name, sf.path, node.lineno,
                f".{func.attr}() with no timeout blocks its thread "
                "indefinitely — a lost notify / unresolved future wedges "
                "serving silently; bound the wait (loop on a timeout) or "
                "lint-allow with the reason it is externally bounded",
            ))
        return out


def _is_none(value: ast.expr) -> bool:
    """``timeout=None`` is spelled-out unboundedness, not a bound."""
    return isinstance(value, ast.Constant) and value.value is None
