"""durable-write: ``# durable``-marked functions must do the full
crash-safe write sequence.

The repo's durability story (serve/journal.py compaction, core/artifacts.py
atomic artifact writes) rests on one idiom: write the complete new content
to a temp file, ``flush()`` it, ``os.fsync()`` it, then ``os.replace()`` it
over the target — any shortcut reintroduces the torn-file failure mode the
idiom exists to kill (a flush-less fsync syncs an empty kernel buffer; a
replace-less write leaves the partial temp as the target on the next crash;
an fsync-less replace can surface a zero-length file after power loss).

The marker is the contract: a function whose ``def`` line (or the line
directly above it) carries a ``# durable`` comment claims crash-atomicity,
and this rule verifies the claim structurally — the body (including nested
functions it defines, not functions it merely calls) must contain all four
operations:

- a ``.write(...)``/``.writelines(...)`` call (the content),
- a ``.flush(...)`` call (user-space buffer -> kernel),
- an ``fsync(...)`` call (kernel -> disk),
- a ``replace(...)`` call (atomic rename over the target).

Helpers that implement only part of the sequence (an append-only journal
segment never renames) simply don't take the marker; callers that delegate
to a marked helper (e.g. ``atomic_write_json``) don't need one either —
the marker belongs on the function that OWNS the sequence.
"""
from __future__ import annotations

import ast

from ..core import Finding, Rule, SourceFile, register

_MARK = "durable"
_NEEDED = {
    "write": ("write", "writelines"),
    "flush": ("flush",),
    "fsync": ("fsync",),
    "os.replace": ("replace",),
}


def _call_names(fn: ast.AST):
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute):
                yield node.func.attr
            elif isinstance(node.func, ast.Name):
                yield node.func.id


def _is_marked(sf: SourceFile, fn) -> bool:
    for line in (fn.lineno, fn.lineno - 1):
        comment = sf.comment(line)
        # exact word "durable": "# durable" / "# durable: <note>" mark; a
        # prose comment merely mentioning durability does not
        if comment and _MARK in comment.replace("#", " ").split(":")[0].split():
            return True
    return False


@register
class DurableWriteRule(Rule):
    name = "durable-write"
    description = (
        "a '# durable'-marked function must pair write + flush + fsync + "
        "os.replace — the full crash-atomic file-replace sequence"
    )

    def check(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _is_marked(sf, node):
                continue
            seen = set(_call_names(node))
            missing = [
                label for label, names in _NEEDED.items()
                if not any(n in seen for n in names)
            ]
            if missing:
                out.append(Finding(
                    self.name, sf.path, node.lineno,
                    f"'# durable' function {node.name} is missing "
                    f"{', '.join(missing)} — without the full write/flush/"
                    "fsync/os.replace sequence a crash can leave a torn or "
                    "empty file",
                ))
        return out
