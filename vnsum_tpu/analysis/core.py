"""Lint framework: source model, rule registry, suppressions, runner.

Design constraints, in order:

- **Parse, never import.** Rules work on AST + comment tokens so the lint
  runs before dependencies are installed and can never be skewed by
  import-time failures (the property scripts/check_metrics_doc.py was built
  around; its successor rule keeps it).
- **Comments are the annotation surface.** Python has no in-language way to
  say "this field is guarded by that lock", so the rules read conventions
  out of the token stream (``# guarded by:``, ``# hot path``) — the
  :class:`SourceFile` model carries a line -> comment map built with
  :mod:`tokenize`, so a ``#`` inside a string literal can never register as
  an annotation.
- **Suppressions carry a reason.** ``# lint-allow[rule]: reason`` on the
  offending line (or the line directly above) silences exactly one rule;
  an empty reason is itself a finding (rule ``suppression``) — the point of
  a domain lint is that every exception is a written-down decision.
"""
from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from dataclasses import asdict, dataclass
from pathlib import Path
from typing import Iterable, Iterator

SUPPRESS_RE = re.compile(r"#\s*lint-allow\[([A-Za-z0-9_-]+)\]:?\s*(.*)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return asdict(self)


class SourceFile:
    """Parsed view of one file: AST with parent links + comment map."""

    def __init__(self, path: str | Path, text: str) -> None:
        self.path = str(path)
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=self.path)
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._lint_parent = parent  # type: ignore[attr-defined]
        # line -> comment string ("#..."); tokenize is string-literal-safe
        self.comments: dict[int, str] = {}
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                self.comments[tok.start[0]] = tok.string

    @classmethod
    def read(cls, path: str | Path) -> "SourceFile":
        return cls(path, Path(path).read_text(encoding="utf-8"))

    def comment(self, line: int) -> str:
        return self.comments.get(line, "")

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = getattr(node, "_lint_parent", None)
        while cur is not None:
            yield cur
            cur = getattr(cur, "_lint_parent", None)


class Rule:
    """A per-file check. Subclasses set ``name``/``description`` and
    implement :meth:`check`; project-scope rules (one run per invocation,
    e.g. metrics-doc) set ``project = True`` and implement
    :meth:`check_project` instead."""

    name: str = ""
    description: str = ""
    project: bool = False

    def check(self, sf: SourceFile) -> list[Finding]:
        return []

    def check_project(self, root: Path) -> list[Finding]:
        return []


_REGISTRY: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    """Class decorator adding a rule (by its ``name``) to the registry."""
    if not cls.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    _REGISTRY[cls.name] = cls()
    return cls


def all_rules() -> dict[str, Rule]:
    from . import rules  # noqa: F401 — importing registers the rule set

    return dict(_REGISTRY)


# -- runner ----------------------------------------------------------------


def iter_py_files(paths: Iterable[str | Path]) -> list[Path]:
    """Expand path arguments to .py files. A path that names nothing —
    missing directory, missing file, or a file that is not .py — raises:
    a typo'd CI argument must fail the gate loudly, never lint an empty
    set and report 'ok'."""
    out: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(
                f for f in sorted(p.rglob("*.py"))
                if "__pycache__" not in f.parts
            )
        elif p.is_file() and p.suffix == ".py":
            out.append(p)
        else:
            raise FileNotFoundError(
                f"lint path {p} is neither a directory nor an existing "
                ".py file"
            )
    return out


def _suppressed(sf: SourceFile, finding: Finding) -> bool:
    """A finding is suppressed by a reasoned lint-allow for its rule on its
    own line or the line directly above (annotation-above style)."""
    for line in (finding.line, finding.line - 1):
        m = SUPPRESS_RE.search(sf.comment(line))
        if m and m.group(1) == finding.rule and m.group(2).strip():
            return True
    return False


def _suppression_hygiene(sf: SourceFile, known: set[str]) -> list[Finding]:
    """Malformed suppressions are findings themselves: a reason is
    mandatory, and the named rule must exist."""
    out = []
    for line, comment in sorted(sf.comments.items()):
        m = SUPPRESS_RE.search(comment)
        if not m:
            continue
        rule, reason = m.group(1), m.group(2).strip()
        if rule not in known:
            out.append(Finding(
                "suppression", sf.path, line,
                f"lint-allow names unknown rule {rule!r}",
            ))
        elif not reason:
            out.append(Finding(
                "suppression", sf.path, line,
                f"lint-allow[{rule}] has no reason — every suppression "
                "must say why the violation is intended",
            ))
    return out


def run_paths(
    paths: Iterable[str | Path],
    root: str | Path | None = None,
    rules: Iterable[str] | None = None,
) -> list[Finding]:
    """Run the (selected) rule set over ``paths``; project-scope rules run
    once against ``root`` (default: cwd). Returns surviving findings —
    suppressed ones are dropped, malformed suppressions are added."""
    registry = all_rules()
    if rules is not None:
        unknown = set(rules) - set(registry)
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}")
        registry = {k: v for k, v in registry.items() if k in rules}
    known_names = set(all_rules())
    file_rules = [r for r in registry.values() if not r.project]
    project_rules = [r for r in registry.values() if r.project]

    findings: list[Finding] = []
    for path in iter_py_files(paths):
        try:
            sf = SourceFile.read(path)
        except SyntaxError as e:
            findings.append(Finding(
                "parse", str(path), e.lineno or 1, f"syntax error: {e.msg}"
            ))
            continue
        for rule in file_rules:
            for f in rule.check(sf):
                if not _suppressed(sf, f):
                    findings.append(f)
        findings.extend(_suppression_hygiene(sf, known_names))
    for rule in project_rules:
        findings.extend(rule.check_project(Path(root) if root else Path.cwd()))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def render_findings(findings: list[Finding], as_json: bool = False) -> str:
    if as_json:
        return json.dumps([f.to_dict() for f in findings], indent=2)
    if not findings:
        return "ok: no findings"
    lines = [f.render() for f in findings]
    lines.append(f"{len(findings)} finding(s)")
    return "\n".join(lines)
