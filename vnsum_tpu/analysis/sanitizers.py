"""Runtime sanitizers: lockdep-style lock-order detection + transfer guard.

Both are **opt-in via the ``VNSUM_SANITIZERS`` env var** and constructed
away when off: :func:`make_lock` returns a plain ``threading.Lock`` (zero
wrapper, zero extra acquisitions — the serving-goodput guard in
tests/test_analysis_sanitizers.py pins this) and
:func:`hot_path_transfer_guard` a ``nullcontext``. Values: ``1``/``all``
enables everything, or a comma list of ``lock`` / ``transfer``.

**Lock order.** Deadlocks in a queue -> scheduler -> engine -> cache stack
are ordering bugs long before they are hangs: thread A holds the queue lock
while touching metrics, thread B must never hold the metrics lock while
touching the queue. The detector wraps each serve/cache/obs lock in a
:class:`TrackedLock` that records, per blocking acquisition, a wait-for
edge from every lock the thread already holds to the one it is acquiring
— lock *names* (one node per lock site, not per instance), which is the
class-level discipline lockdep checks. A new edge that closes a cycle
raises :class:`LockOrderError` at the acquisition that would introduce the
deadlock, with the cycle spelled out — BEFORE any thread actually hangs,
and regardless of whether the schedule that would hang ever fires.
Non-blocking probes (``acquire(blocking=False)``) add no edges: a trylock
cannot wait, so it cannot deadlock — and Condition's ``_is_owned`` probe
must not self-edge. The wrapper satisfies ``threading.Condition``'s lock
protocol, so the RequestQueue's Condition-over-Lock works unchanged.

**Transfer guard.** The static half of the hot-loop contract is the
``host-sync-in-hot-path`` lint (every acknowledged sync is an explicit,
suppressed ``jax.device_get``); this is the runtime half:
:func:`hot_path_transfer_guard` wraps the engine's decode/prefill dispatch
loops in ``jax.transfer_guard_device_to_host("disallow")``, so any
*implicit* device->host transfer (a stray ``np.asarray`` on a device
array, a ``float()`` on a traced metric) errors instead of silently
serializing the pipeline. Explicit ``device_get`` passes. Note: on CPU JAX
device<->host is zero-copy and the guard never fires — it is wired for TPU
runs; CPU sanitizer tests verify the guarded path stays green and the
context is actually installed.
"""
from __future__ import annotations

import contextlib
import os
import threading

_FLAG = "VNSUM_SANITIZERS"


def _enabled(kind: str) -> bool:
    val = os.environ.get(_FLAG, "").strip()
    if not val or val == "0":
        return False
    if val in ("1", "all"):
        return True
    return kind in {p.strip() for p in val.split(",")}


def lock_sanitizer_enabled() -> bool:
    return _enabled("lock")


def transfer_sanitizer_enabled() -> bool:
    return _enabled("transfer")


class LockOrderError(RuntimeError):
    """Acquiring this lock here closes a cycle in the wait-for graph."""


class LockGraph:
    """Global wait-for graph over lock names + per-thread held stacks."""

    def __init__(self) -> None:
        # meta-lock guarding the graph itself; never a TrackedLock (the
        # detector must not detect itself)
        self._mu = threading.Lock()
        self._edges: dict[str, set[str]] = {}
        self._local = threading.local()
        self.violations: list[str] = []

    def held(self) -> list[str]:
        st = getattr(self._local, "held", None)
        if st is None:
            st = self._local.held = []
        return st

    def _reaches_locked(self, src: str, dst: str) -> list[str] | None:
        """DFS path src -> dst over recorded edges, else None."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def note_blocking_acquire(self, name: str) -> None:
        """Record held->name edges; raise on the edge that closes a cycle.
        Called BEFORE blocking, so the violation reports at the acquisition
        that would introduce the deadlock instead of hanging in it. The
        offending edge is recorded anyway, so one inconsistent ordering
        reports once rather than re-raising forever in a retry loop."""
        held = self.held()
        if not held:
            return
        with self._mu:
            for h in held:
                if name in self._edges.get(h, ()):
                    continue
                path = self._reaches_locked(name, h) if h != name else [name]
                self._edges.setdefault(h, set()).add(name)
                if path is not None:
                    cycle = " -> ".join(path + [name])
                    msg = (
                        f"lock-order cycle: acquiring {name!r} while "
                        f"holding {h!r}, but an inverse ordering exists: "
                        f"{cycle}"
                    )
                    self.violations.append(msg)
                    raise LockOrderError(msg)

    def note_acquired(self, name: str) -> None:
        self.held().append(name)

    def note_released(self, name: str) -> None:
        held = self.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def edges(self) -> dict[str, set[str]]:
        with self._mu:
            return {k: set(v) for k, v in self._edges.items()}

    def reset(self) -> None:
        """Clear graph + violations in place (tests) — existing TrackedLock
        instances keep pointing at this graph, so clearing must not swap
        the object."""
        with self._mu:
            self._edges.clear()
            self.violations.clear()


_GRAPH = LockGraph()


def lock_graph() -> LockGraph:
    return _GRAPH


class TrackedLock:
    """threading.Lock wrapper feeding the wait-for graph.

    Condition-compatible: ``threading.Condition(TrackedLock(...))`` works —
    Condition's release/re-acquire in ``wait()`` flows through this wrapper
    and keeps the held stack honest, and its ``_is_owned`` fallback probes
    with ``acquire(False)``, which records no edge (trylocks cannot wait).
    """

    __slots__ = ("name", "_graph", "_inner", "acquisitions")

    def __init__(self, name: str, graph: LockGraph | None = None) -> None:
        self.name = name
        self._graph = graph or _GRAPH
        self._inner = threading.Lock()
        self.acquisitions = 0  # incremented while holding — consistent

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            self._graph.note_blocking_acquire(self.name)  # may raise
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._graph.note_acquired(self.name)
            self.acquisitions += 1
        return got

    def release(self) -> None:
        self._inner.release()
        self._graph.note_released(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


def make_lock(name: str) -> "threading.Lock | TrackedLock":
    """THE lock constructor for serve/cache/obs shared state. Plain
    ``threading.Lock`` unless the lock sanitizer is enabled — the disabled
    path adds nothing to acquire/release (no wrapper exists at all)."""
    if not lock_sanitizer_enabled():
        return threading.Lock()
    return TrackedLock(name, _GRAPH)


def lock_order_violations() -> list[str]:
    return list(_GRAPH.violations)


def hot_path_transfer_guard():
    """Context manager for the engine's decode/prefill dispatch loops:
    ``nullcontext`` normally; under the transfer sanitizer, implicit
    device->host transfers raise while explicit ``jax.device_get`` (the
    lint-acknowledged syncs) passes."""
    if not transfer_sanitizer_enabled():
        return contextlib.nullcontext()
    import jax

    return jax.transfer_guard_device_to_host("disallow")
