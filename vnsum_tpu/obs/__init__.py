"""Unified observability: request tracing, engine telemetry, histograms,
and Perfetto-loadable timelines — stdlib-only, near-zero-cost when off.

Before this package the system's performance story lived in one-off bench
scripts: PR 1's 8.5x goodput and PR 2's 2.48 accepted-drafts/step were
measured once and committed. Serving at the ROADMAP's "fast as the hardware
allows" requires the system to CONTINUOUSLY tell us where time goes — batch
formation stalls and sync boundaries are exactly the hidden costs Kernel
Looping (arXiv:2410.23668) shows dominating peak inference, and BASS
(arXiv:2404.15778) shows batched speculation only pays when acceptance is
measured per batch, not spot-checked.

Six pieces, one span model:

- :mod:`trace`     — `RequestTrace` (request id carried across the HTTP ->
                     queue -> scheduler -> engine thread handoffs),
                     `BatchTrace` (per-engine-batch step telemetry), the
                     contextvar `emit()` hook backends publish through, and
                     the bounded `ObsHub` ring with request sampling
- :mod:`histogram` — fixed-bucket Prometheus histograms with
                     bucket-derived percentiles (p50/p95/p99 in bench JSON)
- :mod:`telemetry` — rolling-window ratios for "now" gauges (rolling
                     spec acceptance, rolling tokens/s)
- :mod:`window`    — ring-of-sub-windows histograms/counters: "last
                     minute" quantiles and counts with the cumulative
                     histogram's observe cost — the SLO engine's
                     (`serve/slo.py`) and usage ledger's substrate
- :mod:`recorder`  — the flight recorder: a bounded ring of typed
                     lifecycle events, dumped atomically on anomalies
                     (brownout, fatal, quarantine, SLO fast-burn, drain)
- :mod:`export`    — Chrome trace-event JSON (loads in chrome://tracing and
                     ui.perfetto.dev): one track per request, one per
                     engine batch; `save_chrome_trace` drops the dump next
                     to XLA device profiles from `core.profiling`

Consumers: `serve/metrics.py` (histogram registry + /metrics), the
scheduler (span recording + TTFT), `backend/engine.py` and `backend/fake.py`
(phase emission), `core/profiling.Tracer` (pipeline spans rebased onto the
same `SpanRecorder`), and the `/debug/trace` endpoint (`serve/server.py`).
"""
from .histogram import Histogram
from .recorder import FlightRecorder
from .telemetry import Rolling
from .trace import (
    BatchTrace,
    ObsHub,
    RequestTrace,
    Span,
    SpanRecorder,
    current_collector,
    emit,
    reset_collector,
    set_collector,
)
from .window import WindowedCounter, WindowedHistogram

__all__ = [
    "BatchTrace",
    "FlightRecorder",
    "Histogram",
    "ObsHub",
    "RequestTrace",
    "Rolling",
    "Span",
    "SpanRecorder",
    "WindowedCounter",
    "WindowedHistogram",
    "current_collector",
    "emit",
    "reset_collector",
    "set_collector",
]
