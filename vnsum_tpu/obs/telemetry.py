"""Rolling-window aggregates for engine telemetry gauges.

Cumulative counters answer "how much since boot"; operators watching a live
server need "how is it doing NOW". :class:`Rolling` keeps the last N
(numerator, denominator) pairs — accepted/drafted tokens, generated
tokens/engine-seconds — so `serve/metrics.py` can export
``vnsum_serve_spec_acceptance_rolling`` and
``vnsum_serve_tokens_per_second_rolling`` without unbounded state or a
time-series dependency. O(1) per observation (deque append + running sums).
"""
from __future__ import annotations

from collections import deque


class Rolling:
    """Windowed ratio of two running sums over the last ``window`` samples.

    Not internally locked — owners (ServeMetrics) serialize observations
    under their own lock, same contract as `obs/histogram.py`.
    """

    __slots__ = ("_win", "_num", "_den")

    def __init__(self, window: int = 256) -> None:
        self._win: deque[tuple[float, float]] = deque(maxlen=max(window, 1))
        self._num = 0.0
        self._den = 0.0

    def add(self, num: float, den: float) -> None:
        if len(self._win) == self._win.maxlen:
            old_n, old_d = self._win[0]
            self._num -= old_n
            self._den -= old_d
        self._win.append((num, den))
        self._num += num
        self._den += den

    @property
    def samples(self) -> int:
        return len(self._win)

    def rate(self) -> float:
        """num/den over the window; 0 when the denominator is empty."""
        return self._num / self._den if self._den else 0.0
