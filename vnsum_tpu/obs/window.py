"""Rolling-window histograms and counters: "how is it doing NOW" state.

Every histogram PR 3 built is cumulative-since-start — exactly right for
Prometheus scrapes (the server computes rates), and exactly wrong for the
in-process consumers this repo keeps growing: the SLO engine
(`serve/slo.py`) needs "TTFT p99 over the last minute", the per-tenant
usage ledger (`serve/usage.py`) needs recent latency per tenant, and
neither can afford to retain raw samples.

:class:`WindowedHistogram` is the standard ring-of-sub-windows construction:
a horizon of N fixed-span sub-windows, each an ordinary
`obs/histogram.Histogram`. ``observe()`` lands in the sub-window the
timestamp belongs to (the expired occupant of that ring slot is zeroed in
place — no allocation), so the per-observation cost stays the
two-int-add-plus-float of the underlying histogram. Reads merge the live
sub-windows into one histogram (``merged()``), optionally over just the
most recent ``window_s`` — one ring serves both the fast (~1m) and slow
(~10m) burn-rate windows of the SLO engine.

Resolution note: a read over ``window_s`` covers the ceil(window_s/sub_s)
most recent sub-windows — between window_s - sub_s and window_s of real
time depending on where "now" sits inside the current sub-window. The SLO
math divides fractions, not absolute counts, so this granularity error
cancels; pick sub-window counts so sub_s << fast window (the serving
default is a 10s sub-window under a 60s fast window).

Exemplars: ``observe(..., exemplar=trace_id)`` remembers the most recent
(trace_id, value, timestamp) per BUCKET, aged out past the horizon — the
OpenMetrics-style breadcrumb that links a bad p99 bucket straight to its
request's timeline in ``/debug/trace``.

Like `obs/histogram.py` and `obs/telemetry.py`, nothing here locks: owners
(`serve/metrics.ServeMetrics`) already serialize observations and reads
under their own lock. ``now`` is injectable everywhere so the window-math
property tests drive a synthetic clock.
"""
from __future__ import annotations

import math
import time

from .histogram import Histogram


class _Ring:
    """The epoch/slot bookkeeping both windowed types share: which ring
    slot an observation at time ``t`` lands in (recycling the expired
    occupant in place), and which slots are still live for a read.

    epoch = which absolute sub-window interval a slot currently holds;
    -1 = never written. A slot whose epoch trails the current one has
    fully expired and is recycled on the next write that lands in it."""

    __slots__ = ("horizon_s", "sub_s", "_epochs")

    def __init__(self, horizon_s: float, sub_windows: int) -> None:
        if horizon_s <= 0 or sub_windows < 1:
            raise ValueError("horizon_s must be > 0 and sub_windows >= 1")
        self.horizon_s = float(horizon_s)
        self.sub_s = self.horizon_s / int(sub_windows)
        self._epochs = [-1] * int(sub_windows)

    def write_slot(self, now: float) -> tuple[int, bool]:
        """(slot for an observation at ``now``, whether the caller must
        zero the slot's expired occupant first)."""
        e = int(now // self.sub_s)
        slot = e % len(self._epochs)
        recycle = self._epochs[slot] != e
        if recycle:
            self._epochs[slot] = e
        return slot, recycle

    def live_slots(self, now: float, window_s: float | None):
        """Slots of the sub-windows live within ``window_s`` (default: the
        whole horizon), most recent first."""
        e = int(now // self.sub_s)
        k = len(self._epochs)
        if window_s is not None:
            k = min(k, max(1, math.ceil(window_s / self.sub_s)))
        for j in range(k):
            ep = e - j
            if ep < 0:
                break
            slot = ep % len(self._epochs)
            if self._epochs[slot] == ep:
                yield slot


class WindowedHistogram:
    """Ring of ``sub_windows`` fixed-bucket histograms spanning
    ``horizon_s`` seconds, merged on read."""

    __slots__ = ("bounds", "_ring", "_subs", "_exemplars", "_clock")

    def __init__(self, bounds, horizon_s: float = 600.0,
                 sub_windows: int = 60, clock=time.monotonic) -> None:
        self.bounds = tuple(float(x) for x in bounds)
        self._ring = _Ring(horizon_s, sub_windows)
        self._subs = [Histogram(self.bounds) for _ in range(int(sub_windows))]
        # per-bucket most recent exemplar: (trace_id, value, t) or None
        self._exemplars: list[tuple | None] = [None] * (len(self.bounds) + 1)
        self._clock = clock

    @property
    def horizon_s(self) -> float:
        return self._ring.horizon_s

    @property
    def sub_s(self) -> float:
        return self._ring.sub_s

    def observe(self, value: float, now: float | None = None,
                exemplar: str | None = None) -> None:
        now = self._clock() if now is None else now
        slot, recycle = self._ring.write_slot(now)
        if recycle:
            # in place, no allocation on the observe path
            self._subs[slot].reset()
        self._subs[slot].observe(value)
        if exemplar is not None:
            idx = self._subs[slot].bucket_index(value)
            self._exemplars[idx] = (exemplar, value, now)

    def merged(self, window_s: float | None = None,
               now: float | None = None) -> Histogram:
        """One histogram over the live sub-windows — the whole horizon by
        default, or just the most recent ``window_s`` of it."""
        now = self._clock() if now is None else now
        out = Histogram(self.bounds)
        for slot in self._ring.live_slots(now, window_s):
            out.merge_from(self._subs[slot])
        return out

    def exemplars(self, window_s: float | None = None,
                  now: float | None = None) -> list[tuple | None]:
        """Per-bucket (trace_id, value, t) exemplars no older than
        ``window_s`` (default: the horizon)."""
        now = self._clock() if now is None else now
        max_age = self.horizon_s if window_s is None else float(window_s)
        return [
            ex if ex is not None and now - ex[2] <= max_age else None
            for ex in self._exemplars
        ]


class WindowedCounter:
    """Keyed monotone counts over the same ring construction — the windowed
    request/error/shed tallies the SLO engine's error-rate and availability
    objectives divide. O(1) add; reads sum the live sub-windows."""

    __slots__ = ("_ring", "_subs", "_clock")

    def __init__(self, horizon_s: float = 600.0, sub_windows: int = 60,
                 clock=time.monotonic) -> None:
        self._ring = _Ring(horizon_s, sub_windows)
        self._subs: list[dict[str, float]] = [
            {} for _ in range(int(sub_windows))
        ]
        self._clock = clock

    @property
    def horizon_s(self) -> float:
        return self._ring.horizon_s

    @property
    def sub_s(self) -> float:
        return self._ring.sub_s

    def add(self, key: str, n: float = 1, now: float | None = None) -> None:
        now = self._clock() if now is None else now
        slot, recycle = self._ring.write_slot(now)
        if recycle:
            self._subs[slot].clear()
        sub = self._subs[slot]
        sub[key] = sub.get(key, 0) + n

    def totals(self, window_s: float | None = None,
               now: float | None = None) -> dict[str, float]:
        """{key: count} summed over the live sub-windows of ``window_s``."""
        now = self._clock() if now is None else now
        out: dict[str, float] = {}
        for slot in self._ring.live_slots(now, window_s):
            for key, n in self._subs[slot].items():
                out[key] = out.get(key, 0) + n
        return out

    def total(self, key: str, window_s: float | None = None,
              now: float | None = None) -> float:
        return self.totals(window_s, now).get(key, 0)
