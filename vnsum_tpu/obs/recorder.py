"""Flight recorder: a cheap bounded ring of typed lifecycle events, dumped
on anomalies.

The journal (serve/journal.py) answers "what happened to request X" and the
trace ring (obs/trace.py) answers "where did request X's time go" — but
neither answers the post-mortem question "what was the SERVER doing in the
seconds before it browned out / quarantined / breached its SLO". This is
that black box: every scheduler lifecycle transition (admit / dispatch /
complete / failed / shed / cancel / preempt / requeue / rung change /
journal replay / SLO breach) appends one tuple-cheap event to a bounded
deque, and anomaly triggers snapshot the whole ring to disk through the
existing crash-safe `core/artifacts.atomic_write_json` writer.

Dump triggers (wired in serve/scheduler.py, serve/server.py, serve/slo.py):
brownout entry, fatal engine failure, poison quarantine, sustained SLO
fast-burn, and SIGTERM drain. Dumps are throttled per reason
(``min_dump_interval_s``) so a quarantine storm produces one recording, not
a disk full of near-identical ones; with no ``directory`` configured the
ring still records and serves ``GET /debug/flightrecorder``, and dump()
returns None.

Cost when armed: one lock + deque.append per event — events fire per
REQUEST lifecycle transition (never per token or per scrape), the same
budget class as the metrics counters. A scheduler built with
``recorder=None`` pays only ``is None`` checks (the bench A/B's all-off
arm). Thread-safe: admit events fire under the queue lock, cancels from
HTTP handler threads, everything else from the scheduler thread — the
recorder lock is innermost like the journal's and takes no other lock
while held.
"""
from __future__ import annotations

import itertools
import time
from collections import deque
from pathlib import Path

from ..analysis.sanitizers import make_lock
from ..core.artifacts import atomic_write_json
from ..core.logging import get_logger

logger = get_logger("vnsum.obs.recorder")

# typed event kinds — one vocabulary shared with the journal where the two
# overlap (EV_* in serve/journal.py), so a dump's event sequence can be
# checked against the ledger's record for the same rid
EVENT_KINDS = (
    "admit", "dispatch", "complete", "failed", "shed", "cancel",
    "preempt", "requeue", "fault", "bisect", "rung_change",
    "journal_replay", "slo_breach", "stream",
    # watchdog liveness verdicts (serve/watchdog.py): a thread/dispatch
    # declared stalled, and a wedged-dispatch recovery that answered it
    "stall", "watchdog_recover",
    # fleet-router routing decisions (serve/router.py keeps its own ring —
    # the routing half of every incident bundle): a dispatch routed to a
    # worker, mark-down/mark-up transitions, a journal-handoff failover,
    # a worker process restart, and a minted incident id
    "route", "markdown", "markup", "failover", "handoff_replay",
    "worker_restart", "incident",
)

_dump_ids = itertools.count(1)


class FlightRecorder:
    """Bounded ring of typed lifecycle events + anomaly-triggered dumps."""

    def __init__(self, capacity: int = 4096,
                 directory: str | Path | None = None,
                 min_dump_interval_s: float = 5.0) -> None:
        self.capacity = max(int(capacity), 16)
        self.directory = Path(directory) if directory else None
        self.min_dump_interval_s = float(min_dump_interval_s)
        # lock-order-sanitizer hook: plain threading.Lock in production.
        # Innermost by contract — record() runs under the queue lock (the
        # admission hook) and must never acquire another serve lock
        self._lock = make_lock("obs.recorder")
        self._events: deque = deque(maxlen=self.capacity)  # guarded by: _lock
        self._dropped = 0                                  # guarded by: _lock
        self._seq = 0                                      # guarded by: _lock
        self._last_dump: dict[str, float] = {}             # guarded by: _lock
        self.dumps_written = 0  # monotone; racy scrape reads are fine
        self._t0 = time.monotonic()
        self._wall0 = time.time()

    # -- recording --------------------------------------------------------

    def record(self, kind: str, rid: str = "", **fields) -> None:
        """Append one typed event. ``rid`` is the request's trace_id ("" for
        server-level events like rung changes); extra fields must be
        JSON-serializable scalars/lists (the dump writer will not coerce)."""
        t = time.monotonic()
        with self._lock:
            self._seq += 1
            if len(self._events) == self.capacity:
                self._dropped += 1
            self._events.append((self._seq, t, kind, rid, fields or None))

    # -- export -----------------------------------------------------------

    def snapshot(self) -> dict:
        """The ring as a JSON-shaped dict — `GET /debug/flightrecorder` and
        every dump share this one serialization. Event timestamps are
        seconds since server start (t_rel) plus the wall-clock epoch of the
        start, so post-mortems can line events up with external logs."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
            total = self._seq
        return {
            "started_wall": self._wall0,
            "capacity": self.capacity,
            "events_recorded": total,
            "events_dropped": dropped,
            "events": [
                {
                    "seq": seq,
                    "t_rel": round(t - self._t0, 6),
                    "kind": kind,
                    **({"rid": rid} if rid else {}),
                    **(fields or {}),
                }
                for seq, t, kind, rid, fields in events
            ],
        }

    def dump(self, reason: str) -> Path | None:
        """Snapshot the ring to ``flight_<reason>_<utc-ms>_<n>.json`` in the
        configured directory (atomic write). Throttled per reason; no-op
        (returns None) when no directory is configured or the reason dumped
        within ``min_dump_interval_s``."""
        if self.directory is None:
            return None
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(reason)
            if last is not None and now - last < self.min_dump_interval_s:
                return None
            self._last_dump[reason] = now
        payload = {
            "reason": reason,
            "dumped_wall": time.time(),
            **self.snapshot(),
        }
        # wall-clock ms in the name: dumps from successive PROCESSES on one
        # directory (chaos-soak restarts) must never overwrite each other
        path = self.directory / (
            f"flight_{reason}_{int(time.time() * 1000)}"
            f"_{next(_dump_ids):03d}.json"
        )
        try:
            atomic_write_json(path, payload)
        except OSError:
            # a full/unwritable disk must not turn an anomaly dump into a
            # second failure inside the scheduler's failure handling or a
            # SIGTERM drain — the ring stays intact for /debug/flightrecorder
            # (the throttle stamp stands: no point retrying for 5s)
            logger.exception("flight recorder dump to %s failed", path)
            return None
        with self._lock:
            # read-modify-write: breach dumps (daemon thread) race
            # scheduler-thread dumps
            self.dumps_written += 1
        logger.warning("flight recorder dumped %d event(s) to %s (%s)",
                       len(payload["events"]), path, reason)
        return path

    def stats_dict(self) -> dict:
        """Scrape-time counters for /metrics (vnsum_serve_recorder_*)."""
        with self._lock:
            return {
                "events": self._seq,
                "dropped": self._dropped,
                "dumps": self.dumps_written,
            }
