"""Chrome trace-event JSON export (Perfetto-loadable timelines).

Serializes the in-memory span model (`obs/trace.py`) into the trace-event
format both `chrome://tracing` and https://ui.perfetto.dev load directly:

- every **engine batch** becomes its own track under the ``engine`` process
  (pid 1): the batch slice, with the backend's phase events (prefill /
  decode segments / spec steps) nested inside it;
- every **request** becomes its own process (pid 100+): track 0 carries the
  request-level slice, and each fanned-out prompt's queue-wait/engine/
  postprocess slices sit on their own sub-track — per-prompt intervals of
  one request overlap in time, and the trace-event format requires slices
  on a single track to nest properly, so overlap gets a track, not a stack.

All host timestamps are `time.monotonic()` seconds; export rebases them to
microseconds from the earliest event so the viewer opens at t=0. Output is a
plain dict — callers `json.dumps` it (the `/debug/trace` endpoint) or hand
it to :func:`save_chrome_trace`.
"""
from __future__ import annotations

import json
import time
from pathlib import Path

ENGINE_PID = 1
REQUEST_PID0 = 100


def chrome_trace(requests, batches) -> dict:
    """Build the trace-event dict from finished Request/Batch traces."""
    # snapshot once: finished traces are sealed, but a shed trace can land
    # in the ring while a straggler span races the seal — never iterate the
    # live span lists
    req_spans = [(r, r.spans_snapshot()) for r in requests]
    epoch = time.monotonic()
    for _, spans in req_spans:
        for sp in spans:
            epoch = min(epoch, sp.t0)
    for b in batches:
        epoch = min(epoch, b.t0)
    us = lambda t: round((t - epoch) * 1e6, 3)  # noqa: E731
    ev: list[dict] = []

    def meta(name, pid, tid, value):
        ev.append({"ph": "M", "name": name, "pid": pid, "tid": tid,
                   "args": {"name": value}})

    def slice_(name, pid, tid, t0, dur, args=None):
        e = {"ph": "X", "name": name, "pid": pid, "tid": tid,
             "ts": us(t0), "dur": round(max(dur, 0.0) * 1e6, 3)}
        if args:
            e["args"] = args
        ev.append(e)

    if batches:
        meta("process_name", ENGINE_PID, 0, "engine")
    for b in batches:
        tid = b.batch_id
        meta("thread_name", ENGINE_PID, tid, f"batch {b.batch_id}")
        t1 = b.t1 if b.t1 is not None else b.t0
        slice_(
            f"batch[occ={b.occupancy}]", ENGINE_PID, tid, b.t0, t1 - b.t0,
            {"occupancy": b.occupancy, "gen_tokens": b.gen_tokens},
        )
        for sp in b.events:
            slice_(sp.name, ENGINE_PID, tid, sp.t0, sp.dur, sp.args)

    for i, (r, spans) in enumerate(req_spans):
        pid = REQUEST_PID0 + i
        meta("process_name", pid, 0, f"request {r.trace_id}")
        tracks = {sp.track for sp in spans}
        for tr in sorted(tracks):
            meta("thread_name", pid, tr,
                 "request" if tr == 0 else f"prompt {tr - 1}")
        for sp in spans:
            slice_(sp.name, pid, sp.track, sp.t0, sp.dur, sp.args)

    return {"traceEvents": ev, "displayTimeUnit": "ms"}


def trace_state_payload(requests) -> list[dict]:
    """Raw span state of finished request traces, JSON-shaped — the wire
    format the fleet router's ``/debug/trace`` stitcher pulls from each
    worker (``GET /debug/obs/snapshot``). Deliberately NOT chrome_trace():
    that export rebases timestamps to a per-process epoch, which destroys
    the cross-process alignment the stitcher needs; this payload keeps the
    worker's monotonic seconds verbatim and lets the router apply its
    probe-estimated clock offset before any rebase."""
    out = []
    for r in requests:
        out.append({
            "trace_id": r.trace_id,
            "parent": getattr(r, "parent", None),
            "status": r.status,
            "t_start": r.t_start,
            "spans": [
                {"name": sp.name, "t0": sp.t0, "dur": sp.dur,
                 "track": sp.track,
                 **({"args": sp.args} if sp.args else {})}
                for sp in r.spans_snapshot()
            ],
        })
    return out


# per-source track spacing in the merged trace: each contributing process
# gets its own block of Perfetto tracks within a request's process group,
# so a worker's per-prompt sub-tracks can never collide with the router's
_SOURCE_TRACK_STRIDE = 1000


def merged_chrome_trace(groups) -> dict:
    """ONE Chrome trace from the span rings of several PROCESSES — the
    fleet stitcher (router ``/debug/trace``). ``groups`` is a list of
    ``{"source": label, "clock_offset_s": off, "traces": [...]}``, where
    ``traces`` is :func:`trace_state_payload` output from that process and
    ``off`` maps its monotonic clock into the reference (router) clock:
    ``t_ref = t + off`` (the router estimates it from probe RTT midpoints;
    its own group carries 0.0).

    Traces sharing a trace_id — the router's root trace and every worker
    hop of the same request, INCLUDING the pre- and post-failover halves
    of a handed-off request — merge into one Perfetto process; each source
    contributes its own track block, named ``<source>:request`` /
    ``<source>:prompt N``."""
    # trace_id -> [(source, clock_offset_s, trace_payload), ...] in group
    # order, so the reference process (the router) lists first
    by_id: dict[str, list] = {}
    for g in groups:
        off = float(g.get("clock_offset_s") or 0.0)
        for t in g.get("traces") or []:
            by_id.setdefault(t["trace_id"], []).append(
                (g.get("source", "?"), off, t)
            )
    epoch = None
    for contribs in by_id.values():
        for _src, off, t in contribs:
            for sp in t.get("spans") or []:
                t_ref = float(sp["t0"]) + off
                epoch = t_ref if epoch is None else min(epoch, t_ref)
    if epoch is None:
        epoch = 0.0
    ev: list[dict] = []
    for i, trace_id in enumerate(sorted(
        by_id,
        key=lambda tid: min(
            (float(sp["t0"]) + off
             for _s, off, t in by_id[tid] for sp in t.get("spans") or []),
            default=0.0,
        ),
    )):
        pid = REQUEST_PID0 + i
        ev.append({"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                   "args": {"name": f"request {trace_id}"}})
        # one track block per contributing (source, hop): the pre- and
        # post-failover halves of one request come from different worker
        # sources and land side by side under the shared trace id
        for j, (source, off, t) in enumerate(by_id[trace_id]):
            base = j * _SOURCE_TRACK_STRIDE
            spans = t.get("spans") or []
            tracks = sorted({int(sp.get("track", 0)) for sp in spans})
            for tr in tracks:
                label = ("request" if tr == 0 else f"prompt {tr - 1}")
                ev.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": base + tr,
                    "args": {"name": f"{source}:{label}"},
                })
            for sp in spans:
                e = {
                    "ph": "X", "name": sp["name"], "pid": pid,
                    "tid": base + int(sp.get("track", 0)),
                    "ts": round((float(sp["t0"]) + off - epoch) * 1e6, 3),
                    "dur": round(max(float(sp["dur"]), 0.0) * 1e6, 3),
                }
                args = dict(sp.get("args") or {})
                args["source"] = source
                if t.get("parent"):
                    args.setdefault("parent_span", t["parent"])
                e["args"] = args
                ev.append(e)
    return {"traceEvents": ev, "displayTimeUnit": "ms"}


def spans_to_chrome(spans, process_name: str = "pipeline") -> dict:
    """Export a flat span list (e.g. `core/profiling.Tracer.timeline()`) as
    one single-process timeline — how offline pipeline runs share the same
    Perfetto workflow as the serving rings."""
    epoch = min((sp.t0 for sp in spans), default=0.0)
    ev: list[dict] = [
        {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
         "args": {"name": process_name}}
    ]
    for sp in spans:
        e = {
            "ph": "X", "name": sp.name, "pid": 1, "tid": sp.track,
            "ts": round((sp.t0 - epoch) * 1e6, 3),
            "dur": round(max(sp.dur, 0.0) * 1e6, 3),
        }
        if sp.args:
            e["args"] = sp.args
        ev.append(e)
    return {"traceEvents": ev, "displayTimeUnit": "ms"}


def save_chrome_trace(trace: dict, path) -> Path:
    """Write a trace dict as .json next to any XLA device traces
    (`core/profiling.device_profile` writes into the same directory when
    armed), so host spans and device timelines open side by side.
    Atomic (write-temp + os.replace, core/artifacts.py): the shutdown dump
    path runs while the process is dying — a crash mid-dump must not leave
    a truncated JSON the next Perfetto load chokes on."""
    from ..core.artifacts import atomic_write_text

    return atomic_write_text(Path(path), json.dumps(trace))


def save_timestamped_trace(trace: dict, directory, prefix: str) -> Path:
    """THE dump naming policy (serve /debug/trace?save=1, serve shutdown,
    pipeline runs): <prefix>_trace_<ts>.json in ``directory``, suffixed
    _1/_2/... instead of silently overwriting when two dumps land within
    the same second."""
    d = Path(directory)
    ts = time.strftime("%Y%m%d_%H%M%S")
    path = d / f"{prefix}_trace_{ts}.json"
    n = 1
    while path.exists():
        path = d / f"{prefix}_trace_{ts}_{n}.json"
        n += 1
    return save_chrome_trace(trace, path)
