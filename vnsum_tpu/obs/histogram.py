"""Fixed-bucket cumulative histograms with Prometheus text rendering.

The serving metrics (`serve/metrics.py`) were flat counters plus one
hand-rolled bucket array; this module makes the histogram a first-class,
reusable unit: ``observe()`` is two integer adds and a float add (no
allocation — safe on a per-request path), rendering emits the standard
Prometheus ``_bucket``/``_sum``/``_count`` cumulative text format, and
``percentile()`` derives p50/p95/p99 from the buckets the way a PromQL
``histogram_quantile`` would (linear interpolation inside the bucket), so
bench scripts can snapshot quantiles without retaining raw samples.

Not internally locked: owners that observe from multiple threads
(`serve/metrics.ServeMetrics`) already serialize under their own lock, and a
second lock per observation would be pure overhead.
"""
from __future__ import annotations


# shared bucket ladders (seconds unless noted). Spans are chosen to cover
# sub-millisecond coalescing waits through multi-second strategy runs; the
# serving metrics registry in serve/metrics.py maps names -> ladders.
WAIT_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                  1.0, 2.5, 5.0)
TTFT_BUCKETS_S = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                  10.0)
E2E_BUCKETS_S = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                 30.0, 60.0)
OCCUPANCY_BUCKETS = (1, 2, 4, 8, 16, 32, 64)
ACCEPT_BUCKETS = (0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 6.0, 8.0)
# a /metrics render costs tens of microseconds to low milliseconds — a
# self-metric on the WAIT ladder (floor 1ms) would put every scrape in the
# first bucket and report nothing
SCRAPE_BUCKETS_S = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3,
                    5e-3, 0.01, 0.025, 0.05, 0.1)


def _fmt(v: float) -> str:
    """Prometheus-style number: integral values without the trailing .0."""
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


class HistogramMergeError(ValueError):
    """Two histograms with different bucket ladders cannot be merged.

    Typed (not a bare ValueError) because the fleet federation layer
    (serve/federation.py) merges histograms scraped off REMOTE processes:
    a worker running a different build can legitimately ship a different
    ladder, and the scrape loop must catch exactly this condition and
    skip the series rather than silently corrupting the rollup counts or
    swallowing unrelated ValueErrors."""


class Histogram:
    """Cumulative fixed-bucket histogram (Prometheus semantics).

    ``counts[i]`` is the NON-cumulative count of observations in
    ``(bounds[i-1], bounds[i]]``; the final slot is the +Inf tail. Rendering
    accumulates, matching the ``le``-labelled cumulative contract scrapers
    expect.
    """

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds) -> None:
        b = tuple(float(x) for x in bounds)
        if not b or list(b) != sorted(b):
            raise ValueError("bucket bounds must be non-empty and ascending")
        self.bounds = b
        self.counts = [0] * (len(b) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.sum += value
        self.count += 1
        for i, ub in enumerate(self.bounds):
            if value <= ub:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def reset(self) -> None:
        """Zero in place (no allocation — `obs/window.py` recycles expired
        sub-windows through here on the observe path)."""
        for i in range(len(self.counts)):
            self.counts[i] = 0
        self.sum = 0.0
        self.count = 0

    def merge_from(self, other: "Histogram") -> None:
        """Add ``other``'s counts into this histogram (same bounds required)
        — how `obs/window.WindowedHistogram` folds its live sub-windows into
        one readable histogram, and how the fleet federation rolls worker
        histograms up. Mismatched ladders raise :class:`HistogramMergeError`
        instead of silently mis-binning counts."""
        if other.bounds != self.bounds:
            raise HistogramMergeError(
                "cannot merge histograms with different bounds: "
                f"{self.bounds} vs {other.bounds}"
            )
        for i, n in enumerate(other.counts):
            self.counts[i] += n
        self.sum += other.sum
        self.count += other.count

    def bucket_index(self, value: float) -> int:
        """Index of the bucket ``value`` falls in (len(bounds) = +Inf tail)."""
        for i, ub in enumerate(self.bounds):
            if value <= ub:
                return i
        return len(self.bounds)

    def fraction_le(self, x: float) -> float:
        """Fraction of observations <= ``x``, interpolated inside the bucket
        ``x`` falls in — the compliance estimator the SLO engine
        (`serve/slo.py`) judges latency objectives with. The +Inf tail is
        conservatively counted as ABOVE any finite ``x`` (an observation
        past the top bound is a violation we cannot bound). Empty histogram
        = vacuous compliance (1.0)."""
        if not self.count:
            return 1.0
        cum = 0
        lo = 0.0
        for i, ub in enumerate(self.bounds):
            if x < ub:
                frac = (x - lo) / (ub - lo) if ub > lo else 1.0
                return (cum + self.counts[i] * max(min(frac, 1.0), 0.0)) / self.count
            cum += self.counts[i]
            lo = ub
        return cum / self.count

    def percentile(self, q: float) -> float:
        """Quantile estimate from the buckets (histogram_quantile rules):
        find the bucket where the cumulative count crosses ``q * count``,
        interpolate linearly inside it. Observations in the +Inf tail report
        the highest finite bound — a floor, exactly like PromQL."""
        if not self.count:
            return 0.0
        rank = q * self.count
        cum = 0
        lo = 0.0
        for i, ub in enumerate(self.bounds):
            prev = cum
            cum += self.counts[i]
            if cum >= rank:
                frac = (rank - prev) / self.counts[i] if self.counts[i] else 0.0
                return lo + (ub - lo) * frac
            lo = ub
        return self.bounds[-1]

    # -- export ----------------------------------------------------------

    def render(self, name: str, help_: str,
               exemplars: list | None = None) -> list[str]:
        """Prometheus text-format lines: HELP/TYPE then cumulative
        ``_bucket{le=...}`` rows, ``_sum``, ``_count``. ``exemplars`` is an
        optional per-bucket list of (trace_id, value, t) tuples (see
        `obs/window.WindowedHistogram.exemplars`): buckets with one get the
        OpenMetrics-style ``# {trace_id="..."} value`` suffix that links a
        bad latency bucket straight to its request in ``/debug/trace``."""
        lines = [f"# HELP {name} {help_}", f"# TYPE {name} histogram"]
        cum = 0
        for i, (ub, n) in enumerate(zip(self.bounds, self.counts)):
            cum += n
            line = f'{name}_bucket{{le="{_fmt(ub)}"}} {cum}'
            if exemplars is not None and i < len(exemplars) and exemplars[i]:
                ex_id, ex_val, _t = exemplars[i]
                line += f' # {{trace_id="{ex_id}"}} {round(ex_val, 6)}'
            lines.append(line)
        cum += self.counts[-1]
        tail = f'{name}_bucket{{le="+Inf"}} {cum}'
        if exemplars is not None and exemplars[-1]:
            ex_id, ex_val, _t = exemplars[-1]
            tail += f' # {{trace_id="{ex_id}"}} {round(ex_val, 6)}'
        lines.append(tail)
        lines.append(f"{name}_sum {round(self.sum, 6)}")
        lines.append(f"{name}_count {cum}")
        return lines

    def to_dict(self) -> dict:
        """Snapshot for bench JSON: buckets plus derived p50/p95/p99 — the
        quantiles BENCH_*.json files report instead of bare means."""
        return {
            "buckets": {
                **{_fmt(ub): n for ub, n in zip(self.bounds, self.counts)},
                "+Inf": self.counts[-1],
            },
            "sum": round(self.sum, 6),
            "count": self.count,
            "p50": round(self.percentile(0.50), 6),
            "p95": round(self.percentile(0.95), 6),
            "p99": round(self.percentile(0.99), 6),
        }

    def state_dict(self) -> dict:
        """Raw mergeable state (bounds + non-cumulative counts) — the wire
        format the fleet federation scrapes off each worker's JSON snapshot
        endpoint. Distinct from :meth:`to_dict`, whose bucket keys are
        render-formatted strings and whose quantiles are derived."""
        return {
            "bounds": list(self.bounds),
            "counts": list(self.counts),
            "sum": self.sum,
            "count": self.count,
        }

    @classmethod
    def from_state(cls, state: dict) -> "Histogram":
        """Rebuild a histogram from :meth:`state_dict` output (possibly
        deserialized from another process). Malformed state — a counts
        vector that does not match the ladder — raises
        :class:`HistogramMergeError`, the same typed error a downstream
        merge would hit."""
        h = cls(state["bounds"])
        counts = [int(n) for n in state["counts"]]
        if len(counts) != len(h.counts):
            raise HistogramMergeError(
                f"counts length {len(counts)} does not match ladder of "
                f"{len(h.bounds)} bounds (+Inf tail)"
            )
        h.counts = counts
        h.sum = float(state["sum"])
        h.count = int(state["count"])
        return h

    def copy(self) -> "Histogram":
        h = Histogram(self.bounds)
        h.counts = list(self.counts)
        h.sum = self.sum
        h.count = self.count
        return h
