"""Fixed-bucket cumulative histograms with Prometheus text rendering.

The serving metrics (`serve/metrics.py`) were flat counters plus one
hand-rolled bucket array; this module makes the histogram a first-class,
reusable unit: ``observe()`` is two integer adds and a float add (no
allocation — safe on a per-request path), rendering emits the standard
Prometheus ``_bucket``/``_sum``/``_count`` cumulative text format, and
``percentile()`` derives p50/p95/p99 from the buckets the way a PromQL
``histogram_quantile`` would (linear interpolation inside the bucket), so
bench scripts can snapshot quantiles without retaining raw samples.

Not internally locked: owners that observe from multiple threads
(`serve/metrics.ServeMetrics`) already serialize under their own lock, and a
second lock per observation would be pure overhead.
"""
from __future__ import annotations


# shared bucket ladders (seconds unless noted). Spans are chosen to cover
# sub-millisecond coalescing waits through multi-second strategy runs; the
# serving metrics registry in serve/metrics.py maps names -> ladders.
WAIT_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                  1.0, 2.5, 5.0)
TTFT_BUCKETS_S = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                  10.0)
E2E_BUCKETS_S = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                 30.0, 60.0)
OCCUPANCY_BUCKETS = (1, 2, 4, 8, 16, 32, 64)
ACCEPT_BUCKETS = (0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 6.0, 8.0)


def _fmt(v: float) -> str:
    """Prometheus-style number: integral values without the trailing .0."""
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


class Histogram:
    """Cumulative fixed-bucket histogram (Prometheus semantics).

    ``counts[i]`` is the NON-cumulative count of observations in
    ``(bounds[i-1], bounds[i]]``; the final slot is the +Inf tail. Rendering
    accumulates, matching the ``le``-labelled cumulative contract scrapers
    expect.
    """

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds) -> None:
        b = tuple(float(x) for x in bounds)
        if not b or list(b) != sorted(b):
            raise ValueError("bucket bounds must be non-empty and ascending")
        self.bounds = b
        self.counts = [0] * (len(b) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.sum += value
        self.count += 1
        for i, ub in enumerate(self.bounds):
            if value <= ub:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def percentile(self, q: float) -> float:
        """Quantile estimate from the buckets (histogram_quantile rules):
        find the bucket where the cumulative count crosses ``q * count``,
        interpolate linearly inside it. Observations in the +Inf tail report
        the highest finite bound — a floor, exactly like PromQL."""
        if not self.count:
            return 0.0
        rank = q * self.count
        cum = 0
        lo = 0.0
        for i, ub in enumerate(self.bounds):
            prev = cum
            cum += self.counts[i]
            if cum >= rank:
                frac = (rank - prev) / self.counts[i] if self.counts[i] else 0.0
                return lo + (ub - lo) * frac
            lo = ub
        return self.bounds[-1]

    # -- export ----------------------------------------------------------

    def render(self, name: str, help_: str) -> list[str]:
        """Prometheus text-format lines: HELP/TYPE then cumulative
        ``_bucket{le=...}`` rows, ``_sum``, ``_count``."""
        lines = [f"# HELP {name} {help_}", f"# TYPE {name} histogram"]
        cum = 0
        for ub, n in zip(self.bounds, self.counts):
            cum += n
            lines.append(f'{name}_bucket{{le="{_fmt(ub)}"}} {cum}')
        cum += self.counts[-1]
        lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{name}_sum {round(self.sum, 6)}")
        lines.append(f"{name}_count {cum}")
        return lines

    def to_dict(self) -> dict:
        """Snapshot for bench JSON: buckets plus derived p50/p95/p99 — the
        quantiles BENCH_*.json files report instead of bare means."""
        return {
            "buckets": {
                **{_fmt(ub): n for ub, n in zip(self.bounds, self.counts)},
                "+Inf": self.counts[-1],
            },
            "sum": round(self.sum, 6),
            "count": self.count,
            "p50": round(self.percentile(0.50), 6),
            "p95": round(self.percentile(0.95), 6),
            "p99": round(self.percentile(0.99), 6),
        }

    def copy(self) -> "Histogram":
        h = Histogram(self.bounds)
        h.counts = list(self.counts)
        h.sum = self.sum
        h.count = self.count
        return h
