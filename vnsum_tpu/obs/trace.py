"""Request/batch span model with cross-thread context propagation.

The serving path hands a request across three threads — the HTTP handler
(`serve/server.py`), the scheduler thread (`serve/scheduler.py`), and back —
and the engine (`backend/engine.py`) runs entirely inside the scheduler
thread. Two propagation mechanisms cover both seams, and both are explicit
about cost when tracing is off:

- **explicit carriage** for the queue handoff: a :class:`RequestTrace` rides
  the `ServeRequest` object itself (`serve/queue.py`), so whichever thread
  dequeues the request can attach spans to it — no thread-local can survive
  that handoff, so none is used;
- **a contextvar collector** for the engine: the scheduler sets the current
  :class:`BatchTrace` around `backend.generate` (:func:`set_collector`), and
  engine code calls the module-level :func:`emit` which no-ops on a single
  contextvar read when no collector is installed. The engine therefore needs
  no knowledge of the serving layer, and pipeline runs can install their own
  collector the same way.

Everything here is stdlib-only (no OpenTelemetry), allocation-free when
disabled (:func:`emit` allocates nothing without a collector; `ObsHub` with
`sample=0` never constructs a RequestTrace), and bounded: finished traces
land in fixed-size rings, never an unbounded list.

Timestamps are `time.monotonic()` seconds throughout; `obs/export.py`
rebases them to microseconds for Chrome trace-event JSON.
"""
from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from dataclasses import dataclass, field

from ..analysis.sanitizers import make_lock


@dataclass
class Span:
    """One closed wall-clock interval on a named track."""

    name: str
    t0: float          # time.monotonic() at entry
    dur: float         # seconds
    track: int = 0     # sub-track within the owning trace (0 = request level)
    args: dict | None = None


class SpanRecorder:
    """Thread-safe span sink with hierarchical naming.

    The shared span primitive under both `core/profiling.Tracer` (pipeline
    runs) and :class:`RequestTrace` (serving): nested ``span()`` blocks get
    `parent/child` names via a per-thread stack, closed spans append to a
    bounded list, and an optional ``on_close(full_name, duration)`` callback
    lets owners aggregate (the Tracer's SpanStats) without a second pass.
    """

    def __init__(self, maxlen: int = 4096, on_close=None) -> None:
        self.maxlen = maxlen
        self.on_close = on_close
        self._spans: list[Span] = []            # guarded by: _lock
        # lock-order-sanitizer hook: plain threading.Lock in production
        self._lock = make_lock("obs.spans")
        self._local = threading.local()

    def _stack(self) -> list[str]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    @contextlib.contextmanager
    def span(self, name: str, track: int = 0, **args):
        stack = self._stack()
        full = "/".join([*stack, name])
        stack.append(name)
        t0 = time.monotonic()
        try:
            yield
        finally:
            dur = time.monotonic() - t0
            stack.pop()
            self.add(full, t0, dur, track=track, **args)
            if self.on_close is not None:
                self.on_close(full, dur)

    def add(self, name: str, t0: float, dur: float, track: int = 0, **args) -> None:
        """Record an externally-timed span (no nesting bookkeeping)."""
        sp = Span(name, t0, dur, track, args or None)
        with self._lock:
            if len(self._spans) < self.maxlen:
                self._spans.append(sp)

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


class RequestTrace:
    """Spans of ONE request, across every thread and queue trip it takes.

    Created at the entry point (HTTP handler or scheduler submit), carried by
    reference on each `ServeRequest` the request fans out into (a summarize
    request's strategy rounds all share one trace), appended to from the
    scheduler thread, and finalized back at the entry point. ``track`` 0 is
    the request level; each fanned-out prompt claims its own sub-track via
    :meth:`next_track` so overlapping per-prompt intervals stay on separate
    Perfetto tracks instead of producing an improperly-nested slice stack.
    """

    # instances constructed since import — the overhead-guard test asserts
    # this does not move during an untraced serving run
    allocations = 0

    __slots__ = ("trace_id", "t_start", "status", "spans", "_lock",
                 "_tracks", "parent")

    def __init__(self, trace_id: str, parent: str | None = None) -> None:
        RequestTrace.allocations += 1
        self.trace_id = trace_id
        # cross-process trace context: the span name of the upstream hop
        # that dispatched this request (the fleet router's proxy span rides
        # in on an X-Parent-Span header). The merged fleet trace uses it to
        # nest worker timelines under the router's root span
        self.parent = parent
        self.t_start = time.monotonic()
        self.status = "open"                    # guarded by: _lock
        self.spans: list[Span] = []             # guarded by: _lock
        # lock-order-sanitizer hook: plain threading.Lock in production
        self._lock = make_lock("obs.trace")
        self._tracks = 0                        # guarded by: _lock

    def next_track(self) -> int:
        with self._lock:
            self._tracks += 1
            return self._tracks

    def add(self, name: str, t0: float, dur: float, track: int = 0, **args) -> None:
        with self._lock:
            # a finished trace is immutable: it may already sit in the
            # export ring. Late spans happen legitimately — a shed aborts
            # the request mid-fan-out while admitted sibling prompts are
            # still queued; their eventual completions must not mutate the
            # closed (possibly being-exported) timeline
            if self.status != "open":
                return
            self.spans.append(Span(name, t0, dur, track, args or None))

    @contextlib.contextmanager
    def span(self, name: str, track: int = 0, **args):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.add(name, t0, time.monotonic() - t0, track, **args)

    def finish(self, status: str = "ok") -> None:
        """Close the request-level span (track 0, full residency) and seal
        the trace against further mutation."""
        self.add("request", self.t_start, time.monotonic() - self.t_start,
                 track=0, status=status)
        with self._lock:
            self.status = status

    def spans_snapshot(self) -> list[Span]:
        """Consistent copy for exporters — finished traces are immutable,
        but a shed trace can be exported while a straggler add() races the
        seal, so exporters never iterate the live list."""
        with self._lock:
            return list(self.spans)


class BatchTrace:
    """Telemetry of ONE engine batch: occupancy plus the step events the
    backend emitted while it was the installed collector.

    The engine's phase events (prefill / decode segments / spec steps) are
    host timestamps around already-dispatched device calls — recording them
    adds no device synchronization the hot path wasn't already paying
    (`backend/engine.py` fetches `done` masks per segment regardless).
    ``first_token_at`` is the host-observed end of the prefill phase, the
    anchor `serve/scheduler.py` derives per-request TTFT from.
    """

    __slots__ = ("batch_id", "t0", "t1", "occupancy", "events",
                 "first_token_at", "gen_tokens")

    def __init__(self, batch_id: int, occupancy: int) -> None:
        self.batch_id = batch_id
        self.t0 = time.monotonic()
        self.t1: float | None = None
        self.occupancy = occupancy
        self.events: list[Span] = []
        self.first_token_at: float | None = None
        self.gen_tokens = 0

    def event(self, name: str, t0: float, dur: float, **args) -> None:
        # single-threaded by the serving contract (one scheduler thread owns
        # the engine), so no lock — list.append is atomic enough for the
        # read-after-generate consumer either way
        self.events.append(Span(name, t0, dur, 0, args or None))
        # TTFT anchor: only a SYNC-BOUNDED prefill end qualifies. Backends
        # whose prefill call returns at async dispatch mark the event
        # synced=False (TpuBackend without instrument=True) — anchoring on
        # that would record near-zero prefill and poison the TTFT quantiles
        # with queue-wait-only values. Absent flag = synchronous backend
        # (FakeBackend's sleep, instrumented engine fetches).
        if (
            self.first_token_at is None
            and name in ("prefill", "spec_prefill")
            and args.get("synced", True)
        ):
            self.first_token_at = t0 + dur

    def close(self, gen_tokens: int = 0) -> None:
        self.t1 = time.monotonic()
        self.gen_tokens = gen_tokens


# -- engine-side collector propagation ---------------------------------------

_collector: contextvars.ContextVar[BatchTrace | None] = contextvars.ContextVar(
    "vnsum_obs_collector", default=None
)


def set_collector(c: BatchTrace | None):
    """Install ``c`` as the current emit() target; returns a token for
    :func:`reset_collector`. The scheduler wraps each backend.generate call;
    pipeline/bench code may install a collector the same way."""
    return _collector.set(c)


def reset_collector(token) -> None:
    _collector.reset(token)


def current_collector() -> BatchTrace | None:
    return _collector.get()


def emit(name: str, t0: float, dur: float, **args) -> None:
    """Record an engine phase event onto the current collector, if any.

    THE hot-path guard: one contextvar read and a None check when tracing is
    off — no allocation, no lock, no timestamp math (callers only compute
    timestamps they already had or guard them behind :func:`current_collector`).
    """
    c = _collector.get()
    if c is not None:
        c.event(name, t0, dur, **args)


# -- hub: sampling + bounded retention ---------------------------------------


class ObsHub:
    """Owns sampling policy and the bounded rings of finished traces.

    One hub per serving process (`serve/server.py` builds it from
    ``--trace-sample`` / ``--trace-ring``). ``sample`` is the fraction of
    requests traced, applied with a deterministic error-diffusion accumulator
    (exactly ``sample`` of requests long-run, no RNG); batches are recorded
    whenever the hub exists — they are few and carry the engine telemetry.
    A hub is never constructed when tracing is disabled, so the disabled
    path's only cost is `is None` checks.
    """

    def __init__(self, sample: float = 1.0, ring: int = 256) -> None:
        self.sample = max(0.0, min(float(sample), 1.0))
        self.ring = max(int(ring), 1)
        # lock-order-sanitizer hook: plain threading.Lock in production
        self._lock = make_lock("obs.hub")
        # error-diffusion start point: the FIRST request is always sampled
        # (the next += sample crosses 1.0 immediately) and the long-run
        # traced fraction is exactly `sample`
        self._acc = 1.0 - self.sample           # guarded by: _lock
        self._requests: list[RequestTrace] = []  # guarded by: _lock
        self._batches: list[BatchTrace] = []    # guarded by: _lock
        self._batch_seq = 0                     # guarded by: _lock
        self.dropped_requests = 0               # guarded by: _lock

    # -- request side ----------------------------------------------------

    def start_request(self, trace_id: str,
                      parent: str | None = None) -> RequestTrace | None:
        """A RequestTrace when this request is sampled, else None.
        ``parent`` carries cross-process trace context (the router's
        X-Parent-Span header) onto the trace."""
        if self.sample <= 0.0:
            return None
        with self._lock:
            self._acc += self.sample
            if self._acc < 1.0:
                return None
            self._acc -= 1.0
        return RequestTrace(trace_id, parent=parent)

    def finish_request(self, trace: RequestTrace | None,
                       status: str = "ok") -> None:
        if trace is None:
            return
        trace.finish(status)
        with self._lock:
            self._requests.append(trace)
            if len(self._requests) > self.ring:
                del self._requests[0]
                self.dropped_requests += 1

    # -- batch side ------------------------------------------------------

    def start_batch(self, occupancy: int) -> BatchTrace:
        with self._lock:
            self._batch_seq += 1
            return BatchTrace(self._batch_seq, occupancy)

    def finish_batch(self, bt: BatchTrace, gen_tokens: int = 0) -> None:
        bt.close(gen_tokens)
        with self._lock:
            self._batches.append(bt)
            if len(self._batches) > self.ring:
                del self._batches[0]

    # -- export ----------------------------------------------------------

    def snapshot(self) -> tuple[list[RequestTrace], list[BatchTrace]]:
        with self._lock:
            return list(self._requests), list(self._batches)

    def chrome_trace(self) -> dict:
        from .export import chrome_trace

        reqs, batches = self.snapshot()
        return chrome_trace(reqs, batches)
