"""Structured jobs: gang-scheduled fan-out over the serving stack.

The paper's strategies are multi-request FAN-OUTS — a map-reduce summarize
splits one document into dozens of chunk prompts, the hierarchical strategy
into a whole tree of them — but the scheduler historically saw each prompt
as an unrelated request: admission was request-level only by convention
(check_admission at the entry point), the queue could split siblings across
batch generations, and the QoS layer preempted random gang members. This
module makes the group a first-class object:

- **Gang admission** — :meth:`MicroBatchScheduler.admit_gang` opens a
  :class:`GangHandle` after ONE pass through the existing request-level
  admission gate (depth / token budget / quota / brownout): the tenant is
  billed once for the whole fan-out, and every internal submit that rides
  the handle's gang id is admission-exempt (``force=True``), exactly the
  contract the summarize path always had — now typed and journaled.
- **Membership journal** — each fan-out round flushes ONE typed ``GANG``
  record listing the (child_rid, phase) pairs admitted since the last
  flush (serve/journal.py::gang), so restart replay reconstructs group
  membership instead of inferring it from ``trace_id#N`` prefixes, and the
  ``GET /v1/requests/<id>`` poll surface reports per-PHASE progress.
- **Affinity** — queue take paths cluster same-gang rows into one slot
  generation (queue.py::_compat_locked): siblings share the template-header
  prefix by construction, so co-scheduling them is the strategy-aware half
  of KV reuse (survey arXiv 2405.13019 §KV-cache reuse) — the radix cache
  can only skip a prefix that is WARM when the row prefills.
- **Group-aware QoS** — the in-flight preemption path evicts whole gangs
  (never strands a half-finished fan-out holding pins) and the preempt
  budget is effectively billed per gang: a whole-gang eviction increments
  every member's count together (serve/inflight.py::_maybe_preempt).
- **Degraded results** — a member failing typed POISON no longer silently
  fails just that child: the reduce proceeds over the survivors, the gang
  is journaled ``partial``, and the parent aggregate folds to a terminal
  ``partial`` state so clients can tell a degraded summary from a complete
  one (journal.py::aggregate_status).

Threading: one internal lock (``make_lock("serve.gang")``) guarding the
group table. It is held only around table mutations — journal and metrics
appends happen OUTSIDE it, so the lock-order graph gains exactly one edge
(callers -> serve.gang) and the journal lock stays innermost.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..analysis.sanitizers import make_lock
from ..core.logging import get_logger

logger = get_logger("vnsum.serve.gang")


@dataclass
class _Gang:
    """One live structured job's group state."""

    gang_id: str
    tenant: str = ""
    # every member rid this gang ever admitted -> its phase ("map" /
    # "reduce" / "outline" / "expand")
    members: dict = field(default_factory=dict)
    # (rid, phase) pairs admitted since the last journal flush
    unflushed: list = field(default_factory=list)
    # journal-less members (no rid to record) still count toward metrics
    member_count: int = 0
    partial: bool = False
    # whole-gang evictions suffered (metrics; the eviction BUDGET rides the
    # members' own preemption counters, which move in lockstep under
    # whole-gang eviction)
    preemptions: int = 0


class GangHandle:
    """The admitted-fan-out token an entry point holds for one structured
    job: carries the gang id its internal submits ride, and finishes the
    group when the request terminally resolves (whatever the outcome — the
    handle tracks liveness, the journal tracks truth)."""

    __slots__ = ("registry", "gang_id")

    def __init__(self, registry: "GangRegistry", gang_id: str) -> None:
        self.registry = registry
        self.gang_id = gang_id

    def finish(self) -> None:
        self.registry.finish(self.gang_id)

    def __enter__(self) -> "GangHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()


class GangRegistry:
    """Live structured-job groups, keyed by gang id (== the request's
    trace id, so gang-cancel and the ``#N`` ledger ids line up for free).

    Always constructed by the scheduler — gang bookkeeping is part of the
    serving contract, never optional; the bench A/B toggles only the
    queue's AFFINITY pick, not the subsystem."""

    def __init__(self, *, journal=None, metrics=None) -> None:
        # lock-order-sanitizer hook: table mutations only — journal/metrics
        # calls happen outside so serve.gang never nests another serve lock
        self._lock = make_lock("serve.gang")
        self._gangs: dict[str, _Gang] = {}  # guarded by: _lock
        self.journal = journal
        self.metrics = metrics

    # -- lifecycle --------------------------------------------------------

    def open(self, gang_id: str, tenant: str = "") -> GangHandle:
        """Register a newly admitted structured job. Idempotent per id (a
        client retrying a request id mid-flight rejoins the live group
        rather than forking a second one)."""
        created = False
        with self._lock:
            if gang_id not in self._gangs:
                self._gangs[gang_id] = _Gang(gang_id=gang_id, tenant=tenant)
                created = True
        if created and self.metrics is not None:
            self.metrics.observe_gang_admitted()
        return GangHandle(self, gang_id)

    def note_member(self, gang_id: str, rid: str | None, phase: str) -> None:
        """Record one fan-out child of ``gang_id`` (called by the scheduler
        right after the child's queue admission assigned its ledger id).
        ``rid`` is None when journaling is off — the member still counts
        toward the group's metrics, it just has no durable identity."""
        with self._lock:
            gang = self._gangs.get(gang_id)
            if gang is None:
                return
            gang.member_count += 1
            if rid is not None and rid not in gang.members:
                gang.members[rid] = phase
                gang.unflushed.append((rid, phase))
        if self.metrics is not None:
            self.metrics.observe_gang_members(1)

    def flush(self, gang_id: str) -> int:
        """Journal the members admitted since the last flush as ONE typed
        GANG record — called once per fan-out ROUND (after its submits),
        so a 40-chunk map round costs one append, and the record lands
        after its members' ACCEPTs (replay reads membership of requests it
        knows). Returns the number of members flushed."""
        with self._lock:
            gang = self._gangs.get(gang_id)
            if gang is None or not gang.unflushed:
                return 0
            batch, gang.unflushed = gang.unflushed, []
        if self.journal is not None:
            self.journal.gang(gang_id, batch)
        return len(batch)

    def mark_partial(self, gang_id: str, reason: str = "poison") -> None:
        """A member failed typed POISON and the reduce proceeds without its
        output: journal the degradation so the parent aggregate (and a
        restarted server's poll surface) reports ``partial``, not
        ``completed``. Idempotent per gang."""
        with self._lock:
            gang = self._gangs.get(gang_id)
            if gang is None or gang.partial:
                first = False
            else:
                gang.partial = True
                first = True
        if not first:
            return
        logger.warning(
            "gang %s degraded: poison member dropped from the reduce",
            gang_id,
        )
        if self.journal is not None:
            self.journal.gang_partial(gang_id, reason)
        if self.metrics is not None:
            self.metrics.observe_gang_partial()

    def note_preemption(self, gang_id: str) -> None:
        """One whole-gang slot eviction (metrics only — the budget rides
        the members' own preemption counters)."""
        with self._lock:
            gang = self._gangs.get(gang_id)
            if gang is not None:
                gang.preemptions += 1
        if self.metrics is not None:
            self.metrics.observe_gang_preemption()

    def finish(self, gang_id: str) -> None:
        """The structured job terminally resolved (completed, failed,
        cancelled — the journal holds which): drop the live group. Any
        still-unflushed members are flushed first so the ledger never
        loses membership to a fast finish. Idempotent."""
        self.flush(gang_id)
        with self._lock:
            self._gangs.pop(gang_id, None)

    # -- replay / introspection -------------------------------------------

    def restore(self, gangs: dict[str, dict]) -> int:
        """Rebuild live groups from the journal's unfinished-gang view at
        startup replay (journal.py::gangs_unfinished) so replayed members
        rejoin their groups: membership is pre-seeded as FLUSHED (the
        journal already holds it) and partiality survives."""
        n = 0
        with self._lock:
            for gid, meta in gangs.items():
                if gid in self._gangs:
                    continue
                self._gangs[gid] = _Gang(
                    gang_id=gid,
                    members=dict(meta.get("members", {})),
                    member_count=len(meta.get("members", {})),
                    partial=bool(meta.get("partial")),
                )
                n += 1
        return n

    def lookup(self, gang_id: str) -> dict | None:
        """{"members": {rid: phase}, "partial": bool} for a LIVE gang, or
        None (terminal gangs answer from the journal's gang_info)."""
        with self._lock:
            gang = self._gangs.get(gang_id)
            if gang is None:
                return None
            return {"members": dict(gang.members),
                    "partial": gang.partial}

    def active(self) -> int:
        with self._lock:
            return len(self._gangs)

    def stats(self) -> dict:
        """Scrape-time gauge block for /metrics (vnsum_serve_gang_*)."""
        return {"active": self.active()}
