"""Bounded request queue with SLO-aware admission control.

Admission is decided at submit time against two budgets — queue depth and
total queued prompt tokens — and rejection is a TYPED result (RequestShed
with a ShedReason), not a dropped connection: the HTTP layer maps it to a
429-style response, the QueuedBackend adapter re-raises it into the calling
strategy, and the metrics layer counts it per reason. Requests carry an
absolute monotonic deadline; expired requests are shed at dispatch time so a
backed-up queue never spends engine capacity on answers nobody is waiting
for (BASS, arXiv:2404.15778 frames both as the load-shedding half of
continuous batching).

The queue itself is deliberately dumb: ordering is FIFO, and all batching
policy (compatibility keys, max-wait/max-batch) lives in take_batch's
caller-supplied parameters so the scheduler owns the policy and the queue
owns the synchronization.
"""
from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from enum import Enum

from ..analysis.sanitizers import make_lock
from ..core.config import GenerationConfig


class ShedReason(str, Enum):
    QUEUE_FULL = "queue_full"
    TOKEN_BUDGET = "token_budget"
    DEADLINE = "deadline"
    SHUTDOWN = "shutdown"
    # per-tenant token-rate quota (serve/qos.py): the tenant's bucket is
    # dry — 429 with a refill-derived Retry-After
    QUOTA = "quota"
    # graceful-degradation ladder bottom rung (serve/supervisor.py): the
    # supervisor browned the server out after repeated resource-class
    # failures — mapped to HTTP 503 + Retry-After, not 429
    BROWNOUT = "brownout"


class RequestShed(RuntimeError):
    """Typed 429/503-style rejection: admission control, deadline shedding,
    or supervisor brownout.

    Raised synchronously by submit() (admission) or delivered through the
    request future (deadline/shutdown shedding after the request was
    admitted). ``retry_after_s`` is the client backoff hint for brownout
    sheds (the HTTP layer renders it as a Retry-After header)."""

    def __init__(self, reason: ShedReason, detail: str = "",
                 retry_after_s: float | None = None) -> None:
        self.reason = reason
        self.retry_after_s = retry_after_s
        super().__init__(
            f"request shed ({reason.value})" + (f": {detail}" if detail else "")
        )


class RequestCancelled(RuntimeError):
    """Typed terminal cancellation: the client asked for it
    (``DELETE /v1/requests/<id>``) or stopped listening (stream disconnect
    past the resume window / idle-consumer timeout). Delivered through the
    request future; the HTTP layer maps it to a 409 and the streaming
    layer to a typed terminal ``error`` event. ``stage`` names where in
    the lifecycle the cancel landed (queued / dispatched / resident) and
    ``reason`` why (api / disconnect)."""

    def __init__(self, stage: str = "", reason: str = "api") -> None:
        self.stage = stage
        self.reason = reason
        super().__init__(
            f"request cancelled ({reason})"
            + (f" while {stage}" if stage else "")
        )


_ids = itertools.count()


@dataclass
class ServeRequest:
    """One prompt awaiting a shared engine batch."""

    prompt: str
    max_new_tokens: int | None = None
    config: GenerationConfig | None = None
    # source text for reference-guided speculative decoding (vnsum_tpu.spec);
    # per-ROW metadata, so it never enters batch_key — requests with
    # different references still coalesce
    reference: str | None = None
    # prefix-cache hint (vnsum_tpu.cache): the prompt prefix the caller
    # expects to recur. Per-ROW metadata like reference — never part of
    # batch_key — but take_batch uses it to CLUSTER compatible requests so
    # shared-prefix rows land in the same engine batch (the engine's usable
    # skip is bounded by the batch's coldest row)
    cache_hint: str | None = None
    # tokens of this prompt the backend's prefix cache already holds
    # (cached_prefix_tokens probe at submit); admission control bills only
    # the difference — a cached 10k-token header shouldn't crowd out work
    # the engine will never actually prefill
    cached_tokens: int = 0
    # absolute time.monotonic() deadline; None = no SLO
    deadline: float | None = None
    est_tokens: int = 0
    request_id: int = field(default_factory=lambda: next(_ids))
    # end-to-end correlation id (vnsum_tpu.obs): defaults to a queue-derived
    # id in __post_init__; the HTTP layer overrides it with the client's
    # X-Request-Id so one id links response header, logs, and /debug/trace.
    # Fanned-out prompts of one request share a trace_id but keep their own
    # request_id — per-ROW metadata, never part of batch_key
    trace_id: str = ""
    # the shared RequestTrace this row's spans land on (None = untraced) and
    # this row's sub-track within it; set by the scheduler at submit
    trace: object | None = field(default=None, repr=False, compare=False)
    trace_track: int = 0
    # scheduler-owned trace lifecycle: True when the scheduler created the
    # trace at submit (no HTTP layer to finalize it) and must finish it on
    # completion
    own_trace: bool = False
    # supervised-retry bookkeeping (serve/supervisor.py): how many FAILED
    # engine dispatches this request has been part of; the supervisor's
    # per-request retry budget caps it
    attempts: int = 0
    # durable-serving id (serve/journal.py): assigned by the journal's
    # ACCEPT record at admission (trace_id, or trace_id#N for fan-out
    # siblings); preset by startup replay so a re-enqueued request keeps
    # its ledger identity instead of journaling a second ACCEPT. None =
    # journaling off, or shed before admission (never accepted)
    journal_rid: str | None = None
    # multi-tenant QoS (serve/qos.py): the declared tenant this request
    # bills against ("" = no tenant table / default) and its priority tier
    # — per-ROW metadata, never part of batch_key. tier "batch" marks the
    # request evictable: the in-flight scheduler may preempt its slot for
    # interactive work and requeue it through the journal's replayable
    # ACCEPT state
    tenant: str = ""
    tier: str = "interactive"
    # structured jobs (serve/gang.py): the gang this row belongs to ("" =
    # ungrouped). Fan-out siblings of one summarize/skeleton request share
    # it; the queue's take paths cluster same-gang rows into one slot
    # generation (so they share the template-header prefix in the radix
    # cache) and the in-flight preemption path evicts whole gangs. Per-ROW
    # metadata, never part of batch_key
    gang_id: str = ""
    # which phase of the structured job this row serves ("map" / "reduce" /
    # "outline" / "expand" / "" for ungrouped) — journal + /v1/requests
    # per-phase progress metadata only, never scheduling policy
    gang_phase: str = ""
    # streaming (serve/stream.py): the per-request emit channel the
    # scheduler pushes decode-progress text into (None = non-streaming).
    # Never compared/printed — it carries a live Queue
    stream: object | None = field(default=None, repr=False, compare=False)
    # True once the journal's STREAMING lifecycle event was appended (the
    # first delta emits it; scheduler-thread-only state)
    stream_journaled: bool = False
    # preemption bookkeeping (serve/inflight.py): how many times this
    # request was evicted mid-decode, and the prefix-cache pins taken at
    # eviction so its cached blocks survive LRU until it terminally
    # resolves — released by the scheduler's resolution paths
    preemptions: int = 0
    preempt_pins: list = field(default_factory=list, repr=False,
                               compare=False)
    enqueued_at: float = field(default_factory=time.monotonic)
    future: Future = field(default_factory=Future)

    def __post_init__(self) -> None:
        if not self.trace_id:
            self.trace_id = f"req-{self.request_id}"

    @property
    def billable_tokens(self) -> int:
        """Prompt tokens the engine will actually prefill — what the
        admission token budget counts."""
        return max(self.est_tokens - self.cached_tokens, 0)

    def batch_key(self) -> tuple:
        """Requests sharing this key can ride one engine batch: the engine
        applies max_new_tokens and the GenerationConfig per CALL, not per
        row, so only same-parameter requests may coalesce. GenerationConfig
        is frozen/hashable by construction."""
        return (self.max_new_tokens, self.config)

    def expired(self, now: float | None = None) -> bool:
        if self.deadline is None:
            return False
        return (time.monotonic() if now is None else now) >= self.deadline


class RequestQueue:
    """FIFO queue with depth + token-budget admission and batch take-out.

    ``max_depth`` bounds queued requests; ``max_queued_tokens`` (0 =
    unlimited) bounds the sum of queued prompt-token estimates so a few
    book-length prompts can't squeeze out hundreds of short ones while
    nominally fitting the depth budget. The estimate is each request's
    BILLABLE tokens — prompt tokens minus its prefix-cache coverage — so
    cached template headers don't consume admission budget the engine will
    never spend prefilling."""

    def __init__(self, max_depth: int = 256, max_queued_tokens: int = 0,
                 tenants=None) -> None:
        self.max_depth = max_depth
        self.max_queued_tokens = max_queued_tokens
        # multi-tenant QoS (serve/qos.py): a TenantTable arms per-tenant
        # token-rate quotas in the admission predicate and routes the take
        # paths' candidate sets through its deficit-round-robin pick. None
        # (and single-tenant candidate sets) = the pre-QoS FIFO, byte for
        # byte
        self.tenants = tenants
        # _cond wraps _lock (one underlying mutex, two names); the
        # guarded-by annotations list both so either entry form satisfies
        # the lint. make_lock = lock-order-sanitizer hook (analysis pkg):
        # a plain threading.Lock unless VNSUM_SANITIZERS enables tracking
        self._lock = make_lock("serve.queue")
        self._cond = threading.Condition(self._lock)
        self._items: list[ServeRequest] = []    # guarded by: _cond, _lock
        self._queued_tokens = 0                 # guarded by: _cond, _lock
        self._closed = False                    # guarded by: _cond, _lock
        self.on_shed = None  # callable(req, ShedReason) | None — metrics hook
        # called under the queue lock BEFORE the scheduler can take the
        # request: counting the admit here means no scrape window where a
        # request is completed but not yet counted as submitted
        self.on_admit = None  # callable(req) | None — metrics hook
        # called under the queue lock with each taken batch (the commit
        # point) — the gang-affinity observability hook (serve/gang.py):
        # the scheduler counts multi-row takes that landed one gang
        # together. Must be cheap and lock-free like on_admit
        self.on_take = None  # callable(list[req]) | None — metrics hook
        # gang-affinity pick (serve/gang.py): when an over-full take must
        # choose, cluster the head's gang first so fan-out siblings ride
        # one slot generation and share their template-header prefix in
        # the radix cache. False = the pre-gang cache-hint clustering only
        # (the bench A/B's off arm)
        self.gang_affinity = True
        # supervisor brownout gate (serve/supervisor.py::admission_gate):
        # callable() -> Retry-After seconds when the degradation ladder is
        # shedding new work, None when admitting. Consulted for EXTERNAL
        # submissions only — internal fan-out of already-admitted requests
        # (force=True) must finish even under brownout
        self.degraded = None
        # watchdog liveness stamp (serve/watchdog.py): the scheduler wires
        # its Heartbeat.beat here so the take loops tick it on every
        # wake-up — an IDLE scheduler parked in a bounded cond-wait still
        # proves liveness. One attribute write per wake-up, called under
        # the queue lock (beat takes no lock of its own). None = unmonitored
        self.heartbeat = None

    # -- producer side ---------------------------------------------------

    def submit(self, req: ServeRequest, *, force: bool = False) -> Future:
        """Admit or shed. Sheds raise RequestShed SYNCHRONOUSLY (the caller
        never gets a future that was doomed at admission).

        ``force=True`` skips the depth/token-budget checks (not the
        shutdown/deadline ones): it is for the INTERNAL fan-out of work
        that was already admitted at the request level — e.g. a summarize
        request whose map round splits into more prompts than max_depth
        must not shed itself against an idle server. External entry points
        must never set it."""
        with self._cond:
            if self._closed:
                self._shed_locked(req, ShedReason.SHUTDOWN)
            if req.expired():
                # Retry-After 1: the client's own deadline passed — "retry
                # now with a fresh deadline", not a server back-off
                self._shed_locked(req, ShedReason.DEADLINE, retry_after_s=1.0)
            if not force:
                shed = self._admission_reason_locked(
                    req.billable_tokens, req.tenant
                )
                if shed is not None:
                    self._shed_locked(req, shed[0], retry_after_s=shed[1])
            self._items.append(req)
            self._queued_tokens += req.billable_tokens
            if self.on_admit is not None:
                self.on_admit(req)
            self._cond.notify_all()
        return req.future

    def _admission_reason_locked(
        self, est_tokens: int, tenant: str = ""
    ) -> tuple[ShedReason, float | None] | None:
        """The ONE depth/token-budget/quota/brownout admission predicate —
        submit() and check_admission() must never diverge on policy.
        Returns (reason, retry_after_s) or None. The degraded gate is
        evaluated exactly ONCE per decision: it doubles as the supervisor's
        recovery probe, so a second call could observe a different (healed)
        ladder and desynchronize the shed from its Retry-After hint.

        Every 429-class reason carries a derived Retry-After: queue_full
        and token_budget scale with backlog (a deeper queue needs a longer
        back-off than a barely-full one), quota is the tenant bucket's
        exact refill time. The quota bucket is consulted LAST so a request
        that would shed on depth/budget anyway never burns quota tokens."""
        if self.degraded is not None:
            retry_after = self.degraded()
            if retry_after is not None:
                return ShedReason.BROWNOUT, retry_after
        if len(self._items) >= self.max_depth:
            return ShedReason.QUEUE_FULL, self._backlog_retry_after_locked()
        if (
            self.max_queued_tokens
            and self._items  # an empty queue always admits one request
            and self._queued_tokens + est_tokens > self.max_queued_tokens
        ):
            return ShedReason.TOKEN_BUDGET, self._backlog_retry_after_locked()
        if self.tenants is not None:
            retry_after = self.tenants.admit(tenant, est_tokens)
            if retry_after is not None:
                return ShedReason.QUOTA, retry_after
        return None

    def _backlog_retry_after_locked(self) -> float:
        """Retry-After for backlog sheds (queue_full / token_budget): the
        queue has no view of engine speed, so the hint scales with depth —
        ~50ms of assumed drain per queued request, clamped to [1, 30]s.
        Deliberately coarse: the point is a depth-proportional back-off
        signal, not a latency forecast."""
        return min(30.0, max(1.0, 0.05 * len(self._items)))

    def check_admission(self, est_tokens: int = 0, tenant: str = "") -> None:
        """Request-level admission probe without enqueueing: raises the same
        typed RequestShed a submit would. Entry points whose work fans out
        through force-submits (the summarize path) call this ONCE up front
        so admission control — including the tenant quota bill for the
        whole request — still applies per request."""
        with self._lock:
            if self._closed:
                raise RequestShed(ShedReason.SHUTDOWN)
            shed = self._admission_reason_locked(est_tokens, tenant)
            if shed is not None:
                raise RequestShed(shed[0], retry_after_s=shed[1])

    def _shed_locked(self, req: ServeRequest, reason: ShedReason,
                     retry_after_s: float | None = None):
        if self.on_shed is not None:
            self.on_shed(req, reason)
        exc = RequestShed(reason, retry_after_s=retry_after_s)
        # resolve the future too, for callers holding it (take-side sheds)
        if not req.future.done():
            req.future.set_exception(exc)
        raise exc

    # -- consumer side ---------------------------------------------------

    def _shed_expired_locked(self, now: float) -> None:
        live = []
        for r in self._items:
            if r.expired(now):
                self._queued_tokens -= r.billable_tokens
                if self.on_shed is not None:
                    self.on_shed(r, ShedReason.DEADLINE)
                if not r.future.done():
                    r.future.set_exception(
                        RequestShed(ShedReason.DEADLINE, retry_after_s=1.0)
                    )
            else:
                live.append(r)
        self._items = live

    def _compat_locked(self, key: tuple, max_take: int) -> list[ServeRequest]:
        """Requests sharing ``key`` — with prefix-cache clustering
        (vnsum_tpu.cache) when more compatible requests wait than one take
        holds: fill with the head's cache_hint group first, because the
        engine's usable prefill skip is bounded by the batch's coldest row,
        so mixing hint groups wastes everyone's cached prefix. FIFO order
        is preserved within each part, and nothing reorders when the take
        drains everyone anyway. The ONE compatibility/clustering policy for
        take_batch and take_upto — the two paths must never diverge.
        (The multi-tenant WFQ pick lives in ``_take_locked``, not here:
        this method also runs speculatively from the wait loops, and the
        deficit-round-robin state must only be charged for requests that
        are actually taken.)

        Gang affinity (serve/gang.py) outranks cache-hint clustering when
        the head row belongs to a gang: siblings of one structured job
        share the SAME template-header hint by construction, so keeping
        the gang together is the strictly stronger form of the same
        cache argument — and it additionally keeps the whole fan-out in
        one slot generation for group-aware preemption. Ungrouped heads
        fall through to the pre-gang behavior byte for byte."""
        compat = [r for r in self._items if r.batch_key() == key]
        if len(compat) <= max_take:
            return compat
        if self.gang_affinity and compat[0].gang_id:
            gang = compat[0].gang_id
            compat = (
                [r for r in compat if r.gang_id == gang]
                + [r for r in compat if r.gang_id != gang]
            )
        elif any(r.cache_hint for r in compat):
            hint = compat[0].cache_hint
            compat = (
                [r for r in compat if r.cache_hint == hint]
                + [r for r in compat if r.cache_hint != hint]
            )
        return compat

    def _take_locked(self, compat: list[ServeRequest],
                     max_take: int) -> list[ServeRequest]:
        """Remove up to ``max_take`` of ``compat`` from the queue and
        release their token bill — the ONE removal/billing block shared by
        both take paths.

        Multi-tenant QoS (serve/qos.py): when a tenant table is configured
        AND the compatible set spans more than one (tenant, tier), the
        deficit-round-robin pick replaces the FIFO prefix — interactive
        tier before batch, token-weighted fair share within a tier, FIFO
        within a tenant. The pick runs HERE (the commit point) so DRR
        deficits are charged exactly once per request actually taken. A
        single-tenant set falls through to the byte-identical pre-QoS
        FIFO/clustering order (the contract tests/test_serve_qos.py pins)."""
        if (
            self.tenants is not None
            and len(compat) > 1
            and self.tenants.multi_tenant(compat)
        ):
            batch = self.tenants.select(compat, max_take)
        else:
            batch = compat[:max_take]
        taken = set(id(r) for r in batch)
        self._items = [r for r in self._items if id(r) not in taken]
        for r in batch:
            self._queued_tokens -= r.billable_tokens
        if self.on_take is not None and batch:
            self.on_take(batch)
        return batch

    def take_batch(self, max_batch: int, max_wait_s: float) -> list[ServeRequest] | None:
        """Block until a batch is ready, then return up to ``max_batch``
        requests sharing the head-of-line request's batch_key. A batch is
        ready when it is full, when the coalescing window ``max_wait_s`` has
        elapsed, or when the queue is closed (drain). Returns None when
        closed and empty — the scheduler's exit signal. Expired requests are
        shed on every wake-up.

        The window anchors on max(head arrival, THIS CALL's entry): under
        light load that is head arrival (a lone request waits at most
        max_wait_s), but after a long engine dispatch the backlog's head is
        already older than any window — anchoring on entry keeps a brief
        coalescing window open so requests unblocked by the *previous*
        batch's responses can join this one instead of fragmenting into
        near-empty dispatches (measured 4.65 -> ~15 occupancy at 16
        closed-loop clients, scripts/bench_serving.py)."""
        t_enter = time.monotonic()
        with self._cond:
            while True:
                if self.heartbeat is not None:
                    self.heartbeat()
                now = time.monotonic()
                self._shed_expired_locked(now)
                if not self._items:
                    if self._closed:
                        return None
                    self._cond.wait(timeout=0.1)
                    continue
                head = self._items[0]
                compat = self._compat_locked(head.batch_key(), max_batch)
                flush_at = max(head.enqueued_at, t_enter) + max_wait_s
                if len(compat) >= max_batch or now >= flush_at or self._closed:
                    return self._take_locked(compat, max_batch)
                self._cond.wait(timeout=max(flush_at - now, 0.001))

    def take_upto(
        self, max_take: int, key: tuple | None = None, wait_s: float = 0.0
    ) -> list[ServeRequest] | None:
        """Slot-feeding take for the in-flight scheduler: up to ``max_take``
        requests compatible with ``key`` (None = the head-of-line request's
        batch_key), FIFO within the key with the same cache-hint clustering
        as take_batch. Admission is billed per slot: each request's billable
        tokens leave the queue budget when its slot is taken, not when a
        whole batch flushes.

        Unlike take_batch there is no coalescing window — the decode
        segment cadence provides natural coalescing — but a positive
        ``wait_s`` blocks up to that long for the FIRST compatible request
        (the idle-loop case). Returns [] when nothing compatible arrived in
        time, and None when the queue is closed and drained (the caller's
        exit signal). Expired requests are shed on every wake-up."""
        if max_take < 1:
            return []
        t_end = time.monotonic() + wait_s
        with self._cond:
            while True:
                if self.heartbeat is not None:
                    self.heartbeat()
                now = time.monotonic()
                self._shed_expired_locked(now)
                if self._items:
                    k = key if key is not None else self._items[0].batch_key()
                    compat = self._compat_locked(k, max_take)
                    if compat:
                        return self._take_locked(compat, max_take)
                elif self._closed:
                    return None
                if now >= t_end:
                    return []
                self._cond.wait(timeout=max(t_end - now, 0.001))

    # -- lifecycle / introspection ---------------------------------------

    def close(self, drain: bool = True) -> None:
        """Stop admitting. drain=True leaves queued requests for the
        scheduler to finish; drain=False sheds them immediately."""
        with self._cond:
            self._closed = True
            if not drain:
                self._shed_pending_locked()
            self._cond.notify_all()

    def _shed_pending_locked(self) -> int:
        n = len(self._items)
        for r in self._items:
            self._queued_tokens -= r.billable_tokens
            if self.on_shed is not None:
                self.on_shed(r, ShedReason.SHUTDOWN)
            if not r.future.done():
                r.future.set_exception(RequestShed(ShedReason.SHUTDOWN))
        self._items = []
        return n

    def shed_pending(self) -> int:
        """Fail every still-queued request with a typed SHUTDOWN shed —
        the scheduler's drain-timeout escape hatch: when the engine thread
        overruns its drain window, nothing may be left hanging on a future
        nobody will ever resolve. Returns the number shed."""
        with self._cond:
            n = self._shed_pending_locked()
            self._cond.notify_all()
            return n

    def cancel_where(self, pred) -> list[ServeRequest]:
        """Remove every queued request matching ``pred`` and release its
        token bill — the queue half of request cancellation. Deliberately
        resolution-free: the SCHEDULER owns the terminal bookkeeping
        (journal CANCELLED, metrics, tenant-bucket refund, the future), so
        this only mutates queue state, symmetric with the take paths.
        ``pred`` runs under the queue lock — it must be cheap and must not
        take other serve locks except leaves (the stream idle probe)."""
        with self._cond:
            out = [r for r in self._items if pred(r)]
            if not out:
                return []
            gone = set(id(r) for r in out)
            self._items = [r for r in self._items if id(r) not in gone]
            for r in out:
                self._queued_tokens -= r.billable_tokens
            self._cond.notify_all()
            return out

    def requeue(self, req: ServeRequest) -> None:
        """Re-admit a PREEMPTED request (serve/inflight.py): no admission
        checks, no on_admit hook — it was already admitted, journaled, and
        counted in its first life, and its future is still the one the
        caller holds. Its token bill re-enters the queue budget (the slots
        it vacated stopped billing at take). Appended even after close():
        a drain must finish preempted work, not strand it; the drain's
        take paths serve everything still queued before exiting."""
        with self._cond:
            self._items.append(req)
            self._queued_tokens += req.billable_tokens
            self._cond.notify_all()

    def waiting_interactive(self, key: tuple) -> int:
        """Queued interactive-tier requests compatible with ``key`` — the
        in-flight scheduler's preemption-demand probe: how many waiting
        requests could ride the resident loop right now if batch-tier
        residents were evicted."""
        with self._lock:
            return sum(
                1 for r in self._items
                if r.tier != "batch" and r.batch_key() == key
            )

    def head_info(self) -> tuple[tuple, float, str] | None:
        """(batch_key, enqueued_at, tier) of the head-of-line request —
        the ONE head-of-line probe: the in-flight scheduler's fairness
        rule (a head whose key can't ride the resident loop eventually
        forces a drain) and its preemption rule (an incompatible
        INTERACTIVE head past grace evicts batch residents) both read it."""
        with self._lock:
            if not self._items:
                return None
            head = self._items[0]
            return head.batch_key(), head.enqueued_at, head.tier

    def head_snapshot(self) -> tuple[tuple, float] | None:
        """(batch_key, enqueued_at) of the head-of-line request, or None —
        head_info without the tier, kept for callers that predate QoS."""
        info = self.head_info()
        return None if info is None else info[:2]

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def queued_tokens(self) -> int:
        with self._lock:
            return self._queued_tokens
