"""Multi-tenant QoS: tenant declarations, token-rate quotas, and the
weighted-fair (deficit-round-robin) pick the request queue schedules with.

The serving stack used to treat every caller identically: one tenant's
151-doc map-reduce fan-out sits in front of every interactive user behind
it, and FIFO order is the starvation. This module is the policy layer that
fixes it (survey arXiv:2405.13019 names iteration-level scheduling with
request priorities as the serving-side lever batching alone cannot
provide):

- :func:`parse_tenant_specs` turns ``--tenants name:weight:token_rate[:tier]``
  strings into :class:`TenantSpec`\\ s (weight > 0 enforced — a zero-weight
  tenant is a misconfiguration, not a muted one);
- :class:`TokenBucket` is the per-tenant rate quota: ``token_rate`` tokens/s
  refill with a bounded burst, and a failed take returns the EXACT
  refill-derived Retry-After seconds the HTTP layer renders;
- :class:`TenantTable` owns the live scheduling state: quota admission
  (:meth:`TenantTable.admit`) consulted by the queue's one admission
  predicate, and the deficit-round-robin pick (:meth:`TenantTable.select`)
  the queue's ``take_batch``/``take_upto`` route their candidate sets
  through. Interactive-tier requests are always picked before batch-tier
  ones (the priority half of QoS — preemption in serve/inflight.py is the
  enforcement half); within a tier, tenants share in proportion to their
  weights over token-costed deficits (DRR, Shreedhar & Varghese '95), and
  within a tenant order stays FIFO.

Fall-through contract (pinned by tests/test_serve_qos.py): with no table —
or with every candidate in one tenant — the queue's behavior is byte-
identical to the pre-QoS FIFO (including the cache-hint clustering), so
single-tenant deployments pay nothing for the feature.

Threading: the table has one internal lock (``make_lock("serve.tenants")``).
The queue lock is always held while consulting it (admission + pick), so
the tenants lock is innermost, next to the journal lock in the lock-order
sanitizer's graph; it never acquires any other serve lock while held.
"""
from __future__ import annotations

import re
import time
from dataclasses import dataclass

from ..analysis.sanitizers import make_lock

# tenant names land verbatim in Prometheus label values — keep them to a
# charset that can never corrupt the exposition format
_NAME_RE = re.compile(r"[A-Za-z0-9_.-]+")


def _label_safe(name: str) -> str:
    """Declared tenants are charset-validated at parse time; names arriving
    on REQUESTS (library callers, replayed journals) are sanitized instead
    of raised on — the scheduling path must serve, never throw."""
    if name and _NAME_RE.fullmatch(name):
        return name
    cleaned = re.sub(r"[^A-Za-z0-9_.-]", "_", name or "")
    return cleaned or DEFAULT_TENANT

# priority tiers: interactive work is picked first and may preempt batch
# work resident in the in-flight loop (serve/inflight.py)
TIER_INTERACTIVE = "interactive"
TIER_BATCH = "batch"
TIERS = (TIER_INTERACTIVE, TIER_BATCH)

# the tenant traffic lands on when no X-Tenant header is sent (auto-added
# to every table unless the operator declares their own "default")
DEFAULT_TENANT = "default"


class UnknownTenant(ValueError):
    """An X-Tenant header naming a tenant the table doesn't declare — the
    HTTP layer maps it to a typed 400, never a silent default."""


@dataclass(frozen=True)
class TenantSpec:
    """One declared tenant: scheduling weight, token-rate quota, tier.

    ``token_rate`` is billable prompt tokens per second (0 = unlimited);
    ``burst`` is the bucket capacity — how many tokens a quiet tenant may
    spend at once before the rate applies (defaults to two seconds of
    refill, floored at one request's worth)."""

    name: str
    weight: float = 1.0
    token_rate: float = 0.0
    tier: str = TIER_INTERACTIVE
    burst: float = 0.0

    def __post_init__(self) -> None:
        # label-safe charset: tenant names become Prometheus label values
        # verbatim (vnsum_serve_qos_*{tenant="..."}), so quotes/backslashes/
        # whitespace would corrupt the whole /metrics exposition
        if not self.name or not _NAME_RE.fullmatch(self.name):
            raise ValueError(
                f"bad tenant name {self.name!r} (want [A-Za-z0-9_.-]+)"
            )
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name!r}: weight must be > 0 (got {self.weight})"
            )
        if self.token_rate < 0:
            raise ValueError(f"tenant {self.name!r}: token_rate must be >= 0")
        if self.tier not in TIERS:
            raise ValueError(
                f"tenant {self.name!r}: tier must be one of {TIERS}"
            )
        if self.burst <= 0:
            # frozen dataclass: derive the default through __setattr__
            object.__setattr__(
                self, "burst", max(self.token_rate * 2.0, 1.0)
            )


def parse_tenant_specs(spec: str) -> dict[str, TenantSpec]:
    """``name:weight:token_rate[:tier]`` entries, comma-separated, into a
    spec map — the ``--tenants`` CLI surface. Raises ValueError on
    duplicates, zero/negative weights, or unknown tiers."""
    out: dict[str, TenantSpec] = {}
    for part in [p.strip() for p in spec.split(",") if p.strip()]:
        fields = part.split(":")
        if len(fields) not in (3, 4):
            raise ValueError(
                f"tenant spec {part!r}: want name:weight:token_rate[:tier]"
            )
        name = fields[0].strip()
        if name in out:
            raise ValueError(f"duplicate tenant {name!r}")
        out[name] = TenantSpec(
            name=name,
            weight=float(fields[1]),
            token_rate=float(fields[2]),
            tier=fields[3].strip() if len(fields) == 4 else TIER_INTERACTIVE,
        )
    if not out:
        raise ValueError("empty --tenants spec")
    return out


class TokenBucket:
    """Classic leaky token bucket: ``rate`` tokens/s refill up to ``burst``
    capacity. ``take`` either consumes and returns None, or refuses and
    returns the refill-derived seconds until the request WOULD fit — the
    Retry-After the typed QUOTA shed carries. Not self-locking: the owning
    TenantTable serializes access."""

    def __init__(self, rate: float, burst: float) -> None:
        self.rate = float(rate)
        self.burst = float(burst)
        self.level = float(burst)
        self._stamp = time.monotonic()

    def _refill(self, now: float) -> None:
        # clamp negative elapsed: tests drive synthetic clocks, and a
        # backwards step must never drain the bucket
        self.level = min(
            self.burst,
            self.level + max(now - self._stamp, 0.0) * self.rate,
        )
        self._stamp = now

    def take(self, tokens: float, now: float | None = None) -> float | None:
        if self.rate <= 0:
            return None  # unlimited tenant
        now = time.monotonic() if now is None else now
        self._refill(now)
        # a request larger than the whole burst can never fit; bill it the
        # full burst instead of refusing forever (it drains the bucket and
        # pays the rate like everyone else)
        need = min(float(tokens), self.burst)
        if need <= self.level:
            self.level -= need
            return None
        return (need - self.level) / self.rate


@dataclass
class _TenantState:
    spec: TenantSpec
    bucket: TokenBucket | None
    deficit: float = 0.0


class TenantTable:
    """Live multi-tenant scheduling state: specs + buckets + DRR deficits.

    ``quantum_tokens`` is the deficit added per DRR visit before weighting;
    larger quanta batch a tenant's turns coarser, smaller ones interleave
    finer — proportionality over long runs is the same either way."""

    def __init__(self, specs: dict[str, TenantSpec],
                 quantum_tokens: float = 256.0) -> None:
        if not specs:
            raise ValueError("TenantTable needs at least one tenant")
        if DEFAULT_TENANT not in specs:
            specs = {**specs, DEFAULT_TENANT: TenantSpec(DEFAULT_TENANT)}
        self.quantum_tokens = float(quantum_tokens)
        # lock-order-sanitizer hook: the queue lock is held while consulting
        # this table (admission + pick), so this lock is always innermost
        self._lock = make_lock("serve.tenants")
        self._tenants: dict[str, _TenantState] = {}  # guarded by: _lock
        self._ring: list[str] = []                   # guarded by: _lock
        self._ring_pos = 0                           # guarded by: _lock
        # tenants interrupted mid-grant by a take filling up: the next
        # visit resumes their unspent deficit WITHOUT a fresh quantum, so
        # take-size truncation never inflates anyone's grant count (that
        # equal count is what makes long-run share converge to the
        # weight ratio)
        self._mid_grant: dict[str, bool] = {}        # guarded by: _lock
        for name, spec in specs.items():
            bucket = (
                TokenBucket(spec.token_rate, spec.burst)
                if spec.token_rate > 0 else None
            )
            self._tenants[name] = _TenantState(spec=spec, bucket=bucket)
            self._ring.append(name)

    # -- resolution / admission ------------------------------------------

    def resolve(self, name: str | None) -> TenantSpec:
        """Header value -> spec; empty/None falls to the default tenant,
        unknown names raise :class:`UnknownTenant` (typed 400 upstream)."""
        with self._lock:
            st = self._tenants.get(name or DEFAULT_TENANT)
            if st is None:
                raise UnknownTenant(
                    f"unknown tenant {name!r} (declared: "
                    f"{sorted(self._tenants)})"
                )
            return st.spec

    def admit(self, tenant: str, tokens: int) -> float | None:
        """Quota gate for the queue's one admission predicate: None admits
        (and bills the bucket), a float is the refill-derived Retry-After
        of a typed QUOTA shed. Unknown tenants (internal fan-out, replay of
        a journal from an older tenant config) admit unlimited. Counting
        lives in ServeMetrics (the one ledger the scrape renders) — this
        table holds only scheduling/quota STATE."""
        with self._lock:
            st = self._tenants.get(tenant or DEFAULT_TENANT)
            if st is None or st.bucket is None:
                return None
            return st.bucket.take(tokens)

    def refund(self, tenant: str, tokens: int, *, deficit: bool = False) -> None:
        """Unwind QoS accounting for a request cancelled before any engine
        work (serve/scheduler.py cancellation): the tokens it billed at
        admission return to its rate bucket (capped at burst — a refund
        never banks beyond the bucket's ceiling), and with ``deficit=True``
        (a request cancelled after take but before dispatch) the DRR
        deficit it drained at the take commit point is credited back, so a
        cancel storm can't silently tax one tenant's long-run share.
        Unknown tenants no-op, mirroring :meth:`admit`."""
        with self._lock:
            st = self._tenants.get(tenant or DEFAULT_TENANT)
            if st is None:
                return
            if st.bucket is not None and st.bucket.rate > 0:
                st.bucket.level = min(
                    st.bucket.burst, st.bucket.level + max(float(tokens), 0.0)
                )
            if deficit:
                st.deficit += max(float(tokens), 0.0)

    # -- the deficit-round-robin pick ------------------------------------

    def _state_for_locked(self, name: str) -> _TenantState:
        """Requests may carry tenants the table no longer (or never)
        declares — replayed journals, direct API users. They schedule as a
        weight-1 interactive tenant instead of being dropped."""
        st = self._tenants.get(name or DEFAULT_TENANT)
        if st is None:
            st = _TenantState(spec=TenantSpec(name or DEFAULT_TENANT),
                              bucket=None)
            self._tenants[name] = st
            self._ring.append(name)
        return st

    def select(self, candidates: list, max_take: int) -> list:
        """Pick up to ``max_take`` of ``candidates`` (ServeRequests, queue
        FIFO order) by tier then deficit round robin. Interactive-tier
        candidates are exhausted before any batch-tier one is picked;
        within a tier each backlogged tenant's deficit grows by
        quantum * weight per visit and drains by the picked request's token
        cost, so long-run token share converges to the weight ratio.
        Deficits persist across calls (that IS the long-run memory); a
        tenant whose backlog empties forfeits its remainder — classic DRR,
        no hoarding. Within one tenant, FIFO order is preserved. Always
        returns at least one request when candidates is non-empty."""
        if not candidates or max_take < 1:
            return []
        out: list = []
        with self._lock:
            by_tier: dict[str, dict[str, list]] = {}
            for r in candidates:
                tier = getattr(r, "tier", TIER_INTERACTIVE)
                tier = tier if tier in TIERS else TIER_INTERACTIVE
                # sanitized, so a request-carried name can neither raise
                # here (the take path must serve) nor corrupt a metrics
                # label downstream
                tenant = _label_safe(getattr(r, "tenant", ""))
                # register undeclared tenants (journal replay after a
                # --tenants change, direct API callers) BEFORE the ring
                # loop below: a backlog whose tenant the ring never visits
                # would spin the pick forever with the queue lock held
                self._state_for_locked(tenant)
                by_tier.setdefault(tier, {}).setdefault(tenant, []).append(r)
            for tier in TIERS:
                backlogs = by_tier.get(tier)
                if not backlogs:
                    continue
                while len(out) < max_take and any(backlogs.values()):
                    # the ring persists across calls so visit order — and
                    # therefore quantum accrual — is fair over time, not
                    # reset per take
                    name = self._ring[self._ring_pos % len(self._ring)]
                    backlog = backlogs.get(name)
                    if not backlog:
                        self._ring_pos += 1
                        continue
                    st = self._state_for_locked(name)
                    if not self._mid_grant.get(name):
                        st.deficit += self.quantum_tokens * st.spec.weight
                    self._mid_grant[name] = False
                    while backlog and len(out) < max_take:
                        cost = max(
                            getattr(backlog[0], "billable_tokens", 1), 1
                        )
                        if st.deficit < cost:
                            break
                        st.deficit -= cost
                        out.append(backlog.pop(0))
                    if not backlog:
                        # emptied backlog forfeits its remainder: a quiet
                        # tenant must not bank service it never queued for
                        st.deficit = 0.0
                        backlogs.pop(name, None)
                        self._ring_pos += 1
                    elif len(out) >= max_take and st.deficit >= max(
                        getattr(backlog[0], "billable_tokens", 1), 1
                    ):
                        # interrupted mid-grant by the take filling: stay
                        # on this tenant and resume the unspent deficit
                        # next call, no fresh quantum
                        self._mid_grant[name] = True
                    else:
                        self._ring_pos += 1
                if len(out) >= max_take:
                    break
        return out

    # -- scrape surface ----------------------------------------------------

    def multi_tenant(self, candidates: list) -> bool:
        """True when ``candidates`` span more than one (tenant, tier) — the
        queue's gate for WFQ selection vs the byte-identical FIFO
        fall-through."""
        seen = set()
        for r in candidates:
            seen.add((getattr(r, "tenant", "") or DEFAULT_TENANT,
                      getattr(r, "tier", TIER_INTERACTIVE)))
            if len(seen) > 1:
                return True
        return False

    def stats(self) -> dict:
        """Scrape-time snapshot of CONFIG + quota state per tenant:
        {tenant: {weight, token_rate, tier, bucket_tokens}}. Per-tenant
        request/shed counters live in ServeMetrics (the one ledger), never
        here — two ledgers for the same facts would drift."""
        now = time.monotonic()
        with self._lock:
            out = {}
            for name, st in self._tenants.items():
                bucket_tokens = None
                if st.bucket is not None:
                    st.bucket._refill(now)
                    bucket_tokens = round(st.bucket.level, 3)
                out[name] = {
                    "weight": st.spec.weight,
                    "token_rate": st.spec.token_rate,
                    "tier": st.spec.tier,
                    "bucket_tokens": bucket_tokens,
                }
            return out
